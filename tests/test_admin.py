"""Admin API (CommandHandler), Maintainer/ExternalQueue, and CLI tests.

Role parity: reference `src/main/test/CommandHandlerTests.cpp` and
CommandLine smoke coverage.
"""

import json
import threading
import urllib.request

import pytest

from stellar_core_tpu.main.application import Application
from stellar_core_tpu.main.commandline import main as cli_main
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.testing import AppLedgerAdapter
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


@pytest.fixture
def app():
    cfg = Config.test_config(0)
    cfg.DATABASE = "sqlite3://:memory:"
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    a = Application(clock, cfg)
    a.start()
    yield a
    a.stop()


def cmd(app, name, **params):
    status, body = app.command_handler.handle_command(
        name, {k: str(v) for k, v in params.items()})
    return status, body


# ------------------------------------------------------------- introspection

def test_info_metrics_quorum_scp_peers(app):
    st, info = cmd(app, "info")
    assert st == 200
    assert info["ledger"]["num"] == 1
    assert info["ledger"]["synced"] is True
    st, m = cmd(app, "metrics")
    assert st == 200 and isinstance(m, dict)
    st, q = cmd(app, "quorum")
    assert st == 200
    st, s = cmd(app, "scp")
    assert st == 200 and "tracking" in s
    st, p = cmd(app, "peers")
    assert st == 200


def test_unknown_command(app):
    st, body = cmd(app, "no-such-endpoint")
    assert st == 404
    assert "commands" in body and "info" in body["commands"]


def test_numeric_param_validation_returns_400_not_500(app):
    """ISSUE 4 satellite: negative / non-numeric limit-style params are
    rejected as 400-style error dicts instead of raising in the HTTP
    thread (which showed up as a 500 with a stack-trace string)."""
    for name, params in (
            ("scp", {"limit": "-1"}),
            ("scp", {"limit": "abc"}),
            ("scp", {"slot": "-2", "timeline": "true"}),
            ("trace", {"action": "dump", "limit": "nope"}),
            ("trace", {"action": "dump", "limit": "-5"}),
            ("trace", {"action": "start", "capacity": "0"}),
            ("trace", {"action": "start", "capacity": "xyz"}),
            ("timeline", {"slot": "x"}),
    ):
        st, body = cmd(app, name, **params)
        assert st == 400, (name, params, st, body)
        assert "error" in body and "parameter" in body["error"]
    # valid values still work after the rejects
    st, body = cmd(app, "scp", limit="3")
    assert st == 200
    st, body = cmd(app, "trace", action="status")
    assert st == 200 and body["enabled"] is False


def test_faults_set_rejects_unknown_site_with_400(app):
    """ISSUE 5 satellite: arming a typo'd site would silently no-op
    forever; `set` validates against the F1 registry
    (util.faults.KNOWN_SITES, docs/robustness.md site catalog)."""
    st, body = cmd(app, "faults", action="set", site="device.dispach")
    assert st == 400
    assert "unknown fault site" in body["error"]
    assert "device.dispatch" in body["error"]   # suggests the catalog
    assert not app.faults.configured()          # nothing got armed

    # malformed schedule params are 400s too, not 500 stack traces
    # n=0 and p=0 included: a count-0 or probability-0 site would be
    # armed yet never fire — the same silent-no-op class the
    # unknown-site 400 exists to prevent
    for bad in ({"p": "lots"}, {"p": "-0.5"}, {"p": "1.5"}, {"p": "nan"},
                {"p": "0"}, {"n": "-3"}, {"n": "0"}, {"after": "-1"}):
        st, body = cmd(app, "faults", action="set",
                       site="device.dispatch", **bad)
        assert st == 400 and "parameter" in body["error"], (bad, body)
    assert not app.faults.configured()

    # a registered site still arms and clears
    st, body = cmd(app, "faults", action="set", site="device.dispatch",
                   p="0.5", n="3", after="2")
    assert st == 200 and body["status"] == "armed"
    s = body["sites"]["device.dispatch"]
    assert (s["probability"], s["remaining"], s["skip"]) == (0.5, 3, 2)
    st, body = cmd(app, "faults", action="clear")
    assert st == 200 and not app.faults.configured()


def test_verifier_endpoint_on_plain_cpu_backend(app):
    """ISSUE 6: the cockpit endpoint works for every backend, including
    the breaker-less plain CPU verifier (the resilient/threaded shapes
    are covered in tests/test_verifier_cockpit.py)."""
    st, body = cmd(app, "verifier")
    assert st == 200
    assert body["configured_backend"] == "cpu"
    assert body["verifier"] == "cpu"
    assert "breaker" not in body            # plain cpu has no breaker
    assert body["queue"]["depth"] == 0
    assert body["warmup"]["state"] == "idle"
    assert "compile_cache" in body and "buckets" in body
    assert body["counters"]["pending"] == 0
    assert "verifier" in app.command_handler.command_names()


def test_metrics_prometheus_format_over_http(app):
    """format=prometheus serves text exposition with the 0.0.4 content
    type through the real HTTP server."""
    port = app.command_handler.start_http(port=0)
    got = []

    def fetch():
        url = "http://127.0.0.1:%d/metrics?format=prometheus" % port
        with urllib.request.urlopen(url, timeout=10) as r:
            got.append((r.status, r.headers["Content-Type"],
                        r.read().decode()))

    t = threading.Thread(target=fetch)
    t.start()
    # handler hops to the main loop; crank until the reply lands
    app.crank_until(lambda: bool(got), max_cranks=200000)
    t.join(timeout=5)
    status, ctype, text = got[0]
    assert status == 200
    assert ctype.startswith("text/plain; version=0.0.4")
    assert "# TYPE sct_" in text
    assert "sct_crypto_verify_cache_hit" in text
    app.command_handler.stop_http()


# ------------------------------------------------------------- transactions

def test_tx_submission_via_handler(app):
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**9)
    frame = alice.tx([alice.op_payment(root.account_id, 100)])
    st, body = cmd(app, "tx", blob=frame.envelope.to_xdr().hex())
    assert st == 200 and body["status"] == "PENDING"
    st, body = cmd(app, "manualclose")
    assert st == 200
    assert adapter.balance(alice.account_id) < 10**9 - 100
    # duplicate detection
    frame2 = alice.tx([alice.op_payment(root.account_id, 1)])
    cmd(app, "tx", blob=frame2.envelope.to_xdr().hex())
    st, body = cmd(app, "tx", blob=frame2.envelope.to_xdr().hex())
    assert body["status"] == "DUPLICATE"


def test_tx_missing_blob(app):
    st, body = cmd(app, "tx")
    assert body["status"] == "ERROR"


# ------------------------------------------------------------- upgrades / ll

def test_upgrades_roundtrip(app):
    st, body = cmd(app, "upgrades", mode="set", basefee=250,
                   upgradetime=0)
    assert st == 200
    st, body = cmd(app, "upgrades", mode="get")
    assert body["fee"] == 250
    st, body = cmd(app, "upgrades", mode="clear")
    assert st == 200


def test_ll_sets_levels(app):
    st, before = cmd(app, "ll")
    assert st == 200
    st, after = cmd(app, "ll", level="debug", partition="Herder")
    assert after["Herder"].lower() == "debug"
    cmd(app, "ll", level="info", partition="Herder")


# ------------------------------------------------------- cursors/maintenance

def test_cursors_and_maintenance(app):
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**9)
    for _ in range(6):
        alice.pay(root, 10)
    lcl = app.ledger_manager.last_closed_ledger_num()
    assert lcl >= 7
    rows_before = app.database.execute(
        "SELECT COUNT(*) FROM txhistory").fetchone()[0]
    assert rows_before > 0

    # a lagging cursor pins everything
    cmd(app, "setcursor", id="A", cursor=1)
    st, body = cmd(app, "maintenance", count=1000)
    assert body["rows_deleted"] == 0

    # advance the cursor: history below it may go (bounded by checkpoint
    # retention, so force a tiny frequency to observe deletion)
    app.config.CHECKPOINT_FREQUENCY = 4
    cmd(app, "setcursor", id="A", cursor=lcl)
    st, body = cmd(app, "maintenance", count=1000)
    assert st == 200 and body["rows_deleted"] > 0
    st, cursors = cmd(app, "getcursor")
    assert cursors == {"A": lcl}
    cmd(app, "dropcursor", id="A")
    st, cursors = cmd(app, "getcursor")
    assert cursors == {}


# ------------------------------------------------------------- HTTP surface

def test_http_server_roundtrip(app):
    port = app.command_handler.start_http(port=0)
    done = []

    def fetch():
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/info" % port, timeout=10) as r:
            done.append(json.loads(r.read()))

    t = threading.Thread(target=fetch)
    t.start()
    # handler hops to the main loop; crank until the reply lands
    app.crank_until(lambda: bool(done), max_cranks=200000)
    t.join(timeout=5)
    assert done and done[0]["ledger"]["num"] == 1


# ------------------------------------------------------------------ CLI

def test_cli_key_tools(capsys):
    assert cli_main(["gen-seed"]) == 0
    out = capsys.readouterr().out
    seed = [l for l in out.splitlines() if l.startswith("Secret")][0].split()[-1]
    pub = [l for l in out.splitlines() if l.startswith("Public")][0].split()[-1]
    assert cli_main(["sec-to-pub", "--seed", seed]) == 0
    assert capsys.readouterr().out.strip() == pub
    assert cli_main(["convert-id", pub]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["type"] == "public_key"
    assert cli_main(["version"]) == 0
    assert "stellar-core-tpu" in capsys.readouterr().out


def test_cli_new_db_and_offline_info(tmp_path, capsys):
    from stellar_core_tpu.crypto import strkey
    from stellar_core_tpu.crypto.hashing import sha256
    from stellar_core_tpu.crypto.keys import SecretKey
    seed = strkey.encode_seed(
        SecretKey.from_seed(sha256(b"test-cli-node")).seed)
    conf = tmp_path / "node.toml"
    conf.write_text(
        'DATABASE = "sqlite3://%s"\n'
        'NODE_SEED = "%s"\n'
        'BUCKET_DIR_PATH = "%s"\n'
        % (tmp_path / "node.db", seed, tmp_path / "buckets"))
    assert cli_main(["new-db", "--conf", str(conf)]) == 0
    out = capsys.readouterr().out
    assert "genesis" in out
    assert cli_main(["offline-info", "--conf", str(conf)]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["ledger"]["num"] == 1


def test_cli_print_xdr_and_sign(tmp_path, capsys):
    cfg = Config.test_config(0)
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    a = Application(clock, cfg)
    a.start()
    adapter = AppLedgerAdapter(a)
    root = adapter.root_account()
    alice = root.create(10**9)
    frame = alice.tx([alice.op_payment(root.account_id, 5)])
    txf = tmp_path / "tx.hex"
    txf.write_text(frame.envelope.to_xdr().hex())
    assert cli_main(["print-xdr", str(txf),
                     "--filetype", "TransactionEnvelope"]) == 0
    assert "signatures" in capsys.readouterr().out
    from stellar_core_tpu.crypto import strkey
    seed = strkey.encode_seed(alice.sk.seed)
    assert cli_main(["sign-transaction", str(txf), "--seed", seed,
                     "--netid", cfg.NETWORK_PASSPHRASE]) == 0
    signed_hex = capsys.readouterr().out.strip()
    from stellar_core_tpu.xdr import TransactionEnvelope
    env = TransactionEnvelope.from_xdr(bytes.fromhex(signed_hex))
    assert len(env.value.signatures) == 2


def test_metrics_instrumented_after_closes(app):
    """The medida-style catalog (docs/metrics.md) is populated by real
    activity: ledger close timer, tx meters, SCP meters, crypto cache."""
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    a = root.create(10**9)
    app.submit_transaction(a.tx([a.op_payment(root.account_id, 5)]))
    app.manual_close()
    st, m = cmd(app, "metrics")
    assert st == 200
    assert m["ledger.ledger.close"]["count"] >= 2
    # apply-vs-SQL split (reference DBTimeExcluder): components sum to
    # (almost exactly) the whole close
    assert m["ledger.ledger.close.sql"]["count"] == \
        m["ledger.ledger.close"]["count"]
    assert m["ledger.ledger.close.apply"]["count"] == \
        m["ledger.ledger.close"]["count"]
    total = m["ledger.ledger.close"]["mean"]
    parts = m["ledger.ledger.close.sql"]["mean"] + \
        m["ledger.ledger.close.apply"]["mean"]
    assert parts == pytest.approx(total, rel=0.05, abs=5e-4)
    assert m["ledger.transaction.apply"]["count"] >= 2
    assert m["herder.tx.received"]["count"] >= 2
    assert m["scp.envelope.emit"]["count"] >= 1
    assert m["scp.value.externalized"]["count"] >= 2
    assert "crypto.verify.cache-hit" in m
    assert m["scp.timing.externalized"]["count"] >= 1
    assert m["scp.value.nominated"]["count"] >= 1
    assert m["ledger.ledger.num"]["count"] == \
        app.ledger_manager.last_closed_ledger_num()


def test_checkquorum_critical_param(app):
    st, out = cmd(app, "checkquorum", critical="true")
    assert st == 200
    assert out["intersection"] is True
    # standalone self-quorum: the single validator is trivially critical
    # or the list is empty — either way the field is present and a list
    assert isinstance(out["intersection_critical"], list)


def test_generateload_endpoint():
    cfg = Config.test_config(9)
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING = True
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    a.start()
    st, out = cmd(a, "generateload", accounts=5, txs=0)
    assert st == 200 and "error" not in out, out
    a.manual_close()
    st, out = cmd(a, "generateload", accounts=0, txs=8)
    assert st == 200 and "error" not in out, out
    a.manual_close()
    m = a.metrics.to_json()
    # 5 creates may batch into fewer txs; the 8 payments are 1 tx each
    assert m["herder.tx.accepted"]["count"] >= 9
    assert m["ledger.transaction.apply"]["count"] >= 9
    a.stop()


def test_generateload_requires_testing_flag(app):
    st, out = cmd(app, "generateload", accounts=1, txs=1)
    assert "error" in out


def test_testacc_and_testtx_endpoints(app):
    """reference CommandHandler.cpp:103-105 test-only endpoints: testtx
    creates/pays name-derived accounts, testacc reads them back."""
    app.config.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING = True
    st, out = cmd(app, "testtx", **{"from": "root", "to": "bob",
                                    "amount": "100000000",
                                    "create": "true"})
    assert st == 200 and out["status"] == 0, out
    app.manual_close()
    st, acc = app.command_handler.handle_command("testacc", {"name": "bob"})
    assert st == 200 and acc["balance"] == 100000000, acc
    assert acc["id"].startswith("G")
    # bob pays root
    st, out = cmd(app, "testtx", **{"from": "bob", "to": "root",
                                    "amount": "5000"})
    assert st == 200 and out["status"] == 0, out
    app.manual_close()
    st, acc2 = app.command_handler.handle_command("testacc", {"name": "bob"})
    assert st == 200 and acc2["balance"] < 100000000 - 5000 + 1, acc2
    # gated off without the flag
    app.config.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING = False
    st, out = app.command_handler.handle_command("testacc", {"name": "bob"})
    assert "error" in out


# ----------------------------------------------- bans operator surface

def test_bans_list_unban_unban_all(app):
    """ISSUE 8 satellite: `bans?action=list|unban|unban_all` with 400s
    on bad params via the CommandParamError path."""
    from stellar_core_tpu.crypto import strkey
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.crypto.hashing import sha256 as _sha
    bm = app.overlay_manager.ban_manager
    ids = [SecretKey.from_seed(_sha(b"ban%d" % i)).public_key
           for i in range(3)]
    for pk in ids:
        bm.ban_node(pk)
    st, body = cmd(app, "bans")
    assert st == 200 and len(body["bans"]) == 3
    st, body = cmd(app, "bans", action="list")
    assert st == 200 and len(body["bans"]) == 3
    # unban by hex-XDR
    st, body = cmd(app, "bans", action="unban",
                   node=ids[0].to_xdr().hex())
    assert st == 200 and len(body["bans"]) == 2
    assert not bm.is_banned(ids[0])
    # unban by strkey
    st, body = cmd(app, "bans", action="unban",
                   node=strkey.encode_public_key(ids[1].key_bytes))
    assert st == 200 and len(body["bans"]) == 1
    # bad params are 400s, not 500s
    st, body = cmd(app, "bans", action="unban", node="not-a-key")
    assert st == 400 and "node" in body["error"]
    st, body = cmd(app, "bans", action="unban")
    assert st == 400
    st, body = cmd(app, "bans", action="frobnicate")
    assert st == 400 and "action" in body["error"]
    # unban_all clears the set (and the DB table)
    st, body = cmd(app, "bans", action="unban_all")
    assert st == 200 and body["unbanned"] == 1 and body["bans"] == []
    assert app.database.execute(
        "SELECT COUNT(*) FROM bans").fetchone()[0] == 0


# ------------------------------------------- tx hardening + ingress (ISSUE 18)

def test_tx_malformed_blob_is_400(app):
    """A blob that is neither hex nor base64, or that decodes to
    garbage, must come back as a 400 CommandParamError — never a 500
    out of the HTTP thread."""
    for blob in ("zzzz-not-hex-not-b64!!", "deadbeef",  # bad XDR bytes
                 "3q2+7w=="):                           # b64 of bad XDR
        st, body = cmd(app, "tx", blob=blob)
        assert st == 400, (blob, st, body)
        assert "blob" in body["error"]


def test_tx_base64_blob_accepted(app):
    """The reference handler accepts base64 envelopes too."""
    import base64
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**9)
    frame = alice.tx([alice.op_payment(root.account_id, 5)])
    b64 = base64.b64encode(frame.envelope.to_xdr()).decode()
    st, body = cmd(app, "tx", blob=b64)
    assert st == 200 and body["status"] == "PENDING"


def test_tx_all_statuses_and_retry_after(app):
    """PENDING / DUPLICATE / ERROR / TRY_AGAIN_LATER all surface, and
    the TRY_AGAIN_LATER answer carries the ingress tier's retry-after
    hint (seconds)."""
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**9)
    frame = alice.tx([alice.op_payment(root.account_id, 7)])
    blob = frame.envelope.to_xdr().hex()
    st, body = cmd(app, "tx", blob=blob)
    assert st == 200 and body["status"] == "PENDING"
    st, body = cmd(app, "tx", blob=blob)
    assert body["status"] == "DUPLICATE"
    # a broken seqnum fails check_valid -> ERROR with a result detail
    bad = alice.tx([alice.op_payment(root.account_id, 7)], seq=10**9)
    st, body = cmd(app, "tx", blob=bad.envelope.to_xdr().hex())
    assert body["status"] == "ERROR"
    # arm the admission-stall fault site: the next submission is
    # throttled with an explicit backpressure hint (F1 chaos leg)
    st, body = cmd(app, "faults", action="set", site="ingress.admit-stall",
                   p="1.0", n="1")
    assert st == 200
    fresh = alice.tx([alice.op_payment(root.account_id, 8)],
                     seq=alice.next_seq() + 1)
    st, body = cmd(app, "tx", blob=fresh.envelope.to_xdr().hex())
    assert st == 200 and body["status"] == "TRY_AGAIN_LATER"
    assert body["retry_after"] > 0
    cmd(app, "faults", action="clear")


def test_ingress_status_set_class_reset(app):
    """`ingress[?action=status|set-class|reset]` (A1 row): status dumps
    the class table + counters, set-class re-pins an account at runtime,
    reset zeroes the counters, and every bad param is a 400."""
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**9)
    strkey = alice.sk.strkey_public()
    st, body = cmd(app, "ingress")
    assert st == 200 and body["enabled"] is True
    assert set(body["classes"]) == {"priority", "default", "untrusted"}
    assert body["intake"]["depth"] <= body["intake"]["cap"]
    # runtime re-pin: alice joins the untrusted class
    st, body = cmd(app, "ingress", action="set-class",
                   account=strkey, **{"class": "untrusted"})
    assert st == 200 and body["class"] == "untrusted"
    ing = app.herder.ingress
    assert ing.class_of(alice.sk.public_key.key_bytes).name == "untrusted"
    st, body = cmd(app, "ingress")
    assert body["overrides"] == 1
    # back to default removes the override
    st, body = cmd(app, "ingress", action="set-class",
                   account=strkey, **{"class": "default"})
    assert st == 200
    assert cmd(app, "ingress")[1]["overrides"] == 0
    # a submission bumps the admitted counter; reset zeroes it
    frame = alice.tx([alice.op_payment(root.account_id, 9)])
    cmd(app, "tx", blob=frame.envelope.to_xdr().hex())
    st, body = cmd(app, "ingress")
    assert body["counters"]["default"]["admitted"] >= 1
    st, body = cmd(app, "ingress", action="reset")
    assert st == 200
    assert cmd(app, "ingress")[1]["counters"]["default"]["admitted"] == 0
    # 400s: unknown class, bad strkey, missing params, unknown action
    for params in ({"action": "set-class", "account": strkey,
                    "class": "vip"},
                   {"action": "set-class", "account": "not-a-key",
                    "class": "priority"},
                   {"action": "set-class"},
                   {"action": "frobnicate"}):
        st, body = cmd(app, "ingress", **params)
        assert st == 400, (params, st, body)
        assert "error" in body


def test_ingress_endpoint_when_disabled():
    """INGRESS_ENABLED=False nodes answer {"enabled": false} instead of
    404ing operators probing a mixed fleet."""
    cfg = Config.test_config(0)
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.INGRESS_ENABLED = False
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    a = Application(clock, cfg)
    a.start()
    try:
        assert a.herder.ingress is None
        st, body = cmd(a, "ingress")
        assert st == 200 and body == {"enabled": False}
    finally:
        a.stop()
