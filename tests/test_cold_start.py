"""Cold-start story (VERDICT r2 #9): a restarted validator must not re-pay
kernel compilation — the persistent XLA cache makes the second process's
warmup fast.

Reference analog: no lazy work on the consensus path; a stellar-core
restart is serving envelopes as soon as state is restored. Here the
equivalent hazard is XLA compilation (~67s on TPU in round 2), so
TpuSigVerifier.warmup() + jax_compilation_cache_dir must turn a restart
into a cache load.
"""

import json
import os
import subprocess
import sys
import time

import pytest

_CHILD = r"""
import json, os, time
t0 = time.perf_counter()
from stellar_core_tpu.crypto.batch_verifier import TpuSigVerifier
from stellar_core_tpu.crypto.keys import SecretKey
v = TpuSigVerifier(compile_cache_dir=os.environ["SCT_TEST_CACHE"])
v.BUCKETS = (32,)
v.warmup(wait=True)
warm_s = time.perf_counter() - t0
sk = SecretKey.from_seed(b"\x31" * 32)
t0 = time.perf_counter()
res = v.verify_many([(sk.public_key.key_bytes, sk.sign(b"m"), b"m")])
verify_s = time.perf_counter() - t0
assert res == [True]
print("COLD_JSON " + json.dumps({"warm_s": warm_s, "verify_s": verify_s}))
"""


def _run_node(cache_dir: str) -> dict:
    env = dict(os.environ)
    env["SCT_TEST_CACHE"] = cache_dir
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    r = subprocess.run([sys.executable, "-c", _CHILD],
                       capture_output=True, text=True, timeout=900,
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    for line in r.stdout.splitlines():
        if line.startswith("COLD_JSON "):
            return json.loads(line[10:])
    raise AssertionError("no COLD_JSON in output: %s" % r.stdout[-300:])


def test_restart_compiles_from_cache(tmp_path):
    """Second process start loads the kernel from the persistent cache —
    dramatically faster than the cold compile. (Absolute restart time on
    this CPU test host is dominated by jax import + cache deserialization;
    the TPU validator's restart compile time is what BENCH records as
    compile_s.)"""
    cache = str(tmp_path / "xla-cache")
    cold = _run_node(cache)
    assert os.path.exists(cache) and os.listdir(cache), \
        "persistent compilation cache was not populated"
    warm = _run_node(cache)
    assert warm["warm_s"] < cold["warm_s"] / 2, (cold, warm)
    # generous absolute bounds: this host runs suites concurrently and the
    # python+jax import alone is ~15s; the RELATIVE checks are the real
    # contract for both warmup and the first live batch
    assert warm["warm_s"] < 120.0, warm
    assert warm["verify_s"] < max(10.0, cold["verify_s"] * 3), (cold, warm)
