"""Close cockpit (ISSUE 9): per-op apply attribution, native-bail
forensics, state-read telemetry, and the surfaces they feed.

- apply_breakdown per-op ms + residual sum to the measured apply wall on
  BOTH the native and the Python path (the bench block's contract);
- a forced-bail txset (an offer op) classifies to the right
  `ledger.apply.native-bail.<reason>` meter and span tag;
- fee-bump and muxed traffic are counted distinctly;
- `applystats` admin endpoint round-trips (status + reset + 400s) and
  the `sct_ledger_apply_*` series appear in `metrics?format=prometheus`;
- LedgerTxnRoot state-read telemetry: per-type lookups, cache hit/miss,
  prefetch coverage and getPrefetchHitRate parity;
- bucket layer: per-level sizes + merge durations.
"""

import pytest

from stellar_core_tpu.herder.txset import TxSetFrame
from stellar_core_tpu.ledger.apply_stats import (
    ApplyStats, frame_traits, op_type_name, txset_prefetch_keys,
)
from stellar_core_tpu.ledger.ledger_manager import (
    LedgerCloseData, LedgerManager,
)
from stellar_core_tpu.main.application import Application
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.native import apply_engine
from stellar_core_tpu.testing import (
    TESTING_NETWORK_ID, TestAccount, root_secret_key,
)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr import (
    Asset, CryptoKeyType, MuxedAccount, OperationType, StellarValue,
    StellarValueExt,
)


# ---------------------------------------------------------------- harness

class _StubConfig:
    DATABASE = "in-memory"
    LEDGER_PROTOCOL_VERSION = 13
    GENESIS_TOTAL_COINS = 10 ** 17
    TESTING_UPGRADE_DESIRED_FEE = 100
    TESTING_UPGRADE_RESERVE = 5_000_000
    TESTING_UPGRADE_MAX_TX_SET_SIZE = 1000
    network_id = TESTING_NETWORK_ID


class _StubApp:
    config = _StubConfig()

    def network_root_key(self):
        return root_secret_key()


class _Shim:
    def __init__(self, lm):
        self.lm = lm
        self.network_id = TESTING_NETWORK_ID

    def header(self):
        return self.lm.root.get_header()

    def seq_num(self, account_id):
        from stellar_core_tpu.xdr import LedgerKey
        e = self.lm.root.get_entry(LedgerKey.account(account_id))
        return e.data.value.seqNum if e is not None else 0


class CloseHarness:
    """One LedgerManager closing real LedgerCloseData through
    close_ledger — the same path consensus and catchup replay use."""

    def __init__(self, native: bool):
        self.lm = LedgerManager(_StubApp())
        self.lm.start_new_ledger()
        self.lm.use_native_apply = native
        self.shim = _Shim(self.lm)

    def account(self, sk):
        return TestAccount(self.shim, sk)

    def close(self, frames):
        lm = self.lm
        header = lm.root.get_header()
        ts = TxSetFrame(TESTING_NETWORK_ID, lm.lcl_hash, frames)
        value = StellarValue(
            txSetHash=ts.get_contents_hash(),
            closeTime=header.scpValue.closeTime + 5,
            upgrades=[], ext=StellarValueExt(0, None))
        lm.close_ledger(LedgerCloseData(header.ledgerSeq + 1, ts, value))


def _payment_frames(h, n=4):
    from stellar_core_tpu.crypto.keys import SecretKey
    root = h.account(root_secret_key())
    sks = [SecretKey.from_seed(bytes([50 + i]) * 32) for i in range(n)]
    h.close([root.tx([root.op_create_account(sk.public_key, 10 ** 10)
                      for sk in sks])])
    accs = [h.account(sk) for sk in sks]
    return [a.tx([a.op_payment(root.account_id, 1000 + i)])
            for i, a in enumerate(accs)]


def _breakdown_sums(ab):
    total_ms = sum(ab["per_op_ms"].values()) + ab["other_ms"]
    wall_ms = ab["apply_wall_s"] * 1e3
    # per-op values are rounded to 1 µs; generous absolute slack
    assert abs(total_ms - wall_ms) < max(0.5, 1e-3 * wall_ms), \
        (total_ms, wall_ms)
    assert ab["other_ms"] >= 0.0


# ------------------------------------------------- breakdown sums to wall

def test_python_path_breakdown_sums_to_wall():
    h = CloseHarness(native=False)
    frames = _payment_frames(h)
    h.close(frames)
    stats = h.lm.apply_stats
    ab = stats.apply_breakdown()
    assert stats.closes["python"] == 2 and stats.closes.get("native", 0) == 0
    assert ab["op_counts"]["payment"] == 4
    assert ab["op_counts"]["create-account"] == 4
    assert ab["per_op_ms"]["payment"] > 0
    _breakdown_sums(ab)
    # the Python path also feeds the per-op latency histograms
    hist = stats.metrics.to_json().get("ledger.apply.op.payment.seconds")
    assert hist and hist["count"] == 4
    # every close bailed with a classified reason (the gate is off)
    assert ab["bails"].get("disabled") == 2


@pytest.mark.skipif(apply_engine() is None,
                    reason="native apply engine unavailable")
def test_native_path_breakdown_sums_to_wall():
    h = CloseHarness(native=True)
    frames = _payment_frames(h)
    h.close(frames)
    stats = h.lm.apply_stats
    ab = stats.apply_breakdown()
    assert stats.closes["native"] == 2
    # the native engine's (count, ns) table attributes per op type
    assert ab["op_counts"]["payment"] == 4
    assert ab["op_counts"]["create-account"] == 4
    assert ab["per_op_ms"]["payment"] > 0
    _breakdown_sums(ab)
    assert ab["bails"] == {}


# ------------------------------------------------- native-bail forensics

@pytest.mark.skipif(apply_engine() is None,
                    reason="native apply engine unavailable")
def test_forced_bail_residual_classifies():
    """Full op coverage (ISSUE 13) drove the op-type bails to zero —
    offers now run natively. The residual bail taxonomy still
    classifies: a non-ed25519 signer key keeps the whole close on the
    Python path, metered as `signer-key-type`."""
    h = CloseHarness(native=True)
    root = h.account(root_secret_key())
    usd = Asset.credit("USD", root.account_id)
    f = root.tx([root.op_manage_sell_offer(Asset.native(), usd, 10, 1, 1)])
    h.close([f])
    stats = h.lm.apply_stats
    # offers are covered: the engine ran the close, nothing bailed
    assert stats.bails == {}
    assert stats.closes["native"] == 1
    assert op_type_name(OperationType.MANAGE_SELL_OFFER) == \
        "manage-sell-offer"
    # residual: a pre-auth-tx signer arm is outside the engine subset
    from stellar_core_tpu.xdr import Signer, SignerKey
    f2 = root.tx([root.op_set_options(signer=Signer(
        key=SignerKey.pre_auth_tx(b"\x07" * 32), weight=1))])
    h.close([f2])
    assert stats.bails == {"signer-key-type": 1}
    m = stats.metrics.to_json().get(
        "ledger.apply.native-bail.signer-key-type")
    assert m and m["count"] == 1
    assert stats.closes["python"] == 1
    assert stats.last_close["bail"] == "signer-key-type"


@pytest.mark.skipif(apply_engine() is None,
                    reason="native apply engine unavailable")
def test_fee_bump_native_and_counts_distinctly():
    h = CloseHarness(native=True)
    root = h.account(root_secret_key())
    from stellar_core_tpu.crypto.keys import SecretKey
    sk = SecretKey.from_seed(bytes([77]) * 32)
    h.close([root.tx([root.op_create_account(sk.public_key, 10 ** 10)])])
    a = h.account(sk)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=200)
    from stellar_core_tpu.transactions.transaction_frame import (
        FeeBumpTransactionFrame,
    )
    from stellar_core_tpu.xdr import (
        EnvelopeType, FeeBumpTransaction, FeeBumpTransactionEnvelope,
        TransactionEnvelope, _Ext,
    )
    from stellar_core_tpu.xdr.transaction import _InnerTxEnvelope
    fb = FeeBumpTransaction(
        feeSource=root.muxed, fee=1000,
        innerTx=_InnerTxEnvelope(EnvelopeType.ENVELOPE_TYPE_TX,
                                 inner.envelope.value),
        ext=_Ext.v0())
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
        FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
    frame = FeeBumpTransactionFrame(TESTING_NETWORK_ID, env)
    frame.add_signature(root.sk)
    h.close([frame])
    stats = h.lm.apply_stats
    # fee bumps joined the native subset (ISSUE 13): no bail, the
    # engine applied the close, and the mix counter still counts them
    assert stats.bails == {}
    assert stats.tx["fee_bump"] == 1
    assert stats.closes["native"] == 2


def test_failed_close_seals_window_and_sum_contract_survives():
    """A close that RAISES mid-apply still seals the cockpit window
    (path "failed", via close_ledger's exception handler → abort_close):
    the per-op seconds recorded for the doomed close join a matching
    apply wall, so other_ms stays >= 0 and the breakdown keeps adding
    up."""
    h = CloseHarness(native=False)
    frames = _payment_frames(h)

    orig_apply = type(frames[1]).apply

    def exploding_apply(self, ltx, verifier=None, stats=None):
        raise RuntimeError("injected mid-apply failure")

    # frame 0 applies (and records its op) before frame 1 detonates
    frames[1].apply = exploding_apply.__get__(frames[1])
    with pytest.raises(RuntimeError, match="injected mid-apply"):
        h.close(frames)
    frames[1].apply = orig_apply.__get__(frames[1])

    stats = h.lm.apply_stats
    assert stats.closes.get("failed") == 1
    assert stats._close is None          # window sealed, not leaked
    assert stats.last_close["path"] == "failed"
    # frame 0's payment was recorded; its seconds cannot outgrow the wall
    assert stats.ops["payment"]["count"] >= 1
    _breakdown_sums(stats.apply_breakdown())
    # a later close opens a fresh window and attributes normally
    h.close([frames[0]])
    assert stats.closes["python"] == 2   # setup close + this one
    _breakdown_sums(stats.apply_breakdown())

    # failure AFTER apply but before the close is durable (here: the
    # tx-history store) must also classify "failed" — the window is
    # sealed only once the close commits, so closes.{native|python}
    # never counts a close that didn't
    def boom(*a, **k):
        raise RuntimeError("post-apply failure")
    h.lm._store_txs = boom
    with pytest.raises(RuntimeError, match="post-apply"):
        h.close([frames[2]])
    del h.lm._store_txs
    assert stats.closes["failed"] == 2
    assert stats.closes["python"] == 2   # unchanged
    _breakdown_sums(stats.apply_breakdown())


def test_frame_traits_muxed_detection():
    h = CloseHarness(native=False)
    root = h.account(root_secret_key())
    plain = root.tx([root.op_payment(root.account_id, 1)])
    assert frame_traits(plain) == (False, False)
    muxed_dest = MuxedAccount(
        CryptoKeyType.KEY_TYPE_MUXED_ED25519, None)
    from stellar_core_tpu.xdr.basic import MuxedAccountMed25519
    muxed_dest.value = MuxedAccountMed25519(
        id=7, ed25519=root.account_id.key_bytes)
    f = root.tx([root.op_payment(root.account_id, 1)])
    f.tx.operations[0].body.value.destination = muxed_dest
    assert frame_traits(f) == (False, True)


# ------------------------------------------------- prefetch key collection

def test_txset_prefetch_keys_cover_sources_and_destinations():
    h = CloseHarness(native=False)
    root = h.account(root_secret_key())
    from stellar_core_tpu.crypto.keys import SecretKey
    sk = SecretKey.from_seed(bytes([60]) * 32)
    usd = Asset.credit("USD", root.account_id)
    f1 = root.tx([root.op_create_account(sk.public_key, 10 ** 9)])
    f2 = root.tx([root.op_payment(sk.public_key, 5, asset=usd)])
    keys = txset_prefetch_keys([f1, f2])
    kinds = [k.disc for k in keys]
    from stellar_core_tpu.xdr import LedgerEntryType
    assert kinds.count(LedgerEntryType.ACCOUNT) == 2   # root + dest, deduped
    assert kinds.count(LedgerEntryType.TRUSTLINE) == 2  # src + dest USD lines


# ------------------------------------ full app: endpoint, prometheus, reads

@pytest.fixture
def app():
    cfg = Config.test_config(0)
    cfg.DATABASE = "sqlite3://:memory:"
    a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    a.start()
    yield a
    a.stop()


def _drive_closes(app, n_payments=6):
    from stellar_core_tpu.simulation.load_generator import LoadGenerator
    lg = LoadGenerator(app)
    lg.generate_accounts(4)
    app.manual_close()
    lg.generate_payments(n_payments)
    app.clock.set_virtual_time(app.clock.now() + 1.0)
    app.manual_close()


def test_applystats_endpoint_roundtrip(app):
    _drive_closes(app)
    st, body = app.command_handler.handle_command("applystats", {})
    assert st == 200
    assert body["closes"]["native"] + body["closes"]["python"] >= 2
    assert body["ops"]  # per-op table populated
    assert body["last_close"]["reads"]["write_set"] > 0
    assert "prefetch" in body["state_reads"]
    # reset zeroes the aggregates but keeps the endpoint shape
    st, body = app.command_handler.handle_command(
        "applystats", {"action": "reset"})
    assert st == 200 and body["status"] == "reset"
    st, body = app.command_handler.handle_command("applystats", {})
    assert st == 200
    assert body["closes"] == {"native": 0, "python": 0}
    assert body["ops"] == {}
    # malformed action is a 400, not a 500
    st, body = app.command_handler.handle_command(
        "applystats", {"action": "bogus"})
    assert st == 400 and "action" in body["error"]


def test_prometheus_series_roundtrip(app):
    _drive_closes(app)
    st, text = app.command_handler.handle_command(
        "metrics", {"format": "prometheus"})
    assert st == 200 and isinstance(text, str)
    lines = text.splitlines()
    # fixed cockpit series are present from the first scrape
    for needle in ("sct_ledger_apply_wall", "sct_ledger_apply_read_set",
                   "sct_ledger_apply_prefetch_coverage_pct",
                   "sct_ledger_apply_state_cache_hit_total"):
        assert any(line.startswith(needle) for line in lines), needle
    # dynamic per-op series carry real counts
    tot = next(line for line in lines if line.startswith(
        "sct_ledger_apply_op_payment_count_total"))
    assert float(tot.split()[-1]) > 0


def test_state_read_telemetry_and_prefetch(app):
    # the bulk-prefetch cockpit serves the PYTHON apply path (the
    # native engine does its own static-key loads; close_ledger skips
    # the duplicate pass — ISSUE 13)
    app.ledger_manager.use_native_apply = False
    _drive_closes(app)
    stats = app.ledger_manager.apply_stats
    r = stats.to_json()["state_reads"]
    assert r["prefetch"]["calls"] >= 2
    assert r["prefetch"]["requested"] > 0
    assert r["prefetch"]["cached"] <= r["prefetch"]["requested"]
    assert 0.0 <= stats.prefetch_hit_rate() <= 1.0
    # per-type lookup meters registered under the documented prefix
    mj = app.metrics.to_json(prefix="ledger.apply.state.lookup.")
    assert mj  # at least one entry type was looked up in SQL
    cov = app.metrics.to_json().get("ledger.apply.prefetch.coverage-pct")
    assert cov and cov["count"] >= 2


def test_close_span_tagged_with_op_mix(app):
    app.tracer.enable()
    _drive_closes(app)
    spans = [s for s in app.tracer.spans() if s.name == "close.apply"]
    assert spans
    tagged = [s for s in spans if s.tags and "op_mix" in s.tags]
    assert tagged, "close.apply spans must carry op-mix tags"
    last = tagged[-1]
    assert last.tags["apply_path"] in ("native", "python")
    assert "reads" in last.tags
    assert isinstance(last.tags["op_mix"], dict)


def test_bucket_merge_and_level_telemetry(tmp_path):
    cfg = Config.test_config(0)
    cfg.DATABASE = "sqlite3://:memory:"
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.enable_buckets(str(tmp_path / "buckets"))
    app.start()
    try:
        for _ in range(3):
            _drive_closes(app, n_payments=2)
        stats = app.ledger_manager.apply_stats
        b = stats.to_json()["buckets"]
        assert b["merges"] > 0
        assert b["merge_seconds"] >= 0.0
        assert b["levels"]  # per-level sizes recorded at snapshot
        g = app.metrics.to_json().get("bucket.level.0.entries")
        assert g is not None
        hist = app.metrics.to_json().get("bucket.merge.seconds")
        assert hist and hist["count"] > 0
    finally:
        app.stop()


# -------------------------------------------------- traced replay contract

def test_traced_replay_breakdown_both_paths(tmp_path):
    """The bench contract end to end on a REAL catchup replay: publish a
    small history once, replay it twice (native on / pinned to Python),
    and assert each leg's apply_breakdown sums to its apply wall."""
    import os
    from stellar_core_tpu.catchup.catchup_work import CatchupConfiguration
    from stellar_core_tpu.history.archive import HistoryArchive
    from stellar_core_tpu.work.basic_work import State

    archive_root = str(tmp_path / "archive")
    os.makedirs(archive_root, exist_ok=True)

    def make_app(n, writable):
        cfg = Config.test_config(n)
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.CHECKPOINT_FREQUENCY = 8
        arch = HistoryArchive.local_dir("bench", archive_root)
        d = {"get": arch.get_tmpl, "mkdir": arch.mkdir_tmpl}
        if writable:
            d["put"] = arch.put_tmpl
        cfg.HISTORY = {"bench": d}
        a = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
        a.start()
        return a

    pub = make_app(0, True)
    from stellar_core_tpu.testing import AppLedgerAdapter
    ad = AppLedgerAdapter(pub)
    root = ad.root_account()
    from stellar_core_tpu.crypto.keys import SecretKey
    sks = [SecretKey.from_seed(bytes([90 + i]) * 32) for i in range(3)]
    pub.submit_transaction(root.tx(
        [root.op_create_account(sk.public_key, 10 ** 10) for sk in sks]))
    pub.manual_close()
    senders = [TestAccount(ad, sk) for sk in sks]
    pub.clock.set_virtual_time(pub.clock.now() + 10.0)
    target = pub.history_manager.published_checkpoints + 1
    while pub.history_manager.published_checkpoints < target:
        for s in senders:
            pub.submit_transaction(
                s.tx([s.op_payment(root.account_id, 100)]))
        pub.clock.set_virtual_time(pub.clock.now() + 1.0)
        pub.manual_close()
        pub.crank_until(
            lambda: pub.history_manager.publish_queue() == [], 20000)

    for native in (True, False):
        if native and apply_engine() is None:
            continue
        app = make_app(1, False)
        app.tracer.enable(capacity=65536)
        app.ledger_manager.use_native_apply = native
        app.clock.set_virtual_time(pub.clock.now() + 10.0)
        work = app.catchup_manager.start_catchup(
            CatchupConfiguration.complete())
        for _ in range(10 ** 6):
            if work.is_done():
                break
            app.crank(False)
        assert work.state == State.SUCCESS
        ab = app.ledger_manager.apply_stats.apply_breakdown()
        path = "native" if native else "python"
        assert ab["closes"][path] > 0, ab["closes"]
        assert ab["per_op_ms"].get("payment", 0) > 0
        _breakdown_sums(ab)
        # the replayed closes' spans carry the cockpit tags
        spans = [s for s in app.tracer.spans()
                 if s.name == "close.apply" and s.tags
                 and "op_mix" in s.tags]
        assert spans
        app.stop()
    pub.stop()


# --------------------------------------------------- native-bail taxonomy


def test_native_bail_taxonomy_is_metric_safe_and_classify_stable():
    """The registry side of sctlint rule N4: the taxonomy table in
    docs/observability.md (parsed by the same
    `analysis.crules.native_bail_taxonomy` the lint rule uses) is what
    every C `ctx_bail`/`env_bail` literal and Python `_bail` gate must
    classify into. Here the table itself is held to the cockpit's
    contracts: every reason is a valid metric-name segment for
    `ledger.apply.native-bail.<reason>`, `_classify_engine_bail` is
    idempotent on the already-classified exact reasons (only the
    numeric `op-<n>` family rewrites), and each dynamic `op-<n>` the C
    engine can emit classifies INTO the dynamic row's family."""
    import os
    import re

    from stellar_core_tpu.analysis.crules import native_bail_taxonomy
    from stellar_core_tpu.ledger.apply_stats import OP_TYPE_NAMES
    from stellar_core_tpu.ledger.native_apply import _classify_engine_bail

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "docs", "observability.md"),
              encoding="utf-8") as fh:
        taxonomy = native_bail_taxonomy(fh.read())
    assert len(taxonomy) >= 25, "taxonomy table went missing or short"
    assert set(taxonomy.values()) <= {"c", "python"}, taxonomy
    seg = re.compile(r"^[a-z0-9<>-]+$")
    for reason in taxonomy:
        assert seg.match(reason), \
            "taxonomy reason %r is not metric-name safe" % reason
        if "<" not in reason:
            assert _classify_engine_bail(reason) == reason, \
                "classifier rewrites exact reason %r" % reason
    # the C engine's dynamic family: op-<n> classifies to op-<name>,
    # which the `op-<type>` row covers
    dyn = [r for r in taxonomy if "<" in r]
    assert "op-<type>" in dyn
    for v, name in OP_TYPE_NAMES.items():
        assert _classify_engine_bail("op-%d" % v) == "op-" + name
