"""Fee-bump envelope vectors, ported from the reference's
FeeBumpTransactionTests.cpp section matrix (validity codes, fee
processing, inner-failure reporting)."""

import pytest

# fee bumps are CAP-0015 (protocol 13): the whole module runs at
# v13 semantics; the explicit not-supported test pins its versions
pytestmark = pytest.mark.min_version(13)

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.testing import TestAccount, TestLedger, root_secret_key
from stellar_core_tpu.transactions.transaction_frame import (
    FeeBumpTransactionFrame,
)
from stellar_core_tpu.xdr import (
    EnvelopeType, FeeBumpTransaction, FeeBumpTransactionEnvelope,
    TransactionEnvelope, TransactionResultCode, _Ext,
)
from stellar_core_tpu.xdr.transaction import _InnerTxEnvelope


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return TestAccount(ledger, root_secret_key())


def bump(ledger, sponsor, inner_frame, fee=1000, sign=True,
         signers=None):
    fb = FeeBumpTransaction(
        feeSource=sponsor.muxed, fee=fee,
        innerTx=_InnerTxEnvelope(EnvelopeType.ENVELOPE_TYPE_TX,
                                 inner_frame.envelope.value),
        ext=_Ext.v0())
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
        FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
    frame = FeeBumpTransactionFrame(ledger.network_id, env)
    for sk in (signers if signers is not None
               else ([sponsor.sk] if sign else [])):
        frame.add_signature(sk)
    return frame


def test_insufficient_fee_below_min(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    # fee must cover (inner ops + 1) * baseFee = 200
    f = bump(ledger, sponsor, inner, fee=199)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txINSUFFICIENT_FEE


def test_insufficient_fee_rate_below_inner_bid(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=900)
    # outer fee below the inner bid is an invalid replacement
    f = bump(ledger, sponsor, inner, fee=400)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txINSUFFICIENT_FEE


def test_fee_source_missing(ledger, root):
    a = root.create(10**9)
    ghost = TestAccount(ledger, SecretKey.pseudo_random_for_testing())
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    f = bump(ledger, ghost, inner)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txNO_ACCOUNT


def test_bad_signatures_missing_and_wrong(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    f = bump(ledger, sponsor, inner, sign=False)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txBAD_AUTH
    wrong = SecretKey.pseudo_random_for_testing()
    f = bump(ledger, sponsor, inner, signers=[wrong])
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txBAD_AUTH


def test_extra_signatures_rejected(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    extra = SecretKey.pseudo_random_for_testing()
    f = bump(ledger, sponsor, inner, signers=[sponsor.sk, extra])
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txBAD_AUTH_EXTRA


def test_insufficient_balance_on_fee_source(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**7)   # two reserves, nothing spare
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    f = bump(ledger, sponsor, inner, fee=10**7)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txINSUFFICIENT_BALANCE


def test_inner_invalid_reports_inner_pair(ledger, root):
    a = root.create(10**9)
    sponsor = root.create(10**9)
    # inner bad seq: invalid at transaction level
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100,
                 seq=a.next_seq() + 50)
    f = bump(ledger, sponsor, inner)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txFEE_BUMP_INNER_FAILED
    pair = f.result.result.value
    assert pair.transactionHash == f.inner.contents_hash()
    assert pair.result.code == TransactionResultCode.txBAD_SEQ


@pytest.mark.min_version(13)
def test_inner_op_failure_fee_still_charged_to_sponsor(ledger, root):
    """Inner operation fails at apply: the sponsor pays the fee, the
    inner source pays nothing, and the result carries the inner pair
    (reference 'inner transaction fails, operation level')."""
    a = root.create(10**9)
    sponsor = root.create(10**9)
    ghost = SecretKey.pseudo_random_for_testing()
    inner = a.tx([a.op_payment(ghost.public_key, 5)], fee=100)  # NO_DEST
    f = bump(ledger, sponsor, inner, fee=1000)
    bal_sponsor = sponsor.balance()
    bal_a = a.balance()
    seq_a = ledger.seq_num(a.account_id)
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txFEE_BUMP_INNER_FAILED
    # sponsor paid the (effective) fee; inner source untouched except seq
    assert sponsor.balance() == bal_sponsor - f.fee_charged(ledger.header())
    assert ledger.balance(a.account_id) == bal_a
    assert ledger.seq_num(a.account_id) == seq_a + 1  # inner seq consumed


def test_fee_charged_capped_at_effective_base_fee(ledger, root):
    """feeCharged = min(bid, baseFee * (ops+1)) — the bid is a ceiling,
    not the charge (reference 'fee processing')."""
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    f = bump(ledger, sponsor, inner, fee=10**6)
    bal_sponsor = sponsor.balance()
    assert ledger.apply_frame(f), f.result
    charged = bal_sponsor - sponsor.balance()
    assert charged == 2 * ledger.header().baseFee
    assert f.result.code == TransactionResultCode.txFEE_BUMP_INNER_SUCCESS
    pair = f.result.result.value
    assert pair.result.code == TransactionResultCode.txSUCCESS


# --------------------------------------------- set-options / change-trust
# (reference SetOptionsTests.cpp / ChangeTrustTests.cpp key scenarios)

from stellar_core_tpu.transactions.operations import (  # noqa: E402
    ChangeTrustResultCode, SetOptionsResultCode,
)
from stellar_core_tpu.xdr import Asset  # noqa: E402


def op_code(frame):
    return frame.result.op_results[0].value.value.disc


def test_set_options_signer_cap(ledger, root):
    a = root.create(10**10)
    for i in range(20):
        k = SecretKey.from_seed(bytes([60 + i]) + b"\x01" * 31)
        f = a.tx([a.op_add_signer(k.public_key.key_bytes)])
        assert ledger.apply_frame(f), (i, f.result)
    k = SecretKey.from_seed(bytes([90]) + b"\x01" * 31)
    f = a.tx([a.op_add_signer(k.public_key.key_bytes)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == SetOptionsResultCode.TOO_MANY_SIGNERS


def test_set_options_signer_remove_and_master_lockout(ledger, root):
    a = root.create(10**9)
    b = root.create(10**9)
    k = SecretKey.from_seed(b"\x41" * 32)
    assert ledger.apply_frame(a.tx([a.op_add_signer(k.public_key.key_bytes)]))
    # weight 0 removes the signer
    assert ledger.apply_frame(
        a.tx([a.op_add_signer(k.public_key.key_bytes, weight=0)]))
    # master weight 0 with no other signers: account can no longer sign
    assert ledger.apply_frame(a.tx([a.op_set_options(master_weight=0)]))
    f = a.tx([a.op_payment(b.account_id, 1)])
    assert not ledger.apply_frame(f)
    assert f.result.code == TransactionResultCode.txBAD_AUTH


def test_set_options_bad_signer_is_self(ledger, root):
    a = root.create(10**9)
    f = a.tx([a.op_add_signer(a.account_id.key_bytes)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == SetOptionsResultCode.BAD_SIGNER


def test_set_options_threshold_range_and_flags(ledger, root):
    a = root.create(10**9)
    f = a.tx([a.op_set_options(med=256)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == SetOptionsResultCode.THRESHOLD_OUT_OF_RANGE
    f = a.tx([a.op_set_options(set_flags=1, clear_flags=1)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == SetOptionsResultCode.BAD_FLAGS
    f = a.tx([a.op_set_options(set_flags=0x100)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == SetOptionsResultCode.UNKNOWN_FLAG


def test_change_trust_limits(ledger, root):
    issuer = root.create(10**9)
    a = root.create(10**9)
    usd = Asset.credit("USD", issuer.account_id)
    assert a.change_trust(usd, 1000)
    assert issuer.pay(a, 500, usd)
    # reducing the limit below the balance is invalid
    f = a.tx([a.op_change_trust(usd, 400)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == ChangeTrustResultCode.INVALID_LIMIT
    # deleting (limit 0) with a balance is invalid; after paying it back
    # the line deletes and frees the subentry
    f = a.tx([a.op_change_trust(usd, 0)])
    assert not ledger.apply_frame(f)
    assert a.pay(issuer, 500, usd)
    assert ledger.apply_frame(a.tx([a.op_change_trust(usd, 0)]))
    from stellar_core_tpu.xdr import LedgerKey
    assert ledger.root.get_entry(
        LedgerKey.trustline(a.account_id, usd)) is None  # line deleted


def test_change_trust_self_not_allowed(ledger, root):
    issuer = root.create(10**9)
    own = Asset.credit("OWN", issuer.account_id)
    f = issuer.tx([issuer.op_change_trust(own, 1000)])
    assert not ledger.apply_frame(f)
    assert op_code(f) == ChangeTrustResultCode.SELF_NOT_ALLOWED


def test_outer_auth_rechecked_at_apply(ledger, root):
    """The outer envelope re-validates at apply (reference fee-bump apply
    runs commonValid + processSignatures over the outer sigs): revoking
    the sponsor's master key between validation and apply fails the bump
    with txBAD_AUTH while still charging the fee."""
    a = root.create(10**9)
    sponsor = root.create(10**9)
    inner = a.tx([a.op_payment(root.account_id, 1)], fee=100)
    f = bump(ledger, sponsor, inner, fee=1000)
    # validate now (passes), then the sponsor locks itself out
    from stellar_core_tpu.ledger.ledgertxn import LedgerTxn
    ltx = LedgerTxn(ledger.root)
    assert f.check_valid(ltx, 0, None)
    ltx.rollback()
    assert ledger.apply_frame(
        sponsor.tx([sponsor.op_set_options(master_weight=0)]))
    bal = sponsor.balance()
    # replay-shaped close: fees/seqs are consumed, then apply re-checks
    # the outer auth and fails the bump
    (ok,) = ledger.close_with([f])
    assert not ok
    assert f.result.code == TransactionResultCode.txBAD_AUTH
    assert sponsor.balance() == bal - f.fee_charged(ledger.header())


def test_fee_bump_not_supported_below_v13():
    """Reference FeeBumpTransactionTests 'not supported': the envelope
    is structurally valid at v12 but commonValid gates it."""
    from stellar_core_tpu.xdr import TransactionResultCode
    led = TestLedger(ledger_version=12)
    r = TestAccount(led, root_secret_key())
    a = r.create(10**9)
    sponsor = r.create(10**9)
    inner = a.tx([a.op_payment(r.account_id, 100)])
    fb = bump(led, sponsor, inner)
    assert not led.apply_frame(fb)
    assert fb.result.code == TransactionResultCode.txNOT_SUPPORTED
    # v13: same envelope applies fine
    led13 = TestLedger(ledger_version=13)
    r13 = TestAccount(led13, root_secret_key())
    a13 = r13.create(10**9)
    sp13 = r13.create(10**9)
    inner13 = a13.tx([a13.op_payment(r13.account_id, 100)])
    assert led13.apply_frame(bump(led13, sp13, inner13)), "v13 fee bump"
