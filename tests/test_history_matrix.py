"""History publish/catchup matrix (VERDICT r3 item: reference-scale
HistoryTests coverage).

Role parity: each test names its reference scenario from
`/root/reference/src/history/test/HistoryTests.cpp:38-1242` — stalled
publishes, publish/catchup alternation, pristine queued snapshots,
publish-queue persistence across restart, prefix/recent catchup targets,
mid-archive protocol transitions, multi-archive publishes, corrupt
buckets, tampered ledger chains, and re-initializing an existing store.
"""

import gzip
import os

import pytest

from stellar_core_tpu.catchup import CatchupConfiguration
from stellar_core_tpu.history.archive import (HistoryArchive, category_path,
                                              hex8)
from stellar_core_tpu.history.archive_state import HistoryArchiveState
from stellar_core_tpu.main.application import Application
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.testing import AppLedgerAdapter
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work.basic_work import State

FREQ = 8


def make_app(tmp_path, n, archive_root, writable=True, db_file=None,
             extra_archives=()):
    cfg = Config.test_config(n)
    cfg.DATABASE = ("sqlite3://%s" % db_file) if db_file \
        else "sqlite3://:memory:"
    cfg.CHECKPOINT_FREQUENCY = FREQ
    cfg.HISTORY = {}
    for name, root in (("test", archive_root),) + tuple(extra_archives):
        arch = HistoryArchive.local_dir(name, str(root))
        d = {"get": arch.get_tmpl, "mkdir": arch.mkdir_tmpl}
        if writable:
            d["put"] = arch.put_tmpl
        cfg.HISTORY[name] = d
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application(clock, cfg)
    app.enable_buckets(str(tmp_path / ("buckets-%d" % n)))
    app.start()
    return app


def close_with_traffic(app, upto):
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**10)
    while app.ledger_manager.last_closed_ledger_num() < upto:
        f = alice.tx([alice.op_payment(root.account_id, 1000)])
        app.submit_transaction(f)
        app.manual_close()
    return alice


def advance(app, upto):
    """More closes on an app whose root DSL account already exists."""
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    while app.ledger_manager.last_closed_ledger_num() < upto:
        f = root.tx([root.op_payment(root.account_id, 1)])
        app.submit_transaction(f)
        app.manual_close()


def drain_publishes(app):
    app.crank_until(lambda: app.history_manager.publish_queue() == [],
                    max_cranks=5000)


def run_work(app, work, max_cranks=200000):
    for _ in range(max_cranks):
        if work.is_done():
            break
        app.crank(False)
    assert work.is_done(), "work did not finish"
    return work.state


def break_archive_puts(app, name="test"):
    arch = app.history_manager.archives[name]
    saved = arch.put_tmpl
    arch.put_tmpl = "false"          # every put now exits 1
    return saved


def tip_hash(app, seq):
    row = app.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (seq,)).fetchone()
    return row[0]


# ---------------------------------------------------------------- publish

def test_stalled_publish_retries_then_succeeds(tmp_path):
    """A failing archive put leaves the checkpoint queued (in order);
    publishing resumes once the archive recovers. Reference
    HistoryTests.cpp:900 'Publish catchup alternation with stall' stall
    half + publish retry semantics."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app = make_app(tmp_path, 0, archive_root)
    saved = break_archive_puts(app)
    close_with_traffic(app, FREQ + 2)       # past checkpoint FREQ-1
    app.crank_until(lambda: app.history_manager.failed_publishes > 0,
                    max_cranks=5000)
    assert app.history_manager.publish_queue() == [FREQ - 1]
    assert app.history_manager.published_checkpoints == 0
    # archive heals: the queued checkpoint publishes on the next attempt
    app.history_manager.archives["test"].put_tmpl = saved
    app.history_manager.publish_queued_history()
    assert app.history_manager.publish_queue() == []
    assert app.history_manager.published_checkpoints == 1
    assert (archive_root / ".well-known" / "stellar-history.json").exists()


def test_publish_queue_persists_across_restart(tmp_path):
    """Queued-but-unpublished checkpoints survive a restart and publish
    on the next start. Reference HistoryTests.cpp:1035 'persist publish
    queue'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    db_file = str(tmp_path / "node.db")
    app = make_app(tmp_path, 0, archive_root, db_file=db_file)
    break_archive_puts(app)
    close_with_traffic(app, FREQ + 2)
    app.crank_until(lambda: app.history_manager.failed_publishes > 0,
                    max_cranks=5000)
    assert app.history_manager.publish_queue() == [FREQ - 1]
    app.stop()
    # second incarnation on the same DB with a HEALTHY archive:
    # Application.start() resumes queued publishes
    app2 = make_app(tmp_path, 0, archive_root, db_file=db_file)
    drain_publishes(app2)
    assert app2.history_manager.publish_queue() == []
    has = HistoryArchiveState.from_json(
        (archive_root / ".well-known" / "stellar-history.json").read_text())
    assert has.current_ledger == FREQ - 1


def test_queued_has_stays_pristine_until_publish(tmp_path):
    """The HAS snapshotted into the publish queue reflects the checkpoint
    ledger even when the bucket list keeps evolving before the publish
    happens. Reference HistoryTests.cpp:971 'HAS in publishqueue remains
    in pristine state until publish'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app = make_app(tmp_path, 0, archive_root)
    saved = break_archive_puts(app)
    close_with_traffic(app, 2 * FREQ + 3)   # TWO checkpoints queue up
    app.crank_until(
        lambda: len(app.history_manager.publish_queue()) == 2,
        max_cranks=5000)
    queued = {
        seq: app.history_manager._queued_has(seq)
        for seq in app.history_manager.publish_queue()}
    app.history_manager.archives["test"].put_tmpl = saved
    app.history_manager.publish_queued_history()
    assert app.history_manager.publish_queue() == []
    # each published per-checkpoint HAS equals its queue-time snapshot
    for seq, has0 in queued.items():
        p = archive_root / category_path("history", seq, ".json")
        got = HistoryArchiveState.from_json(p.read_text())
        assert got.current_ledger == seq == has0.current_ledger
        assert got.bucket_hashes() == has0.bucket_hashes()


def test_publish_to_multiple_archives(tmp_path):
    """Each checkpoint publishes to EVERY writable archive, and a fresh
    node can catch up from the second one. Reference HistoryTests.cpp:417
    'History publish to multiple archives'."""
    root1, root2 = tmp_path / "arch1", tmp_path / "arch2"
    os.makedirs(root1)
    os.makedirs(root2)
    app = make_app(tmp_path, 0, root1,
                   extra_archives=(("backup", root2),))
    close_with_traffic(app, FREQ + 2)
    drain_publishes(app)
    for root in (root1, root2):
        assert (root / ".well-known" / "stellar-history.json").exists()
        assert (root / category_path("ledger", FREQ - 1,
                                     ".xdr.gz")).exists()
    # catch up from the SECOND archive only
    app_b = make_app(tmp_path, 1, root2, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.SUCCESS
    assert app_b.ledger_manager.last_closed_ledger_num() == FREQ - 1
    assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app, FREQ - 1)


def test_initialize_existing_history_store_fails(tmp_path):
    """`new-hist` refuses to overwrite an initialized archive. Reference
    HistoryTests.cpp:1221 'initialize existing history store fails'."""
    from stellar_core_tpu.main.commandline import main as cli_main
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    arch = HistoryArchive.local_dir("test", str(archive_root))
    from stellar_core_tpu.crypto import strkey
    from stellar_core_tpu.crypto.hashing import sha256
    from stellar_core_tpu.crypto.keys import SecretKey
    seed = strkey.encode_seed(
        SecretKey.from_seed(sha256(b"history-matrix-node")).seed)
    cfg_path = tmp_path / "node.cfg"
    cfg_path.write_text(
        'DATABASE = "sqlite3://:memory:"\n'
        'NODE_SEED = "%s"\n'
        'RUN_STANDALONE = true\n'
        'UNSAFE_QUORUM = true\n'
        '[HISTORY.test]\n'
        'get = "%s"\nput = "%s"\nmkdir = "%s"\n'
        % (seed, arch.get_tmpl.replace('"', ''),
           arch.put_tmpl.replace('"', ''),
           arch.mkdir_tmpl.replace('"', '')))
    assert cli_main(["new-hist", "--conf", str(cfg_path), "test"]) == 0
    assert (archive_root / ".well-known" / "stellar-history.json").exists()
    # second init must fail and leave the store untouched
    before = (archive_root / ".well-known" /
              "stellar-history.json").read_text()
    assert cli_main(["new-hist", "--conf", str(cfg_path), "test"]) != 0
    assert (archive_root / ".well-known" /
            "stellar-history.json").read_text() == before


# ---------------------------------------------------------------- catchup

def test_publish_catchup_alternation_with_stall(tmp_path):
    """B alternates catchups as A publishes more checkpoints; when A
    stops publishing, B's next catchup makes no progress; when A resumes,
    B heals again. Reference HistoryTests.cpp:900 'Publish catchup
    alternation with stall'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, FREQ + 2)
    drain_publishes(app_a)
    app_b = make_app(tmp_path, 1, archive_root, writable=False)

    for round_no in range(2):           # catchup, advance, catchup again
        work = app_b.catchup_manager.start_catchup(
            CatchupConfiguration.complete())
        assert run_work(app_b, work) == State.SUCCESS
        tip = app_a.history_manager.published_checkpoints * FREQ - 1
        assert app_b.ledger_manager.last_closed_ledger_num() == tip
        assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app_a, tip)
        advance(app_a, app_a.ledger_manager.last_closed_ledger_num() + FREQ)
        drain_publishes(app_a)

    # stall: A keeps closing but STOPS publishing → the archive freezes
    b_lcl = app_b.ledger_manager.last_closed_ledger_num()
    break_archive_puts(app_a)
    has = HistoryArchiveState.from_json(
        (archive_root / ".well-known" / "stellar-history.json").read_text())
    advance(app_a, app_a.ledger_manager.last_closed_ledger_num() + 2 * FREQ)
    assert HistoryArchiveState.from_json(
        (archive_root / ".well-known" /
         "stellar-history.json").read_text()).current_ledger \
        == has.current_ledger           # archive genuinely stalled
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    if work is not None:
        run_work(app_b, work)
    assert app_b.ledger_manager.last_closed_ledger_num() >= b_lcl
    assert app_b.ledger_manager.last_closed_ledger_num() <= \
        has.current_ledger


def test_catchup_to_prefix_target(tmp_path):
    """Catchup with an explicit to_ledger strictly inside the archive
    lands exactly there, not at the tip. Reference HistoryTests.cpp:709
    'History prefix catchup'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, 3 * FREQ + 2)     # 3 checkpoints
    drain_publishes(app_a)
    target = 2 * FREQ - 1                       # middle checkpoint ledger
    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration(to_ledger=target))
    assert run_work(app_b, work) == State.SUCCESS
    assert app_b.ledger_manager.last_closed_ledger_num() == target
    assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app_a, target)


def test_catchup_recent_replays_only_suffix(tmp_path):
    """CATCHUP_RECENT applies buckets at an anchor then replays only the
    recent suffix: txhistory holds just the replayed ledgers while the
    chain tip matches. Reference HistoryTests.cpp:1146 'Catchup
    recent'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, 3 * FREQ + 2)
    drain_publishes(app_a)
    tip = 3 * FREQ - 1
    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.recent(FREQ))
    assert run_work(app_b, work) == State.SUCCESS
    assert app_b.ledger_manager.last_closed_ledger_num() == tip
    assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app_a, tip)
    replayed = [r[0] for r in app_b.database.execute(
        "SELECT DISTINCT ledgerseq FROM txhistory ORDER BY ledgerseq")]
    assert replayed, "recent catchup replayed nothing"
    assert min(replayed) >= 2 * FREQ, \
        "recent catchup replayed the whole archive (%r)" % replayed[:3]


def test_second_gap_triggers_second_catchup(tmp_path):
    """A node that already healed once heals AGAIN when a later gap
    appears (catchup is re-enterable). Reference HistoryTests.cpp:1106
    'catchup with a gap'."""
    from tests.test_catchup import make_lcd_from_db
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, FREQ + 2)
    drain_publishes(app_a)
    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.SUCCESS
    first_lcl = app_b.ledger_manager.last_closed_ledger_num()

    # A advances well past another checkpoint; B hears only the LATEST
    # close → gap → online catchup from the archive
    advance(app_a, first_lcl + 2 * FREQ)
    drain_publishes(app_a)
    a_tip = app_a.ledger_manager.last_closed_ledger_num()
    app_b.ledger_manager.value_externalized(make_lcd_from_db(app_a, a_tip))
    assert app_b.catchup_manager.catchup_running() or \
        app_b.ledger_manager.last_closed_ledger_num() >= a_tip - 1
    for _ in range(200000):
        if app_b.ledger_manager.last_closed_ledger_num() >= a_tip:
            break
        app_b.crank(False)
    assert app_b.ledger_manager.last_closed_ledger_num() == a_tip
    assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app_a, a_tip)


def test_protocol_transition_mid_archive_replays(tmp_path):
    """An armed base-fee/protocol upgrade lands mid-archive; a full
    replay carries the transition and ends byte-identical. Reference
    HistoryTests.cpp:675 'History catchup with different modes' over
    version boundaries (+ Upgrades applied at close)."""
    from stellar_core_tpu.herder.upgrades import UpgradeParameters
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, FREQ - 2)
    # arm a base-fee upgrade: applies on the next close (mid-checkpoint)
    p = UpgradeParameters()
    p.upgrade_time = 0
    p.base_fee = 250
    app_a.herder.upgrades.set_parameters(p)
    advance(app_a, 2 * FREQ + 2)
    drain_publishes(app_a)
    assert app_a.ledger_manager.lcl_header.baseFee == 250
    tip = 2 * FREQ - 1
    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.SUCCESS
    assert app_b.ledger_manager.last_closed_ledger_num() == tip
    assert app_b.ledger_manager.lcl_hash.hex() == tip_hash(app_a, tip)
    assert app_b.ledger_manager.lcl_header.baseFee == 250


def test_corrupt_bucket_fails_minimal_catchup(tmp_path):
    """A flipped byte inside a bucket file breaks its content hash and
    bucket-mode catchup fails rather than installing bad state.
    Reference HistoryTests.cpp:128 'History bucket verification'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, 2 * FREQ + 2)
    drain_publishes(app_a)
    # corrupt the LARGEST published bucket (surely referenced by the HAS)
    has = HistoryArchiveState.from_json(
        (archive_root / ".well-known" / "stellar-history.json").read_text())
    bucket_files = [
        archive_root / "bucket" / h[0:2] / h[2:4] / h[4:6] /
        ("bucket-%s.xdr.gz" % h) for h in has.bucket_hashes()]
    bucket_files = [b for b in bucket_files if b.exists()]
    victim = max(bucket_files, key=lambda p: p.stat().st_size)
    raw = bytearray(gzip.decompress(victim.read_bytes()))
    raw[len(raw) // 2] ^= 0xFF
    victim.write_bytes(gzip.compress(bytes(raw)))

    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.minimal())
    assert run_work(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_ledger_num() <= 1


def test_tampered_mid_chain_header_fails_verification(tmp_path):
    """A ledger header modified mid-archive (valid gzip, broken hash
    chain) fails chain verification. Reference HistoryTests.cpp:196
    'Ledger chain verification'."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root)
    app_a = make_app(tmp_path, 0, archive_root)
    close_with_traffic(app_a, 2 * FREQ + 2)
    drain_publishes(app_a)
    victim = archive_root / category_path("ledger", FREQ - 1, ".xdr.gz")
    raw = bytearray(gzip.decompress(victim.read_bytes()))
    # flip a byte past the record mark of the first entry: corrupts a
    # header field, so back-links/hashes stop matching
    raw[40] ^= 0x01
    victim.write_bytes(gzip.compress(bytes(raw)))

    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_ledger_num() <= 1
