"""Quorum intersection checker + QuorumTracker tests.

Role parity: reference `src/herder/test/QuorumIntersectionTests.cpp`
(known-topology matrices) and QuorumTracker coverage in HerderTests.
"""

import pytest

from stellar_core_tpu.crypto.hashing import sha256
from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.herder.quorum_intersection import (
    QuorumIntersectionChecker, QuorumTracker)
from stellar_core_tpu.xdr import PublicKey, SCPQuorumSet


def keys(n):
    return [SecretKey.from_seed(sha256(b"qic-%d" % i)).public_key
            for i in range(n)]


def qs(threshold, validators, inner=()):
    return SCPQuorumSet(threshold=threshold, validators=list(validators),
                        innerSets=list(inner))


def qmap_of(nodes, qsets):
    return {k.key_bytes: q for k, q in zip(nodes, qsets)}


def check(qmap):
    return QuorumIntersectionChecker(qmap) \
        .network_enjoys_quorum_intersection()


# ----------------------------------------------------------------- basics

def test_singleton_network():
    (a,) = keys(1)
    assert check({a.key_bytes: qs(1, [a])})


def test_empty_network():
    assert check({})


def test_symmetric_3_of_4_intersects():
    ks = keys(4)
    q = qs(3, ks)
    assert check(qmap_of(ks, [q] * 4))


def test_symmetric_2_of_4_splits():
    """Threshold 2-of-4: {A,B} and {C,D} are disjoint quorums."""
    ks = keys(4)
    q = qs(2, ks)
    c = QuorumIntersectionChecker(qmap_of(ks, [q] * 4))
    assert not c.network_enjoys_quorum_intersection()
    assert c.last_split is not None
    side_a, side_b = c.last_split
    assert not (set(side_a) & set(side_b))


def test_two_disjoint_cliques_split():
    a, b, c, d = keys(4)
    q1 = qs(2, [a, b])
    q2 = qs(2, [c, d])
    assert not check({a.key_bytes: q1, b.key_bytes: q1,
                      c.key_bytes: q2, d.key_bytes: q2})


def test_bridged_cliques_intersect():
    """Two cliques that both require a shared bridge node intersect."""
    a, b, c, d, e = keys(5)
    q1 = qs(3, [a, b, e])
    q2 = qs(3, [c, d, e])
    qe = qs(3, [a, b, e])
    assert check({a.key_bytes: q1, b.key_bytes: q1,
                  c.key_bytes: q2, d.key_bytes: q2,
                  e.key_bytes: qe})


def test_majority_of_5_intersects():
    ks = keys(5)
    q = qs(3, ks)
    assert check(qmap_of(ks, [q] * 5))


def test_inner_sets():
    """Nested slices: 2-of-{A, {2-of-B,C,D}} style qsets."""
    a, b, c, d = keys(4)
    inner = qs(2, [b, c, d])
    top = qs(2, [a], inner=[inner])
    assert check({a.key_bytes: top, b.key_bytes: top,
                  c.key_bytes: top, d.key_bytes: top})


def test_missing_qset_never_satisfied():
    """A node with unknown qset can't be part of any quorum, but the rest
    of the network still enjoys intersection."""
    ks = keys(4)
    q = qs(3, ks)
    qmap = qmap_of(ks, [q] * 4)
    qmap[ks[3].key_bytes] = None
    assert check(qmap)   # remaining 3-of-4 quorums all intersect


def test_contract_to_maximal_quorum():
    ks = keys(4)
    q = qs(3, ks)
    c = QuorumIntersectionChecker(qmap_of(ks, [q] * 4))
    assert c.contract_to_maximal_quorum(c.full) == c.full
    # a 2-node subset of 3-of-4 contains no quorum
    assert c.contract_to_maximal_quorum(0b0011) == 0
    assert c.is_a_quorum(0b0111)
    assert c.is_minimal_quorum(0b0111)
    assert not c.is_minimal_quorum(c.full)


def test_interrupt():
    ks = keys(6)
    q = qs(4, ks)
    c = QuorumIntersectionChecker(qmap_of(ks, [q] * 6))
    c.interrupted = True
    with pytest.raises(InterruptedError):
        c.network_enjoys_quorum_intersection()


# ------------------------------------------------------------ QuorumTracker

def test_tracker_expand_and_rebuild():
    a, b, c = keys(3)
    qa = qs(2, [a, b])
    qb = qs(2, [b, c])
    qc = qs(1, [c])
    t = QuorumTracker(a, lambda: qa)
    # local closure starts with a's qset deps
    assert t.is_node_definitely_in_quorum(a)
    assert t.is_node_definitely_in_quorum(b)
    assert not t.is_node_definitely_in_quorum(c)
    # expanding b pulls in c
    assert t.expand(b, qb)
    assert t.is_node_definitely_in_quorum(c)
    assert t.expand(c, qc)
    # unknown node fails expansion → rebuild path
    d = SecretKey.from_seed(sha256(b"qic-d")).public_key
    assert not t.expand(d, qc)
    known = {a.key_bytes: qa, b.key_bytes: qb, c.key_bytes: qc}
    t.rebuild(lambda nid: known.get(nid.key_bytes))
    got = t.get_quorum()
    assert set(got) == {a.key_bytes, b.key_bytes, c.key_bytes}
    assert all(v is not None for v in got.values())


def test_herder_tracker_via_simulation():
    """After a loopback network externalizes, every node's transitive
    quorum map holds all validators and intersection passes."""
    from stellar_core_tpu.simulation import topologies
    sim = topologies.core(4, 3)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(2), 30000)
    for node in sim.nodes.values():
        h = node.app.herder
        assert len(h.quorum_tracker.get_quorum()) == 4
        res = h.check_quorum_intersection()
        assert res["intersection"] is True
        assert res["node_count"] == 4
    sim.stop_all_nodes()


def test_intersection_critical_groups():
    """Reference 'quorum intersection criticality' scenario
    (QuorumIntersectionTests.cpp:824-880): two org groups {0,1,2} and
    {4,5,6} bridged by org3; the graph enjoys intersection in good
    configuration, and exactly org3 is intersection-critical."""
    import math

    from stellar_core_tpu.herder.quorum_intersection import (
        QuorumIntersectionChecker, intersection_critical_groups,
    )
    from stellar_core_tpu.xdr import PublicKey, SCPQuorumSet

    def nid(i):
        return bytes([i + 1]) * 32

    def pk(i):
        return PublicKey.ed25519(nid(i))

    links = [(0, 1), (1, 2), (4, 5), (4, 6), (5, 6),
             (0, 3), (1, 3), (2, 3), (4, 3), (6, 3)]
    neigh = {i: {i} for i in range(7)}
    for a, b in links:
        neigh[a].add(b)
        neigh[b].add(a)

    def qset(i):
        members = sorted(neigh[i])
        return SCPQuorumSet(
            threshold=math.ceil(0.67 * len(members)),
            validators=[pk(m) for m in members], innerSets=[])

    qmap = {nid(i): qset(i) for i in range(7)}
    assert QuorumIntersectionChecker(
        qmap).network_enjoys_quorum_intersection()
    crit = intersection_critical_groups(qmap)
    assert crit == [{nid(3)}], crit


def _pubnet_like(norgs=100, per_org=3, tier1=7, tier1_threshold=None):
    """A pubnet-shaped transitive map: a tier-1 backbone of `tier1` orgs
    that everyone (including tier-1) builds quorums from, plus
    `norgs - tier1` dependent orgs. ~norgs*per_org nodes total. This is
    the real topology shape the reference's SCC pruning exploits
    (QuorumIntersectionCheckerImpl.h refinement 8)."""
    orgs = [[SecretKey.from_seed(sha256(b"pub-%d-%d" % (o, i))).public_key
             for i in range(per_org)] for o in range(norgs)]
    org_inner = [qs(2, org) for org in orgs]
    t1 = org_inner[:tier1]
    thr = tier1_threshold if tier1_threshold is not None \
        else (2 * tier1 + 2) // 3
    top = qs(thr, [], inner=t1)
    return {k.key_bytes: top for org in orgs for k in org}


def test_pubnet_scale_intersection_within_budget():
    """~100 orgs / 300 nodes with a tier-1 backbone: the checker finishes
    well inside an operator-tolerable budget and reports intersection
    (reference runs this on a worker thread against pubnet,
    HerderImpl.cpp:140-144)."""
    import time
    qmap = _pubnet_like()
    assert len(qmap) == 300
    t0 = time.monotonic()
    c = QuorumIntersectionChecker(qmap)
    ok = c.network_enjoys_quorum_intersection()
    elapsed = time.monotonic() - t0
    assert ok is True
    assert elapsed < 45.0, "pubnet-scale check took %.1fs" % elapsed


def test_pubnet_scale_split_detected_within_budget():
    """Same scale with a tier-1 threshold low enough to split (3 of 7):
    two disjoint tier-1 triples exist and the checker finds them fast."""
    import time
    qmap = _pubnet_like(tier1_threshold=3)
    t0 = time.monotonic()
    c = QuorumIntersectionChecker(qmap)
    ok = c.network_enjoys_quorum_intersection()
    elapsed = time.monotonic() - t0
    assert ok is False
    assert c.last_split is not None
    a, b = c.last_split
    assert not (set(a) & set(b))
    assert elapsed < 45.0, "split detection took %.1fs" % elapsed


def test_pubnet_scale_interrupt_honored():
    """The interrupt flag aborts a pubnet-scale run promptly — the hook
    the herder's worker thread uses (reference HerderImpl.cpp:140-144)."""
    import threading
    import time
    # fully symmetric map: worst case, would run a very long time
    orgs = [[SecretKey.from_seed(sha256(b"sym-%d-%d" % (o, i))).public_key
             for i in range(3)] for o in range(40)]
    org_inner = [qs(2, org) for org in orgs]
    top = qs(27, [], inner=org_inner)
    qmap = {k.key_bytes: top for org in orgs for k in org}
    c = QuorumIntersectionChecker(qmap)

    def interrupt_soon():
        time.sleep(0.3)
        c.interrupted = True

    threading.Thread(target=interrupt_soon, daemon=True).start()
    t0 = time.monotonic()
    with pytest.raises(InterruptedError):
        c.network_enjoys_quorum_intersection()
    assert time.monotonic() - t0 < 5.0


# --------------------------------------------- herder background worker

def _make_app(tmp_path):
    from stellar_core_tpu.main.application import Application
    from stellar_core_tpu.main.config import Config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock
    cfg = Config.test_config(0)
    cfg.DATABASE = "sqlite3://:memory:"
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def test_background_check_completes_and_installs_result(tmp_path):
    """start_quorum_intersection_check runs off-thread and posts the
    result back to the main loop."""
    app = _make_app(tmp_path)
    h = app.herder
    assert h.start_quorum_intersection_check() is True
    assert h.quorum_check_recalculating is True
    # result arrives via post_to_main on a later crank
    assert app.crank_until(
        lambda: not h.quorum_check_recalculating, 100000)
    res = h.last_quorum_intersection
    assert res is not None and res["intersection"] is True
    assert h.get_json_info()["transitive"]["recalculating"] is False
    app.stop()


def test_long_check_does_not_stall_close_and_is_interruptible(tmp_path):
    """A check that would run forever neither blocks ledger close nor
    survives interrupt_quorum_intersection() from the main loop
    (reference HerderImpl.cpp:140-144)."""
    import time
    from stellar_core_tpu.herder import quorum_intersection as qi
    app = _make_app(tmp_path)
    h = app.herder

    def hang_until_interrupted(self):
        while not self.interrupted:
            time.sleep(0.005)
        raise InterruptedError("quorum intersection check interrupted")

    orig = qi.QuorumIntersectionChecker.network_enjoys_quorum_intersection
    qi.QuorumIntersectionChecker.network_enjoys_quorum_intersection = \
        hang_until_interrupted
    try:
        assert h.start_quorum_intersection_check() is True
        # a second request while one is in flight is refused, not queued
        assert h.start_quorum_intersection_check() is False
        lcl = app.ledger_manager.last_closed_ledger_num()
        for _ in range(3):
            app.manual_close()   # closes proceed while the worker "runs"
        assert app.ledger_manager.last_closed_ledger_num() == lcl + 3
        assert h.quorum_check_recalculating is True
        h.interrupt_quorum_intersection()
        deadline = time.monotonic() + 30.0
        while h.quorum_check_recalculating and \
                time.monotonic() < deadline:
            app.clock.crank(False)
            time.sleep(0.001)
        assert h.quorum_check_recalculating is False
        assert h.last_quorum_intersection.get("interrupted") is True
    finally:
        qi.QuorumIntersectionChecker.\
            network_enjoys_quorum_intersection = orig
        app.stop()


def test_interrupt_reaches_criticality_scan_inner_checkers():
    """The criticality scan builds a throwaway checker per candidate
    group; the outer checker's interrupt flag must reach them (reference
    threads ONE shared flag through the whole reanalysis), otherwise a
    shutdown-time interrupt lands between groups and the worker burns on."""
    from stellar_core_tpu.herder.quorum_intersection import (
        intersection_critical_groups,
    )
    ks = keys(5)
    q = qs(4, ks)                  # symmetric 4-of-5: candidates exist
    qmap = qmap_of(ks, [q] * 5)
    outer = QuorumIntersectionChecker(qmap)
    outer.interrupted = True       # set BEFORE the scan starts
    with pytest.raises(InterruptedError):
        intersection_critical_groups(qmap, parent=outer)
