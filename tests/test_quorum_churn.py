"""Quorum churn: consensus survives runtime quorum-set reconfiguration and
validator loss (BASELINE.md measurement config "multi-node simulation
under quorum churn"; reference analog: HerderTests' qset updates +
Simulation node removal)."""

from stellar_core_tpu.simulation import topologies
from stellar_core_tpu.xdr import SCPQuorumSet


def _lcl(node):
    return node.app.ledger_manager.last_closed_ledger_num()


def _hash_at(node, seq):
    db = node.app.database
    if db is None:
        return None
    row = db.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (seq,)).fetchone()
    return row[0] if row else None


def test_quorum_reconfig_and_validator_loss():
    sim = topologies.core(4, 3)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(3), 30000)

    names = list(sim.nodes)
    dropped = names[-1]
    rest = names[:-1]

    # runtime churn: surviving nodes adopt a 2-of-3 qset without `dropped`
    new_qset = SCPQuorumSet(
        threshold=2,
        validators=[sim.nodes[n].app.config.NODE_SEED.public_key
                    for n in rest],
        innerSets=[])
    for n in rest:
        sim.nodes[n].app.config.QUORUM_SET = new_qset

    # the dropped validator goes dark: the net drops every message to or
    # from it (a crash fault, not a byzantine one)
    orig_deliver = sim._deliver

    def deliver(to, frm, raw):
        if to != dropped and frm != dropped:
            orig_deliver(to, frm, raw)

    sim._deliver = deliver

    target = max(_lcl(sim.nodes[n]) for n in rest) + 3
    assert sim.crank_until(
        lambda: all(_lcl(sim.nodes[n]) >= target for n in rest), 60000), \
        {n: _lcl(sim.nodes[n]) for n in rest}

    # chain agreement at a common height among survivors
    common = min(_lcl(sim.nodes[n]) for n in rest)
    hashes = {sim.nodes[n].app.ledger_manager.lcl_header.previousLedgerHash
              if _lcl(sim.nodes[n]) == common else None for n in rest}
    hashes.discard(None)
    assert len(hashes) <= 1
    sim.stop_all_nodes()


def test_quorum_threshold_raise_still_live():
    """Raising the threshold to n-of-n mid-run keeps the net live while
    all validators stay up."""
    sim = topologies.core(3, 2)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(2), 30000)
    full = SCPQuorumSet(
        threshold=3,
        validators=[sim.nodes[n].app.config.NODE_SEED.public_key
                    for n in sim.nodes],
        innerSets=[])
    for n in sim.nodes:
        sim.nodes[n].app.config.QUORUM_SET = full
    target = max(_lcl(v) for v in sim.nodes.values()) + 3
    assert sim.crank_until(
        lambda: all(_lcl(v) >= target for v in sim.nodes.values()),
        60000), {n: _lcl(v) for n, v in sim.nodes.items()}
    sim.stop_all_nodes()
