"""Quorum churn: consensus survives runtime quorum-set reconfiguration and
validator loss (BASELINE.md measurement config "multi-node simulation
under quorum churn"; reference analog: HerderTests' qset updates +
Simulation node removal)."""

from stellar_core_tpu.simulation import topologies
from stellar_core_tpu.xdr import SCPQuorumSet


def _lcl(node):
    return node.app.ledger_manager.last_closed_ledger_num()


def _hash_at(node, seq):
    db = node.app.database
    if db is None:
        return None
    row = db.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (seq,)).fetchone()
    return row[0] if row else None


def test_quorum_reconfig_and_validator_loss():
    sim = topologies.core(4, 3)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(3), 30000)

    names = list(sim.nodes)
    dropped = names[-1]
    rest = names[:-1]

    # runtime churn: surviving nodes adopt a 2-of-3 qset without `dropped`
    new_qset = SCPQuorumSet(
        threshold=2,
        validators=[sim.nodes[n].app.config.NODE_SEED.public_key
                    for n in rest],
        innerSets=[])
    for n in rest:
        sim.nodes[n].app.config.QUORUM_SET = new_qset

    # the dropped validator goes dark: the net drops every message to or
    # from it (a crash fault, not a byzantine one)
    orig_deliver = sim._deliver

    def deliver(to, frm, raw):
        if to != dropped and frm != dropped:
            orig_deliver(to, frm, raw)

    sim._deliver = deliver

    target = max(_lcl(sim.nodes[n]) for n in rest) + 3
    assert sim.crank_until(
        lambda: all(_lcl(sim.nodes[n]) >= target for n in rest), 60000), \
        {n: _lcl(sim.nodes[n]) for n in rest}

    # chain agreement at a common height among survivors
    common = min(_lcl(sim.nodes[n]) for n in rest)
    hashes = {sim.nodes[n].app.ledger_manager.lcl_header.previousLedgerHash
              if _lcl(sim.nodes[n]) == common else None for n in rest}
    hashes.discard(None)
    assert len(hashes) <= 1
    sim.stop_all_nodes()


def test_quorum_threshold_raise_still_live():
    """Raising the threshold to n-of-n mid-run keeps the net live while
    all validators stay up."""
    sim = topologies.core(3, 2)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(2), 30000)
    full = SCPQuorumSet(
        threshold=3,
        validators=[sim.nodes[n].app.config.NODE_SEED.public_key
                    for n in sim.nodes],
        innerSets=[])
    for n in sim.nodes:
        sim.nodes[n].app.config.QUORUM_SET = full
    target = max(_lcl(v) for v in sim.nodes.values()) + 3
    assert sim.crank_until(
        lambda: all(_lcl(v) >= target for v in sim.nodes.values()),
        60000), {n: _lcl(v) for n, v in sim.nodes.items()}
    sim.stop_all_nodes()


def test_in_quorum_filtering():
    """Envelopes from validators OUTSIDE the core's transitive quorum are
    discarded by core nodes, while the outside validators (who DO track
    the core) still externalize (reference HerderTests.cpp:1735 'In
    quorum filtering')."""
    from stellar_core_tpu.crypto.hashing import sha256
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.simulation import topologies
    from stellar_core_tpu.xdr import SCPQuorumSet

    sim = topologies.core(4, 3)
    sim.start_all_nodes()
    assert sim.crank_until(lambda: sim.have_all_externalized(2), 60000)

    core_nodes = list(sim.nodes.values())
    core_ids = {n.app.config.node_id().key_bytes for n in core_nodes}
    core_qset = core_nodes[0].app.config.QUORUM_SET

    # extra validators E_i: they trust the core, the core ignores them
    extras = []
    for i in range(3):
        sk = SecretKey.from_seed(sha256(b"E_%d" % i))
        q = SCPQuorumSet(threshold=core_qset.threshold,
                         validators=list(core_qset.validators),
                         innerSets=[])
        node = sim.add_node(sk, q)
        node.app.start()
        sim.connect(node.name, core_nodes[0].name)
        extras.append(node)
    extra_ids = {e.app.config.node_id().key_bytes for e in extras}

    assert sim.crank_until(
        lambda: all(n.app.ledger_manager.last_closed_ledger_num() >= 4
                    for n in core_nodes), 200000)

    # core nodes' SCP state contains NO statements from the extras
    for n in core_nodes:
        for seq in (3, 4):
            slot = n.app.herder.scp.get_slot(seq, False)
            if slot is None:
                continue
            for env in slot.get_current_state():
                assert env.statement.nodeID.key_bytes not in extra_ids, \
                    "core node recorded an out-of-quorum statement"

    # ...but the extras DO hear the core (the core is in their quorum)
    # and track its externalized slots, even though they cannot close
    # without an archive to catch up from
    for e in extras:
        assert (e.app.herder.tracking_slot or 0) >= 3
    sim.stop_all_nodes()
