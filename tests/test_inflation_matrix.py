"""Inflation distribution scenarios, ported from the reference's
InflationTests.cpp (:285-560 'inflation scenarios'): vote tallies across
many accounts, the 0.05% winner threshold, share math against an
in-test oracle, and feePool/totalCoins conservation. All at protocol 11
(the last protocol with inflation; the 12+ retirement is pinned in
test_restart_continuity)."""

import pytest

from stellar_core_tpu.testing import TestAccount, TestLedger, root_secret_key
from stellar_core_tpu.transactions.operations import (
    InflationOpFrame, InflationResultCode,
)
from stellar_core_tpu.xdr import OperationBody, OperationType

RATE = InflationOpFrame.INFLATION_RATE_TRILLIONTHS
WIN_MIN = InflationOpFrame.INFLATION_WIN_MIN_PERCENT


def setup_net(vote_balances):
    """Voters with given balances, each voting for its own dest account.
    Returns (ledger, runner, [dest accounts], [voter accounts])."""
    led = TestLedger()
    led.header().ledgerVersion = 11
    root = TestAccount(led, root_secret_key())
    led.header().scpValue.closeTime = \
        InflationOpFrame.INFLATION_FREQUENCY + 1
    voters, dests = [], []
    for bal in vote_balances:
        v = root.create(bal)
        d = root.create(10**9)
        assert led.apply_frame(v.tx([v.op_set_options(
            inflation_dest=d.account_id)]))
        voters.append(v)
        dests.append(d)
    runner = root.create(10**9)
    return led, runner, dests, voters


def run_inflation(led, acct):
    f = acct.tx([acct.op(OperationBody(OperationType.INFLATION, None))])
    ok = led.apply_frame(f)
    return ok, f


def oracle(led, winner_votes):
    """Expected (per-winner payouts, minted) — the reference payout rule:
    share = floor(amountToDole * votes / totalCoins), amountToDole =
    minted + feePool. The pool already includes the runner's own 100
    stroop fee when the op applies (fees are charged first), and the
    leftover stays pooled."""
    total = led.header().totalCoins
    minted = total * RATE // 10**12
    dole = minted + led.header().feePool + 100
    return [dole * v // total for v in winner_votes], minted


def test_two_guys_over_threshold():
    total0 = TestLedger().header().totalCoins
    threshold = total0 * WIN_MIN // 10**12
    # voter balances set BEFORE fees: two clear the threshold, one misses
    led, runner, dests, voters = setup_net(
        [threshold + 10**9, 2 * threshold, threshold // 2])
    # votes = voter balances at run time (fees already subtracted)
    votes = [led.balance(v.account_id) for v in voters]
    assert votes[0] >= threshold and votes[1] >= threshold
    assert votes[2] < threshold
    before = [led.balance(d.account_id) for d in dests]
    want, minted = oracle(led, votes[:2])
    total_before = led.header().totalCoins
    ok, f = run_inflation(led, runner)
    assert ok, f.result
    paid = [led.balance(d.account_id) - b for d, b in zip(dests, before)]
    assert paid[:2] == want
    assert paid[2] == 0
    assert led.header().totalCoins == total_before + minted
    payouts = f.result.op_results[0].value.value.value
    assert sorted(p.amount for p in payouts) == sorted(want)


def test_no_one_over_min():
    total0 = TestLedger().header().totalCoins
    threshold = total0 * WIN_MIN // 10**12
    led, runner, dests, _ = setup_net([threshold // 3, threshold // 4])
    before = [led.balance(d.account_id) for d in dests]
    total_before = led.header().totalCoins
    pool_before = led.header().feePool
    ok, f = run_inflation(led, runner)
    assert ok
    assert f.result.op_results[0].value.value.value == []
    assert [led.balance(d.account_id) for d in dests] == before
    minted = led.header().totalCoins - total_before
    assert minted == total_before * RATE // 10**12
    # everything (old pool + mint) stays pooled, plus the runner's fee
    assert led.header().feePool == pool_before + minted + 100


def test_all_votes_to_one_destination():
    total0 = TestLedger().header().totalCoins
    threshold = total0 * WIN_MIN // 10**12
    led = TestLedger()
    led.header().ledgerVersion = 11
    root = TestAccount(led, root_secret_key())
    led.header().scpValue.closeTime = \
        InflationOpFrame.INFLATION_FREQUENCY + 1
    dest = root.create(10**9)
    voters = [root.create(threshold) for _ in range(3)]
    for v in voters:
        assert led.apply_frame(v.tx([v.op_set_options(
            inflation_dest=dest.account_id)]))
    runner = root.create(10**9)
    votes = sum(led.balance(v.account_id) for v in voters)
    (want,), minted = oracle(led, [votes])
    before = led.balance(dest.account_id)
    ok, f = run_inflation(led, runner)
    assert ok, f.result
    assert led.balance(dest.account_id) - before == want
    payouts = f.result.op_results[0].value.value.value
    assert len(payouts) == 1 and payouts[0].amount == want


def test_fifty_fifty_split():
    total0 = TestLedger().header().totalCoins
    bal = total0 // 100          # each holds 1% — far over threshold
    led, runner, dests, voters = setup_net([bal, bal])
    votes = [led.balance(v.account_id) for v in voters]
    want, minted = oracle(led, votes)
    before = [led.balance(d.account_id) for d in dests]
    pool_before = led.header().feePool
    total_before = led.header().totalCoins
    ok, f = run_inflation(led, runner)
    assert ok, f.result
    paid = [led.balance(d.account_id) - b for d, b in zip(dests, before)]
    assert paid == want
    # conservation: leftover of the dole (incl. the runner's fee,
    # swept into the pool before the op ran) stays pooled
    dole = minted + pool_before + 100
    assert led.header().feePool == dole - sum(want)
    assert led.header().totalCoins == total_before + minted
