"""History publish + catchup tests.

Role parity: reference `src/history/test/HistoryTests.cpp:38-1035`
(CatchupSimulation: publish to a tmpdir file archive, generate ledgers,
catch a second app up from it) and `src/catchup/test/CatchupWorkTests.cpp`
(range arithmetic).
"""

import os

import pytest

from stellar_core_tpu.catchup import (CatchupConfiguration,
                                      calculate_catchup_range)
from stellar_core_tpu.history.archive import HistoryArchive
from stellar_core_tpu.history.checkpoints import (checkpoint_containing,
                                                  checkpoints_in_range,
                                                  first_in_checkpoint,
                                                  is_last_in_checkpoint)
from stellar_core_tpu.ledger.ledger_manager import (LedgerCloseData,
                                                    LedgerManagerState)
from stellar_core_tpu.main.application import Application
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.testing import AppLedgerAdapter
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.work.basic_work import State
from stellar_core_tpu.xdr import LedgerHeader, TransactionEnvelope

FREQ = 8  # small checkpoints so tests stay fast


# ---------------------------------------------------------------- arithmetic

def test_checkpoint_arithmetic():
    assert checkpoint_containing(1, 64) == 63
    assert checkpoint_containing(63, 64) == 63
    assert checkpoint_containing(64, 64) == 127
    assert is_last_in_checkpoint(63, 64)
    assert not is_last_in_checkpoint(64, 64)
    assert first_in_checkpoint(63, 64) == 1
    assert first_in_checkpoint(127, 64) == 64
    assert list(checkpoints_in_range(1, 130, 64)) == [63, 127, 191]


def test_catchup_range_complete():
    r = calculate_catchup_range(1, CatchupConfiguration(100, 2**32 - 1), 64)
    assert not r.apply_buckets
    assert (r.replay_first, r.replay_last) == (2, 100)


def test_catchup_range_minimal():
    r = calculate_catchup_range(1, CatchupConfiguration(127, 0), 64)
    assert r.apply_buckets and r.apply_buckets_at == 127
    assert r.replay_count() == 0
    # mid-checkpoint target: buckets at the checkpoint below
    r = calculate_catchup_range(1, CatchupConfiguration(100, 0), 64)
    assert r.apply_buckets and r.apply_buckets_at == 63
    assert (r.replay_first, r.replay_last) == (64, 100)


def test_catchup_range_recent():
    r = calculate_catchup_range(1, CatchupConfiguration(127, 10), 64)
    assert r.apply_buckets and r.apply_buckets_at == 63
    assert (r.replay_first, r.replay_last) == (64, 127)
    # count covers the whole gap -> pure replay
    r = calculate_catchup_range(120, CatchupConfiguration(127, 10), 64)
    assert not r.apply_buckets
    assert (r.replay_first, r.replay_last) == (121, 127)


# ---------------------------------------------------------------- fixtures

def make_app(tmp_path, n, archive_root, writable=True, protocol=None):
    cfg = Config.test_config(n)
    if protocol is not None:
        cfg.LEDGER_PROTOCOL_VERSION = protocol
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.CHECKPOINT_FREQUENCY = FREQ
    arch = HistoryArchive.local_dir("test", str(archive_root))
    d = {"get": arch.get_tmpl, "mkdir": arch.mkdir_tmpl}
    if writable:
        d["put"] = arch.put_tmpl
    cfg.HISTORY = {"test": d}
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application(clock, cfg)
    app.enable_buckets(str(tmp_path / ("buckets-%d" % n)))
    app.start()
    return app


def close_ledgers_with_traffic(app, upto):
    """Manual-close ledgers with a payment in most of them."""
    adapter = AppLedgerAdapter(app)
    root = adapter.root_account()
    alice = root.create(10**10)
    while app.ledger_manager.last_closed_ledger_num() < upto:
        f = alice.tx([alice.op_payment(root.account_id, 1000)])
        app.submit_transaction(f)
        app.manual_close()
    return alice


def run_work(app, work, max_cranks=200000):
    for _ in range(max_cranks):
        if work.is_done():
            break
        app.crank(False)
    assert work.is_done(), "work did not finish"
    return work.state


@pytest.fixture
def publisher(tmp_path):
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root, exist_ok=True)
    app = make_app(tmp_path, 0, archive_root)
    close_ledgers_with_traffic(app, 2 * FREQ + 3)   # past two checkpoints
    # let queued publishes run
    app.crank_until(lambda: app.history_manager.publish_queue() == [],
                    max_cranks=5000)
    assert app.history_manager.published_checkpoints >= 2
    return app, tmp_path, archive_root


# ---------------------------------------------------------------- publish

def test_publish_layout(publisher):
    app, tmp_path, archive_root = publisher
    c1 = FREQ - 1
    assert (archive_root / ".well-known" /
            "stellar-history.json").exists()
    h = "%08x" % c1
    sub = h[0:2] + "/" + h[2:4] + "/" + h[4:6]
    for cat in ("ledger", "transactions", "results", "scp"):
        assert (archive_root / cat / h[0:2] / h[2:4] / h[4:6] /
                ("%s-%s.xdr.gz" % (cat, h))).exists(), cat
    # HAS names real bucket files
    from stellar_core_tpu.history.archive_state import HistoryArchiveState
    has = HistoryArchiveState.from_json(
        (archive_root / ".well-known" / "stellar-history.json").read_text())
    assert has.current_ledger == 2 * FREQ - 1
    for hh in has.bucket_hashes():
        assert (archive_root / "bucket" / hh[0:2] / hh[2:4] / hh[4:6] /
                ("bucket-%s.xdr.gz" % hh)).exists()


# ---------------------------------------------------------------- catchup

def test_catchup_complete(publisher):
    app_a, tmp_path, archive_root = publisher
    app_b = make_app(tmp_path, 1, archive_root, writable=False)
    tip = 2 * FREQ - 1

    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert work is not None
    assert run_work(app_b, work) == State.SUCCESS

    lm_b = app_b.ledger_manager
    assert lm_b.last_closed_ledger_num() == tip
    # byte-identical chain
    row = app_a.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (tip,)).fetchone()
    assert lm_b.lcl_hash.hex() == row[0]
    assert lm_b.is_synced()


def test_catchup_minimal_buckets(publisher):
    app_a, tmp_path, archive_root = publisher
    app_b = make_app(tmp_path, 2, archive_root, writable=False)
    tip = 2 * FREQ - 1

    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.minimal())
    assert run_work(app_b, work) == State.SUCCESS

    lm_b = app_b.ledger_manager
    assert lm_b.last_closed_ledger_num() == tip
    row = app_a.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (tip,)).fetchone()
    assert lm_b.lcl_hash.hex() == row[0]
    # bucket list restored bit-for-bit
    assert app_b.bucket_manager.get_hash() == \
        app_a.ledger_manager.lcl_header.bucketListHash or \
        app_b.bucket_manager.get_hash() == \
        lm_b.lcl_header.bucketListHash
    # state usable: root balance matches A's at that ledger
    root = app_b.network_root_key().public_key
    assert AppLedgerAdapter(app_b).balance(root) > 0


def make_lcd_from_db(app_src, seq):
    """Rebuild the LedgerCloseData node A externalized for `seq`."""
    from stellar_core_tpu.herder.txset import TxSetFrame
    from stellar_core_tpu.transactions.transaction_frame import \
        TransactionFrame
    db = app_src.database
    hrow = db.execute(
        "SELECT data FROM ledgerheaders WHERE ledgerseq = ?",
        (seq,)).fetchone()
    header = LedgerHeader.from_xdr(hrow[0])
    frames = [
        TransactionFrame.make_from_wire(
            app_src.config.network_id, TransactionEnvelope.from_xdr(r[0]))
        for r in db.execute(
            "SELECT txbody FROM txhistory WHERE ledgerseq = ? "
            "ORDER BY txindex", (seq,)).fetchall()]
    ts = TxSetFrame(app_src.config.network_id,
                    header.previousLedgerHash, frames)
    return LedgerCloseData(seq, ts, header.scpValue)


def test_online_catchup_with_buffered_ledgers(publisher):
    """A node that falls behind buffers live ledgers, heals from the
    archive, then drains the buffer (reference CatchupManagerImpl)."""
    app_a, tmp_path, archive_root = publisher
    top = app_a.ledger_manager.last_closed_ledger_num()   # 2*FREQ+3
    tip = 2 * FREQ - 1                                    # archive tip

    app_b = make_app(tmp_path, 3, archive_root, writable=False)
    cm = app_b.catchup_manager
    lm_b = app_b.ledger_manager

    # live stream arrives with a gap: first seq far ahead of genesis
    for seq in range(tip + 1, top + 1):
        lm_b.value_externalized(make_lcd_from_db(app_a, seq))
    assert lm_b.state == LedgerManagerState.LM_CATCHING_UP_STATE
    assert cm.buffered_count() == top - tip
    assert cm.catchup_running()

    app_b.crank_until(lambda: not cm.catchup_running(), max_cranks=200000)
    # catchup hit the archive tip, then the buffer drained to `top`
    assert lm_b.last_closed_ledger_num() == top
    assert lm_b.is_synced()
    row = app_a.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (top,)).fetchone()
    assert lm_b.lcl_hash.hex() == row[0]


def test_catchup_detects_corrupt_archive(publisher):
    """Flip a byte in a published ledger file: VerifyLedgerChainWork must
    fail the catchup (reference VerifyLedgerChainWork hash checks)."""
    app_a, tmp_path, archive_root = publisher
    import gzip
    c = "%08x" % (FREQ - 1)
    p = (archive_root / "ledger" / c[0:2] / c[2:4] / c[4:6] /
         ("ledger-%s.xdr.gz" % c))
    raw = bytearray(gzip.decompress(p.read_bytes()))
    raw[40] ^= 0xFF
    p.write_bytes(gzip.compress(bytes(raw)))

    app_b = make_app(tmp_path, 4, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_ledger_num() == 1


def test_trusted_anchor_rejects_wrong_chain(publisher):
    """A consensus-derived trusted hash that doesn't match the archive's
    chain must fail the catchup before any state is touched."""
    from stellar_core_tpu.catchup.catchup_work import CatchupWork
    app_a, tmp_path, archive_root = publisher
    tip = 2 * FREQ - 1
    app_b = make_app(tmp_path, 6, archive_root, writable=False)
    work = CatchupWork(app_b, CatchupConfiguration.complete(),
                       trusted_hash=(tip, b"\x13" * 32))
    app_b.work_scheduler.schedule_work(work)
    assert run_work(app_b, work) == State.FAILURE
    assert app_b.ledger_manager.last_closed_ledger_num() == 1

    # and the matching anchor passes
    row = app_a.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (tip,)).fetchone()
    app_c = make_app(tmp_path, 7, archive_root, writable=False)
    work = CatchupWork(app_c, CatchupConfiguration.complete(),
                       trusted_hash=(tip, bytes.fromhex(row[0])))
    app_c.work_scheduler.schedule_work(work)
    assert run_work(app_c, work) == State.SUCCESS
    assert app_c.ledger_manager.last_closed_ledger_num() == tip


def test_prewarm_batches_checkpoint_sigs(publisher):
    """Catchup replay drains whole-checkpoint signature batches through
    the verifier (SURVEY.md §3.4 TPU batch site)."""
    app_a, tmp_path, archive_root = publisher

    from stellar_core_tpu.crypto.batch_verifier import CpuSigVerifier

    class CountingVerifier(CpuSigVerifier):
        def __init__(self):
            self.batches = []
            self.distinct = set()

        def prewarm_many(self, triples):
            self.batches.append(len(triples))
            self.distinct.update(triples)
            return super().prewarm_many(triples)

    app_b = make_app(tmp_path, 5, archive_root, writable=False)
    cv = CountingVerifier()
    app_b.sig_verifier = cv
    # the CPU-backend + native-apply combination skips the bulk
    # checkpoint drain entirely (the engine resolves signer sets in C
    # per tx, and batching buys nothing on a synchronous backend —
    # DownloadApplyTxsWork._prewarm_redundant); pin the Python apply
    # path, the consumer the whole-checkpoint prewarm exists to feed
    app_b.ledger_manager.use_native_apply = False

    # the prewarm must cache under the exact (key, sig, contents-hash)
    # the apply-time SignatureChecker looks up: after the per-checkpoint
    # prewarm dispatch, NO further raw verifies happen (regression: a
    # wrong message in the triples made every sig verify twice and, under
    # the TPU backend, dispatched a tiny device batch per tx)
    from stellar_core_tpu.crypto import keys as _keys
    _keys.flush_verify_cache()
    raw_calls = [0]
    orig_raw = _keys.raw_verify
    orig_batch = _keys.raw_verify_batch
    _keys.raw_verify = lambda k, s, m: (
        raw_calls.__setitem__(0, raw_calls[0] + 1) or orig_raw(k, s, m))

    def counting_batch(triples):
        # CpuSigVerifier.verify_many drains misses through ONE native
        # batch call now; count each triple like a raw verify
        raw_calls[0] += len(triples)
        return orig_batch(triples)

    _keys.raw_verify_batch = counting_batch
    try:
        work = app_b.catchup_manager.start_catchup(
            CatchupConfiguration.complete())
        assert run_work(app_b, work) == State.SUCCESS
    finally:
        _keys.raw_verify = orig_raw
        _keys.raw_verify_batch = orig_batch
    # one bulk batch per checkpoint covering many ledgers' signatures,
    # plus per-ledger incremental prewarms that are cache-covered no-ops
    assert len(cv.batches) >= 2
    assert max(cv.batches) > 1
    # every DISTINCT signature triple raw-verifies exactly once — the
    # apply path and the incremental prewarms all hit the cache
    assert raw_calls[0] == len(cv.distinct)


@pytest.mark.min_version(13)
def test_replay_history_containing_fee_bump(publisher):
    """A fee-bump envelope in published history replays byte-exactly
    (checkpoint prewarm collects outer fee-source + inner signatures)."""
    from stellar_core_tpu.transactions.transaction_frame import (
        FeeBumpTransactionFrame,
    )
    from stellar_core_tpu.xdr import (
        EnvelopeType, FeeBumpTransaction, FeeBumpTransactionEnvelope,
        TransactionEnvelope, _Ext,
    )
    from stellar_core_tpu.xdr.transaction import _InnerTxEnvelope

    app_a, tmp_path, archive_root = publisher
    ad = AppLedgerAdapter(app_a)
    root = ad.root_account()
    payer = root.create(10**9)
    sponsor = root.create(10**9)
    inner = payer.tx([payer.op_payment(root.account_id, 77)], fee=100)
    fb = FeeBumpTransaction(
        feeSource=sponsor.muxed, fee=1000,
        innerTx=_InnerTxEnvelope(EnvelopeType.ENVELOPE_TYPE_TX,
                                 inner.envelope.value),
        ext=_Ext.v0())
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
        FeeBumpTransactionEnvelope(tx=fb, signatures=[]))
    frame = FeeBumpTransactionFrame(app_a.config.network_id, env)
    frame.add_signature(sponsor.sk)
    assert app_a.submit_transaction(frame) == 0
    app_a.manual_close()
    # run to the next checkpoint boundary and publish it
    while (app_a.ledger_manager.last_closed_ledger_num() + 1) % FREQ:
        app_a.manual_close()
    app_a.crank_until(lambda: app_a.history_manager.publish_queue() == [],
                      max_cranks=5000)

    app_b = make_app(tmp_path, 9, archive_root, writable=False)
    work = app_b.catchup_manager.start_catchup(
        CatchupConfiguration.complete())
    assert run_work(app_b, work) == State.SUCCESS
    lm_b = app_b.ledger_manager
    assert lm_b.lcl_hash.hex() == app_a.database.execute(
        "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq = ?",
        (lm_b.last_closed_ledger_num(),)).fetchone()[0]
    assert AppLedgerAdapter(app_b).balance(payer.account_id) == \
        ad.balance(payer.account_id)


def test_bucket_apply_resumes_pre12_shadowed_merges(tmp_path):
    """r5 regression: a bucket-apply catchup at protocol < 12 must resume
    the publisher's in-flight SHADOWED merges exactly — the HAS now
    serializes each level's next merge (output hash, or input+shadow
    hashes while in flight), and assume_state reconstructs it. Before the
    fix, restart_merges re-kicked pre-12 merges shadowless, the replayer's
    bucketListHash forked on its first own close, and the buffered drain
    rejected every later ledger ("txset based on wrong ledger")."""
    archive_root = tmp_path / "archive"
    os.makedirs(archive_root, exist_ok=True)
    app_a = make_app(tmp_path, 0, archive_root, protocol=9)
    close_ledgers_with_traffic(app_a, 2 * FREQ + 3)
    app_a.crank_until(lambda: app_a.history_manager.publish_queue() == [],
                      max_cranks=5000)
    assert app_a.ledger_manager.lcl_header.ledgerVersion == 9

    app_b = make_app(tmp_path, 3, archive_root, writable=False, protocol=9)
    top = app_a.ledger_manager.last_closed_ledger_num()
    tip = 2 * FREQ - 1
    for seq in range(tip + 1, top + 1):
        app_b.ledger_manager.value_externalized(make_lcd_from_db(app_a, seq))
    app_b.crank_until(
        lambda: not app_b.catchup_manager.catchup_running(),
        max_cranks=200000)
    assert app_b.ledger_manager.last_closed_ledger_num() == top
    assert app_b.ledger_manager.lcl_hash == app_a.ledger_manager.lcl_hash
