"""Perf-regression ledger tests (ISSUE 6): schema validation of every
committed bench artifact, deterministic ingest into bench/history.jsonl,
the direction-aware comparator, and the `bench.py --compare` gate driven
end to end with a tiny deterministic CPU replay leg against synthetic
baselines (the acceptance criterion: nonzero on an injected regression,
zero on a clean run).
"""

import copy
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import bench_compare as bc          # noqa: E402

HISTORY = os.path.join(REPO, "bench", "history.jsonl")


# ------------------------------------------------------------ committed set

def test_committed_artifacts_pass_schema_check():
    """tools/bench_compare.py --check over every committed BENCH_*.json,
    MULTICHIP_*.json and bench/history.jsonl: malformed bench artifacts
    must fail fast instead of silently dropping out of the trajectory."""
    paths = bc.default_artifacts()
    assert len(paths) >= 11, paths          # 6 BENCH + 5 MULTICHIP
    errors = []
    for p in paths + [HISTORY]:
        errors.extend(bc.check_artifact(p))
    assert not errors, errors
    # the CLI agrees (the tier-1 invocation named in ISSUE 6)
    assert bc.main(["--check"]) == 0


def test_history_matches_fresh_reingest():
    """bench/history.jsonl is exactly what ingest produces from the
    committed artifacts — the committed ledger can never drift from its
    sources."""
    fresh = bc.ingest(bc.default_artifacts())
    committed = bc.load_history(HISTORY)
    assert fresh == committed


def test_history_covers_the_headline_metrics():
    best = bc.best_baselines(bc.load_history(HISTORY))
    # device verify headline (129k sigs/s, BENCH_r05 cached block)
    dev = best[("ed25519_verifies_per_sec_per_chip", "tpu")]
    assert dev["value"] > 100_000
    assert best[("replay_ledgers_per_sec", "tpu")]["value"] > 0
    assert best[("native_apply_speedup", "cpu")]["value"] > 4
    assert best[("multichip_devices", "axon")]["value"] >= 8
    # direction-aware best: the lowest committed warm-compile time wins
    warm = best[("device_compile_warm_s", "tpu")]
    assert warm["direction"] == "lower"


def test_malformed_artifacts_fail_check(tmp_path):
    bad_json = tmp_path / "BENCH_r99.json"
    bad_json.write_text("{not json")
    assert bc.check_artifact(str(bad_json))

    bad_payload = tmp_path / "BENCH_r98.json"
    bad_payload.write_text(json.dumps(
        {"metric": 5, "unit": "sigs/s", "value": "fast"}))
    errs = bc.check_artifact(str(bad_payload))
    assert any("'metric'" in e for e in errs)
    assert any("'value'" in e for e in errs)

    bad_multichip = tmp_path / "MULTICHIP_r99.json"
    bad_multichip.write_text(json.dumps({"n_devices": "eight", "rc": 0,
                                         "ok": True, "skipped": False}))
    assert any("n_devices" in e
               for e in bc.check_artifact(str(bad_multichip)))

    # rc=0 wrapper with no parsed payload is malformed; rc!=0 is a
    # valid record of a failed run
    wrapper = {"n": 1, "cmd": "x", "rc": 0, "tail": ""}
    w = tmp_path / "BENCH_r97.json"
    w.write_text(json.dumps(wrapper))
    assert bc.check_artifact(str(w))
    wrapper["rc"] = 124
    w.write_text(json.dumps(wrapper))
    assert not bc.check_artifact(str(w))

    bad_hist = tmp_path / "history.jsonl"
    bad_hist.write_text(json.dumps({"metric": "m", "unit": "u",
                                    "value": 1.0, "platform": "p",
                                    "direction": "sideways",
                                    "source": "s"}) + "\n{oops\n")
    errs = bc.check_artifact(str(bad_hist))
    assert any("direction" in e for e in errs)
    assert any("bad JSON" in e for e in errs)


# --------------------------------------------------- overlay_breakdown

def _good_overlay_breakdown():
    return {
        "recv_bytes": 1000, "send_bytes": 900,
        "recv_msgs": 10, "send_msgs": 9,
        "flood": {"unique": 10, "duplicates": 5,
                  "duplication_ratio": 0.5},
        "tx_latency_ms": {"count": 3, "p50": 100.0, "p95": 200.0},
        "stage_seconds": {"submit-to-queue": 0.1,
                          "queue-to-include": 0.2,
                          "include-to-externalize": 0.3,
                          "externalize-to-apply": 0.4},
        "total_seconds": 1.0,
        "outcomes": {"applied": 3},
    }


def test_overlay_breakdown_validates_and_normalizes():
    ob = _good_overlay_breakdown()
    assert bc.validate_overlay_breakdown(ob, "t") == []
    recs = bc.overlay_breakdown_records(ob, "scenario-flood", "src")
    by = {r["metric"]: r for r in recs}
    assert by["flood_duplication_ratio"]["value"] == 0.5
    assert by["flood_duplication_ratio"]["direction"] == "lower"
    assert by["tx_latency_total_p95_ms"]["value"] == 200.0
    assert by["tx_latency_total_p95_ms"]["direction"] == "lower"
    for r in recs:
        assert bc.validate_record(r, "t") == []


def test_overlay_breakdown_idle_run_emits_no_latency_records():
    """A 0-count run must never commit a 0-valued latency baseline (any
    later real latency would then gate as a regression forever)."""
    ob = _good_overlay_breakdown()
    ob["tx_latency_ms"] = {"count": 0, "p50": 0.0, "p95": 0.0}
    ob["flood"] = {"unique": 0, "duplicates": 0,
                   "duplication_ratio": 0.0}
    assert bc.validate_overlay_breakdown(ob, "t") == []
    assert bc.overlay_breakdown_records(ob, "p", "src") == []


def test_fleet_payload_overlay_breakdown_normalizes():
    """A `bench.py --fleet` payload carries its overlay_breakdown at
    the payload level (no embedded records list) — records_from_bench
    must derive the wire-cockpit records under the payload's stable
    platform key."""
    blob = {"metric": "fleet_slot_latency", "unit": "ms",
            "platform": "fleet-sim", "nodes": 3,
            "overlay_breakdown": _good_overlay_breakdown()}
    recs = bc.records_from_bench(blob, "BENCH_r99.json")
    by = {r["metric"]: r for r in recs}
    assert by["flood_duplication_ratio"]["platform"] == "fleet-sim"
    assert by["tx_latency_total_p95_ms"]["platform"] == "fleet-sim"
    assert all(r["direction"] == "lower" for r in recs)


# --------------------------------------------------- fleet_verify

def _good_fleet_verify():
    return {
        "1": {"devices": 1, "fleet_sigs_per_s": 480.0,
              "per_device_sigs_per_s": 480.0, "warm_restart_s": 2.5},
        "4": {"devices": 4, "fleet_sigs_per_s": 1000.0,
              "per_device_sigs_per_s": 250.0, "warm_restart_s": 3.1},
    }


def test_fleet_verify_validates_and_normalizes():
    fv = _good_fleet_verify()
    assert bc.validate_fleet_verify(fv, "t") == []
    recs = bc.fleet_verify_records(fv, "src")
    by = {(r["metric"], r["platform"]): r for r in recs}
    assert by[("fleet_sigs_per_s", "verify-fleet-cpu4")]["value"] == 1000.0
    assert by[("fleet_sigs_per_s", "verify-fleet-cpu4")]["direction"] == \
        "higher"
    assert by[("per_device_sigs_per_s", "verify-fleet-cpu1")]["value"] == \
        480.0
    assert by[("warm_restart_s", "verify-fleet-cpu4")]["direction"] == \
        "lower"
    assert len(recs) == 6
    for r in recs:
        assert bc.validate_record(r, "t") == []


def test_fleet_verify_schema_violations_fail_check():
    fv = _good_fleet_verify()
    fv["4"]["per_device_sigs_per_s"] = 900.0     # != fleet/devices
    errs = bc.validate_fleet_verify(fv, "t")
    assert any("inconsistent" in e for e in errs)
    fv = _good_fleet_verify()
    fv["4"]["devices"] = 2                       # key/devices mismatch
    assert any("matching its key" in e
               for e in bc.validate_fleet_verify(fv, "t"))
    fv = _good_fleet_verify()
    fv["1"]["warm_restart_s"] = -1
    assert any("warm_restart_s" in e
               for e in bc.validate_fleet_verify(fv, "t"))
    fv = _good_fleet_verify()
    fv["1"]["fleet_sigs_per_s"] = 0
    assert any("fleet_sigs_per_s" in e
               for e in bc.validate_fleet_verify(fv, "t"))


def test_fleet_verify_payload_normalizes_and_checks(tmp_path):
    """A `bench.py --fleet-verify` artifact (payload-level fleet_verify
    block + fleet_speedup) derives per-device-count records through
    records_from_bench, and check_artifact enforces the block schema."""
    import json
    blob = {"metric": "fleet_verify_sigs_per_s", "unit": "sigs/s",
            "value": 1000.0, "platform": "verify-fleet-cpu",
            "fleet_speedup": 2.08, "fleet_verify": _good_fleet_verify()}
    recs = bc.records_from_bench(blob, "BENCH_r99.json")
    by = {(r["metric"], r["platform"]): r for r in recs}
    assert ("fleet_sigs_per_s", "verify-fleet-cpu1") in by
    assert by[("fleet_verify_speedup", "verify-fleet-cpu")]["value"] == \
        2.08
    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps(blob))
    assert bc.check_artifact(str(p)) == []
    blob["fleet_verify"]["4"]["fleet_sigs_per_s"] = None
    p.write_text(json.dumps(blob))
    assert any("fleet_sigs_per_s" in e for e in bc.check_artifact(str(p)))


def test_overlay_breakdown_sum_contract_enforced(tmp_path):
    ob = _good_overlay_breakdown()
    ob["stage_seconds"]["queue-to-include"] = 5.0    # no longer sums
    errs = bc.validate_overlay_breakdown(ob, "t")
    assert any("no longer accounts" in e for e in errs)
    # ratio inconsistency is caught too
    ob2 = _good_overlay_breakdown()
    ob2["flood"]["duplication_ratio"] = 0.9
    assert any("inconsistent" in e
               for e in bc.validate_overlay_breakdown(ob2, "t"))
    # and the walk finds a breakdown nested inside a scenario artifact
    bad = tmp_path / "BENCH_r96.json"
    bad.write_text(json.dumps({"metric": "m", "unit": "u", "value": 1.0,
                               "scenarios": {"flood": {
                                   "overlay_breakdown": ob}}}))
    assert any("no longer accounts" in e
               for e in bc.check_artifact(str(bad)))


def _good_bucketdb():
    return {
        "small": {"accounts": 10**4, "close_ms_p50": 50.0,
                  "close_ms_mean": 52.0},
        "large": {"accounts": 10**6, "close_ms_p50": 55.0,
                  "close_ms_mean": 57.0},
        "latency_ratio": 1.1,
        "prefetch_hit_rate_pct": 99.5,
        "bloom_fp_pct": 1.2,
        "sql_point_lookups": 0,
    }


def test_bucketdb_block_normalizes_and_checks(tmp_path):
    """A `bench.py --bucketdb` artifact (ISSUE 14) derives the
    direction-aware flatness/hit-rate/FP records, and check_artifact
    enforces the block's own acceptance gates."""
    import json
    blob = {"metric": "bucketdb_latency_ratio", "unit": "x",
            "value": 1.1, "platform": "bucketdb-cpu",
            "bucketdb_bench": _good_bucketdb()}
    recs = bc.records_from_bench(blob, "BENCH_r98.json")
    by = {r["metric"]: r for r in recs}
    assert by["bucketdb_latency_ratio"]["direction"] == "lower"
    assert by["bucketdb_prefetch_hit_rate_pct"]["direction"] == "higher"
    assert by["bucketdb_bloom_fp_pct"]["direction"] == "lower"
    assert by["bucketdb_close_large_p50_ms"]["value"] == 55.0
    p = tmp_path / "BENCH_r98.json"
    p.write_text(json.dumps(blob))
    assert bc.check_artifact(str(p)) == []


def test_validate_bucketdb_enforces_the_gates():
    # ratio must match the legs AND stay under the 1.25x gate
    bd = _good_bucketdb()
    bd["latency_ratio"] = 0.5
    assert any("!= large/small" in e for e in bc.validate_bucketdb(bd, "t"))
    bd = _good_bucketdb()
    bd["large"]["close_ms_p50"] = 100.0
    bd["latency_ratio"] = 2.0
    assert any("1.25x" in e for e in bc.validate_bucketdb(bd, "t"))
    # the zero-SQL gate: a leaked point lookup fails the artifact
    bd = _good_bucketdb()
    bd["sql_point_lookups"] = 3
    assert any("sql_point_lookups" in e
               for e in bc.validate_bucketdb(bd, "t"))
    # prefetch hit-rate and bloom FP bands
    bd = _good_bucketdb()
    bd["prefetch_hit_rate_pct"] = 80.0
    assert any("prefetch_hit_rate_pct" in e
               for e in bc.validate_bucketdb(bd, "t"))
    bd = _good_bucketdb()
    bd["bloom_fp_pct"] = 9.0
    assert any("bloom_fp_pct" in e for e in bc.validate_bucketdb(bd, "t"))
    # scale ordering
    bd = _good_bucketdb()
    bd["large"]["accounts"] = 10**3
    assert any("must exceed" in e for e in bc.validate_bucketdb(bd, "t"))
    assert bc.validate_bucketdb(_good_bucketdb(), "t") == []


def test_committed_bucketdb_artifact_meets_its_gates():
    """The committed BENCH_r13 artifact must pass its own acceptance
    gates (validate_bucketdb runs in check over every committed
    artifact; this pins the r13 headline numbers directly)."""
    import json
    import os
    path = os.path.join(os.path.dirname(bc.__file__), os.pardir,
                        "BENCH_r13_bucketdb.json")
    blob = json.load(open(path))
    bd = blob["bucketdb_bench"]
    assert bc.validate_bucketdb(bd, "r13") == []
    assert bd["latency_ratio"] <= 1.25
    assert bd["prefetch_hit_rate_pct"] >= 95.0
    assert bd["sql_point_lookups"] == 0
    assert bd["large"]["accounts"] == 10**6


# ------------------------------------------------------------- ingress

def _good_ingress():
    return {
        "oversubscription": 6.9,
        "decided": 800, "admitted": 160, "throttled": 520, "shed": 120,
        "shed_ratio": 120 / 800,
        "priority": {"submitted": 48, "applied": 46,
                     "goodput": 46 / 48},
        "intake": {"depth": 3, "cap": 24},
        "sources": {"tracked": 512, "cap": 4096},
        "outcomes": {"applied": 50, "rejected": 10,
                     "shed": 120, "throttled": 520},
        "tx_latency_p95_ms": 4000.0, "unloaded_p95_ms": 6000.0,
        "p95_ratio": 4000.0 / 6000.0,
    }


def test_ingress_block_validates_and_normalizes():
    """An `overload` scenario ingress block (ISSUE 18) passes the
    schema gate and derives the four direction-aware records."""
    ib = _good_ingress()
    assert bc.validate_ingress(ib, "t") == []
    recs = bc.ingress_records(ib, "scenario-overload", "src")
    by = {r["metric"]: r for r in recs}
    assert by["ingress_priority_goodput"]["direction"] == "higher"
    assert by["ingress_priority_goodput"]["value"] == pytest.approx(46 / 48)
    assert by["ingress_shed_ratio"]["direction"] == "higher"
    assert by["ingress_tx_latency_p95_ms"]["direction"] == "lower"
    assert by["ingress_p95_vs_unloaded_ratio"]["direction"] == "lower"
    assert by["ingress_p95_vs_unloaded_ratio"]["value"] == \
        pytest.approx(2 / 3)
    for r in recs:
        assert bc.validate_record(r, "t") == []
    # an idle/empty block emits nothing (never commit a 0-baseline)
    assert bc.ingress_records({"decided": 0}, "p", "s") == []


def test_validate_ingress_enforces_the_gates():
    # decision counters must reconcile
    ib = _good_ingress()
    ib["admitted"] = 200
    assert any("admitted+throttled+shed" in e
               for e in bc.validate_ingress(ib, "t"))
    # shed_ratio must be shed/decided
    ib = _good_ingress()
    ib["shed_ratio"] = 0.5
    assert any("shed/decided" in e for e in bc.validate_ingress(ib, "t"))
    # goodput must be applied/submitted, applied <= submitted
    ib = _good_ingress()
    ib["priority"]["goodput"] = 0.1
    assert any("applied/submitted" in e
               for e in bc.validate_ingress(ib, "t"))
    ib = _good_ingress()
    ib["priority"]["applied"] = 99
    assert any("applied <= submitted" in e
               for e in bc.validate_ingress(ib, "t"))
    # p95 ratio must be its own numerator/denominator
    ib = _good_ingress()
    ib["p95_ratio"] = 3.0
    assert any("p95/unloaded" in e for e in bc.validate_ingress(ib, "t"))
    # the bounded-memory gate travels with the artifact
    ib = _good_ingress()
    ib["intake"]["depth"] = 100
    assert any("exceeds its cap" in e for e in bc.validate_ingress(ib, "t"))
    ib = _good_ingress()
    ib["sources"]["tracked"] = 10**6
    assert any("exceeds its cap" in e for e in bc.validate_ingress(ib, "t"))
    # the funnel can never report more sheds than the tier decided
    ib = _good_ingress()
    ib["outcomes"]["shed"] = 10**6
    assert any("exceeds the ingress" in e
               for e in bc.validate_ingress(ib, "t"))
    assert bc.validate_ingress(_good_ingress(), "t") == []


def test_check_artifact_walks_ingress_blocks(tmp_path):
    """`check` rejects a committed artifact whose ingress block violates
    the boundedness gate — the schema travels with the file."""
    blob = {"metric": "scenario_overload", "unit": "count", "value": 1.0,
            "platform": "scenario-overload", "ingress": _good_ingress()}
    p = tmp_path / "BENCH_r97.json"
    p.write_text(json.dumps(blob))
    assert bc.check_artifact(str(p)) == []
    blob["ingress"]["intake"]["depth"] = 999
    p.write_text(json.dumps(blob))
    assert any("exceeds its cap" in e for e in bc.check_artifact(str(p)))


# ------------------------------------------------------------ comparator

def _rec(metric, value, platform="p", direction="higher", **kw):
    return bc.make_record(metric, "u", value, platform, direction,
                          "test", **kw)


def test_compare_is_direction_aware():
    history = [_rec("rate", 100.0), _rec("rate", 80.0),
               _rec("lat", 10.0, direction="lower"),
               _rec("lat", 25.0, direction="lower")]
    # best = rate 100 (higher), lat 10 (lower)
    current = [_rec("rate", 95.0), _rec("lat", 10.5)]
    current[1]["direction"] = "lower"
    report = bc.compare(current, history, tolerance=0.1)
    assert not report["regressions"]
    assert len(report["ok"]) == 2

    report = bc.compare([_rec("rate", 89.0)], history, tolerance=0.1)
    assert len(report["regressions"]) == 1
    assert report["regressions"][0]["best"] == 100.0

    bad_lat = _rec("lat", 11.5, direction="lower")
    report = bc.compare([bad_lat], history, tolerance=0.1)
    assert len(report["regressions"]) == 1

    # a better-than-best run is an improvement, never a regression
    report = bc.compare([_rec("rate", 140.0)], history, tolerance=0.1)
    assert report["improvements"] and not report["regressions"]

    # unknown (metric, platform) pairs never gate
    report = bc.compare([_rec("rate", 1.0, platform="other")], history)
    assert report["new"] and not report["regressions"]


def test_compare_platform_keys_baselines_apart():
    history = [_rec("replay_ledgers_per_sec", 3.34, platform="tpu")]
    tiny = _rec("replay_ledgers_per_sec", 90.0, platform="cpu-tiny")
    report = bc.compare([tiny], history)
    assert report["new"] and not report["regressions"]


# --------------------------------------------- end-to-end gate (acceptance)

@pytest.fixture(scope="module")
def tiny_leg_records():
    """ONE tiny deterministic CPU replay leg, shared by the gate tests
    below (seeded content; seconds, not minutes)."""
    import bench
    return bench.compare_leg()


def test_tiny_leg_records_validate(tiny_leg_records):
    # 5 classic records + the close-cockpit apply records (ISSUE 9):
    # apply_wall_s, one apply_op_<type>_ms per op type seen, apply_other_ms
    assert len(tiny_leg_records) >= 8
    for rec in tiny_leg_records:
        assert not bc.validate_record(rec), rec
    assert {r["platform"] for r in tiny_leg_records} == \
        {"cpu-tiny", "openssl-cpu-tiny"}
    by_metric = {r["metric"]: r for r in tiny_leg_records}
    assert by_metric["replay_ledgers_per_sec"]["value"] > 0
    assert by_metric["replay_wall_s"]["direction"] == "lower"
    assert by_metric["apply_wall_s"]["direction"] == "lower"
    assert by_metric["apply_op_payment_ms"]["value"] > 0
    assert by_metric["apply_other_ms"]["platform"] == "cpu-tiny"


def _write_history(path, records):
    with open(path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")


def _synthetic_baseline(records, regress=False):
    """Baselines from the measured tiny-leg values: equal to current for
    a clean run; absurdly better than current (x100 / /100) to inject a
    synthetic regression no real container could beat."""
    base = copy.deepcopy(records)
    for rec in base:
        rec["source"] = "synthetic-baseline"
        if regress:
            rec["value"] = (rec["value"] * 100.0
                            if rec["direction"] == "higher"
                            else rec["value"] / 100.0)
    return base


def test_compare_gate_clean_and_regressed_inprocess(
        tiny_leg_records, tmp_path, capsys):
    import bench
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps({"records": tiny_leg_records}))

    n = len(tiny_leg_records)
    clean = tmp_path / "clean.jsonl"
    _write_history(str(clean), _synthetic_baseline(tiny_leg_records))
    rc = bench.compare_main(["--compare", "--input", str(cur),
                             "--history", str(clean)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    assert not report["regressions"]
    assert len(report["ok"]) + len(report["improvements"]) == n

    regressed = tmp_path / "regressed.jsonl"
    _write_history(str(regressed),
                   _synthetic_baseline(tiny_leg_records, regress=True))
    rc = bench.compare_main(["--compare", "--input", str(cur),
                             "--history", str(regressed)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    # every nonzero-valued record loses to its absurd synthetic best
    # (a zero-valued per-op total cannot regress against base 0)
    want = sum(1 for r in tiny_leg_records if r["value"] > 0)
    assert len(report["regressions"]) == want
    # every regression names the synthetic best it lost to
    assert all(r["best_source"] == "synthetic-baseline"
               for r in report["regressions"])


def test_compare_gate_record_appends_stamped_records(
        tiny_leg_records, tmp_path, capsys):
    import bench
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps({"records": tiny_leg_records}))
    hist = tmp_path / "history.jsonl"
    _write_history(str(hist), _synthetic_baseline(tiny_leg_records))
    rc = bench.compare_main(["--compare", "--record",
                             "--input", str(cur),
                             "--history", str(hist)])
    capsys.readouterr()
    assert rc == 0
    n = len(tiny_leg_records)
    recs = bc.load_history(str(hist))
    assert len(recs) == 2 * n
    appended = recs[n:]
    for rec in appended:
        assert not bc.validate_record(rec), rec
        assert rec["at_unix"] is not None
    # the recorded run is now the baseline the next run gates against
    best = bc.best_baselines(recs)
    assert best[("replay_ledgers_per_sec", "cpu-tiny")]["value"] == \
        next(r["value"] for r in tiny_leg_records
             if r["metric"] == "replay_ledgers_per_sec")


def test_compare_gate_cli_exit_codes(tiny_leg_records, tmp_path):
    """The real `bench.py --compare` CLI exits 0 on a clean run and
    nonzero on an injected synthetic regression (acceptance
    criterion), via actual subprocess exit codes."""
    cur = tmp_path / "current.json"
    cur.write_text(json.dumps({"records": tiny_leg_records}))
    clean = tmp_path / "clean.jsonl"
    _write_history(str(clean), _synthetic_baseline(tiny_leg_records))
    regressed = tmp_path / "regressed.jsonl"
    _write_history(str(regressed),
                   _synthetic_baseline(tiny_leg_records, regress=True))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for hist, want_rc in ((clean, 0), (regressed, 1)):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--compare",
             "--input", str(cur), "--history", str(hist)],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=240)
        assert proc.returncode == want_rc, \
            (hist, proc.returncode, proc.stdout[-500:],
             proc.stderr[-500:])
        report = json.loads(proc.stdout)
        assert ("regressions" in report and
                bool(report["regressions"]) == bool(want_rc))
