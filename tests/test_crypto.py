"""Crypto boundary tests.

Role parity: reference `src/crypto/test/CryptoTests.cpp:30-258` — hash
vectors, strkey round trips, sign/verify, verify cache behavior — plus the
batch-verifier semantics contract (CPU vs TPU-kernel equivalence).
"""

import hashlib
import os

import pytest

from stellar_core_tpu.crypto import strkey
from stellar_core_tpu.crypto.batch_verifier import (
    CpuSigVerifier, TpuSigVerifier, make_verifier,
)
from stellar_core_tpu.crypto.curve25519 import (
    curve25519_derive_public, curve25519_derive_shared,
    curve25519_random_secret,
)
from stellar_core_tpu.crypto.hashing import (
    SHA256, hkdf_expand, hkdf_extract, hmac_sha256, hmac_sha256_verify,
    sha256, siphash24,
)
from stellar_core_tpu.crypto.keys import (
    KeyUtils, PubKeyUtils, SecretKey, flush_verify_cache, raw_verify,
    verify_cache_stats,
)


def test_sha256_vectors():
    assert sha256(b"").hex() == (
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")
    assert sha256(b"abc").hex() == (
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad")
    assert SHA256().add(b"a").add(b"bc").finish() == sha256(b"abc")


def test_hmac_hkdf():
    # RFC 4231 test case 2
    mac = hmac_sha256(b"Jefe", b"what do ya want for nothing?")
    assert mac.hex() == ("5bdcc146bf60754e6a042426089575c7"
                         "5a003f089d2739839dec58b964ec3843")
    assert hmac_sha256_verify(b"Jefe", b"what do ya want for nothing?", mac)
    prk = hkdf_extract(bytes.fromhex("0b" * 22),
                       salt=bytes.fromhex("000102030405060708090a0b0c"))
    okm = hkdf_expand(prk, bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"), 42)
    assert okm.hex().startswith("3cb25f25faacd57a90434f64d0362f2a")


def test_siphash_reference_vector():
    # SipHash-2-4 official test vector: key 000102..0f, input 00 01 .. 3e
    key = bytes(range(16))
    msg = bytes(range(15))
    assert siphash24(key, msg) == 0xA129CA6149BE45E5


def test_strkey_roundtrip():
    raw = os.urandom(32)
    s = strkey.encode_public_key(raw)
    assert s[0] == "G"
    assert strkey.decode_public_key(s) == raw
    seed = strkey.encode_seed(raw)
    assert seed[0] == "S"
    assert strkey.decode_seed(seed) == raw
    with pytest.raises(ValueError):
        strkey.decode_public_key(seed)
    # checksum corruption
    bad = s[:-1] + ("A" if s[-1] != "A" else "B")
    with pytest.raises(Exception):
        strkey.decode_public_key(bad)


def test_sign_verify_and_cache():
    flush_verify_cache()
    sk = SecretKey.pseudo_random_for_testing()
    msg = b"hello consensus"
    sig = sk.sign(msg)
    assert PubKeyUtils.verify_sig(sk.public_key, sig, msg)
    st0 = verify_cache_stats()
    assert PubKeyUtils.verify_sig(sk.public_key, sig, msg)
    st1 = verify_cache_stats()
    assert st1["hits"] == st0["hits"] + 1
    assert not PubKeyUtils.verify_sig(sk.public_key, sig, msg + b"!")
    bad = bytearray(sig)
    bad[3] ^= 0xFF
    assert not PubKeyUtils.verify_sig(sk.public_key, bytes(bad), msg)


def test_secret_key_strkey_roundtrip():
    sk = SecretKey.pseudo_random_for_testing()
    sk2 = SecretKey.from_strkey_seed(sk.strkey_seed())
    assert sk2.public_key == sk.public_key
    assert KeyUtils.from_strkey(sk.strkey_public()) == sk.public_key


def test_decorated_signature_hint():
    sk = SecretKey.pseudo_random_for_testing()
    ds = sk.sign_decorated(b"m")
    assert ds.hint == sk.public_key.key_bytes[-4:]


def test_x25519_ecdh_agreement():
    a = curve25519_random_secret()
    b = curve25519_random_secret()
    pa, pb = curve25519_derive_public(a), curve25519_derive_public(b)
    k1 = curve25519_derive_shared(a, pb, pa, pb)
    k2 = curve25519_derive_shared(b, pa, pa, pb)
    assert k1 == k2 and len(k1) == 32


def test_cpu_batch_verifier():
    v = make_verifier("cpu")
    sk = SecretKey.pseudo_random_for_testing()
    f = v.enqueue(sk.public_key, sk.sign(b"x"), b"x")
    v.flush()
    assert f.result() is True
    trips = [(sk.public_key.key_bytes, sk.sign(b"m%d" % i, ), b"m%d" % i)
             for i in range(4)]
    trips.append((sk.public_key.key_bytes, b"\x00" * 64, b"nope"))
    assert v.verify_many(trips) == [True] * 4 + [False]


@pytest.mark.slow
def test_tpu_kernel_matches_cpu_semantics():
    """The contract: identical accept/reject decisions to OpenSSL, including
    corrupted sigs, wrong messages, non-canonical S, bad point encodings."""
    flush_verify_cache()
    v = TpuSigVerifier()
    v.BUCKETS = (32,)
    sks = [SecretKey.pseudo_random_for_testing() for _ in range(8)]
    pubs, sigs, msgs = [], [], []
    for i in range(24):
        sk = sks[i % 8]
        m = b"msg-%d" % i
        s = bytearray(sk.sign(m))
        if i % 5 == 1:
            s[i % 64] ^= 1 << (i % 8)      # corrupt sig
        if i % 7 == 2:
            m = m + b"-tampered"           # wrong msg
        if i == 9:
            s[32:] = (2**252 + 27742317777372353535851937790883648493
                      ).to_bytes(32, "little")  # S == L (non-canonical)
        pubs.append(sk.public_key.key_bytes)
        sigs.append(bytes(s))
        msgs.append(m)
    # add a bad pubkey encoding (y >= p)
    pubs.append(b"\xff" * 32)
    sigs.append(sks[0].sign(b"z"))
    msgs.append(b"z")
    want = [raw_verify(p, s, m) for p, s, m in zip(pubs, sigs, msgs)]
    got = v.verify_many(list(zip(pubs, sigs, msgs)))
    assert got == want
