"""Account-state helpers shared by operations.

Role parity: reference `src/transactions/TransactionUtils.{h,cpp}` (load*,
addBalance, getAvailableBalance, reserve math) and
`src/ledger/LedgerTxnHeader` utilities.
"""

from __future__ import annotations

from typing import Optional

from ..xdr import (
    AccountEntry, AccountFlags, Asset, LedgerEntry, LedgerEntryData,
    LedgerEntryType, LedgerHeader, LedgerKey, TrustLineEntry, TrustLineFlags,
    _Ext,
)

INT64_MAX = 2**63 - 1
MAX_SUBENTRIES = 1000


def first_ledger_seq_for_account(header: LedgerHeader) -> int:
    return header.ledgerSeq


def starting_sequence_number(header: LedgerHeader) -> int:
    """New accounts start at ledgerSeq << 32 (reference
    getStartingSequenceNumber)."""
    return header.ledgerSeq << 32


def base_reserve(header: LedgerHeader) -> int:
    return header.baseReserve


def min_balance(header: LedgerHeader, num_subentries: int) -> int:
    """(2 + numSubEntries) * baseReserve (reference getMinBalance for
    protocol >= 9)."""
    return (2 + num_subentries) * header.baseReserve


def load_account(ltx, account_id) -> Optional[LedgerEntry]:
    return ltx.load(LedgerKey.account(account_id))


def load_account_entry(ltx, account_id) -> Optional[AccountEntry]:
    e = load_account(ltx, account_id)
    return e.data.value if e is not None else None


def load_trustline(ltx, account_id, asset: Asset) -> Optional[LedgerEntry]:
    return ltx.load(LedgerKey.trustline(account_id, asset))


def account_available_balance(header: LedgerHeader,
                              acc: AccountEntry) -> int:
    return max(0, acc.balance - min_balance(header, acc.numSubEntries))


def add_balance(header: LedgerHeader, entry: LedgerEntry,
                delta: int) -> bool:
    """Adjust native balance respecting reserve floor and INT64 ceiling
    (reference addBalance, TransactionUtils.cpp)."""
    acc = entry.data.value
    new = acc.balance + delta
    if new < 0 or new > INT64_MAX:
        return False
    if delta < 0 and new < min_balance(header, acc.numSubEntries):
        return False
    acc.balance = new
    return True


def add_trust_balance(tl: TrustLineEntry, delta: int) -> bool:
    if not (tl.flags & TrustLineFlags.AUTHORIZED_FLAG):
        return False
    new = tl.balance + delta
    if new < 0 or new > tl.limit:
        return False
    tl.balance = new
    return True


def trustline_authorized(tl: TrustLineEntry) -> bool:
    return bool(tl.flags & TrustLineFlags.AUTHORIZED_FLAG)


def change_subentries(header: LedgerHeader, entry: LedgerEntry,
                      delta: int) -> bool:
    """Add/remove subentries, enforcing reserve on add (reference
    addNumEntries)."""
    acc = entry.data.value
    new_count = acc.numSubEntries + delta
    if new_count < 0 or new_count > MAX_SUBENTRIES:
        return False
    if delta > 0 and acc.balance < min_balance(header, new_count):
        return False
    acc.numSubEntries = new_count
    return True


def make_account_entry(account_id, balance: int, seq_num: int,
                       last_modified: int = 0) -> LedgerEntry:
    acc = AccountEntry(
        accountID=account_id, balance=balance, seqNum=seq_num,
        numSubEntries=0, inflationDest=None, flags=0, homeDomain="",
        thresholds=bytes([1, 0, 0, 0]), signers=[], ext=_Ext.v0())
    return LedgerEntry(
        lastModifiedLedgerSeq=last_modified,
        data=LedgerEntryData(LedgerEntryType.ACCOUNT, acc), ext=_Ext.v0())


class ThresholdLevel:
    LOW = 0
    MEDIUM = 1
    HIGH = 2


def account_threshold(acc: AccountEntry, level: int) -> int:
    return acc.thresholds[1 + level]


def account_master_weight(acc: AccountEntry) -> int:
    return acc.thresholds[0]


def is_auth_required(acc: AccountEntry) -> bool:
    return bool(acc.flags & AccountFlags.AUTH_REQUIRED_FLAG)


def is_immutable_auth(acc: AccountEntry) -> bool:
    return bool(acc.flags & AccountFlags.AUTH_IMMUTABLE_FLAG)
