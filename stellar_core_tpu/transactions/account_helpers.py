"""Account-state helpers shared by operations.

Role parity: reference `src/transactions/TransactionUtils.{h,cpp}` (load*,
addBalance, getAvailableBalance, reserve math) and
`src/ledger/LedgerTxnHeader` utilities.
"""

from __future__ import annotations

from typing import Optional

from ..xdr import (
    AccountEntry, AccountEntryExt, AccountEntryExtensionV1, AccountFlags,
    Asset, LedgerEntry, LedgerEntryData, LedgerEntryType, LedgerHeader,
    LedgerKey, Liabilities, TrustLineEntry, TrustLineEntryExt,
    TrustLineEntryExtensionV1, TrustLineFlags, _Ext,
)

INT64_MAX = 2**63 - 1
MAX_SUBENTRIES = 1000

# protocol version introducing liabilities (reference
# src/transactions/TransactionUtils.cpp gating)
LIABILITIES_VERSION = 10


def first_ledger_seq_for_account(header: LedgerHeader) -> int:
    return header.ledgerSeq


def starting_sequence_number(header: LedgerHeader) -> int:
    """New accounts start at ledgerSeq << 32 (reference
    getStartingSequenceNumber)."""
    return header.ledgerSeq << 32


def base_reserve(header: LedgerHeader) -> int:
    return header.baseReserve


def min_balance(header: LedgerHeader, num_subentries: int) -> int:
    """(2 + numSubEntries) * baseReserve (reference getMinBalance for
    protocol >= 9)."""
    return (2 + num_subentries) * header.baseReserve


def load_account(ltx, account_id) -> Optional[LedgerEntry]:
    return ltx.load(LedgerKey.account(account_id))


def load_account_entry(ltx, account_id) -> Optional[AccountEntry]:
    e = load_account(ltx, account_id)
    return e.data.value if e is not None else None


def load_trustline(ltx, account_id, asset: Asset) -> Optional[LedgerEntry]:
    return ltx.load(LedgerKey.trustline(account_id, asset))


# -- liabilities (protocol >= 10; reference TransactionUtils.cpp:165-440) ---

def _raw_liabilities(dv) -> tuple:
    """(buying, selling) off an AccountEntry or TrustLineEntry."""
    if dv.ext.disc == 0:
        return (0, 0)
    li = dv.ext.value.liabilities
    return (li.buying, li.selling)


def _prepare_liabilities(dv) -> Liabilities:
    """Promote the entry extension to v1 and return its Liabilities."""
    if dv.ext.disc == 0:
        li = Liabilities(buying=0, selling=0)
        if isinstance(dv, AccountEntry):
            dv.ext = AccountEntryExt(1, AccountEntryExtensionV1(
                liabilities=li, ext=_Ext.v0()))
        else:
            dv.ext = TrustLineEntryExt(1, TrustLineEntryExtensionV1(
                liabilities=li, ext=_Ext.v0()))
    return dv.ext.value.liabilities


def get_buying_liabilities(header: LedgerHeader, entry: LedgerEntry) -> int:
    if header.ledgerVersion < LIABILITIES_VERSION:
        return 0
    return _raw_liabilities(entry.data.value)[0]


def get_selling_liabilities(header: LedgerHeader, entry: LedgerEntry) -> int:
    if header.ledgerVersion < LIABILITIES_VERSION:
        return 0
    return _raw_liabilities(entry.data.value)[1]


def add_buying_liabilities(header: LedgerHeader, entry: LedgerEntry,
                           delta: int) -> bool:
    """Reference addBuyingLiabilities (TransactionUtils.cpp:285): buying
    liabilities may not push balance past INT64_MAX (native) or the
    trustline limit."""
    if delta == 0:
        return True
    dv = entry.data.value
    buying, _selling = _raw_liabilities(dv)
    if entry.data.disc == LedgerEntryType.ACCOUNT:
        max_liab = INT64_MAX - dv.balance
    else:
        # maintain-or-more: liabilities on existing offers stay
        # adjustable (reference checkAuthorization in addBuyingLiabilities)
        if not trustline_authorized_to_maintain(dv):
            return False
        max_liab = dv.limit - dv.balance
    new = buying + delta
    if new < 0 or new > max_liab:
        return False
    _prepare_liabilities(dv).buying = new
    return True


def add_selling_liabilities(header: LedgerHeader, entry: LedgerEntry,
                            delta: int) -> bool:
    """Reference addSellingLiabilities (TransactionUtils.cpp:373): selling
    liabilities may not encumber the reserve (native) or exceed the
    trustline balance."""
    if delta == 0:
        return True
    dv = entry.data.value
    _buying, selling = _raw_liabilities(dv)
    if entry.data.disc == LedgerEntryType.ACCOUNT:
        max_liab = dv.balance - min_balance(header, dv.numSubEntries)
        if max_liab < 0:
            return False
    else:
        if not trustline_authorized_to_maintain(dv):
            return False
        max_liab = dv.balance
    new = selling + delta
    if new < 0 or new > max_liab:
        return False
    _prepare_liabilities(dv).selling = new
    return True


def account_available_balance(header: LedgerHeader,
                              acc: AccountEntry) -> int:
    """balance - reserve - selling liabilities (reference
    getAvailableBalance, TransactionUtils.cpp:440)."""
    avail = acc.balance - min_balance(header, acc.numSubEntries)
    if header.ledgerVersion >= LIABILITIES_VERSION:
        avail -= _raw_liabilities(acc)[1]
    return max(0, avail)


def trustline_available_balance(header: LedgerHeader,
                                tl: TrustLineEntry) -> int:
    avail = tl.balance
    if header.ledgerVersion >= LIABILITIES_VERSION:
        avail -= _raw_liabilities(tl)[1]
    return max(0, avail)


def max_amount_receive(header: LedgerHeader, entry: LedgerEntry) -> int:
    """Headroom below the ceiling minus buying liabilities (reference
    getMaxAmountReceive, TransactionUtils.cpp:509)."""
    dv = entry.data.value
    if entry.data.disc == LedgerEntryType.ACCOUNT:
        out = INT64_MAX
        if header.ledgerVersion >= LIABILITIES_VERSION:
            out -= dv.balance + _raw_liabilities(dv)[0]
        return out
    if not trustline_authorized_to_maintain(dv):
        # maintain-or-more, like every capacity primitive (reference
        # getMaxAmountReceive → checkAuthorization)
        return 0
    out = dv.limit - dv.balance
    if header.ledgerVersion >= LIABILITIES_VERSION:
        out -= _raw_liabilities(dv)[0]
    return out


def add_balance(header: LedgerHeader, entry: LedgerEntry,
                delta: int) -> bool:
    """Adjust native balance respecting reserve floor, INT64 ceiling, and
    liabilities (reference addBalance, TransactionUtils.cpp:220)."""
    acc = entry.data.value
    new = acc.balance + delta
    if new < 0 or new > INT64_MAX:
        return False
    if header.ledgerVersion >= LIABILITIES_VERSION:
        buying, selling = _raw_liabilities(acc)
        if delta < 0 and \
                new - min_balance(header, acc.numSubEntries) < selling:
            return False
        if new > INT64_MAX - buying:
            return False
    elif delta < 0 and new < min_balance(header, acc.numSubEntries):
        return False
    acc.balance = new
    return True


def add_trust_balance(header: LedgerHeader, entry: LedgerEntry,
                      delta: int) -> bool:
    """Adjust a trustline balance respecting limit, authorization, and
    liabilities (reference addBalance TRUSTLINE arm)."""
    tl = entry.data.value
    if delta == 0:
        return True
    # the balance PRIMITIVE accepts maintain-or-more so existing offers
    # can execute (reference checkAuthorization,
    # TransactionUtils.cpp:18-34); payments enforce FULL authorization
    # at the op level. Pre-13 lines can only carry the AUTHORIZED bit,
    # so this is version-safe.
    if not (tl.flags & TrustLineFlags.AUTH_LEVELS_MASK):
        return False
    new = tl.balance + delta
    if new < 0 or new > tl.limit:
        return False
    if header.ledgerVersion >= LIABILITIES_VERSION:
        buying, selling = _raw_liabilities(tl)
        if new < selling:
            return False
        if new > tl.limit - buying:
            return False
    tl.balance = new
    return True


def trustline_authorized(tl: TrustLineEntry) -> bool:
    return bool(tl.flags & TrustLineFlags.AUTHORIZED_FLAG)


def trustline_authorized_to_maintain(tl: TrustLineEntry) -> bool:
    """Either auth level: enough to keep/release/execute existing
    liabilities (reference isAuthorizedToMaintainLiabilities)."""
    return bool(tl.flags & TrustLineFlags.AUTH_LEVELS_MASK)


def change_subentries(header: LedgerHeader, entry: LedgerEntry,
                      delta: int) -> bool:
    """Add/remove subentries, enforcing reserve (incl. selling
    liabilities) on add (reference addNumEntries:333-369)."""
    acc = entry.data.value
    new_count = acc.numSubEntries + delta
    if new_count < 0 or new_count > MAX_SUBENTRIES:
        return False
    eff_min = min_balance(header, new_count)
    if header.ledgerVersion >= LIABILITIES_VERSION:
        eff_min += _raw_liabilities(acc)[1]
    if delta > 0 and acc.balance < eff_min:
        return False
    acc.numSubEntries = new_count
    return True


def make_account_entry(account_id, balance: int, seq_num: int,
                       last_modified: int = 0) -> LedgerEntry:
    acc = AccountEntry(
        accountID=account_id, balance=balance, seqNum=seq_num,
        numSubEntries=0, inflationDest=None, flags=0, homeDomain="",
        thresholds=bytes([1, 0, 0, 0]), signers=[], ext=AccountEntryExt.v0())
    return LedgerEntry(
        lastModifiedLedgerSeq=last_modified,
        data=LedgerEntryData(LedgerEntryType.ACCOUNT, acc), ext=_Ext.v0())


class ThresholdLevel:
    LOW = 0
    MEDIUM = 1
    HIGH = 2


def account_threshold(acc: AccountEntry, level: int) -> int:
    return acc.thresholds[1 + level]


def account_master_weight(acc: AccountEntry) -> int:
    return acc.thresholds[0]


def is_auth_required(acc: AccountEntry) -> bool:
    return bool(acc.flags & AccountFlags.AUTH_REQUIRED_FLAG)


def is_immutable_auth(acc: AccountEntry) -> bool:
    return bool(acc.flags & AccountFlags.AUTH_IMMUTABLE_FLAG)
