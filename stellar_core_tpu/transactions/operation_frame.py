"""OperationFrame: per-operation validity + apply logic.

Role parity: reference `src/transactions/OperationFrame.{h,cpp}` — op-level
source account resolution, threshold-level signature check, doCheckValid
(ledger-independent) and doApply (against a LedgerTxn).
"""

from __future__ import annotations

from typing import Optional

from ..xdr import (
    LedgerKey, Operation, OperationResult, OperationResultCode, OperationType,
    PublicKey,
)
from .account_helpers import (
    ThresholdLevel, account_master_weight, account_threshold, load_account,
)
from .signature_checker import SignatureChecker


class OperationFrame:
    """Base class; subclasses implement do_check_valid/do_apply and may
    override threshold_level/needed_signers."""

    op_type: int = -1

    def __init__(self, op: Operation, parent_tx) -> None:
        self.op = op
        self.tx = parent_tx
        self.result: Optional[OperationResult] = None

    # -- source account -----------------------------------------------------
    def source_account_id(self) -> PublicKey:
        if self.op.sourceAccount is not None:
            return self.op.sourceAccount.account_id
        return self.tx.source_account_id()

    # -- signature / threshold ----------------------------------------------
    def threshold_level(self) -> int:
        return ThresholdLevel.MEDIUM

    def check_signature(self, ltx, checker: SignatureChecker) -> bool:
        """Resolve the op source account and check its signers at the op's
        threshold level; ops on missing accounts need the raw key signature
        (reference OperationFrame::checkSignature)."""
        acc_id = self.source_account_id()
        entry = ltx.load_without_record(LedgerKey.account(acc_id))
        if entry is not None:
            acc = entry.data.value
            needed = account_threshold(acc, self.threshold_level())
            signers = list(acc.signers)
            mw = account_master_weight(acc)
            if mw > 0:
                from ..xdr import Signer, SignerKey
                signers.append(Signer(key=SignerKey.ed25519(acc_id.key_bytes),
                                      weight=mw))
            return checker.check_signature(signers, needed)
        # account does not exist: a valid signature from exactly that key
        from ..xdr import Signer, SignerKey
        return checker.check_signature(
            [Signer(key=SignerKey.ed25519(acc_id.key_bytes), weight=1)], 0)

    # -- validity / apply ---------------------------------------------------
    def set_code(self, code: int) -> bool:
        self.result = OperationResult(code, None)
        return False

    def set_inner(self, inner_code: int, payload=None) -> bool:
        """Record an inner (op-type-specific) result; success iff code 0."""
        from ..xdr import OperationInner
        arm_cls = OperationInner.xdr_arms[self.op_type][1]
        self.result = OperationResult.inner(
            self.op_type, arm_cls(inner_code, payload))
        return inner_code == 0

    def check_valid(self, ltx) -> bool:
        """Ledger-independent checks (amounts, codes). `ltx` gives header
        access for version gating only."""
        header = ltx.get_header()
        if not self.is_version_supported(header.ledgerVersion):
            # reference OperationFrame::checkValid → opNOT_SUPPORTED
            return self.set_code(OperationResultCode.opNOT_SUPPORTED)
        return self.do_check_valid(header)

    def apply(self, ltx) -> bool:
        # version gate holds at apply too: replayed history can reach
        # apply without this process having run checkValid
        if not self.is_version_supported(ltx.get_header().ledgerVersion):
            return self.set_code(OperationResultCode.opNOT_SUPPORTED)
        # the op source must exist AT APPLY (reference OperationFrame::
        # checkValid forApply arm, v8+): an earlier op in the same tx may
        # have merged it away — that fails THIS op, not the process
        if ltx.load_without_record(
                LedgerKey.account(self.source_account_id())) is None:
            return self.set_code(OperationResultCode.opNO_ACCOUNT)
        return self.do_apply(ltx)

    # subclass hooks
    def is_version_supported(self, ledger_version: int) -> bool:
        """Ops retired by protocol upgrades override this (reference
        OperationFrame::isVersionSupported)."""
        return True

    def do_check_valid(self, header) -> bool:
        raise NotImplementedError

    def do_apply(self, ltx) -> bool:
        raise NotImplementedError


_REGISTRY: dict[int, type] = {}


def register_op(cls):
    _REGISTRY[cls.op_type] = cls
    return cls


def make_operation_frame(op: Operation, parent_tx) -> OperationFrame:
    t = op.body.disc
    cls = _REGISTRY.get(t)
    if cls is None:
        raise ValueError("unsupported operation type %d" % t)
    return cls(op, parent_tx)
