"""TransactionFrame: validity checking, fee/sequence processing, apply.

Role parity: reference `src/transactions/TransactionFrame.cpp`:
- checkValid (:594-629) / commonValid (:443-502): time bounds, seq number,
  fee floor, source existence, low-threshold signature check, fee balance.
- processFeeSeqNum (:505): charge fee into the fee pool, consume seq num.
- apply (:778-835): SignatureChecker over the contents hash, processSignatures
  (op-level sig checks up front), then per-op nested LedgerTxn apply with
  all-or-nothing rollback.
Plus FeeBumpTransactionFrame (reference FeeBumpTransactionFrame.cpp).

The SignatureChecker receives the injected BatchSigVerifier: under the TPU
backend every checkValid/apply becomes a batched device call site
(SURVEY.md hot callers #2/#3).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..crypto.hashing import sha256
from ..crypto.batch_verifier import BatchSigVerifier, CpuSigVerifier
from ..xdr import (
    EnvelopeType, FeeBumpTransactionEnvelope, LedgerKey, OperationResult,
    OperationResultCode, PublicKey, Transaction, TransactionEnvelope,
    TransactionResult, TransactionResultCode, TransactionResultPair,
    TransactionSignaturePayload, TransactionV1Envelope, _Ext,
)
from ..xdr.transaction import _TaggedTransaction, _TxResultResult
from .account_helpers import (
    ThresholdLevel, account_available_balance, account_threshold,
    account_master_weight, load_account,
)
from ..ledger.ledgertxn import delta_to_changes
from .operation_frame import make_operation_frame
from .signature_checker import SignatureChecker
from . import operations as _ops  # noqa: F401  (populates the op registry)
from . import offers as _offers   # noqa: F401


def _signer_keys_of(ltx, acc_id: bytes,
                    cache: Optional[dict] = None) -> frozenset:
    """Ed25519 signer-key set of one account: master key + account
    signers (reference SignatureChecker scans the same set). `cache`
    memoizes per account so batch collection over many frames loads and
    parses each account entry once."""
    if cache is not None:
        got = cache.get(acc_id)
        if got is not None:
            return got
    from ..xdr import SignerKeyType
    keys = {acc_id}  # master key; also the missing-account case
    entry = ltx.load_without_record(
        LedgerKey.account(PublicKey.ed25519(acc_id)))
    if entry is not None:
        for s in entry.data.value.signers:
            if s.key.disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519:
                keys.add(s.key.value)
    out = frozenset(keys)
    if cache is not None:
        cache[acc_id] = out
    return out


def collect_sig_triples(ltx, account_ids, signatures,
                        contents_hash: bytes,
                        signer_cache: Optional[dict] = None
                        ) -> List[Tuple[bytes, bytes, bytes]]:
    """Hint-matching (ed25519-key, signature, contents-hash) pairs against
    the signer sets (master key + account signers) of `account_ids`.
    Shared by the tx and fee-bump frames' candidate_sig_triples — the
    collection half of TxSetFrame's two-phase prewarm."""
    keys = set()
    for acc_id in account_ids:
        keys |= _signer_keys_of(ltx, acc_id, signer_cache)
    out = []
    for ds in signatures:
        for kb in keys:
            if ds.hint == kb[-4:]:
                out.append((kb, ds.signature, contents_hash))
    return out


def frames_sig_triples(ltx, frames) -> List[Tuple[bytes, bytes, bytes]]:
    """Deduped candidate triples for a BATCH of frames — the shared
    collection step of both prewarm sites (TxSetFrame.check_or_trim and
    catchup's whole-checkpoint drain). One signer-set resolution per
    distinct account across the whole batch."""
    seen: dict = {}
    signer_cache: dict = {}
    for f in frames:
        for t in f.candidate_sig_triples(ltx, signer_cache):
            seen[t] = None
    return list(seen)


def _make_result(fee_charged: int, code: int,
                 op_results: Optional[List[OperationResult]] = None
                 ) -> TransactionResult:
    if code in (TransactionResultCode.txSUCCESS,
                TransactionResultCode.txFAILED):
        rr = _TxResultResult(code, op_results or [])
    else:
        rr = _TxResultResult(code, None)
    return TransactionResult(feeCharged=fee_charged, result=rr,
                             ext=_Ext.v0())


# commonValid failure codes reached BEFORE the sequence-number stage: a tx
# failing with one of these at apply does NOT consume its seq num
# (reference ValidationType kInvalid vs kInvalidUpdateSeqNum ladder,
# TransactionFrame.cpp:443-502)
_PRE_SEQ_FAILURES = frozenset((
    TransactionResultCode.txTOO_EARLY,
    TransactionResultCode.txTOO_LATE,
    TransactionResultCode.txMISSING_OPERATION,
    TransactionResultCode.txINSUFFICIENT_FEE,
    TransactionResultCode.txNO_ACCOUNT,
    TransactionResultCode.txBAD_SEQ,
))


class TransactionFrame:
    def __init__(self, network_id: bytes,
                 envelope: TransactionEnvelope) -> None:
        assert envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX
        self.network_id = network_id
        self.envelope = envelope
        self.tx: Transaction = envelope.value.tx
        self.signatures = envelope.value.signatures
        self.op_frames = [make_operation_frame(op, self)
                          for op in self.tx.operations]
        self._result: Optional[TransactionResult] = _make_result(
            0, TransactionResultCode.txSUCCESS,
            [None] * len(self.op_frames))
        self._native_result_b: Optional[bytes] = None
        self._contents_hash: Optional[bytes] = None
        self._env_bytes: Optional[bytes] = None
        self._full_hash: Optional[bytes] = None
        self._env_sig_fp: tuple = ()
        self._sig_frozen = False
        self.op_metas: List[list] = []     # per-op LedgerEntryChanges
        self._fee_meta: list = []          # fee/seq processing changes
        self.tx_changes: list = []         # apply-time seq/signer changes
        self._native_meta_b: Optional[bytes] = None  # TransactionMeta XDR
        self._native_fee_b: Optional[bytes] = None   # LedgerEntryChanges

    # -- identity -----------------------------------------------------------
    @classmethod
    def make_from_wire(cls, network_id: bytes, env: TransactionEnvelope):
        if env.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            return FeeBumpTransactionFrame(network_id, env)
        return cls(network_id, env)

    def source_account_id(self) -> PublicKey:
        return self.tx.sourceAccount.account_id

    def seq_account_id(self) -> PublicKey:
        """The account whose sequence number this envelope consumes —
        the queue/txset chain key (reference getSourceID; for fee bumps
        the INNER source, not the fee source)."""
        return self.source_account_id()

    def fee_account_id(self) -> PublicKey:
        """The account the fee is charged to (reference getFeeSourceID)."""
        return self.source_account_id()

    @property
    def seq_num(self) -> int:
        return self.tx.seqNum

    @property
    def fee_bid(self) -> int:
        return self.tx.fee

    def num_operations(self) -> int:
        return len(self.tx.operations)

    def signature_payload(self) -> bytes:
        p = TransactionSignaturePayload(
            networkId=self.network_id,
            taggedTransaction=_TaggedTransaction(
                EnvelopeType.ENVELOPE_TYPE_TX, self.tx))
        return p.to_xdr()

    def contents_hash(self) -> bytes:
        if self._contents_hash is None:
            self._contents_hash = sha256(self.signature_payload())
        return self._contents_hash

    def _sig_fingerprint(self) -> tuple:
        return tuple((ds.hint, ds.signature) for ds in self.signatures)

    def freeze_signatures(self) -> None:
        """Promise that this frame's signature list will never change
        (history-replay frames parsed from immutable wire): the
        envelope_bytes fingerprint re-check is skipped from now on. The
        fingerprint walk is ~20 tuple builds per call on the bench's
        multisig frames and replay serializes each frame several times
        per close."""
        self.envelope_bytes()   # prime the cache under the full check
        self._sig_frozen = True

    def envelope_bytes(self) -> bytes:
        """Canonical wire bytes of the signed envelope, cached —
        serialized once per frame for hashing, txset hashing, history
        rows, and flood messages. The cache is guarded by a fingerprint
        of the signature list (the one surface callers mutate directly,
        e.g. test harnesses and the fuzz corpus), so any signature change
        recomputes — unless freeze_signatures() declared the list
        immutable."""
        if self._sig_frozen and self._env_bytes is not None:
            return self._env_bytes
        fp = self._sig_fingerprint()
        if self._env_bytes is None or fp != self._env_sig_fp:
            self._env_bytes = self.envelope.to_xdr()
            self._full_hash = None
            self._env_sig_fp = fp
        return self._env_bytes

    def full_hash(self) -> bytes:
        """Hash of the whole signed envelope (identity in txsets)."""
        b = self.envelope_bytes()   # revalidates the signature fingerprint
        if self._full_hash is None:
            self._full_hash = sha256(b)
        return self._full_hash

    def invalidate_caches(self) -> None:
        """Drop every cached serialization/hash. Mutating any tx BODY
        field after first serialization (test/fuzz harnesses do this)
        must be followed by this call — the envelope_bytes fingerprint
        only tracks the signature list."""
        self._contents_hash = None
        self._env_bytes = None
        self._full_hash = None
        self._env_sig_fp = ()

    def add_signature(self, secret_key) -> None:
        """Sign the CONTENTS HASH (reference SignatureUtils::sign signs
        sha256(signature payload), not the raw payload)."""
        self._env_bytes = None
        self._full_hash = None
        self.signatures.append(
            secret_key.sign_decorated(self.contents_hash()))

    # -- batched signature collection ----------------------------------------
    def tx_meta(self):
        """TransactionMeta v1 for the last apply (reference txmeta column;
        downstream-consumer form — not part of any consensus hash)."""
        from ..xdr import OperationMeta, TransactionMeta, TransactionMetaV1
        if self._native_meta_b is not None:
            return TransactionMeta.from_xdr(self._native_meta_b)
        return TransactionMeta(1, TransactionMetaV1(
            txChanges=list(self.tx_changes),
            operations=[OperationMeta(changes=ch) for ch in self.op_metas]))

    def set_native_apply_output(self, result_b: bytes, fee_changes_b: bytes,
                                meta_b: bytes) -> None:
        """Install the native apply engine's per-tx outputs (all XDR
        bytes): the TransactionResult, the fee-phase LedgerEntryChanges,
        and the TransactionMeta. Downstream consumers (result_pair,
        fee_meta rows, tx_meta) then behave exactly as after a Python
        apply — both meta parses are deferred until someone reads the
        object form, and the history writers take the bytes directly
        (fee_meta_xdr / tx_meta_xdr / result_pair_xdr), so the hot
        replay path never parses them at all."""
        self._result = None     # parsed lazily from _native_result_b
        self._native_result_b = result_b
        self._fee_meta = None
        self._native_fee_b = fee_changes_b
        self._native_meta_b = meta_b

    @property
    def result(self) -> TransactionResult:
        if self._result is None and self._native_result_b is not None:
            self._result = TransactionResult.from_xdr(
                self._native_result_b)
        return self._result

    @result.setter
    def result(self, r: TransactionResult) -> None:
        self._result = r
        self._native_result_b = None

    def result_pair_xdr(self) -> bytes:
        """TransactionResultPair wire bytes (transactionHash ‖ result) —
        the native engine's result bytes verbatim when it applied this
        tx, so the close's result-set hash and the txhistory row never
        parse or re-serialize the result on the replay fast path."""
        rb = self._native_result_b
        if rb is None:
            rb = self.result.to_xdr()
        return self.contents_hash() + rb

    @property
    def fee_meta(self) -> list:
        if self._fee_meta is None and self._native_fee_b is not None:
            from ..xdr import LedgerEntryChanges
            from ..xdr.codec import xdr_from
            self._fee_meta = xdr_from(LedgerEntryChanges,
                                      self._native_fee_b)
        return self._fee_meta

    @fee_meta.setter
    def fee_meta(self, changes: list) -> None:
        self._fee_meta = changes
        self._native_fee_b = None

    def fee_meta_xdr(self) -> bytes:
        """LedgerEntryChanges wire bytes of the fee phase — the native
        engine's output verbatim when it applied this tx."""
        if self._native_fee_b is not None:
            return self._native_fee_b
        from ..xdr import LedgerEntryChanges
        from ..xdr.codec import xdr_bytes
        return xdr_bytes(LedgerEntryChanges, self._fee_meta)

    def tx_meta_xdr(self) -> bytes:
        """TransactionMeta wire bytes of the last apply."""
        if self._native_meta_b is not None:
            return self._native_meta_b
        return self.tx_meta().to_xdr()

    def candidate_sig_triples(self, ltx, signer_cache: Optional[dict] = None
                              ) -> List[Tuple[bytes, bytes, bytes]]:
        """Every (ed25519-key, signature, contents-hash) pair a
        SignatureChecker over this tx could end up verifying: hint-matching
        pairs against the signer sets (master key + account signers) of the
        tx source and every op source. Used by TxSetFrame.check_or_trim's
        two-phase prewarm — one device dispatch for the whole set, then the
        per-tx walk completes off the warm verify cache (reference hot
        caller #3, TxSetFrame.cpp:277-359, batched the TPU way)."""
        accs = {self.source_account_id().key_bytes}
        for f in self.op_frames:
            accs.add(f.source_account_id().key_bytes)
        return collect_sig_triples(ltx, accs, self.signatures,
                                   self.contents_hash(), signer_cache)

    # -- fees ---------------------------------------------------------------
    def min_fee(self, header) -> int:
        return header.baseFee * max(1, self.num_operations())

    def fee_charged(self, header, base_fee: Optional[int] = None) -> int:
        """Effective fee: bid capped by per-op base fee (protocol >= 11
        semantics: charge baseFee per op, never more than bid)."""
        eff_base = base_fee if base_fee is not None else header.baseFee
        return min(self.fee_bid, eff_base * max(1, self.num_operations()))

    # -- validity -----------------------------------------------------------
    def _common_valid(self, checker: SignatureChecker, ltx,
                      current_seq: int, applying: bool) -> int:
        header = ltx.load_header()
        tb = self.tx.timeBounds
        if tb is not None:
            close_time = header.scpValue.closeTime
            if tb.minTime and close_time < tb.minTime:
                return TransactionResultCode.txTOO_EARLY
            if tb.maxTime and close_time > tb.maxTime:
                return TransactionResultCode.txTOO_LATE
        if not self.tx.operations:
            return TransactionResultCode.txMISSING_OPERATION
        if self.fee_bid < self.min_fee(header):
            return TransactionResultCode.txINSUFFICIENT_FEE
        src = load_account(ltx, self.source_account_id())
        if src is None:
            return TransactionResultCode.txNO_ACCOUNT
        acc = src.data.value
        if not applying or header.ledgerVersion >= 10:
            # pre-10 the sequence number was consumed when taking fees, so
            # the apply-time check is skipped; from v10 it is consumed
            # during apply and re-checked here (reference commonValid
            # TransactionFrame.cpp:462-475, isBadSeq:438)
            seq = current_seq if current_seq != 0 else acc.seqNum
            if seq == 2**63 - 1 or self.tx.seqNum != seq + 1:
                return TransactionResultCode.txBAD_SEQ
        if not self._check_signature(checker, acc, ThresholdLevel.LOW):
            return TransactionResultCode.txBAD_AUTH
        # fee must come from the AVAILABLE balance (net of reserve and
        # selling liabilities; reference commonValid + getAvailableBalance)
        if not applying and account_available_balance(header, acc) < \
                self.fee_charged(header):
            return TransactionResultCode.txINSUFFICIENT_BALANCE
        return TransactionResultCode.txSUCCESS

    def _check_signature(self, checker: SignatureChecker, acc,
                         level: int) -> bool:
        from ..xdr import Signer, SignerKey
        signers = list(acc.signers)
        mw = account_master_weight(acc)
        if mw > 0:
            signers.append(Signer(
                key=SignerKey.ed25519(acc.accountID.key_bytes), weight=mw))
        return checker.check_signature(signers,
                                       account_threshold(acc, level))

    def check_valid(self, ltx_parent, current_seq: int = 0,
                    verifier: Optional[BatchSigVerifier] = None) -> bool:
        """Full validity check against (a temporary child of) ltx_parent.
        Never mutates state. Reference TransactionFrame::checkValid:594."""
        from ..ledger.ledgertxn import LedgerTxn
        verifier = verifier or CpuSigVerifier()
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verifier)
        ltx = LedgerTxn(ltx_parent)
        try:
            code = self._common_valid(checker, ltx, current_seq, False)
            if code != TransactionResultCode.txSUCCESS:
                self.result = _make_result(0, code)
                return False
            ok = True
            op_results = []
            for f in self.op_frames:
                # op-level signature check happens at checkValid time too
                # (reference OperationFrame::checkValid with !forApply)
                if not f.check_signature(ltx, checker):
                    f.set_code(OperationResultCode.opBAD_AUTH)
                    ok = False
                elif not f.check_valid(ltx):
                    ok = False
                op_results.append(f.result)
            if not ok:
                self.result = _make_result(
                    self.fee_charged(ltx.load_header()),
                    TransactionResultCode.txFAILED, op_results)
                return False
            if not checker.check_all_signatures_used():
                self.result = _make_result(
                    0, TransactionResultCode.txBAD_AUTH_EXTRA)
                return False
            self.result = _make_result(
                self.fee_charged(ltx.load_header()),
                TransactionResultCode.txSUCCESS, op_results)
            return True
        finally:
            ltx.rollback()

    # -- fee & seq processing ------------------------------------------------
    def process_fee_seq_num(self, ltx, base_fee: Optional[int]) -> None:
        """Charge the fee and consume the sequence number (reference
        processFeeSeqNum:505). Runs for every tx in the set before any
        apply."""
        header = ltx.load_header()
        fee = self.fee_charged(header, base_fee)
        src = load_account(ltx, self.source_account_id())
        assert src is not None, "fee processing on missing account"
        acc = src.data.value
        fee = min(fee, max(0, acc.balance))
        acc.balance -= fee
        if header.ledgerVersion <= 9:
            # older protocols consumed the sequence number when taking
            # fees; from v10 it is consumed during apply (reference
            # processFeeSeqNum:530-538 vs processSeqNum:369-379)
            acc.seqNum = self.tx.seqNum
        header.feePool += fee
        self.result = _make_result(fee, TransactionResultCode.txSUCCESS,
                                   [None] * len(self.op_frames))

    def _process_seq_num(self, ltx) -> None:
        """Consume the sequence number during apply, protocol >= 10
        (reference processSeqNum:369-379); runs even when the tx itself
        fails post-seq-stage validation."""
        header = ltx.load_header()
        if header.ledgerVersion < 10:
            return
        src = load_account(ltx, self.source_account_id())
        assert src is not None, "seq processing on missing account"
        acc = src.data.value
        if acc.seqNum > self.tx.seqNum:
            raise RuntimeError("unexpected account state in seq processing")
        acc.seqNum = self.tx.seqNum

    # -- apply --------------------------------------------------------------
    def _remove_one_time_signer(self, ltx) -> None:
        """Consume this tx's pre-auth-tx signer: remove it from the tx
        source and every op source account the first time the tx reaches
        signature processing at apply (reference
        removeOneTimeSignerFromAllSourceAccounts:543-566; no-op at v7)."""
        from ..xdr import SignerKey
        from .account_helpers import change_subentries
        header = ltx.load_header()
        if header.ledgerVersion == 7:
            return
        target = SignerKey.pre_auth_tx(self.contents_hash())
        accounts = {self.source_account_id().key_bytes:
                    self.source_account_id()}
        for f in self.op_frames:
            sid = f.source_account_id()
            accounts[sid.key_bytes] = sid
        for sid in accounts.values():
            entry = load_account(ltx, sid)
            if entry is None:
                continue    # source removed by an earlier merge
            acc = entry.data.value
            signers = list(acc.signers)
            idx = next((i for i, s in enumerate(signers)
                        if s.key == target), None)
            if idx is not None:
                signers.pop(idx)
                acc.signers = signers
                change_subentries(header, entry, -1)

    def process_signatures(self, checker: SignatureChecker, ltx) -> bool:
        """Protocol >= 10: check every op's signatures before applying any
        (reference processSignatures:384). Win or lose, the tx's
        pre-auth-tx signer is consumed (reference :420). Pre-10 this
        phase does nothing — op sigs check during each op's apply, and
        one-time signers are removed only after ALL ops succeed."""
        if ltx.load_header().ledgerVersion < 10:
            return True
        ok = True
        for f in self.op_frames:
            if not f.check_signature(ltx, checker):
                f.set_code(OperationResultCode.opBAD_AUTH)
                ok = False
        self._remove_one_time_signer(ltx)
        if ok and not checker.check_all_signatures_used():
            self.result = _make_result(
                self.result.feeCharged,
                TransactionResultCode.txBAD_AUTH_EXTRA)
            return False
        if not ok:
            self.result = _make_result(
                self.result.feeCharged, TransactionResultCode.txFAILED,
                [f.result for f in self.op_frames])
        return ok

    def apply(self, ltx_parent,
              verifier: Optional[BatchSigVerifier] = None,
              stats=None) -> bool:
        """Apply under a child txn of ltx_parent; on any op failure roll back
        every op's effects (fees/seqnums were already consumed).
        Reference apply:778-835 / applyOperations:676.

        `stats` (ledger/apply_stats.py ApplyStats) attributes each op's
        apply latency to its wire type — the close cockpit's Python-path
        per-op histograms."""
        from ..ledger.ledgertxn import LedgerTxn
        verifier = verifier or CpuSigVerifier()
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verifier)
        self._native_meta_b = None   # this apply owns the meta again
        fee = self.result.feeCharged
        # phase 1 — tx-level txn: apply-time commonValid re-check (state
        # may have changed since nomination) against the SAME checker as
        # the per-op checks, plus the v10+ sequence-number consumption.
        # This txn COMMITS into the close even when the tx (or later, an
        # op) fails — a failed tx still burns its seq num (reference
        # apply:778-835, ltxTx commit :806).
        ltx_tx = LedgerTxn(ltx_parent)
        try:
            code = self._common_valid(checker, ltx_tx, 0, True)
            if code not in _PRE_SEQ_FAILURES:
                # validation got past the seq-num stage (reference
                # cv >= kInvalidUpdateSeqNum → processSeqNum)
                self._process_seq_num(ltx_tx)
            if code == TransactionResultCode.txSUCCESS:
                sigs_ok = self.process_signatures(checker, ltx_tx)
            else:
                sigs_ok = False
                if ltx_tx.load_header().ledgerVersion >= 13:
                    # v13 fast-fail consumes the pre-auth signer for ANY
                    # invalid tx (reference processSignatures:396-400 has
                    # no pre-seq exclusion)
                    self._remove_one_time_signer(ltx_tx)
            self.tx_changes = delta_to_changes(ltx_tx.get_delta())
            ltx_tx.commit()
        except Exception:
            self.result = _make_result(
                fee, TransactionResultCode.txINTERNAL_ERROR)
            self.tx_changes = []
            if ltx_tx._open:
                ltx_tx.rollback()   # never leave the nested txn
                # registered: the NEXT frame's LedgerTxn(parent) asserts
            return False
        if code != TransactionResultCode.txSUCCESS:
            self.result = _make_result(fee, code)
            return False
        if not sigs_ok:
            # process_signatures set the result
            return False
        # phase 2 — apply every op (even after a failure) inside nested
        # txns; the ops-level txn rolls back wholesale if any failed —
        # reference applyOperations semantics — while the committed seq
        # consumption above survives, including on internal errors
        ops_ltx = LedgerTxn(ltx_parent)
        try:
            ok = True
            op_results = []
            op_metas = []
            # pre-10 each op re-resolves its signature set against the
            # CURRENT state at its own apply (reference OperationFrame::
            # apply → checkSignature pre-10): an earlier op removing a
            # signer or lowering a weight invalidates later ops. From 10
            # the set resolved once in process_signatures above.
            pre10 = ops_ltx.load_header().ledgerVersion < 10
            if stats is not None:
                from ..ledger.apply_stats import op_type_name
                from ..util.timer import real_perf_counter
            for f in self.op_frames:
                # per-op attribution (stats): the op's whole handling —
                # signature resolution (pre-10), apply, delta
                # serialization, nested-txn commit/rollback — charges to
                # its wire type, mirroring the native engine's table
                t_op = real_perf_counter() if stats is not None else 0.0
                op_ltx = LedgerTxn(ops_ltx)
                try:
                    if pre10 and not f.check_signature(op_ltx, checker):
                        f.set_code(OperationResultCode.opBAD_AUTH)
                        ok = False
                        op_metas.append([])
                        op_ltx.rollback()
                    elif f.apply(op_ltx):
                        op_metas.append(delta_to_changes(op_ltx.get_delta()))
                        op_ltx.commit()
                    else:
                        ok = False
                        op_metas.append([])
                        op_ltx.rollback()
                except Exception:
                    op_ltx.rollback()
                    raise
                if stats is not None:
                    stats.record_op(op_type_name(f.op.body.disc),
                                    seconds=real_perf_counter() - t_op,
                                    sample=True)
                op_results.append(f.result)
            self.op_metas = op_metas if ok else [[] for _ in op_results]
            if ok and ops_ltx.load_header().ledgerVersion < 10:
                # pre-10: signatures-used check + one-time signer removal
                # happen only after every op applied (reference
                # applyOperations:713-730, txChangesAfter)
                if not checker.check_all_signatures_used():
                    self.result = _make_result(
                        fee, TransactionResultCode.txBAD_AUTH_EXTRA)
                    ops_ltx.rollback()
                    return False
                self._remove_one_time_signer(ops_ltx)
            if ok:
                self.result = _make_result(
                    fee, TransactionResultCode.txSUCCESS, op_results)
                ops_ltx.commit()
            else:
                self.result = _make_result(
                    fee, TransactionResultCode.txFAILED, op_results)
                ops_ltx.rollback()
            return ok
        except Exception:
            self.result = _make_result(
                fee, TransactionResultCode.txINTERNAL_ERROR)
            if ops_ltx._open:
                ops_ltx.rollback()
            return False

    def result_pair(self) -> TransactionResultPair:
        return TransactionResultPair(transactionHash=self.contents_hash(),
                                     result=self.result)


class FeeBumpTransactionFrame:
    """Outer fee-bump envelope wrapping an inner v1 transaction
    (reference FeeBumpTransactionFrame.cpp)."""

    def __init__(self, network_id: bytes,
                 envelope: TransactionEnvelope) -> None:
        assert envelope.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP
        self.network_id = network_id
        self.envelope = envelope
        fb = envelope.value.tx
        self.fee_bump = fb
        self.signatures = envelope.value.signatures
        inner_env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX, fb.innerTx.value)
        self.inner = TransactionFrame(network_id, inner_env)
        self._native_result_b: Optional[bytes] = None
        self._native_fee_b: Optional[bytes] = None
        self._native_meta_b: Optional[bytes] = None
        self.result: TransactionResult = _make_result(
            0, TransactionResultCode.txFEE_BUMP_INNER_SUCCESS)
        self._contents_hash: Optional[bytes] = None
        self._env_bytes: Optional[bytes] = None
        self._full_hash: Optional[bytes] = None
        self._env_sig_fp: tuple = ()
        self._sig_frozen = False
        self.fee_meta: list = []

    def set_native_apply_output(self, result_b: bytes, fee_changes_b: bytes,
                                meta_b: bytes) -> None:
        """Install the native apply engine's per-tx outputs (all XDR
        bytes) — the fee-bump twin of TransactionFrame's installer. The
        result wraps the inner pair; the meta is the INNER tx's apply
        meta (tx_meta delegates to it on the Python path too)."""
        self._result = None
        self._native_result_b = result_b
        self._fee_meta = None
        self._native_fee_b = fee_changes_b
        self._native_meta_b = meta_b

    @property
    def result(self) -> TransactionResult:
        if self._result is None and self._native_result_b is not None:
            self._result = TransactionResult.from_xdr(
                self._native_result_b)
        return self._result

    @result.setter
    def result(self, r: TransactionResult) -> None:
        self._result = r
        self._native_result_b = None

    @property
    def fee_meta(self) -> list:
        if self._fee_meta is None and self._native_fee_b is not None:
            from ..xdr import LedgerEntryChanges
            from ..xdr.codec import xdr_from
            self._fee_meta = xdr_from(LedgerEntryChanges,
                                      self._native_fee_b)
        return self._fee_meta

    @fee_meta.setter
    def fee_meta(self, changes: list) -> None:
        self._fee_meta = changes
        self._native_fee_b = None

    @property
    def op_metas(self):
        return self.inner.op_metas

    def tx_meta(self):
        from ..xdr import TransactionMeta
        if self._native_meta_b is not None:
            return TransactionMeta.from_xdr(self._native_meta_b)
        return self.inner.tx_meta()

    def tx_meta_xdr(self) -> bytes:
        if self._native_meta_b is not None:
            return self._native_meta_b
        return self.inner.tx_meta_xdr()

    def fee_meta_xdr(self) -> bytes:
        if self._native_fee_b is not None:
            return self._native_fee_b
        from ..xdr import LedgerEntryChanges
        from ..xdr.codec import xdr_bytes
        return xdr_bytes(LedgerEntryChanges, self.fee_meta)

    def source_account_id(self) -> PublicKey:
        return self.fee_bump.feeSource.account_id

    def seq_account_id(self) -> PublicKey:
        """Chain key = the inner tx's source (whose seqNum is consumed),
        NOT the fee source (reference FeeBumpTransactionFrame::
        getSourceID returns the inner source)."""
        return self.inner.source_account_id()

    def fee_account_id(self) -> PublicKey:
        return self.fee_bump.feeSource.account_id

    @property
    def seq_num(self) -> int:
        return self.inner.seq_num

    @property
    def fee_bid(self) -> int:
        return self.fee_bump.fee

    def num_operations(self) -> int:
        return self.inner.num_operations() + 1

    def signature_payload(self) -> bytes:
        p = TransactionSignaturePayload(
            networkId=self.network_id,
            taggedTransaction=_TaggedTransaction(
                EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, self.fee_bump))
        return p.to_xdr()

    def contents_hash(self) -> bytes:
        if self._contents_hash is None:
            self._contents_hash = sha256(self.signature_payload())
        return self._contents_hash

    def _sig_fingerprint(self) -> tuple:
        return (tuple((ds.hint, ds.signature) for ds in self.signatures),
                self.inner._sig_fingerprint())

    def freeze_signatures(self) -> None:
        self.inner.freeze_signatures()
        self.envelope_bytes()   # prime under the full check
        self._sig_frozen = True

    def result_pair_xdr(self) -> bytes:
        rb = self._native_result_b
        if rb is None:
            rb = self.result.to_xdr()
        return self.contents_hash() + rb

    def envelope_bytes(self) -> bytes:
        if self._sig_frozen and self._env_bytes is not None:
            return self._env_bytes
        fp = self._sig_fingerprint()
        if self._env_bytes is None or fp != self._env_sig_fp:
            self._env_bytes = self.envelope.to_xdr()
            self._full_hash = None
            self._env_sig_fp = fp
        return self._env_bytes

    def full_hash(self) -> bytes:
        b = self.envelope_bytes()
        if self._full_hash is None:
            self._full_hash = sha256(b)
        return self._full_hash

    def add_signature(self, secret_key) -> None:
        self._env_bytes = None
        self._full_hash = None
        self.signatures.append(
            secret_key.sign_decorated(self.contents_hash()))

    def candidate_sig_triples(self, ltx, signer_cache: Optional[dict] = None
                              ) -> List[Tuple[bytes, bytes, bytes]]:
        """Fee-bump outer signatures (fee source signers) + the inner tx's
        triples; see TransactionFrame.candidate_sig_triples."""
        out = collect_sig_triples(
            ltx, {self.source_account_id().key_bytes}, self.signatures,
            self.contents_hash(), signer_cache)
        out.extend(self.inner.candidate_sig_triples(ltx, signer_cache))
        return out

    def min_fee(self, header) -> int:
        return header.baseFee * self.num_operations()

    def fee_charged(self, header, base_fee: Optional[int] = None) -> int:
        eff_base = base_fee if base_fee is not None else header.baseFee
        return min(self.fee_bid, eff_base * self.num_operations())

    def _inner_pair(self):
        from ..xdr import InnerTransactionResultPair
        return InnerTransactionResultPair(
            transactionHash=self.inner.contents_hash(),
            result=self.inner.result)

    def _common_valid(self, checker: SignatureChecker, ltx,
                      applying: bool) -> int:
        """Outer-envelope checks shared by check_valid and apply
        (reference FeeBumpTransactionFrame::commonValid): protocol gate,
        fee floors, fee-source existence, LOW-threshold auth,
        all-signatures-used, and (when not applying) the fee-source
        balance."""
        header = ltx.load_header()
        if header.ledgerVersion < 13:
            # fee bumps are CAP-0015, protocol 13 (reference commonValid
            # → txNOT_SUPPORTED below)
            return TransactionResultCode.txNOT_SUPPORTED
        if self.fee_bid < self.min_fee(header) or \
                self.fee_bid < self.inner.fee_bid:
            return TransactionResultCode.txINSUFFICIENT_FEE
        src = load_account(ltx, self.source_account_id())
        if src is None:
            return TransactionResultCode.txNO_ACCOUNT
        acc = src.data.value
        from ..xdr import Signer, SignerKey
        signers = list(acc.signers)
        mw = account_master_weight(acc)
        if mw > 0:
            signers.append(Signer(
                key=SignerKey.ed25519(acc.accountID.key_bytes),
                weight=mw))
        if not checker.check_signature(
                signers, account_threshold(acc, ThresholdLevel.LOW)):
            return TransactionResultCode.txBAD_AUTH
        if not checker.check_all_signatures_used():
            return TransactionResultCode.txBAD_AUTH_EXTRA
        if not applying and account_available_balance(header, acc) < \
                self.fee_charged(header):
            return TransactionResultCode.txINSUFFICIENT_BALANCE
        return TransactionResultCode.txSUCCESS

    def check_valid(self, ltx_parent, current_seq: int = 0,
                    verifier=None) -> bool:
        from ..ledger.ledgertxn import LedgerTxn
        verifier = verifier or CpuSigVerifier()
        ltx = LedgerTxn(ltx_parent)
        try:
            checker = SignatureChecker(self.contents_hash(),
                                       self.signatures, verifier)
            code = self._common_valid(checker, ltx, False)
            if code != TransactionResultCode.txSUCCESS:
                self.result = _make_result(0, code)
                return False
        finally:
            ltx.rollback()
        if not self.inner.check_valid(ltx_parent, current_seq, verifier):
            self.result = _make_result(
                0, TransactionResultCode.txFEE_BUMP_INNER_FAILED)
            self.result.result = _TxResultResult(
                TransactionResultCode.txFEE_BUMP_INNER_FAILED,
                self._inner_pair())
            return False
        self.result = TransactionResult(
            feeCharged=0,
            result=_TxResultResult(
                TransactionResultCode.txFEE_BUMP_INNER_SUCCESS,
                self._inner_pair()),
            ext=_Ext.v0())
        return True

    def process_fee_seq_num(self, ltx, base_fee: Optional[int]) -> None:
        header = ltx.load_header()
        fee = self.fee_charged(header, base_fee)
        src = load_account(ltx, self.source_account_id())
        assert src is not None
        acc = src.data.value
        fee = min(fee, max(0, acc.balance))
        acc.balance -= fee
        header.feePool += fee
        # the inner seq num is NOT consumed here: fee bumps exist only at
        # protocol >= 13, where sequence numbers are consumed during the
        # inner tx's apply (reference FeeBumpTransactionFrame
        # processFeeSeqNum:343-367 charges the fee source only)
        self.result = TransactionResult(
            feeCharged=fee,
            result=_TxResultResult(
                TransactionResultCode.txFEE_BUMP_INNER_SUCCESS,
                self._inner_pair()),
            ext=_Ext.v0())

    def apply(self, ltx_parent, verifier=None, stats=None) -> bool:
        # re-check the OUTER envelope at apply like the reference
        # (FeeBumpTransactionFrame::apply → commonValid + processSignatures
        # over the outer signatures): fee-source auth may have changed
        # since validation, and every outer signature must be used
        from ..ledger.ledgertxn import LedgerTxn
        checker = SignatureChecker(self.contents_hash(), self.signatures,
                                   verifier or CpuSigVerifier())
        self._native_meta_b = None   # this apply owns the meta again
        ltx = LedgerTxn(ltx_parent)
        try:
            code = self._common_valid(checker, ltx, True)
            if code != TransactionResultCode.txSUCCESS:
                self.result = _make_result(self.result.feeCharged, code)
                return False
        finally:
            ltx.rollback()
        self.inner.result = _make_result(
            0, TransactionResultCode.txSUCCESS,
            [None] * len(self.inner.op_frames))
        ok = self.inner.apply(ltx_parent, verifier, stats=stats)
        code = (TransactionResultCode.txFEE_BUMP_INNER_SUCCESS if ok
                else TransactionResultCode.txFEE_BUMP_INNER_FAILED)
        self.result = TransactionResult(
            feeCharged=self.result.feeCharged,
            result=_TxResultResult(code, self._inner_pair()),
            ext=_Ext.v0())
        return ok

    def result_pair(self) -> TransactionResultPair:
        return TransactionResultPair(transactionHash=self.contents_hash(),
                                     result=self.result)
