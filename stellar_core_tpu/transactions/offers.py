"""Offer and path-payment operation frames.

Role parity: reference `src/transactions/ManageOfferOpFrameBase.cpp`,
`ManageSellOfferOpFrame.cpp`, `ManageBuyOfferOpFrame.cpp`,
`CreatePassiveSellOfferOpFrame.cpp`, `PathPaymentStrictReceiveOpFrame.cpp`,
`PathPaymentStrictSendOpFrame.cpp` — all built on OfferExchange
(offer_exchange.py).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..xdr import (
    Asset, LedgerEntry, LedgerEntryData, LedgerEntryType, LedgerKey,
    ManageOfferSuccessResult, ManageOfferSuccessResultOffer, OfferEntry,
    OfferEntryFlags, OperationType, PathPaymentSuccess, Price,
    SimplePaymentResult, TrustLineFlags, _Ext,
)
from .account_helpers import (
    INT64_MAX, change_subentries, load_account, load_trustline,
)
from .offer_exchange import (
    CrossResult, _available_to_receive, _available_to_sell, _credit, _debit,
    acquire_liabilities, adjust_offer, cross_offers, offer_liabilities,
    release_liabilities,
)
from .operation_frame import OperationFrame, register_op
from .operations import _valid_asset


class ManageOfferResultCode:
    SUCCESS = 0
    MALFORMED = -1
    SELL_NO_TRUST = -2
    SELL_NOT_AUTHORIZED = -3
    BUY_NO_TRUST = -4
    BUY_NOT_AUTHORIZED = -5
    LINE_FULL = -6
    UNDERFUNDED = -7
    CROSS_SELF = -8
    SELL_NO_ISSUER = -9
    BUY_NO_ISSUER = -10
    NOT_FOUND = -11
    LOW_RESERVE = -12


class PathPaymentResultCode:
    SUCCESS = 0
    MALFORMED = -1
    UNDERFUNDED = -2
    SRC_NO_TRUST = -3
    SRC_NOT_AUTHORIZED = -4
    NO_DESTINATION = -5
    NO_TRUST = -6
    NOT_AUTHORIZED = -7
    LINE_FULL = -8
    NO_ISSUER = -9
    TOO_FEW_OFFERS = -10
    OFFER_CROSS_SELF = -11
    OVER_SENDMAX = -12       # strict receive
    UNDER_DESTMIN = -12      # strict send (same wire value, different arm)


def _offer_deleted() -> ManageOfferSuccessResultOffer:
    return ManageOfferSuccessResultOffer(2, None)


class _ManageOfferBase(OperationFrame):
    """Shared crossing + book-entry logic (reference
    ManageOfferOpFrameBase)."""

    passive = False
    # True for ManageBuyOffer: the crossing/residual caps are expressed
    # on the buying (wheat) side instead of the sell amount
    is_buy = False

    # subclass accessors -----------------------------------------------------
    def _params(self) -> Tuple[Asset, Asset, int, Price, int]:
        """(selling, buying, sell_amount, price(buying per selling),
        offer_id)"""
        raise NotImplementedError

    def _is_delete(self) -> bool:
        selling, buying, amount, price, offer_id = self._params()
        return amount == 0 and offer_id != 0

    def _wheat_receive_cap(self) -> int:
        """Cap on units of `buying` acquired while crossing AND promised
        by the residual. INT64_MAX for sell offers (the sell amount caps
        the other side); ManageBuyOffer overrides with buyAmount
        (reference applyOperationSpecificLimits,
        ManageBuyOfferOpFrame.cpp:69-76)."""
        return INT64_MAX

    def do_check_valid(self, header) -> bool:
        selling, buying, amount, price, offer_id = self._params()
        if not _valid_asset(selling) or not _valid_asset(buying) or \
                selling == buying:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        if price.n <= 0 or price.d <= 0 or amount < 0 or offer_id < 0:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        if amount == 0 and offer_id == 0:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        return self.set_inner(
            ManageOfferResultCode.SUCCESS,
            ManageOfferSuccessResult(offersClaimed=[],
                                     offer=_offer_deleted()))

    def _check_trust(self, ltx, src_id, selling: Asset,
                     buying: Asset, header) -> Optional[int]:
        """Posting/updating needs FULL authorization on both lines;
        maintain-liabilities is not enough (reference checkOfferValid,
        ManageOfferOpFrameBase.cpp:28-97; issuer-existence checks only
        pre-13)."""
        if not selling.is_native and src_id != selling.issuer:
            if header.ledgerVersion < 13 and ltx.load_without_record(
                    LedgerKey.account(selling.issuer)) is None:
                return ManageOfferResultCode.SELL_NO_ISSUER
            tl = ltx.load_without_record(
                LedgerKey.trustline(src_id, selling))
            if tl is None:
                return ManageOfferResultCode.SELL_NO_TRUST
            if not (tl.data.value.flags & TrustLineFlags.AUTHORIZED_FLAG):
                return ManageOfferResultCode.SELL_NOT_AUTHORIZED
        if not buying.is_native and src_id != buying.issuer:
            if header.ledgerVersion < 13 and ltx.load_without_record(
                    LedgerKey.account(buying.issuer)) is None:
                return ManageOfferResultCode.BUY_NO_ISSUER
            tl = ltx.load_without_record(
                LedgerKey.trustline(src_id, buying))
            if tl is None:
                return ManageOfferResultCode.BUY_NO_TRUST
            if not (tl.data.value.flags & TrustLineFlags.AUTHORIZED_FLAG):
                return ManageOfferResultCode.BUY_NOT_AUTHORIZED
        return None

    def do_apply(self, ltx) -> bool:
        """Reference ManageOfferOpFrameBase::doApply:200-460: release the
        old offer's liabilities, check the posted offer is fully backable,
        cross, clamp the residual to capacity, acquire liabilities."""
        selling, buying, amount, price, offer_id = self._params()
        src_id = self.source_account_id()
        header = ltx.load_header()

        if not self._is_delete():
            # deletes skip trust checks entirely (reference
            # checkOfferValid "don't bother loading trust lines")
            err = self._check_trust(ltx, src_id, selling, buying, header)
            if err is not None:
                return self.set_inner(err)

        existing_flags = 0
        is_update = False
        if offer_id != 0:
            key = LedgerKey.offer(src_id, offer_id)
            existing = ltx.load(key)
            if existing is None:
                return self.set_inner(ManageOfferResultCode.NOT_FOUND)
            # free the balance this offer encumbered before erasing it
            release_liabilities(ltx, existing.data.value)
            existing_flags = existing.data.value.flags
            ltx.erase(key)  # pulled from the book; subentry kept for now
            is_update = True

        if self._is_delete():
            src = load_account(ltx, src_id)
            change_subentries(header, src, -1)
            return self.set_inner(
                ManageOfferResultCode.SUCCESS,
                ManageOfferSuccessResult(offersClaimed=[],
                                         offer=_offer_deleted()))

        # the posted offer must be fully backable by the available limit
        # and balance (reference computeOfferExchangeParameters:161-186);
        # a NEW offer also consumes a subentry's reserve first
        if not is_update:
            src = load_account(ltx, src_id)
            if not change_subentries(header, src, +1):
                return self.set_inner(ManageOfferResultCode.LOW_RESERVE)
        buy_liab, sell_liab = offer_liabilities(price.n, price.d, amount)
        max_sell_funds = _available_to_sell(ltx, src_id, selling)
        recv_cap = _available_to_receive(ltx, src_id, buying)
        if recv_cap < buy_liab or recv_cap <= 0:
            return self.set_inner(ManageOfferResultCode.LINE_FULL)
        if max_sell_funds < sell_liab:
            return self.set_inner(ManageOfferResultCode.UNDERFUNDED)
        if max_sell_funds <= 0 and amount > 0:
            return self.set_inner(ManageOfferResultCode.UNDERFUNDED)

        # a buy offer's caps live on the wheat (buying) side — the sell
        # side is limited by funds only (reference
        # applyOperationSpecificLimits: sell offers clamp sheep, buy
        # offers clamp wheat)
        wheat_cap = self._wheat_receive_cap()
        max_sell = max_sell_funds if self.is_buy \
            else min(amount, max_sell_funds)
        code, bought, sold, claims = cross_offers(
            ltx, src_id, selling, buying,
            max_buy=min(recv_cap, wheat_cap),
            max_sell=max_sell, price_limit=(price.n, price.d),
            passive_taker=self.passive)
        if code == CrossResult.CROSSED_SELF:
            return self.set_inner(ManageOfferResultCode.CROSS_SELF)
        # settle taker net amounts
        assert _debit(ltx, src_id, selling, sold)
        assert _credit(ltx, src_id, buying, bought)

        # residual amount clamped to post-trade capacity (reference
        # adjustOffer idempotence, ManageOfferOpFrameBase.cpp:375-402:
        # v10+ only — the legacy path posts the raw remainder). For a buy
        # offer, the residual promises the REMAINING buy amount
        sheep_resid = INT64_MAX if self.is_buy else (amount - sold)
        if header.ledgerVersion >= 10:
            remaining = adjust_offer(
                price.n, price.d,
                min(sheep_resid, _available_to_sell(ltx, src_id, selling)),
                min(_available_to_receive(ltx, src_id, buying),
                    wheat_cap - bought))
        else:
            remaining = max_sell - sold

        if remaining > 0:
            if is_update:
                new_id = offer_id
            else:
                header.idPool += 1
                new_id = header.idPool
            flags = OfferEntryFlags.PASSIVE_FLAG if (
                self.passive or
                (existing_flags & OfferEntryFlags.PASSIVE_FLAG)) else 0
            oe = OfferEntry(sellerID=src_id, offerID=new_id, selling=selling,
                            buying=buying, amount=remaining, price=price,
                            flags=flags, ext=_Ext.v0())
            entry = LedgerEntry(
                lastModifiedLedgerSeq=header.ledgerSeq,
                data=LedgerEntryData(LedgerEntryType.OFFER, oe),
                ext=_Ext.v0())
            ltx.create(entry)
            assert acquire_liabilities(ltx, oe), \
                "acquire after backability check must succeed"
            arm = ManageOfferSuccessResultOffer(1 if is_update else 0, oe)
        else:
            # no offer stays: give back the subentry taken above (new) or
            # the one the erased offer held (update)
            src = load_account(ltx, src_id)
            change_subentries(header, src, -1)
            arm = _offer_deleted()
        return self.set_inner(
            ManageOfferResultCode.SUCCESS,
            ManageOfferSuccessResult(offersClaimed=claims, offer=arm))


@register_op
class ManageSellOfferOpFrame(_ManageOfferBase):
    op_type = OperationType.MANAGE_SELL_OFFER

    def _params(self):
        b = self.op.body.value
        return b.selling, b.buying, b.amount, b.price, b.offerID


@register_op
class CreatePassiveSellOfferOpFrame(_ManageOfferBase):
    op_type = OperationType.CREATE_PASSIVE_SELL_OFFER
    passive = True

    def _params(self):
        b = self.op.body.value
        return b.selling, b.buying, b.amount, b.price, 0


@register_op
class ManageBuyOfferOpFrame(_ManageOfferBase):
    op_type = OperationType.MANAGE_BUY_OFFER
    is_buy = True

    def is_version_supported(self, ledger_version: int) -> bool:
        # introduced in protocol 11 (reference
        # ManageBuyOfferOpFrame::isVersionSupported)
        return ledger_version >= 11

    def _wheat_receive_cap(self) -> int:
        b = self.op.body.value
        return b.buyAmount if b.buyAmount > 0 else INT64_MAX

    def _is_delete(self) -> bool:
        # delete is buyAmount == 0 — NOT the converted sell amount,
        # which floors to 0 for small buyAmount at sub-unit prices
        # (reference isDeleteOffer, ManageBuyOfferOpFrame.cpp:46-49)
        b = self.op.body.value
        return b.buyAmount == 0 and b.offerID != 0

    def _params(self):
        b = self.op.body.value
        # buy price is buying-per-selling from the buyer's view: price of
        # buyAmount units. Equivalent sell offer: sell amount =
        # buyAmount*n/d (rounded down), price inverted.
        sell_amount = (b.buyAmount * b.price.n) // b.price.d \
            if b.buyAmount > 0 else 0
        inv = Price(n=b.price.d, d=b.price.n)
        return b.selling, b.buying, sell_amount, inv, b.offerID

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if not _valid_asset(b.selling) or not _valid_asset(b.buying) or \
                b.selling == b.buying:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        if b.price.n <= 0 or b.price.d <= 0 or b.buyAmount < 0 or \
                b.offerID < 0:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        if b.buyAmount == 0 and b.offerID == 0:
            return self.set_inner(ManageOfferResultCode.MALFORMED)
        return self.set_inner(
            ManageOfferResultCode.SUCCESS,
            ManageOfferSuccessResult(offersClaimed=[],
                                     offer=_offer_deleted()))


class _PathPaymentBase(OperationFrame):
    def _dest_credit_code(self, ltx, dest_id, asset: Asset,
                          amount: int) -> Optional[int]:
        if asset.is_native:
            # int64 balance headroom (reference canBuyAtMost on native:
            # crediting past INT64_MAX is LINE_FULL, not a crash)
            if _available_to_receive(ltx, dest_id, asset) < amount:
                return PathPaymentResultCode.LINE_FULL
            return None
        if dest_id == asset.issuer:
            return None
        if ltx.load_without_record(
                LedgerKey.account(asset.issuer)) is None:
            return PathPaymentResultCode.NO_ISSUER
        tl = ltx.load_without_record(LedgerKey.trustline(dest_id, asset))
        if tl is None:
            return PathPaymentResultCode.NO_TRUST
        t = tl.data.value
        if not (t.flags & TrustLineFlags.AUTHORIZED_FLAG):
            return PathPaymentResultCode.NOT_AUTHORIZED
        if _available_to_receive(ltx, dest_id, asset) < amount:
            return PathPaymentResultCode.LINE_FULL
        return None

    def _src_debit_code(self, ltx, src_id, asset: Asset,
                        amount: int) -> Optional[int]:
        if asset.is_native:
            if _available_to_sell(ltx, src_id, asset) < amount:
                return PathPaymentResultCode.UNDERFUNDED
            return None
        if src_id == asset.issuer:
            return None
        if ltx.load_without_record(
                LedgerKey.account(asset.issuer)) is None:
            return PathPaymentResultCode.NO_ISSUER
        tl = ltx.load_without_record(LedgerKey.trustline(src_id, asset))
        if tl is None:
            return PathPaymentResultCode.SRC_NO_TRUST
        t = tl.data.value
        if not (t.flags & TrustLineFlags.AUTHORIZED_FLAG):
            return PathPaymentResultCode.SRC_NOT_AUTHORIZED
        if _available_to_sell(ltx, src_id, asset) < amount:
            return PathPaymentResultCode.UNDERFUNDED
        return None


@register_op
class PathPaymentStrictReceiveOpFrame(_PathPaymentBase):
    op_type = OperationType.PATH_PAYMENT_STRICT_RECEIVE

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if b.destAmount <= 0 or b.sendMax <= 0:
            return self.set_inner(PathPaymentResultCode.MALFORMED)
        assets = [b.sendAsset, b.destAsset] + list(b.path)
        if not all(_valid_asset(a) for a in assets):
            return self.set_inner(PathPaymentResultCode.MALFORMED)
        return self.set_inner(
            PathPaymentResultCode.SUCCESS,
            PathPaymentSuccess(offers=[], last=SimplePaymentResult(
                destination=self.source_account_id(),
                asset=b.destAsset, amount=0)))

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        src_id = self.source_account_id()
        dest_id = b.destination.account_id
        if load_account(ltx, dest_id) is None:
            return self.set_inner(PathPaymentResultCode.NO_DESTINATION)
        code = self._dest_credit_code(ltx, dest_id, b.destAsset,
                                      b.destAmount)
        if code is not None:
            return self.set_inner(code)

        chain = [b.sendAsset] + list(b.path) + [b.destAsset]
        needed = b.destAmount
        all_claims = []
        # walk backwards: acquire `needed` of chain[i+1] with chain[i]
        for i in range(len(chain) - 2, -1, -1):
            have_asset, want_asset = chain[i], chain[i + 1]
            if have_asset == want_asset:
                continue
            res, bought, sold, claims = cross_offers(
                ltx, src_id, have_asset, want_asset, max_buy=needed,
                max_sell=INT64_MAX)
            if res == CrossResult.CROSSED_SELF:
                return self.set_inner(PathPaymentResultCode.OFFER_CROSS_SELF)
            if bought < needed:
                return self.set_inner(PathPaymentResultCode.TOO_FEW_OFFERS)
            all_claims = claims + all_claims
            needed = sold
        if needed > b.sendMax:
            return self.set_inner(PathPaymentResultCode.OVER_SENDMAX)
        code = self._src_debit_code(ltx, src_id, b.sendAsset, needed)
        if code is not None:
            return self.set_inner(code)
        assert _debit(ltx, src_id, b.sendAsset, needed)
        assert _credit(ltx, dest_id, b.destAsset, b.destAmount)
        return self.set_inner(
            PathPaymentResultCode.SUCCESS,
            PathPaymentSuccess(
                offers=all_claims,
                last=SimplePaymentResult(destination=dest_id,
                                         asset=b.destAsset,
                                         amount=b.destAmount)))


@register_op
class PathPaymentStrictSendOpFrame(_PathPaymentBase):
    op_type = OperationType.PATH_PAYMENT_STRICT_SEND

    def is_version_supported(self, ledger_version: int) -> bool:
        # introduced by CAP-0018's companion in protocol 12 (reference
        # PathPaymentStrictSendOpFrame::isVersionSupported)
        return ledger_version >= 12

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if b.sendAmount <= 0 or b.destMin <= 0:
            return self.set_inner(PathPaymentResultCode.MALFORMED)
        assets = [b.sendAsset, b.destAsset] + list(b.path)
        if not all(_valid_asset(a) for a in assets):
            return self.set_inner(PathPaymentResultCode.MALFORMED)
        return self.set_inner(
            PathPaymentResultCode.SUCCESS,
            PathPaymentSuccess(offers=[], last=SimplePaymentResult(
                destination=self.source_account_id(),
                asset=b.destAsset, amount=0)))

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        src_id = self.source_account_id()
        dest_id = b.destination.account_id
        if load_account(ltx, dest_id) is None:
            return self.set_inner(PathPaymentResultCode.NO_DESTINATION)
        code = self._src_debit_code(ltx, src_id, b.sendAsset, b.sendAmount)
        if code is not None:
            return self.set_inner(code)
        assert _debit(ltx, src_id, b.sendAsset, b.sendAmount)

        chain = [b.sendAsset] + list(b.path) + [b.destAsset]
        have = b.sendAmount
        all_claims = []
        for i in range(len(chain) - 1):
            have_asset, want_asset = chain[i], chain[i + 1]
            if have_asset == want_asset:
                continue
            res, bought, sold, claims = cross_offers(
                ltx, src_id, have_asset, want_asset, max_buy=INT64_MAX,
                max_sell=have)
            if res == CrossResult.CROSSED_SELF:
                return self.set_inner(PathPaymentResultCode.OFFER_CROSS_SELF)
            if bought == 0 or sold < have:
                # couldn't convert everything: not enough offers
                return self.set_inner(PathPaymentResultCode.TOO_FEW_OFFERS)
            all_claims += claims
            have = bought
        if have < b.destMin:
            return self.set_inner(PathPaymentResultCode.UNDER_DESTMIN)
        code = self._dest_credit_code(ltx, dest_id, b.destAsset, have)
        if code is not None:
            return self.set_inner(code)
        assert _credit(ltx, dest_id, b.destAsset, have)
        return self.set_inner(
            PathPaymentResultCode.SUCCESS,
            PathPaymentSuccess(
                offers=all_claims,
                last=SimplePaymentResult(destination=dest_id,
                                         asset=b.destAsset, amount=have)))
