"""OfferExchange: the order-book crossing engine.

Role parity: reference `src/transactions/OfferExchange.cpp` (exchangeV10,
crossOfferV10, convertWithOffers) and `util/numeric.cpp` (128-bit rounding).
Python integers are arbitrary precision, so the exchange math here is exact
rational arithmetic with explicit rounding direction instead of 128-bit
intrinsics.

Vocabulary (as in the reference): the resting offer sells WHEAT and buys
SHEEP at price n/d = sheep per wheat. The taker receives wheat and sends
sheep.

Rounding contract: the resting offer owner never receives less than the
price implies — sheep is rounded UP for a given wheat, or wheat rounded
DOWN for a given sheep budget. Zero-amount trades are rejected.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..xdr import (
    Asset, ClaimOfferAtom, LedgerEntry, LedgerKey, OfferEntryFlags,
    TrustLineFlags, ledger_entry_key,
)
from .account_helpers import (
    INT64_MAX, add_balance, change_subentries, load_account, load_trustline,
    min_balance,
)


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def exchange(offer_amount: int, n: int, d: int, max_wheat_receive: int,
             max_sheep_send: int) -> Tuple[int, int]:
    """Exact crossing amounts: returns (wheat_received, sheep_sent)."""
    wheat = min(offer_amount, max_wheat_receive)
    if wheat <= 0 or max_sheep_send <= 0:
        return 0, 0
    sheep = _ceil_div(wheat * n, d)
    if sheep > max_sheep_send:
        wheat = (max_sheep_send * d) // n
        wheat = min(wheat, offer_amount, max_wheat_receive)
        sheep = _ceil_div(wheat * n, d)
    if wheat <= 0 or sheep <= 0 or sheep > max_sheep_send:
        return 0, 0
    return wheat, sheep


def _available_to_sell(ltx, account_id, asset: Asset) -> int:
    """How much of `asset` the account can actually deliver."""
    header = ltx.get_header()
    if asset.is_native:
        acc_e = ltx.load_without_record(LedgerKey.account(account_id))
        if acc_e is None:
            return 0
        acc = acc_e.data.value
        return max(0, acc.balance - min_balance(header, acc.numSubEntries))
    if account_id == asset.issuer:
        return INT64_MAX
    tl_e = ltx.load_without_record(LedgerKey.trustline(account_id, asset))
    if tl_e is None or not (tl_e.data.value.flags &
                            TrustLineFlags.AUTHORIZED_FLAG):
        return 0
    return max(0, tl_e.data.value.balance)


def _available_to_receive(ltx, account_id, asset: Asset) -> int:
    if asset.is_native:
        acc_e = ltx.load_without_record(LedgerKey.account(account_id))
        if acc_e is None:
            return 0
        return INT64_MAX - acc_e.data.value.balance
    if account_id == asset.issuer:
        return INT64_MAX
    tl_e = ltx.load_without_record(LedgerKey.trustline(account_id, asset))
    if tl_e is None or not (tl_e.data.value.flags &
                            TrustLineFlags.AUTHORIZED_FLAG):
        return 0
    tl = tl_e.data.value
    return max(0, tl.limit - tl.balance)


def _credit(ltx, account_id, asset: Asset, amount: int) -> bool:
    if amount == 0:
        return True
    header = ltx.get_header()
    if asset.is_native:
        e = load_account(ltx, account_id)
        return e is not None and add_balance(header, e, amount)
    if account_id == asset.issuer:
        return True  # issuer receiving its own asset burns it
    e = load_trustline(ltx, account_id, asset)
    if e is None:
        return False
    tl = e.data.value
    if tl.balance + amount > tl.limit:
        return False
    tl.balance += amount
    return True


def _debit(ltx, account_id, asset: Asset, amount: int) -> bool:
    if amount == 0:
        return True
    header = ltx.get_header()
    if asset.is_native:
        e = load_account(ltx, account_id)
        return e is not None and add_balance(header, e, -amount)
    if account_id == asset.issuer:
        return True  # issuer paying its own asset mints it
    e = load_trustline(ltx, account_id, asset)
    if e is None or e.data.value.balance < amount:
        return False
    e.data.value.balance -= amount
    return True


class CrossResult:
    SUCCESS = 0
    PARTIAL = 1          # book exhausted before filling
    CROSSED_SELF = 2
    BAD_PRICE_LIMIT = 3  # remaining book worse than limit (manage offer)


def cross_offers(ltx, taker_id, sell_asset: Asset, buy_asset: Asset,
                 max_buy: int, max_sell: int,
                 price_limit: Optional[Tuple[int, int]] = None,
                 passive_taker: bool = False
                 ) -> Tuple[int, int, int, List[ClaimOfferAtom]]:
    """Cross the (selling=buy_asset, buying=sell_asset) book until the taker
    has bought max_buy, spent max_sell, hit the price limit, or emptied the
    book.

    price_limit (n, d): the taker's own price (sell per buy). Resting offers
    with sheep-per-wheat price strictly greater than d/n don't cross; at
    exactly d/n, a passive taker doesn't cross.

    Returns (code, bought, sold, claims). Offer owners' balances are
    adjusted in place; the taker's are NOT (caller settles net amounts).
    """
    bought = 0
    sold = 0
    claims: List[ClaimOfferAtom] = []
    while bought < max_buy and sold < max_sell:
        best = ltx.best_offer(buy_asset, sell_asset)
        if best is None:
            return CrossResult.PARTIAL, bought, sold, claims
        offer = best.data.value
        n, d = offer.price.n, offer.price.d
        if price_limit is not None:
            ln, ld = price_limit
            # offer price (sheep/wheat) vs taker reciprocal limit (ld/ln)
            lhs = n * ln
            rhs = d * ld
            if lhs > rhs:
                return CrossResult.BAD_PRICE_LIMIT, bought, sold, claims
            if lhs == rhs and (passive_taker or
                               (offer.flags & OfferEntryFlags.PASSIVE_FLAG)):
                return CrossResult.BAD_PRICE_LIMIT, bought, sold, claims
        if offer.sellerID == taker_id:
            return CrossResult.CROSSED_SELF, bought, sold, claims

        owner = offer.sellerID
        key = ledger_entry_key(best)
        wheat_cap = min(offer.amount,
                        _available_to_sell(ltx, owner, buy_asset))
        recv_cap = _available_to_receive(ltx, owner, sell_asset)
        if recv_cap < INT64_MAX:
            wheat_cap = min(wheat_cap, (recv_cap * d) // n)
        if wheat_cap <= 0:
            # unfunded/unreceivable offer: garbage-collect it
            _erase_offer(ltx, key, owner)
            continue
        wheat, sheep = exchange(wheat_cap, n, d, max_buy - bought,
                                max_sell - sold)
        if wheat == 0:
            return CrossResult.SUCCESS, bought, sold, claims
        # settle the owner's side
        ok1 = _debit(ltx, owner, buy_asset, wheat)
        ok2 = _credit(ltx, owner, sell_asset, sheep)
        assert ok1 and ok2, "owner settlement failed after capacity check"
        live = ltx.load(key)
        o = live.data.value
        o.amount -= wheat
        if o.amount <= 0 or wheat == wheat_cap and wheat < offer.amount:
            # fully taken, or residual is unfunded
            _erase_offer(ltx, key, owner)
        bought += wheat
        sold += sheep
        claims.append(ClaimOfferAtom(
            sellerID=owner, offerID=offer.offerID, assetSold=buy_asset,
            amountSold=wheat, assetBought=sell_asset, amountBought=sheep))
    return CrossResult.SUCCESS, bought, sold, claims


def _erase_offer(ltx, key: LedgerKey, owner) -> None:
    ltx.erase(key)
    acc = load_account(ltx, owner)
    if acc is not None:
        change_subentries(ltx.get_header(), acc, -1)
