"""OfferExchange: the order-book crossing engine.

Role parity: reference `src/transactions/OfferExchange.cpp` (exchangeV10,
crossOfferV10, convertWithOffers) and `util/numeric.cpp` (128-bit rounding).
Python integers are arbitrary precision, so the exchange math here is exact
rational arithmetic with explicit rounding direction instead of 128-bit
intrinsics.

Vocabulary (as in the reference): the resting offer sells WHEAT and buys
SHEEP at price n/d = sheep per wheat. The taker receives wheat and sends
sheep.

Rounding contract: the resting offer owner never receives less than the
price implies — sheep is rounded UP for a given wheat, or wheat rounded
DOWN for a given sheep budget. Zero-amount trades are rejected.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..xdr import (
    Asset, ClaimOfferAtom, LedgerEntry, LedgerKey, OfferEntryFlags,
    TrustLineFlags, ledger_entry_key,
)
from .account_helpers import (
    INT64_MAX, LIABILITIES_VERSION, add_balance, add_buying_liabilities,
    add_selling_liabilities, add_trust_balance, change_subentries,
    get_buying_liabilities, get_selling_liabilities, load_account,
    load_trustline, min_balance,
)

# either auth level lets EXISTING offers execute (CAP-0018)
_AUTH_ANY = TrustLineFlags.AUTH_LEVELS_MASK


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def exchange(offer_amount: int, n: int, d: int, max_wheat_receive: int,
             max_sheep_send: int) -> Tuple[int, int]:
    """Exact crossing amounts: returns (wheat_received, sheep_sent)."""
    wheat = min(offer_amount, max_wheat_receive)
    if wheat <= 0 or max_sheep_send <= 0:
        return 0, 0
    sheep = _ceil_div(wheat * n, d)
    if sheep > max_sheep_send:
        wheat = (max_sheep_send * d) // n
        wheat = min(wheat, offer_amount, max_wheat_receive)
        sheep = _ceil_div(wheat * n, d)
    if wheat <= 0 or sheep <= 0 or sheep > max_sheep_send:
        return 0, 0
    return wheat, sheep


def _available_to_sell(ltx, account_id, asset: Asset) -> int:
    """How much of `asset` the account can actually deliver (reference
    canSellAtMost: available balance net of reserve and SELLING
    liabilities)."""
    header = ltx.get_header()
    if asset.is_native:
        acc_e = ltx.load_without_record(LedgerKey.account(account_id))
        if acc_e is None:
            return 0
        acc = acc_e.data.value
        avail = acc.balance - min_balance(header, acc.numSubEntries)
        if header.ledgerVersion >= LIABILITIES_VERSION:
            avail -= get_selling_liabilities(header, acc_e)
        return max(0, avail)
    if account_id == asset.issuer:
        return INT64_MAX
    tl_e = ltx.load_without_record(LedgerKey.trustline(account_id, asset))
    if tl_e is None or not (tl_e.data.value.flags & _AUTH_ANY):
        # maintain-liabilities is enough to EXECUTE existing offers
        # (reference canSellAtMost isAuthorizedToMaintainLiabilities)
        return 0
    avail = tl_e.data.value.balance
    if header.ledgerVersion >= LIABILITIES_VERSION:
        avail -= get_selling_liabilities(header, tl_e)
    return max(0, avail)


def _available_to_receive(ltx, account_id, asset: Asset) -> int:
    """Reference canBuyAtMost: headroom net of BUYING liabilities."""
    header = ltx.get_header()
    if asset.is_native:
        acc_e = ltx.load_without_record(LedgerKey.account(account_id))
        if acc_e is None:
            return 0
        out = INT64_MAX - acc_e.data.value.balance
        if header.ledgerVersion >= LIABILITIES_VERSION:
            out -= get_buying_liabilities(header, acc_e)
        return max(0, out)
    if account_id == asset.issuer:
        return INT64_MAX
    tl_e = ltx.load_without_record(LedgerKey.trustline(account_id, asset))
    if tl_e is None or not (tl_e.data.value.flags & _AUTH_ANY):
        # (reference canBuyAtMost isAuthorizedToMaintainLiabilities)
        return 0
    tl = tl_e.data.value
    out = tl.limit - tl.balance
    if header.ledgerVersion >= LIABILITIES_VERSION:
        out -= get_buying_liabilities(header, tl_e)
    return max(0, out)


def _credit(ltx, account_id, asset: Asset, amount: int) -> bool:
    if amount == 0:
        return True
    header = ltx.get_header()
    if asset.is_native:
        e = load_account(ltx, account_id)
        return e is not None and add_balance(header, e, amount)
    if account_id == asset.issuer:
        return True  # issuer receiving its own asset burns it
    e = load_trustline(ltx, account_id, asset)
    if e is None:
        return False
    return add_trust_balance(header, e, amount)


def _debit(ltx, account_id, asset: Asset, amount: int) -> bool:
    if amount == 0:
        return True
    header = ltx.get_header()
    if asset.is_native:
        e = load_account(ltx, account_id)
        return e is not None and add_balance(header, e, -amount)
    if account_id == asset.issuer:
        return True  # issuer paying its own asset mints it
    e = load_trustline(ltx, account_id, asset)
    if e is None:
        return False
    return add_trust_balance(header, e, -amount)


# -- offer liabilities (reference TransactionUtils.cpp:590-632 + ManageOffer
#    getOfferBuying/SellingLiabilities) --------------------------------------

def offer_liabilities(n: int, d: int, amount: int):
    """(buying, selling) liabilities a resting offer of `amount` at price
    n/d encumbers: the owner owes `amount` wheat (selling) and has claim
    to ceil(amount*n/d) sheep (buying)."""
    wheat, sheep = exchange(amount, n, d, INT64_MAX, INT64_MAX)
    return sheep, wheat


def adjust_offer(n: int, d: int, max_sell: int, max_receive: int) -> int:
    """Largest posting amount backable by max_sell/max_receive (reference
    adjustOffer, OfferExchange.cpp:903: exchangeV10 with unbounded taker,
    NORMAL rounding — idempotent on adjusted offers). Models a buyer with
    no limits crossing the offer, so sheep always stays: round toward the
    taker, then zero the offer entirely if either side would eat more
    than 1% price error (checkPriceErrorBound, OfferExchange.cpp:174) —
    this is what deletes dust offers at the v10 upgrade."""
    if max_sell <= 0 or max_receive <= 0:
        return 0
    wheat_value = min(max_sell * n, max_receive * d)
    if n > d:  # wheat more valuable
        wheat = wheat_value // n
        sheep = (wheat * n) // d
    else:
        sheep = wheat_value // d
        wheat = _ceil_div(sheep * d, n)
    if wheat <= 0 or sheep <= 0:
        return 0
    # |100·n·wheat − 100·d·sheep| ≤ n·wheat  (≤1% relative price error)
    if abs(100 * n * wheat - 100 * d * sheep) > n * wheat:
        return 0
    return wheat


def apply_offer_liabilities(ltx, offer, sign: int) -> bool:
    """Acquire (+1) or release (-1) the liabilities an offer encumbers on
    its owner's account/trustlines (reference
    acquireOrReleaseLiabilities, TransactionUtils.cpp:134-206)."""
    header = ltx.get_header()
    if header.ledgerVersion < LIABILITIES_VERSION:
        return True
    buying_liab, selling_liab = offer_liabilities(
        offer.price.n, offer.price.d, offer.amount)
    seller = offer.sellerID
    ok = True
    if offer.buying.is_native:
        e = load_account(ltx, seller)
        ok = e is not None and \
            add_buying_liabilities(header, e, sign * buying_liab)
    elif seller != offer.buying.issuer:
        e = load_trustline(ltx, seller, offer.buying)
        ok = e is not None and \
            add_buying_liabilities(header, e, sign * buying_liab)
    if not ok:
        return False
    if offer.selling.is_native:
        e = load_account(ltx, seller)
        ok = e is not None and \
            add_selling_liabilities(header, e, sign * selling_liab)
    elif seller != offer.selling.issuer:
        e = load_trustline(ltx, seller, offer.selling)
        ok = e is not None and \
            add_selling_liabilities(header, e, sign * selling_liab)
    return ok


def acquire_liabilities(ltx, offer) -> bool:
    return apply_offer_liabilities(ltx, offer, +1)


def release_liabilities(ltx, offer) -> None:
    ok = apply_offer_liabilities(ltx, offer, -1)
    assert ok, "releasing offer liabilities must succeed"


class CrossResult:
    SUCCESS = 0
    PARTIAL = 1          # book exhausted before filling
    CROSSED_SELF = 2
    BAD_PRICE_LIMIT = 3  # remaining book worse than limit (manage offer)


def cross_offers(ltx, taker_id, sell_asset: Asset, buy_asset: Asset,
                 max_buy: int, max_sell: int,
                 price_limit: Optional[Tuple[int, int]] = None,
                 passive_taker: bool = False
                 ) -> Tuple[int, int, int, List[ClaimOfferAtom]]:
    """Cross the (selling=buy_asset, buying=sell_asset) book until the taker
    has bought max_buy, spent max_sell, hit the price limit, or emptied the
    book.

    price_limit (n, d): the taker's own price (sell per buy). Resting offers
    with sheep-per-wheat price strictly greater than d/n don't cross; at
    exactly d/n, a passive taker doesn't cross.

    Returns (code, bought, sold, claims). Offer owners' balances are
    adjusted in place; the taker's are NOT (caller settles net amounts).
    """
    bought = 0
    sold = 0
    claims: List[ClaimOfferAtom] = []
    while bought < max_buy and sold < max_sell:
        best = ltx.best_offer(buy_asset, sell_asset)
        if best is None:
            return CrossResult.PARTIAL, bought, sold, claims
        offer = best.data.value
        n, d = offer.price.n, offer.price.d
        if price_limit is not None:
            ln, ld = price_limit
            # offer price (sheep/wheat) vs taker reciprocal limit (ld/ln)
            lhs = n * ln
            rhs = d * ld
            if lhs > rhs:
                return CrossResult.BAD_PRICE_LIMIT, bought, sold, claims
            if lhs == rhs and (passive_taker or
                               (offer.flags & OfferEntryFlags.PASSIVE_FLAG)):
                return CrossResult.BAD_PRICE_LIMIT, bought, sold, claims
        if offer.sellerID == taker_id:
            return CrossResult.CROSSED_SELF, bought, sold, claims

        owner = offer.sellerID
        key = ledger_entry_key(best)
        # release the resting offer's liabilities up front so the owner's
        # full capacity is visible to the exchange; re-acquired below if
        # the offer survives (reference crossOfferV10 shape)
        release_liabilities(ltx, offer)
        wheat_cap = min(offer.amount,
                        _available_to_sell(ltx, owner, buy_asset))
        recv_cap = _available_to_receive(ltx, owner, sell_asset)
        if recv_cap < INT64_MAX:
            wheat_cap = min(wheat_cap, (recv_cap * d) // n)
        if wheat_cap <= 0:
            # unfunded/unreceivable offer: garbage-collect it
            _erase_offer(ltx, key, owner)
            continue
        wheat, sheep = exchange(wheat_cap, n, d, max_buy - bought,
                                max_sell - sold)
        if wheat == 0:
            # taker exhausted; restore the resting offer's liabilities
            assert acquire_liabilities(ltx, offer), \
                "re-acquire after release must succeed"
            return CrossResult.SUCCESS, bought, sold, claims
        # settle the owner's side
        ok1 = _debit(ltx, owner, buy_asset, wheat)
        ok2 = _credit(ltx, owner, sell_asset, sheep)
        assert ok1 and ok2, "owner settlement failed after capacity check"
        live = ltx.load(key)
        o = live.data.value
        o.amount -= wheat
        if o.amount <= 0 or wheat == wheat_cap and wheat < offer.amount:
            # fully taken, or residual is unfunded
            _erase_offer(ltx, key, owner)
        elif ltx.get_header().ledgerVersion >= 10:
            # clamp the residual to what the owner can still back, then
            # re-encumber (reference performExchange newAmount + acquire;
            # v10+ only — the legacy engine keeps the raw remainder)
            o.amount = adjust_offer(
                n, d, min(o.amount, _available_to_sell(ltx, owner,
                                                       buy_asset)),
                _available_to_receive(ltx, owner, sell_asset))
            if o.amount <= 0:
                _erase_offer(ltx, key, owner)
            else:
                assert acquire_liabilities(ltx, o), \
                    "re-acquire of clamped residual must succeed"
        bought += wheat
        sold += sheep
        claims.append(ClaimOfferAtom(
            sellerID=owner, offerID=offer.offerID, assetSold=buy_asset,
            amountSold=wheat, assetBought=sell_asset, amountBought=sheep))
    return CrossResult.SUCCESS, bought, sold, claims


def _erase_offer(ltx, key: LedgerKey, owner) -> None:
    ltx.erase(key)
    acc = load_account(ltx, owner)
    if acc is not None:
        change_subentries(ltx.get_header(), acc, -1)
