"""SignatureChecker: multisig weight/threshold accounting over a tx's
signatures.

Role parity: reference `src/transactions/SignatureChecker.{h,cpp}:18-120`:
weight accumulation over ed25519 / pre-auth-tx / hash-x signers, hint
pre-filter, "all signatures used" discipline; and
`src/transactions/SignatureUtils.cpp:27-36` (hint filter + verifySig).

Semantics matched to the reference:
- one call consumes each SIGNER at most once, but a SIGNATURE may satisfy
  multiple calls (multiple ops of one tx share signatures); the "used"
  mark only feeds check_all_signatures_used (txBAD_AUTH_EXTRA).
- success as soon as accumulated weight >= needed_weight (weights capped
  at 255); needed_weight 0 still requires one valid signer.

The verify call goes through the injected BatchSigVerifier: all
hint-matching (signature, signer) pairs are enqueued and flushed in ONE
batch before accumulation — under the TPU backend this is a single device
dispatch per check.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from ..crypto.batch_verifier import BatchSigVerifier, CpuSigVerifier
from ..xdr import (
    DecoratedSignature, PublicKey, Signer, SignerKey, SignerKeyType,
)

_FUZZING_MODE = False  # reference SignatureChecker.cpp:33-35 parity hook


def set_fuzzing_mode(on: bool) -> None:
    global _FUZZING_MODE
    _FUZZING_MODE = on


def _hint_of(b32: bytes) -> bytes:
    return b32[-4:]


class SignatureChecker:
    def __init__(self, network_hash_contents: bytes,
                 signatures: Sequence[DecoratedSignature],
                 verifier: Optional[BatchSigVerifier] = None) -> None:
        self._contents_hash = network_hash_contents
        self._sigs = list(signatures)
        self._used = [False] * len(self._sigs)
        self._verifier = verifier or CpuSigVerifier()
        # hint → signature indices: each check then probes one bucket per
        # signer instead of scanning the sigs × signers cross-product (a
        # 20-sig 20-signer multisig tx is 400 hint compares per check)
        self._by_hint: Dict[bytes, List[int]] = {}
        for i, ds in enumerate(self._sigs):
            self._by_hint.setdefault(ds.hint, []).append(i)

    def check_signature(self, signers: List[Signer],
                        needed_weight: int) -> bool:
        if _FUZZING_MODE:
            return True
        total = 0

        # pre-auth-tx signers match the contents hash directly
        for signer in signers:
            if signer.key.disc == \
                    SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX and \
                    signer.key.value == self._contents_hash:
                total += min(signer.weight, 255)
                if total >= needed_weight:
                    return True

        def verify_all(remaining: List[Signer], verify_fn) -> bool:
            nonlocal total
            for i, ds in enumerate(self._sigs):
                for j, signer in enumerate(remaining):
                    if verify_fn(i, ds, signer):
                        self._used[i] = True
                        total += min(signer.weight, 255)
                        if total >= needed_weight:
                            return True
                        remaining.pop(j)
                        break
            return False

        # hash-x: sha256(signature) equals the signer key
        hashx = [s for s in signers
                 if s.key.disc == SignerKeyType.SIGNER_KEY_TYPE_HASH_X]
        if verify_all(hashx, lambda i, ds, s:
                      hashlib.sha256(ds.signature).digest() == s.key.value):
            return True

        # ed25519: enqueue all hint-matching pairs, flush once, then
        # accumulate from the completed futures
        eds = [s for s in signers
               if s.key.disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519]
        futs: Dict[Tuple[int, bytes], object] = {}
        for signer in eds:
            kb = signer.key.value
            for i in self._by_hint.get(_hint_of(kb), ()):
                futs[(i, kb)] = self._verifier.enqueue(
                    PublicKey.ed25519(kb), self._sigs[i].signature,
                    self._contents_hash)
        if futs:
            self._verifier.flush()

        def ed_ok(i: int, ds: DecoratedSignature, signer: Signer) -> bool:
            fut = futs.get((i, signer.key.value))
            return fut is not None and fut.result()

        return verify_all(eds, ed_ok)

    def check_all_signatures_used(self) -> bool:
        """Reference: any unused signature makes the tx invalid
        (txBAD_AUTH_EXTRA)."""
        if _FUZZING_MODE:
            return True
        return all(self._used)
