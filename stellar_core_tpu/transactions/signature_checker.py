"""SignatureChecker: multisig weight/threshold accounting over a tx's
signatures.

Role parity: reference `src/transactions/SignatureChecker.{h,cpp}:18-120`:
greedy weight accumulation over ed25519 / pre-auth-tx / hash-x signers, hint
pre-filter, "all signatures used" discipline; and
`src/transactions/SignatureUtils.cpp:27-36` (hint filter + verifySig).

The verify call goes through the injected BatchSigVerifier, so this is a
TPU-batch call site in batch mode; in synchronous mode futures complete
immediately.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from ..crypto.batch_verifier import BatchSigVerifier, CpuSigVerifier
from ..xdr import (
    DecoratedSignature, PublicKey, Signer, SignerKey, SignerKeyType,
)

_FUZZING_MODE = False  # reference SignatureChecker.cpp:33-35 parity hook


def set_fuzzing_mode(on: bool) -> None:
    global _FUZZING_MODE
    _FUZZING_MODE = on


def _hint_of(b32: bytes) -> bytes:
    return b32[-4:]


class SignatureChecker:
    def __init__(self, network_hash_contents: bytes,
                 signatures: Sequence[DecoratedSignature],
                 verifier: Optional[BatchSigVerifier] = None) -> None:
        self._contents_hash = network_hash_contents
        self._sigs = list(signatures)
        self._used = [False] * len(self._sigs)
        self._verifier = verifier or CpuSigVerifier()

    def check_signature(self, signers: List[Signer],
                        needed_weight: int) -> bool:
        """Greedy accumulation: for each unused signature matching a signer's
        hint, verify; add weight (capped 255); success when total >=
        needed_weight (0 means any valid signer)."""
        if _FUZZING_MODE:
            return True
        total = 0
        # pre-auth-tx and hash-x signers are checked without sig verify
        for signer in signers:
            k = signer.key
            if k.disc == SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX:
                if k.value == self._contents_hash:
                    total += min(signer.weight, 255)
            elif k.disc == SignerKeyType.SIGNER_KEY_TYPE_HASH_X:
                for i, ds in enumerate(self._sigs):
                    if self._used[i]:
                        continue
                    if hashlib.sha256(ds.signature).digest() == k.value:
                        self._used[i] = True
                        total += min(signer.weight, 255)
                        break
        # ed25519 signers: hint filter then verify (batched)
        pending = []
        for signer in signers:
            k = signer.key
            if k.disc != SignerKeyType.SIGNER_KEY_TYPE_ED25519:
                continue
            hint = _hint_of(k.value)
            for i, ds in enumerate(self._sigs):
                if self._used[i] or ds.hint != hint:
                    continue
                fut = self._verifier.enqueue(
                    PublicKey.ed25519(k.value), ds.signature,
                    self._contents_hash)
                pending.append((i, signer, fut))
        if pending:
            self._verifier.flush()
        seen_signers = set()
        for i, signer, fut in pending:
            if self._used[i] or id(signer) in seen_signers:
                continue
            if fut.result():
                self._used[i] = True
                seen_signers.add(id(signer))
                total += min(signer.weight, 255)
        if needed_weight == 0:
            return total > 0
        return total >= needed_weight

    def check_all_signatures_used(self) -> bool:
        """Reference: any unused signature makes the tx invalid
        (txBAD_AUTH_EXTRA)."""
        if _FUZZING_MODE:
            return True
        return all(self._used)
