"""Concrete operation frames (non-offer ops).

Role parity: reference `src/transactions/*OpFrame.cpp` for: create-account,
payment, set-options, change-trust, allow-trust, account-merge, inflation,
manage-data, bump-sequence. Result codes mirror the public protocol enums.
Offers and path payments live in offers.py (they share OfferExchange).
"""

from __future__ import annotations

from ..xdr import (
    AccountFlags, Asset, AssetType, DataEntry, LedgerEntry, LedgerEntryData,
    LedgerEntryType, LedgerKey, OperationType, SignerKeyType, TrustLineEntry,
    TrustLineEntryExt, TrustLineFlags, _Ext,
)
from .account_helpers import (
    INT64_MAX, ThresholdLevel, add_balance, add_trust_balance,
    change_subentries, is_auth_required, is_immutable_auth, load_account,
    load_trustline, make_account_entry, min_balance,
    starting_sequence_number,
)
from .operation_frame import OperationFrame, register_op


# -- result codes (protocol enums) ------------------------------------------

class CreateAccountResultCode:
    SUCCESS = 0
    MALFORMED = -1
    UNDERFUNDED = -2
    LOW_RESERVE = -3
    ALREADY_EXIST = -4


class PaymentResultCode:
    SUCCESS = 0
    MALFORMED = -1
    UNDERFUNDED = -2
    SRC_NO_TRUST = -3
    SRC_NOT_AUTHORIZED = -4
    NO_DESTINATION = -5
    NO_TRUST = -6
    NOT_AUTHORIZED = -7
    LINE_FULL = -8
    NO_ISSUER = -9


class SetOptionsResultCode:
    SUCCESS = 0
    LOW_RESERVE = -1
    TOO_MANY_SIGNERS = -2
    BAD_FLAGS = -3
    INVALID_INFLATION = -4
    CANT_CHANGE = -5
    UNKNOWN_FLAG = -6
    THRESHOLD_OUT_OF_RANGE = -7
    BAD_SIGNER = -8
    INVALID_HOME_DOMAIN = -9


class ChangeTrustResultCode:
    SUCCESS = 0
    MALFORMED = -1
    NO_ISSUER = -2
    INVALID_LIMIT = -3
    LOW_RESERVE = -4
    SELF_NOT_ALLOWED = -5


class AllowTrustResultCode:
    SUCCESS = 0
    MALFORMED = -1
    NO_TRUST_LINE = -2
    TRUST_NOT_REQUIRED = -3
    CANT_REVOKE = -4
    SELF_NOT_ALLOWED = -5


class AccountMergeResultCode:
    SUCCESS = 0
    MALFORMED = -1
    NO_ACCOUNT = -2
    IMMUTABLE_SET = -3
    HAS_SUB_ENTRIES = -4
    SEQNUM_TOO_FAR = -5
    DEST_FULL = -6


class InflationResultCode:
    SUCCESS = 0
    NOT_TIME = -1


class ManageDataResultCode:
    SUCCESS = 0
    NOT_SUPPORTED_YET = -1
    NAME_NOT_FOUND = -2
    LOW_RESERVE = -3
    INVALID_NAME = -4


class BumpSequenceResultCode:
    SUCCESS = 0
    BAD_SEQ = -1


def _valid_asset(asset: Asset) -> bool:
    if asset.is_native:
        return True
    code = asset.value.assetCode
    trimmed = code.rstrip(b"\x00")
    if not trimmed or b"\x00" in trimmed:
        return False
    if asset.disc == AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
        return 1 <= len(trimmed) <= 4
    return 5 <= len(trimmed) <= 12


@register_op
class CreateAccountOpFrame(OperationFrame):
    op_type = OperationType.CREATE_ACCOUNT

    def do_check_valid(self, header) -> bool:
        if self.op.body.value.startingBalance <= 0:
            return self.set_inner(CreateAccountResultCode.MALFORMED)
        if self.op.body.value.destination == self.source_account_id():
            return self.set_inner(CreateAccountResultCode.MALFORMED)
        return self.set_inner(CreateAccountResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        body = self.op.body.value
        header = ltx.load_header()
        dest_key = LedgerKey.account(body.destination)
        if ltx.load_without_record(dest_key) is not None:
            return self.set_inner(CreateAccountResultCode.ALREADY_EXIST)
        if body.startingBalance < min_balance(header, 0):
            return self.set_inner(CreateAccountResultCode.LOW_RESERVE)
        src = load_account(ltx, self.source_account_id())
        if not add_balance(header, src, -body.startingBalance):
            return self.set_inner(CreateAccountResultCode.UNDERFUNDED)
        entry = make_account_entry(
            body.destination, body.startingBalance,
            starting_sequence_number(header), header.ledgerSeq)
        ltx.create(entry)
        return self.set_inner(CreateAccountResultCode.SUCCESS)


@register_op
class PaymentOpFrame(OperationFrame):
    op_type = OperationType.PAYMENT

    def do_check_valid(self, header) -> bool:
        body = self.op.body.value
        if body.amount <= 0 or not _valid_asset(body.asset):
            return self.set_inner(PaymentResultCode.MALFORMED)
        return self.set_inner(PaymentResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        body = self.op.body.value
        header = ltx.load_header()
        src_id = self.source_account_id()
        dest_id = body.destination.account_id
        asset, amount = body.asset, body.amount

        dest_acc = load_account(ltx, dest_id)
        if dest_acc is None:
            return self.set_inner(PaymentResultCode.NO_DESTINATION)

        if asset.is_native:
            src = load_account(ltx, src_id)
            if src_id != dest_id:
                if not add_balance(header, src, -amount):
                    return self.set_inner(PaymentResultCode.UNDERFUNDED)
                if not add_balance(header, dest_acc, amount):
                    return self.set_inner(PaymentResultCode.LINE_FULL)
            return self.set_inner(PaymentResultCode.SUCCESS)

        issuer = asset.issuer
        # source side (liability-aware: cannot spend encumbered balance)
        if src_id != issuer:
            stl = load_trustline(ltx, src_id, asset)
            if stl is None:
                return self.set_inner(PaymentResultCode.SRC_NO_TRUST)
            tl = stl.data.value
            if not (tl.flags & TrustLineFlags.AUTHORIZED_FLAG):
                return self.set_inner(PaymentResultCode.SRC_NOT_AUTHORIZED)
            if not add_trust_balance(header, stl, -amount):
                return self.set_inner(PaymentResultCode.UNDERFUNDED)
        else:
            if load_account(ltx, issuer) is None:
                return self.set_inner(PaymentResultCode.NO_ISSUER)
        # destination side (cannot receive into buying-encumbered headroom)
        if dest_id != issuer:
            dtl = load_trustline(ltx, dest_id, asset)
            if dtl is None:
                return self.set_inner(PaymentResultCode.NO_TRUST)
            tl = dtl.data.value
            if not (tl.flags & TrustLineFlags.AUTHORIZED_FLAG):
                return self.set_inner(PaymentResultCode.NOT_AUTHORIZED)
            if not add_trust_balance(header, dtl, amount):
                return self.set_inner(PaymentResultCode.LINE_FULL)
        return self.set_inner(PaymentResultCode.SUCCESS)


@register_op
class SetOptionsOpFrame(OperationFrame):
    op_type = OperationType.SET_OPTIONS

    def threshold_level(self) -> int:
        b = self.op.body.value
        # raising to HIGH when touching thresholds/signers (reference
        # SetOptionsOpFrame::getThresholdLevel)
        if (b.masterWeight is not None or b.lowThreshold is not None
                or b.medThreshold is not None or b.highThreshold is not None
                or b.signer is not None):
            return ThresholdLevel.HIGH
        return ThresholdLevel.MEDIUM

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if b.setFlags is not None and b.clearFlags is not None \
                and (b.setFlags & b.clearFlags) != 0:
            return self.set_inner(SetOptionsResultCode.BAD_FLAGS)
        for v in (b.masterWeight, b.lowThreshold, b.medThreshold,
                  b.highThreshold):
            if v is not None and v > 255:
                return self.set_inner(
                    SetOptionsResultCode.THRESHOLD_OUT_OF_RANGE)
        for v in (b.setFlags, b.clearFlags):
            if v is not None and (v & ~AccountFlags.MASK_ACCOUNT_FLAGS):
                return self.set_inner(SetOptionsResultCode.UNKNOWN_FLAG)
        if b.signer is not None:
            if b.signer.key.disc == SignerKeyType.SIGNER_KEY_TYPE_ED25519 \
                    and b.signer.key.value == \
                    self.source_account_id().key_bytes:
                return self.set_inner(SetOptionsResultCode.BAD_SIGNER)
            if b.signer.weight > 255 and header.ledgerVersion > 9:
                # reference SetOptionsOpFrame.cpp:254-258
                return self.set_inner(SetOptionsResultCode.BAD_SIGNER)
        if b.homeDomain is not None and (
                len(b.homeDomain) > 32 or
                any(ord(c) < 0x20 or ord(c) >= 0x7F for c in b.homeDomain)):
            # control and non-ASCII characters are invalid (reference
            # isString32Valid / isStringValid)
            return self.set_inner(SetOptionsResultCode.INVALID_HOME_DOMAIN)
        return self.set_inner(SetOptionsResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        header = ltx.load_header()
        entry = load_account(ltx, self.source_account_id())
        acc = entry.data.value

        if b.inflationDest is not None:
            if ltx.load_without_record(
                    LedgerKey.account(b.inflationDest)) is None:
                return self.set_inner(SetOptionsResultCode.INVALID_INFLATION)
            acc.inflationDest = b.inflationDest
        if b.clearFlags is not None:
            if is_immutable_auth(acc):
                return self.set_inner(SetOptionsResultCode.CANT_CHANGE)
            acc.flags &= ~b.clearFlags
        if b.setFlags is not None:
            if is_immutable_auth(acc):
                return self.set_inner(SetOptionsResultCode.CANT_CHANGE)
            acc.flags |= b.setFlags
        th = bytearray(acc.thresholds)
        if b.masterWeight is not None:
            th[0] = b.masterWeight
        if b.lowThreshold is not None:
            th[1] = b.lowThreshold
        if b.medThreshold is not None:
            th[2] = b.medThreshold
        if b.highThreshold is not None:
            th[3] = b.highThreshold
        acc.thresholds = bytes(th)
        if b.homeDomain is not None:
            acc.homeDomain = b.homeDomain
        if b.signer is not None:
            signers = list(acc.signers)
            idx = next((i for i, s in enumerate(signers)
                        if s.key == b.signer.key), None)
            if b.signer.weight == 0:
                if idx is not None:
                    signers.pop(idx)
                    change_subentries(header, entry, -1)
            elif idx is not None:
                signers[idx].weight = b.signer.weight
            else:
                if len(signers) >= 20:
                    return self.set_inner(
                        SetOptionsResultCode.TOO_MANY_SIGNERS)
                if not change_subentries(header, entry, +1):
                    return self.set_inner(SetOptionsResultCode.LOW_RESERVE)
                signers.append(b.signer)
            signers.sort(key=lambda s: s.key.to_xdr())
            acc.signers = signers
        return self.set_inner(SetOptionsResultCode.SUCCESS)


@register_op
class ChangeTrustOpFrame(OperationFrame):
    op_type = OperationType.CHANGE_TRUST

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if b.limit < 0 or b.line.is_native or not _valid_asset(b.line):
            return self.set_inner(ChangeTrustResultCode.MALFORMED)
        return self.set_inner(ChangeTrustResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        header = ltx.load_header()
        src_id = self.source_account_id()
        if src_id == b.line.issuer:
            return self.set_inner(ChangeTrustResultCode.SELF_NOT_ALLOWED)
        key = LedgerKey.trustline(src_id, b.line)
        existing = ltx.load(key)
        if existing is not None:
            # reference order (ChangeTrustOpFrame.cpp:66-93): the limit
            # floor first — balance + buying liabilities (v10+; the
            # helper reports 0 below 10) — THEN delete-without-issuer is
            # legal, and only a non-delete edit needs a live issuer
            from .account_helpers import get_buying_liabilities
            tl = existing.data.value
            if b.limit < tl.balance + get_buying_liabilities(header,
                                                             existing):
                return self.set_inner(ChangeTrustResultCode.INVALID_LIMIT)
            if b.limit == 0:
                ltx.erase(key)
                src = load_account(ltx, src_id)
                change_subentries(header, src, -1)
                return self.set_inner(ChangeTrustResultCode.SUCCESS)
            if ltx.load_without_record(
                    LedgerKey.account(b.line.issuer)) is None:
                return self.set_inner(ChangeTrustResultCode.NO_ISSUER)
            tl.limit = b.limit
            return self.set_inner(ChangeTrustResultCode.SUCCESS)
        if b.limit == 0:
            return self.set_inner(ChangeTrustResultCode.INVALID_LIMIT)
        issuer_acc = ltx.load_without_record(
            LedgerKey.account(b.line.issuer))
        if issuer_acc is None:
            return self.set_inner(ChangeTrustResultCode.NO_ISSUER)
        src = load_account(ltx, src_id)
        if not change_subentries(header, src, +1):
            return self.set_inner(ChangeTrustResultCode.LOW_RESERVE)
        flags = 0 if is_auth_required(issuer_acc.data.value) \
            else TrustLineFlags.AUTHORIZED_FLAG
        tl = TrustLineEntry(accountID=src_id, asset=b.line, balance=0,
                            limit=b.limit, flags=flags,
                            ext=TrustLineEntryExt.v0())
        ltx.create(LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=LedgerEntryData(LedgerEntryType.TRUSTLINE, tl),
            ext=_Ext.v0()))
        return self.set_inner(ChangeTrustResultCode.SUCCESS)


@register_op
class AllowTrustOpFrame(OperationFrame):
    op_type = OperationType.ALLOW_TRUST

    def threshold_level(self) -> int:
        return ThresholdLevel.LOW

    @staticmethod
    def _flag_valid(flag: int, ledger_version: int) -> bool:
        """reference trustLineFlagIsValid (TransactionUtils.cpp): pre-13
        only AUTHORIZED; from 13 also MAINTAIN_LIABILITIES, but never
        both auth bits at once."""
        if ledger_version < 13:
            return (flag & ~TrustLineFlags.MASK_TRUSTLINE_FLAGS) == 0
        both = TrustLineFlags.AUTH_LEVELS_MASK
        return (flag & ~TrustLineFlags.MASK_TRUSTLINE_FLAGS_V13) == 0 \
            and (flag & both) != both

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        code = b.asset.value.rstrip(b"\x00")
        if not code:
            return self.set_inner(AllowTrustResultCode.MALFORMED)
        if not self._flag_valid(b.authorize, header.ledgerVersion):
            return self.set_inner(AllowTrustResultCode.MALFORMED)
        return self.set_inner(AllowTrustResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        header = ltx.load_header()
        issuer_id = self.source_account_id()
        if b.trustor == issuer_id:
            return self.set_inner(AllowTrustResultCode.SELF_NOT_ALLOWED)
        issuer = load_account(ltx, issuer_id)
        acc = issuer.data.value
        if not is_auth_required(acc):
            return self.set_inner(AllowTrustResultCode.TRUST_NOT_REQUIRED)
        not_revocable = not (acc.flags & AccountFlags.AUTH_REVOCABLE_FLAG)
        if not_revocable and b.authorize == 0:
            return self.set_inner(AllowTrustResultCode.CANT_REVOKE)
        code = b.asset.value
        asset = Asset.credit(code.rstrip(b"\x00").decode("ascii"), issuer_id)
        tle = load_trustline(ltx, b.trustor, asset)
        if tle is None:
            return self.set_inner(AllowTrustResultCode.NO_TRUST_LINE)
        tl = tle.data.value
        # downgrading AUTHORIZED → MAINTAIN_LIABILITIES is also a
        # (partial) revocation (reference AllowTrustOpFrame.cpp:99-110)
        fully = bool(tl.flags & TrustLineFlags.AUTHORIZED_FLAG)
        maintain_or_more = bool(
            tl.flags & TrustLineFlags.AUTH_LEVELS_MASK)
        if not_revocable and fully and (
                b.authorize &
                TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG):
            return self.set_inner(AllowTrustResultCode.CANT_REVOKE)
        # a FULL revoke (from >= maintain) pulls the trustor's offers in
        # this asset and releases their liabilities (reference :115-140,
        # protocol >= 10)
        if header.ledgerVersion >= 10 and maintain_or_more and \
                b.authorize == 0:
            self._remove_offers(ltx, header, b.trustor, asset)
        tl.flags = b.authorize
        return self.set_inner(AllowTrustResultCode.SUCCESS)

    @staticmethod
    def _remove_offers(ltx, header, trustor, asset: Asset) -> None:
        from .offer_exchange import release_liabilities
        for entry in ltx.load_offers_by_account(trustor, asset):
            oe = entry.data.value
            release_liabilities(ltx, oe)
            acct = load_account(ltx, trustor)
            change_subentries(header, acct, -1)
            ltx.erase(LedgerKey.offer(trustor, oe.offerID))


@register_op
class AccountMergeOpFrame(OperationFrame):
    op_type = OperationType.ACCOUNT_MERGE

    def threshold_level(self) -> int:
        return ThresholdLevel.HIGH

    def do_check_valid(self, header) -> bool:
        if self.op.body.value.account_id == self.source_account_id():
            return self.set_inner(AccountMergeResultCode.MALFORMED)
        return self.set_inner(AccountMergeResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        header = ltx.load_header()
        src_id = self.source_account_id()
        dest_id = self.op.body.value.account_id
        dest = load_account(ltx, dest_id)
        if dest is None:
            return self.set_inner(AccountMergeResultCode.NO_ACCOUNT)
        src = load_account(ltx, src_id)
        acc = src.data.value
        if is_immutable_auth(acc):
            return self.set_inner(AccountMergeResultCode.IMMUTABLE_SET)
        # signers live inside the account entry and die with it; only
        # OWNED subentries (trustlines/offers/data) block a merge
        # (reference MergeOpFrame.cpp:95: numSubEntries != signers.size())
        if acc.numSubEntries != len(acc.signers):
            return self.set_inner(AccountMergeResultCode.HAS_SUB_ENTRIES)
        # replay protection (reference: seqnum in current ledger's range)
        if acc.seqNum >= starting_sequence_number(header):
            return self.set_inner(AccountMergeResultCode.SEQNUM_TOO_FAR)
        balance = acc.balance
        # v10+: the destination's buying liabilities count against the
        # INT64 ceiling (reference doApply → addBalance → DEST_FULL)
        if not add_balance(header, dest, balance):
            return self.set_inner(AccountMergeResultCode.DEST_FULL)
        ltx.erase(LedgerKey.account(src_id))
        return self.set_inner(AccountMergeResultCode.SUCCESS, balance)


@register_op
class InflationOpFrame(OperationFrame):
    op_type = OperationType.INFLATION

    INFLATION_FREQUENCY = 7 * 24 * 60 * 60  # weekly
    INFLATION_RATE_TRILLIONTHS = 190721000
    INFLATION_WIN_MIN_PERCENT = 500000000  # 0.05% in trillionths
    INFLATION_NUM_WINNERS = 2000

    def threshold_level(self) -> int:
        return ThresholdLevel.LOW

    def is_version_supported(self, ledger_version: int) -> bool:
        # inflation retired by protocol 12 (reference
        # InflationOpFrame::isVersionSupported: version < 12 →
        # opNOT_SUPPORTED afterwards, NOT a success-noop)
        return ledger_version < 12

    def do_check_valid(self, header) -> bool:
        return self.set_inner(InflationResultCode.SUCCESS, [])

    def do_apply(self, ltx) -> bool:
        from ..xdr import AccountID, InflationPayout
        from .account_helpers import max_amount_receive
        header = ltx.load_header()
        close_time = header.scpValue.closeTime
        seq = header.inflationSeq
        next_time = (seq + 1) * self.INFLATION_FREQUENCY
        if close_time < next_time:
            return self.set_inner(InflationResultCode.NOT_TIME)
        # classic mechanism (reference InflationOpFrame::doApply): tally
        # inflationDest votes weighted by balance; winners over 0.05%.
        # The query runs on the LedgerTxn so votes see uncommitted changes
        # in the open txn chain (fees charged this close, earlier ops in
        # this tx) — reference queryInflationWinners merges child deltas.
        total = header.totalCoins
        min_votes = total * self.INFLATION_WIN_MIN_PERCENT // 10**12
        winners = [
            (AccountID.ed25519(kb), v)
            for kb, v in ltx.query_inflation_winners(
                self.INFLATION_NUM_WINNERS, min_votes)]
        inflation_amount = total * self.INFLATION_RATE_TRILLIONTHS // 10**12
        amount_to_dole = inflation_amount + header.feePool
        header.feePool = 0
        header.inflationSeq += 1
        left = amount_to_dole
        payouts = []
        for dest_id, v in winners:
            # each winner's share is its fraction of ALL coins, not of
            # the winning votes (reference bigDivide(amountToDole,
            # w.votes, totalVotes) with totalVotes = lh.totalCoins) —
            # the unclaimed remainder stays in the fee pool
            share = amount_to_dole * v // total
            if share == 0:
                continue
            dest = load_account(ltx, dest_id)
            if dest is None:
                continue  # missing winner: nothing doled
            if header.ledgerVersion >= 10:
                # pre-10 has no receive clamp: an overflowing payout
                # throws below (reference InflationOpFrame.cpp:80-100)
                share = min(share, max_amount_receive(header, dest))
                if share == 0:
                    continue
            if not add_balance(header, dest, share):
                raise RuntimeError("inflation overflowed winner balance")
            left -= share
            if header.ledgerVersion <= 7:
                header.totalCoins += share
            payouts.append(InflationPayout(destination=dest_id,
                                           amount=share))
        # unclaimed funds return to the fee pool; from protocol 8 the
        # minted coins enter circulation regardless of how much was
        # claimed (reference InflationOpFrame.cpp:110-114)
        header.feePool += left
        if header.ledgerVersion > 7:
            header.totalCoins += inflation_amount
        return self.set_inner(InflationResultCode.SUCCESS, payouts)


@register_op
class ManageDataOpFrame(OperationFrame):
    op_type = OperationType.MANAGE_DATA

    def do_check_valid(self, header) -> bool:
        b = self.op.body.value
        if not b.dataName or len(b.dataName) > 64:
            return self.set_inner(ManageDataResultCode.INVALID_NAME)
        return self.set_inner(ManageDataResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        b = self.op.body.value
        header = ltx.load_header()
        src_id = self.source_account_id()
        key = LedgerKey.data(src_id, b.dataName)
        existing = ltx.load(key)
        if b.dataValue is None:
            if existing is None:
                return self.set_inner(ManageDataResultCode.NAME_NOT_FOUND)
            ltx.erase(key)
            src = load_account(ltx, src_id)
            change_subentries(header, src, -1)
            return self.set_inner(ManageDataResultCode.SUCCESS)
        if existing is not None:
            existing.data.value.dataValue = b.dataValue
            return self.set_inner(ManageDataResultCode.SUCCESS)
        src = load_account(ltx, src_id)
        if not change_subentries(header, src, +1):
            return self.set_inner(ManageDataResultCode.LOW_RESERVE)
        de = DataEntry(accountID=src_id, dataName=b.dataName,
                       dataValue=b.dataValue, ext=_Ext.v0())
        ltx.create(LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=LedgerEntryData(LedgerEntryType.DATA, de), ext=_Ext.v0()))
        return self.set_inner(ManageDataResultCode.SUCCESS)


@register_op
class BumpSequenceOpFrame(OperationFrame):
    op_type = OperationType.BUMP_SEQUENCE

    def threshold_level(self) -> int:
        return ThresholdLevel.LOW

    def is_version_supported(self, ledger_version: int) -> bool:
        # introduced in protocol 10 (reference BumpSequenceOpFrame::
        # isVersionSupported)
        return ledger_version >= 10

    def do_check_valid(self, header) -> bool:
        if self.op.body.value.bumpTo < 0:
            return self.set_inner(BumpSequenceResultCode.BAD_SEQ)
        return self.set_inner(BumpSequenceResultCode.SUCCESS)

    def do_apply(self, ltx) -> bool:
        bump_to = self.op.body.value.bumpTo
        src = load_account(ltx, self.source_account_id())
        if bump_to > src.data.value.seqNum:
            src.data.value.seqNum = bump_to
        return self.set_inner(BumpSequenceResultCode.SUCCESS)
