"""PersistentState: durable key/value node state in the DB.

Role parity: reference `src/main/PersistentState.h` — LCL, SCP state,
force-SCP flag, history-archive state, DB schema version.
"""

from __future__ import annotations

from typing import Optional

from ..database.database import Database


class PersistentState:
    kLastClosedLedger = "lastclosedledger"
    kHistoryArchiveState = "historyarchivestate"
    kForceSCPOnNextLaunch = "forcescponnextlaunch"
    kLastSCPData = "scphistory"
    kDatabaseSchema = "databaseschema"
    kNetworkPassphrase = "networkpassphrase"
    kLedgerUpgrades = "ledgerupgrades"

    def __init__(self, db: Database) -> None:
        self._db = db

    def get_state(self, key: str) -> Optional[str]:
        return self._db.get_state(key)

    def set_state(self, key: str, value: str) -> None:
        self._db.set_state(key, value)
        self._db.commit()

    def set_force_scp(self, on: bool) -> None:
        self.set_state(self.kForceSCPOnNextLaunch,
                       "true" if on else "false")

    def get_force_scp(self) -> bool:
        return self.get_state(self.kForceSCPOnNextLaunch) == "true"
