"""Fuzz harness: mutated-XDR smoke fuzzing of the two untrusted intake
surfaces (VERDICT r2 #7).

Role parity: reference AFL harness `src/test/FuzzerImpl.cpp` with `tx` and
`overlay` modes (docs/fuzzing.md; CLI gen-fuzz/fuzz,
CommandLine.cpp:1086-1087). Signature checks short-circuit like
`src/transactions/SignatureChecker.cpp:33-35` so the fuzzer gets past
crypto. This is an in-process mutational fuzzer (AFL itself is not part of
this stack): deterministic PRNG, byte flips / truncations / splices over a
seed corpus of valid messages, asserting the node never throws on hostile
bytes — malformed input must be REJECTED, not crash.

Invariant on both paths: every exception type escaping the parse/dispatch
boundary is a bug; XDR decode errors are expected and counted.
"""

from __future__ import annotations

import random
from typing import Dict, List

from ..crypto.keys import SecretKey
from ..transactions.signature_checker import set_fuzzing_mode
from ..xdr import TransactionEnvelope


def _mutate(r: random.Random, data: bytes) -> bytes:
    buf = bytearray(data)
    for _ in range(r.randint(1, 8)):
        op = r.randrange(5)
        if not buf:
            buf = bytearray(r.randbytes(r.randint(1, 64)))
            continue
        if op == 0:      # bit flip
            buf[r.randrange(len(buf))] ^= 1 << r.randrange(8)
        elif op == 1:    # byte set
            buf[r.randrange(len(buf))] = r.randrange(256)
        elif op == 2:    # truncate
            buf = buf[:r.randrange(len(buf)) + 1]
        elif op == 3:    # insert junk
            i = r.randrange(len(buf) + 1)
            buf[i:i] = r.randbytes(r.randint(1, 16))
        else:            # interesting 32-bit value splice
            v = r.choice([0, 1, 0x7FFFFFFF, 0x80000000, 0xFFFFFFFF])
            i = r.randrange(max(1, len(buf) - 3))
            buf[i:i + 4] = v.to_bytes(4, "big")
    return bytes(buf)


def _tx_corpus(led, root) -> List[bytes]:
    """Valid signed envelopes whose source accounts EXIST on the fuzz
    ledger, so unmutated inputs reach apply (and mutated ones exercise
    checkValid/fee/seq/apply, not just the missing-account early-out)."""
    alice = root.create(10**9)
    sponsor = root.create(10**9)
    sk = SecretKey.from_seed(b"\x21" * 32)
    frames = [
        alice.tx([alice.op_payment(root.account_id, 1234)], seq=alice.next_seq()),
        alice.tx([alice.op_create_account(sk.public_key, 10**8)],
                 seq=alice.next_seq()),
        alice.tx([alice.op_manage_data("k", b"v"),
                  alice.op_payment(root.account_id, 1)],
                 seq=alice.next_seq()),
    ]
    # fee-bump envelope: the outer-union decode path mutates differently
    from ..transactions.transaction_frame import FeeBumpTransactionFrame
    from ..xdr import (EnvelopeType, FeeBumpTransaction,
                       FeeBumpTransactionEnvelope, _Ext)
    from ..xdr.transaction import _InnerTxEnvelope
    inner = alice.tx([alice.op_payment(root.account_id, 9)],
                     seq=alice.next_seq())
    fb = FeeBumpTransaction(
        feeSource=sponsor.muxed, fee=1000,
        innerTx=_InnerTxEnvelope(EnvelopeType.ENVELOPE_TYPE_TX,
                                 inner.envelope.value),
        ext=_Ext.v0())
    bump = FeeBumpTransactionFrame(led.network_id, TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
        FeeBumpTransactionEnvelope(tx=fb, signatures=[])))
    bump.add_signature(sponsor.sk)
    frames.append(bump)
    return [f.envelope.to_xdr() for f in frames]


def fuzz_tx(iterations: int = 10000, seed: int = 1) -> Dict[str, int]:
    """Mutated envelope XDR → decode → frame → checkValid+apply on a test
    ledger (reference TransactionFuzzer role)."""
    from ..testing import TestAccount, TestLedger, root_secret_key
    from ..transactions.transaction_frame import TransactionFrame

    r = random.Random(seed)
    led = TestLedger()
    root = TestAccount(led, root_secret_key())
    corpus = _tx_corpus(led, root)
    stats = {"iterations": 0, "decode_rejects": 0, "frame_rejects": 0,
             "applied": 0}
    set_fuzzing_mode(True)
    try:
        for i in range(iterations):
            stats["iterations"] += 1
            if i % 64 == 0:
                # periodically refresh the corpus with a currently-valid
                # payment so the full fee/seq/apply path stays reachable as
                # the fuzz ledger's sequence numbers advance — but never
                # evict the fee-bump seed (last slot), which covers the
                # outer-union decode path
                corpus[i // 64 % (len(corpus) - 1)] = root.tx(
                    [root.op_payment(root.account_id, 1)]).envelope.to_xdr()
            raw = _mutate(r, r.choice(corpus))
            try:
                env = TransactionEnvelope.from_xdr(raw)
            except Exception:
                stats["decode_rejects"] += 1
                continue
            try:
                frame = TransactionFrame.make_from_wire(led.network_id, env)
            except Exception:
                stats["frame_rejects"] += 1
                continue
            # apply_frame runs checkValid + fee/seq + apply with invariants;
            # any uncaught exception here is a crash finding
            if led.apply_frame(frame):
                stats["applied"] += 1
    finally:
        set_fuzzing_mode(False)
    return stats


def _overlay_corpus(sim, peer) -> tuple:
    """(raw wire frames, StellarMessage XDR blobs) captured from an
    authenticated peer's live traffic."""
    frames: List[bytes] = []
    msgs: List[bytes] = []
    orig_send = peer.transport.send_frame
    orig_dispatch = peer._dispatch

    def cap_send(raw: bytes) -> None:
        frames.append(raw)
        orig_send(raw)

    def cap_dispatch(msg) -> None:
        msgs.append(msg.to_xdr())
        orig_dispatch(msg)

    peer.transport.send_frame = cap_send
    peer._dispatch = cap_dispatch
    sim.crank_all_nodes(300)
    peer.transport.send_frame = orig_send
    peer._dispatch = orig_dispatch
    return frames or [b"\x00" * 40], msgs or [b"\x00" * 12]


def fuzz_overlay(iterations: int = 10000, seed: int = 1) -> Dict[str, int]:
    """Mutated frames into Peer._on_frame on a live authenticated overlay
    connection (reference OverlayFuzzer role): bad MACs, bad XDR, bad
    lengths — the peer may drop, but the node must not throw."""
    from ..simulation import topologies
    from ..simulation.simulation import Simulation

    r = random.Random(seed)
    sim = topologies.core(2, 2, mode=Simulation.OVER_PEERS)
    sim.start_all_nodes()
    assert sim.crank_until(
        lambda: all(
            n.app.overlay_manager.get_authenticated_peers_count() >= 1
            for n in sim.nodes.values()), 30000)
    from ..xdr import StellarMessage

    names = list(sim.nodes)
    node = sim.nodes[names[0]]
    om = node.app.overlay_manager
    peer = list(om.authenticated_peers.values())[0]
    frames, msgs = _overlay_corpus(sim, peer)
    stats = {"iterations": 0, "dropped_reconnects": 0, "net_rebuilds": 0,
             "msg_parse_rejects": 0, "handler_errors": 0}

    def rebuild_net():
        nonlocal sim, node, om
        sim = topologies.core(2, 2, mode=Simulation.OVER_PEERS)
        sim.start_all_nodes()
        sim.crank_until(
            lambda: all(
                n.app.overlay_manager.get_authenticated_peers_count() >= 1
                for n in sim.nodes.values()), 30000)
        names[:] = list(sim.nodes)
        node = sim.nodes[names[0]]
        om = node.app.overlay_manager

    def reconnect() -> bool:
        sim.connect_peers(names[0], names[1])
        sim.crank_until(lambda: bool(om.authenticated_peers), 30000)
        if not om.authenticated_peers:
            # connection state wedged (e.g. stale same-id tiebreak husks
            # after many hostile drops): start a fresh 2-node net
            stats["net_rebuilds"] += 1
            rebuild_net()
        return bool(om.authenticated_peers)

    for i in range(iterations):
        stats["iterations"] += 1
        if i % 8 == 0:
            # frame layer: hostile bytes at the wire — MAC/parse must
            # reject and _on_frame must never raise
            raw = _mutate(r, r.choice(frames))
            peer._on_frame(raw)
            sim.crank_all_nodes(2)
        else:
            # message layer: a well-MAC'd but hostile StellarMessage —
            # the production catch in _on_frame turns handler errors into
            # drops; here we count them (each is a weak-validation signal)
            blob = _mutate(r, r.choice(msgs))
            try:
                msg = StellarMessage.from_xdr(blob)
            except Exception:
                stats["msg_parse_rejects"] += 1
                continue
            try:
                peer._dispatch(msg)
            except Exception:
                stats["handler_errors"] += 1
            sim.crank_all_nodes(2)
        if not om.authenticated_peers:
            stats["dropped_reconnects"] += 1
            if not reconnect():
                break
            peer = list(om.authenticated_peers.values())[0]
    return stats


# -- single-input entry points (reference AFL `fuzz`/`gen-fuzz` contract) ---

def gen_input(mode: str, seed: int = 1) -> bytes:
    """Produce one mutated corpus input file's bytes (reference
    `gen-fuzz`)."""
    r = random.Random(seed)
    if mode == "tx":
        from ..testing import TestAccount, TestLedger, root_secret_key
        led = TestLedger()
        root = TestAccount(led, root_secret_key())
        return _mutate(r, r.choice(_tx_corpus(led, root)))
    from ..xdr import MessageType, StellarMessage
    msg = StellarMessage(MessageType.GET_SCP_QUORUMSET, b"\x00" * 32)
    return _mutate(r, msg.to_xdr())


def run_one(mode: str, data: bytes) -> Dict[str, int]:
    """Run ONE fuzz input and exit (reference `fuzz` single-input AFL
    contract): decode + dispatch; any escape of the parse boundary is a
    crash finding (exception propagates)."""
    stats = {"iterations": 1, "decode_rejects": 0, "applied": 0,
             "handler_errors": 0}
    set_fuzzing_mode(True)
    try:
        if mode == "tx":
            from ..testing import TestAccount, TestLedger, root_secret_key
            from ..transactions.transaction_frame import TransactionFrame
            led = TestLedger()
            root = TestAccount(led, root_secret_key())
            try:
                env = TransactionEnvelope.from_xdr(data)
                frame = TransactionFrame.make_from_wire(
                    led.network_id, env)
            except Exception:
                stats["decode_rejects"] += 1
                return stats
            if led.apply_frame(frame):
                stats["applied"] += 1
            return stats
        # overlay: decode then DISPATCH through a live authenticated peer,
        # mirroring fuzz_overlay's message-layer path so a crash found
        # there reproduces from its input file
        from ..simulation import topologies
        from ..simulation.simulation import Simulation
        from ..xdr import StellarMessage
        try:
            msg = StellarMessage.from_xdr(data)
        except Exception:
            stats["decode_rejects"] += 1
            return stats
        sim = topologies.core(2, 2, mode=Simulation.OVER_PEERS)
        sim.start_all_nodes()
        assert sim.crank_until(
            lambda: all(
                n.app.overlay_manager.get_authenticated_peers_count() >= 1
                for n in sim.nodes.values()), 30000)
        node = sim.nodes[list(sim.nodes)[0]]
        peer = list(node.app.overlay_manager
                    .authenticated_peers.values())[0]
        try:
            peer._dispatch(msg)
        except Exception:
            stats["handler_errors"] += 1
        sim.crank_all_nodes(5)
        return stats
    finally:
        set_fuzzing_mode(False)
