"""Application: ownership and wiring of every subsystem.

Role parity: reference `src/main/Application.h:127-219` /
`ApplicationImpl.cpp` — one Application owns one of each manager; the
managers interact only through the Application facade. start() mirrors
ApplicationImpl::start (ApplicationImpl.cpp:360-464): load LCL → restore
herder state → start overlay/maintenance → resume publishes → optional
FORCE_SCP bootstrap.
"""

from __future__ import annotations

from typing import Optional

from ..crypto.batch_verifier import make_verifier
from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..database.database import Database
from ..invariant.invariants import InvariantManager
from ..ledger.ledger_manager import LedgerManager
from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.timer import ClockMode, VirtualClock
from .config import Config
from .persistent_state import PersistentState

log = get_logger("Ledger")


class AppState:
    APP_CREATED = 0
    APP_ACQUIRING_CONSENSUS = 1
    APP_SYNCED = 2
    APP_STOPPING = 3


class Application:
    def __init__(self, clock: VirtualClock, config: Config) -> None:
        self.clock = clock
        self.config = config
        self.state = AppState.APP_CREATED
        self.metrics = MetricsRegistry(now_fn=clock.now)
        from ..util.status_manager import StatusManager
        self.status_manager = StatusManager()

        # span tracer + flight recorder (util/tracing.py): constructed
        # before every subsystem so each can hold a direct reference;
        # disabled tracing costs one attribute check per span site
        from ..util.tracing import FlightRecorder, Tracer
        self.tracer = Tracer(capacity=config.TRACE_CAPACITY)
        if config.TRACE_ENABLED:
            self.tracer.enable()
        self.flight_recorder = FlightRecorder(
            self.tracer, metrics=self.metrics,
            out_dir=config.FLIGHT_RECORDER_DIR or None,
            node_name=config.node_name(), now_fn=clock.now)

        # per-slot consensus event journal (util/slot_timeline.py):
        # always on (one dict append per event), fed by SCP/herder/ledger
        # hooks and merged fleet-wide by util/fleet.py
        from ..util.slot_timeline import SlotTimeline
        self.slot_timeline = SlotTimeline(
            now_fn=clock.now, max_slots=config.SLOT_TIMELINE_SLOTS)

        # node footprint census (util/footprint.py, ISSUE 19): every
        # bounded structure below registers and self-reports occupancy /
        # capacity — the per-node overhead table behind the admin
        # `footprint` endpoint and the --fleet-scale N-vs-RSS curve
        from ..util.footprint import BoundedStructRegistry
        self.footprint = BoundedStructRegistry(
            metrics=self.metrics, now_fn=clock.now,
            node_name=config.node_name())

        # fault injector (util/faults.py): armed from config and/or the
        # SCT_FAULTS env spec; every subsystem reaches it through
        # app.faults (or a direct reference installed below), and an
        # unconfigured injector is a dict miss per check
        import os as _os
        from ..util.faults import KNOWN_SITES, FaultInjector
        self.faults = FaultInjector(
            seed=int(_os.environ.get("SCT_FAULTS_SEED",
                                     config.FAULTS_SEED)),
            metrics=self.metrics, tracer=self.tracer)
        for site, d in config.FAULTS.items():
            if site not in KNOWN_SITES:
                # operator-facing like the env spec and the admin
                # endpoint: a typo'd config table must kill the node at
                # startup, not soak a chaos run fault-free
                raise ValueError(
                    "unknown fault site %r in FAULTS config; known "
                    "sites: %s" % (site, ", ".join(sorted(KNOWN_SITES))))
            self.faults.configure(
                site, probability=float(d.get("p", 1.0)),
                count=d.get("n"), after=int(d.get("after", 0)))
        env_spec = _os.environ.get("SCT_FAULTS")
        if env_spec:
            self.faults.configure_from_spec(env_spec)

        # database (None in pure in-memory test mode)
        if config.DATABASE == "in-memory":
            self.database: Optional[Database] = None
        elif config.DATABASE.startswith("sqlite3://"):
            self.database = Database(config.DATABASE[len("sqlite3://"):],
                                     self.metrics)
        else:
            self.database = Database(config.DATABASE, self.metrics)
        self.persistent_state = (PersistentState(self.database)
                                 if self.database else None)

        # crypto backend (config-gated; the TPU boundary); device
        # backends sit behind a circuit breaker with a CPU fallback
        self.sig_verifier = make_verifier(
            config.SIG_VERIFY_BACKEND, clock,
            config.SIG_VERIFY_MAX_BATCH,
            config.SIG_VERIFY_COMPILE_CACHE_DIR,
            metrics=self.metrics, tracer=self.tracer,
            faults=self.faults, flight_recorder=self.flight_recorder,
            breaker_threshold=config.SIG_VERIFY_BREAKER_THRESHOLD,
            breaker_cooldown=config.SIG_VERIFY_BREAKER_COOLDOWN)

        # batched SHA-256 boundary (crypto/batch_hasher.py, ISSUE 12):
        # the hashing twin of the verifier — config-gated device
        # backend behind the same breaker knobs, one HasherStats
        # cockpit behind the admin `hasher` endpoint
        from ..crypto.batch_hasher import make_hasher
        self.batch_hasher = make_hasher(
            config.HASH_BACKEND, clock=clock,
            compile_cache_dir=config.SIG_VERIFY_COMPILE_CACHE_DIR,
            metrics=self.metrics, tracer=self.tracer,
            faults=self.faults, flight_recorder=self.flight_recorder,
            breaker_threshold=config.SIG_VERIFY_BREAKER_THRESHOLD,
            breaker_cooldown=config.SIG_VERIFY_BREAKER_COOLDOWN)

        self.invariant_manager = InvariantManager(self.metrics)
        for pattern in config.INVARIANT_CHECKS:
            self.invariant_manager.enable(pattern)

        # downstream close-meta stream (reference METADATA_OUTPUT_STREAM,
        # LedgerManagerImpl.cpp:590,673-678): opened before the first
        # close so no record is ever skipped
        self.close_meta_stream = None
        if config.METADATA_OUTPUT_STREAM:
            from ..ledger.close_meta_stream import CloseMetaStream
            self.close_meta_stream = CloseMetaStream(
                config.METADATA_OUTPUT_STREAM)

        self.bucket_manager = None   # wired in enable_buckets()
        self.history_manager = None  # wired by history layer
        self.catchup_manager = None
        self.overlay_manager = None  # real OverlayManager unless simulated
        self.ledger_manager = LedgerManager(self)

        # state commitments (ledger/state_commitment.py, ISSUE 12):
        # incremental Merkle root over the bucket list + signed
        # light-client checkpoints; active once buckets are enabled
        from ..ledger.state_commitment import StateCommitmentEngine
        self.state_commitment = StateCommitmentEngine(self)

        from ..herder.herder import Herder
        if config.QUORUM_SET is None:
            config.QUORUM_SET = config.self_qset()
        self.herder = Herder(self)

        from ..overlay.overlay_manager import OverlayManager
        self.overlay_manager = OverlayManager(self)

        from ..work.scheduler import WorkScheduler
        self.work_scheduler = WorkScheduler(self.clock)
        from ..process.process_manager import ProcessManager
        self.process_manager = ProcessManager(
            self.clock, config.MAX_CONCURRENT_SUBPROCESSES)

        from ..history.history_manager import HistoryManager
        self.history_manager = HistoryManager(self)
        from ..catchup.catchup_manager import CatchupManager
        self.catchup_manager = CatchupManager(self)

        from .command_handler import CommandHandler
        self.command_handler = CommandHandler(self)
        from .maintainer import ExternalQueue, Maintainer
        self.external_queue = ExternalQueue(self)
        self.maintainer = Maintainer(self)

        self._register_footprint()

    def _register_footprint(self) -> None:
        """Enroll every bounded structure in the footprint census
        (ISSUE 19). Names are LITERALS — sctlint's M1 scanner catalogs
        each as `footprint.struct.<name>` against docs/metrics.md, so a
        new bounded structure can't join the census undocumented."""
        fp = self.footprint
        tl = self.slot_timeline
        fp.track_struct(
            "slot-timeline", "ring",
            lambda: tl.max_slots * tl.max_events_per_slot,
            lambda: sum(len(evs) for evs in tl._slots.values()),
            lambda: sum(len(evs) for evs in tl._slots.values()) * 160)
        lc = self.herder.tx_lifecycle
        fp.track_struct(
            "tx-lifecycle", "map",
            lambda: lc.MAX_TRACKED, lambda: len(lc._pending))
        ss = self.herder.scp_stats
        fp.track_struct(
            "scp-slots", "ring",
            lambda: ss.MAX_SLOTS, lambda: len(ss._slots))
        fp.track_struct(
            "scp-peers", "map",
            lambda: ss.MAX_PEERS, lambda: len(ss.peers))
        ing = self.herder.ingress
        if ing is not None:
            fp.track_struct(
                "ingress-intake", "deque",
                lambda: ing.intake_depth, lambda: ing._intake_total)
            fp.track_struct(
                "ingress-sources", "cache",
                lambda: ing._sources._max, lambda: len(ing._sources))
        ov = self.overlay_manager
        ps = getattr(ov, "prop_stats", None)
        if ps is not None:
            fp.track_struct(
                "prop-hashes", "lru",
                lambda: ps.MAX_HASHES, lambda: len(ps._hashes))
            fp.track_struct(
                "prop-peers", "map",
                lambda: ps.MAX_PEERS, lambda: len(ps.peers))
        cfg = self.config
        fp.track_struct(
            "send-queues", "bytes",
            lambda: cfg.PEER_SEND_QUEUE_LIMIT_BYTES *
            max(1, ov.num_connections()),
            lambda: ov.send_queue_depth()[0],
            lambda: ov.send_queue_depth()[0])
        from ..crypto import keys as _keys
        fp.track_struct(
            "verify-cache", "cache",
            lambda: _keys._verify_cache._max,
            lambda: len(_keys._verify_cache),
            lambda: len(_keys._verify_cache) * 96)
        root = self.ledger_manager.root
        cache = getattr(root, "_cache", None)
        if cache is not None:
            fp.track_struct(
                "entry-cache", "lru",
                lambda: cache._max, lambda: len(cache),
                lambda: len(cache) * 256)

        # -- B1 enrollments (ISSUE 20): every long-lived container the
        # bounded-memory dataflow rule flags is census-tracked here with
        # a declared budget, so growth past the budget surfaces as
        # `over_capacity` in soaks instead of silent RSS creep. Budgets
        # are vocabulary bounds (metric/op/outcome names) or generous
        # operational ceilings, not hard invariants of the code.
        pe = self.herder.pending
        fp.track_struct(
            "pending-txsets", "map",
            lambda: 4096, lambda: len(pe.txsets) + len(pe.qsets))
        fp.track_struct(
            "pending-slot-sets", "map",
            lambda: 16384,
            lambda: sum(len(s) for s in pe.processed.values()) +
            sum(len(s) for s in pe.discarded.values()))
        hd = self.herder
        fp.track_struct(
            "scp-timers", "map",
            # (slot, timer_id) keys; erase_below GC plus the validity
            # bracket bound how many slots hold live timers
            lambda: hd.LEDGER_VALIDITY_BRACKET * 8,
            lambda: len(hd._scp_timers))
        qt = self.herder.quorum_tracker
        fp.track_struct(
            "quorum-tracker", "map",
            lambda: 4096, lambda: len(qt._quorum))
        lc2 = self.herder.tx_lifecycle
        fp.track_struct(
            "tx-outcome-meters", "map",
            lambda: 64, lambda: len(lc2._m_outcome))
        st = self.ledger_manager.apply_stats
        fp.track_struct(
            "apply-meters", "map",
            lambda: 512,
            lambda: len(st._m_lookup) + len(st._m_op) +
            len(st._h_op) + len(st._g_level))
        mreg = self.metrics
        fp.track_struct(
            "metrics-registry", "map",
            lambda: 4096, lambda: len(mreg._metrics))
        fp.track_struct(
            "footprint-gauges", "map",
            lambda: fp.MAX_STRUCTS, lambda: len(fp._g_occ))
        fr = self.flight_recorder
        fp.track_struct(
            "flight-dump-marks", "map",
            lambda: 64, lambda: len(fr._last_dump_at))
        im = self.invariant_manager
        fp.track_struct(
            "invariants", "map",
            lambda: 64, lambda: len(im._registered))
        hm = self.history_manager
        fp.track_struct(
            "history-archives", "map",
            lambda: 64, lambda: len(hm.archives))
        ws = self.work_scheduler
        fp.track_struct(
            "work-roots", "list",
            lambda: 1024, lambda: len(ws._roots))
        ost = getattr(ov, "stats", None)
        if ost is not None:
            fp.track_struct(
                "overlay-type-meters", "map",
                lambda: 256,
                lambda: len(ost._m_type) + len(ost._t_backend))
        pm = getattr(ov, "peer_manager", None)
        if pm is not None:
            fp.track_struct(
                "peer-records", "map",
                lambda: 16384, lambda: len(pm._peers))
        sv = getattr(ov, "survey_manager", None)
        if sv is not None:
            fp.track_struct(
                "survey-state", "map",
                lambda: 16384,
                lambda: len(sv._limiter) + len(sv._surveyed) +
                len(sv.results))

    # -- identity ------------------------------------------------------------
    def network_root_key(self) -> SecretKey:
        """Deterministic genesis root key derived from the network id."""
        return SecretKey.from_seed(sha256(self.config.network_id))

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        # AOT kernel warmup on a background thread: every bucket shape is
        # compiled (or loaded from the persistent cache) before the first
        # envelope can trigger a lazy compile on the consensus path
        if self.config.SIG_VERIFY_WARMUP and \
                getattr(self.sig_verifier, "wants_prewarm", False):
            self.sig_verifier.warmup(wait=False)
        # the hash kernel warms beside the verify kernel: same
        # persistent XLA cache, same no-lazy-compile-on-consensus rule
        if self.config.SIG_VERIFY_WARMUP and \
                getattr(self.batch_hasher, "wants_warmup", False):
            self.batch_hasher.warmup(wait=False)
        lm = self.ledger_manager
        if not lm.load_last_known_ledger():
            lm.start_new_ledger()
        self.herder.restore_scp_state()
        if self.overlay_manager is not None and \
                not self.config.RUN_STANDALONE:
            self.overlay_manager.start()
        if self.history_manager is not None:
            self.history_manager.publish_queued_history()
        self.maintainer.start()
        force = self.config.FORCE_SCP or (
            self.persistent_state is not None and
            self.persistent_state.get_force_scp())
        if force and self.config.NODE_IS_VALIDATOR:
            self.herder.bootstrap()
            self.state = AppState.APP_SYNCED
        else:
            self.state = AppState.APP_ACQUIRING_CONSENSUS

    def crank(self, block: bool = False) -> int:
        n = self.clock.crank(block)
        # dispatch any signature verifies accumulated during this crank's
        # handlers (coalesced: one device batch per burst; no-op when empty)
        self.sig_verifier.flush()
        return n

    def crank_until(self, pred, max_cranks: int = 100000) -> bool:
        # every crank path must flush the batch verifier: an enqueue site
        # that doesn't self-flush would otherwise never complete here
        for _ in range(max_cranks):
            if pred():
                return True
            self.crank(False)
        return pred()

    def stop(self) -> None:
        self.state = AppState.APP_STOPPING
        # persist the cockpit-derived warmup bucket plan beside the XLA
        # cache (ISSUE 11): the next start warms only the shapes this
        # run's real traffic used. Best-effort no-op on CPU backends or
        # when the cockpit saw no traffic.
        save_plan = getattr(self.sig_verifier, "save_warmup_plan", None)
        if save_plan is not None:
            save_plan()
        # interrupt any background quorum-intersection enumeration first:
        # joining that worker can otherwise take minutes (reference
        # HerderImpl.cpp:140-144)
        if self.herder is not None:
            self.herder.interrupt_quorum_intersection()
        self.command_handler.stop_http()
        if self.overlay_manager is not None:
            self.overlay_manager.shutdown()
        self.process_manager.shutdown()
        if self.close_meta_stream is not None:
            self.close_meta_stream.close()

    # -- operations ----------------------------------------------------------
    def manual_close(self) -> None:
        assert self.config.MANUAL_CLOSE, "manualclose requires MANUAL_CLOSE"
        self.herder.trigger_next_ledger(
            self.ledger_manager.last_closed_ledger_num() + 1)
        # drain immediate work without advancing virtual time (future SCP
        # round timers must not fire during a manual close)
        while self.clock.crank_ready():
            pass

    def submit_transaction(self, frame) -> int:
        status = self.herder.recv_transaction(frame)
        if status == 0 and self.overlay_manager is not None:
            from ..xdr import MessageType, StellarMessage
            self.overlay_manager.broadcast_message(
                StellarMessage(MessageType.TRANSACTION, frame.envelope),
                False)
        return status

    @property
    def load_generator(self):
        """Lazy singleton LoadGenerator (admin `generateload`, overload
        scenarios); constructed on first use so apps that never generate
        load pay nothing."""
        if not hasattr(self, "_load_generator"):
            from ..simulation.load_generator import LoadGenerator
            self._load_generator = LoadGenerator(self)
        return self._load_generator

    def enable_buckets(self, bucket_dir: Optional[str] = None) -> None:
        from ..bucket.bucket_index import BucketDbStats
        from ..bucket.bucket_manager import BucketManager
        lm = self.ledger_manager
        self.bucket_manager = BucketManager(
            bucket_dir or self.config.BUCKET_DIR_PATH,
            stats=lm.apply_stats,
            bucketdb_stats=BucketDbStats(metrics=self.metrics,
                                         tracer=self.tracer,
                                         now_fn=self.clock.now),
            faults=self.faults,
            bloom_bits_per_key=self.config.BUCKETDB_BLOOM_BITS_PER_KEY,
            # with reads pinned off nothing consumes the indexes: skip
            # the per-adopt build + sidecar write (lazy build remains)
            eager_index=self.config.BUCKETDB_READS)
        # route SQL-root point reads through BucketDB (ISSUE 14) — only
        # when the bucket list will cover this root's whole entry state:
        # enabled BEFORE start() (genesis seeds the list / restart
        # restores it and detaches on mismatch). A mid-life enable over
        # pre-existing SQL state keeps SQL point reads.
        root = lm.root
        if self.config.BUCKETDB_READS and \
                hasattr(root, "attach_bucketdb") and root._header is None:
            root.attach_bucketdb(self.bucket_manager.bucketdb)

    # -- info ----------------------------------------------------------------
    def get_info(self) -> dict:
        lm = self.ledger_manager
        return {
            "build": self.config.VERSION_STR,
            "network": self.config.NETWORK_PASSPHRASE,
            "ledger": {
                "num": lm.last_closed_ledger_num(),
                "hash": lm.lcl_hash.hex(),
                "version": lm.lcl_header.ledgerVersion,
                "baseFee": lm.lcl_header.baseFee,
                "baseReserve": lm.lcl_header.baseReserve,
                "maxTxSetSize": lm.lcl_header.maxTxSetSize,
                "closeTime": lm.lcl_header.scpValue.closeTime,
            },
            "state": ("Synced!" if self.state == AppState.APP_SYNCED
                      else "Catching up"),
            # per-subsystem rolled-up status lines (reference
            # StatusManager → info "status" array)
            "status": self.status_manager.to_list(),
            "quorum": self.herder.get_json_info(),
        }
