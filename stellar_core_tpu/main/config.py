"""Config: node configuration, parsed from TOML.

Role parity: reference `src/main/Config.{h,cpp}` (~80 knobs; TOML via
cpptoml with validators/quality levels). Python's stdlib tomllib replaces
cpptoml. The knob set covers every subsystem built so far plus the
TPU-specific crypto-backend gate (SIG_VERIFY_BACKEND).
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:       # Python < 3.11: the tomli backport is the
    import tomli as tomllib  # same parser under its pre-stdlib name
from typing import Dict, List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..xdr import PublicKey, SCPQuorumSet


class Config:
    # protocol
    LEDGER_PROTOCOL_VERSION = 13
    OVERLAY_PROTOCOL_VERSION = 12
    OVERLAY_PROTOCOL_MIN_VERSION = 10
    VERSION_STR = "stellar-core-tpu 0.1"

    def __init__(self) -> None:
        # identity / network
        self.NETWORK_PASSPHRASE = "(sct) testing network"
        self.NODE_SEED: Optional[SecretKey] = None
        self.NODE_IS_VALIDATOR = True
        self.NODE_HOME_DOMAIN = ""
        # human-readable node name: flight-recorder filenames, fleet
        # aggregation lanes; defaults to the strkey prefix (node_name())
        self.NODE_NAME = ""
        self.QUORUM_SET: Optional[SCPQuorumSet] = None
        self.UNSAFE_QUORUM = False
        self.FAILURE_SAFETY = -1

        # run modes
        self.RUN_STANDALONE = False
        self.MANUAL_CLOSE = False
        self.FORCE_SCP = False
        self.CATCHUP_COMPLETE = False
        self.CATCHUP_RECENT = 0

        # database / storage
        self.DATABASE = "sqlite3://:memory:"
        self.BUCKET_DIR_PATH = "buckets"
        self.TMP_DIR_PATH = "tmp"
        # BucketDB (bucket/bucket_index.py, ISSUE 14): serve SQL-root
        # point reads from bloom-filtered bucket indexes (SQL stays the
        # write-behind query index). False pins the legacy SQL read
        # path; BLOOM_BITS_PER_KEY sizes the per-bucket filters (10 ≈
        # 1% false-positive rate at optimal k).
        self.BUCKETDB_READS = True
        self.BUCKETDB_BLOOM_BITS_PER_KEY = 10

        # overlay
        self.PEER_PORT = 11625
        self.HTTP_PORT = 11626
        self.PUBLIC_HTTP_PORT = False
        self.KNOWN_PEERS: List[str] = []
        self.PREFERRED_PEERS: List[str] = []
        self.TARGET_PEER_CONNECTIONS = 8
        self.MAX_PENDING_CONNECTIONS = 500
        # connection policy (reference Config.h PREFERRED_PEERS_ONLY /
        # PREFERRED_PEER_KEYS): preferred peers — by address or by strkey
        # node id — always win an authenticated slot (evicting a
        # non-preferred victim at capacity), and strict mode rejects
        # everyone else at authentication
        self.PREFERRED_PEERS_ONLY = False
        self.PREFERRED_PEER_KEYS: List[str] = []
        self.MAX_ADDITIONAL_PEER_CONNECTIONS = -1
        self.PEER_AUTHENTICATION_TIMEOUT = 2.0
        self.PEER_TIMEOUT = 30.0
        self.PEER_STRAGGLER_TIMEOUT = 120.0
        self.MAX_BATCH_WRITE_COUNT = 1024
        self.MAX_BATCH_WRITE_BYTES = 1024 * 1024
        # queued-but-unsent cap per peer; overflowing drops the connection
        self.PEER_SEND_QUEUE_LIMIT_BYTES = 32 * 1024 * 1024
        # per-peer flood-rate defense (overlay/flood_control.py,
        # docs/robustness.md#flood-control): token bucket of
        # FLOOD_RATE_BURST messages refilling at
        # FLOOD_RATE_LIMIT_PER_PEER msgs/s on the app clock; <= 0
        # disables. A message over the limit is dropped unprocessed and
        # scores one ban point; FLOOD_BAN_SCORE_THRESHOLD points (scores
        # halve per ledger close) ban the peer via BanManager.
        self.FLOOD_RATE_LIMIT_PER_PEER = 500.0
        self.FLOOD_RATE_BURST = 5000
        self.FLOOD_BAN_SCORE_THRESHOLD = 500

        # herder
        self.EXPECTED_LEDGER_CLOSE_TIME = 5.0
        self.MAX_SLOTS_TO_REMEMBER = 12
        self.CONSENSUS_STUCK_TIMEOUT_SECONDS = 35.0
        # how far ahead of the current slot SCP envelopes are accepted;
        # beyond it only externalize hints are buffered (recovery path)
        self.LEDGER_VALIDITY_BRACKET = 100
        self.TRANSACTION_QUEUE_PENDING_DEPTH = 4
        self.TRANSACTION_QUEUE_BAN_DEPTH = 10
        self.POOL_LEDGER_MULTIPLIER = 2
        # ingress admission tier (herder/ingress.py, ISSUE 18,
        # docs/robustness.md#ingress--overload): per-source token-bucket
        # rate classes in front of the TransactionQueue. INGRESS_CLASSES
        # is a TOML table of class name -> {rate, burst, max_inflight}
        # overrides merged onto herder.ingress.DEFAULT_CLASSES; the
        # *_ACCOUNTS lists pin strkey account ids to the priority /
        # untrusted classes. INGRESS_ASYNC_INTAKE parks admitted frames
        # in a bounded intake (INGRESS_INTAKE_DEPTH) drained
        # priority-first at each trigger; per-source bucket states are
        # capped at INGRESS_MAX_SOURCES (bounded under 10^6 submitters).
        self.INGRESS_ENABLED = True
        self.INGRESS_ASYNC_INTAKE = False
        self.INGRESS_INTAKE_DEPTH = 512
        self.INGRESS_MAX_SOURCES = 65536
        self.INGRESS_CLASSES: Dict[str, dict] = {}
        self.INGRESS_PRIORITY_ACCOUNTS: List[str] = []
        self.INGRESS_UNTRUSTED_ACCOUNTS: List[str] = []

        # genesis / testing upgrades
        self.GENESIS_TOTAL_COINS = 10**17
        self.TESTING_UPGRADE_DESIRED_FEE = 100
        self.TESTING_UPGRADE_RESERVE = 5_000_000
        self.TESTING_UPGRADE_MAX_TX_SET_SIZE = 100
        self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = False
        self.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING = False

        # history
        self.HISTORY: Dict[str, dict] = {}
        self.CHECKPOINT_FREQUENCY = 64

        # invariants
        self.INVARIANT_CHECKS: List[str] = []

        # workers / process
        self.WORKER_THREADS = 4
        self.MAX_CONCURRENT_SUBPROCESSES = 16

        # conflict-graph parallel close (native/applyc.c, ISSUE 13):
        # disjoint tx clusters apply on worker threads inside the C
        # engine. Workers 0 = auto (min(8, cpu_count)); 1 or
        # NATIVE_PARALLEL_APPLY=False pins the serial native path.
        self.NATIVE_PARALLEL_APPLY = True
        self.NATIVE_PARALLEL_WORKERS = 0
        # pipelined catchup (historywork/apply_works.py): verify ledger
        # N+1's signatures on a worker while ledger N applies
        self.CATCHUP_PIPELINE = True

        # TPU crypto backend gate (this build's headline knob):
        # "cpu" (default, OpenSSL), "tpu" (JAX batched), "tpu-async"
        self.SIG_VERIFY_BACKEND = "cpu"
        self.SIG_VERIFY_MAX_BATCH = 8192
        # AOT-compile all kernel bucket shapes at startup (background
        # thread) so no lazy compile lands on the consensus path
        self.SIG_VERIFY_WARMUP = True
        # persistent XLA compilation cache (None = env or ~/.cache default)
        self.SIG_VERIFY_COMPILE_CACHE_DIR: Optional[str] = None

        # device-dispatch circuit breaker (crypto/batch_verifier.py,
        # docs/robustness.md): consecutive dispatch failures before the
        # verifier trips to the CPU fallback, and how long it stays
        # there before the half-open reprobe
        self.SIG_VERIFY_BREAKER_THRESHOLD = 3
        self.SIG_VERIFY_BREAKER_COOLDOWN = 30.0

        # batched SHA-256 boundary (crypto/batch_hasher.py, ISSUE 12):
        # "cpu" (default, hashlib), "cpu-resilient" (breaker-wrapped CPU,
        # for chaos runs on device-less containers), "tpu" (JAX batched
        # kernel behind the breaker + CPU fallback). The hasher shares
        # the SIG_VERIFY_BREAKER_* knobs and compile-cache dir — one
        # device failure domain, one operator surface.
        self.HASH_BACKEND = "cpu"
        # signed state-checkpoint cadence (ledger/state_commitment.py):
        # a StateCheckpoint {seq, header hash, Merkle root, node sig} is
        # emitted every N closes; <= 0 disables emission (the Merkle
        # root still updates incrementally for the admin endpoint)
        self.STATE_CHECKPOINT_INTERVAL = 8

        # fault injection (util/faults.py, docs/robustness.md): TOML table
        # of site name -> {p, n, after}; merged with the SCT_FAULTS env
        # spec ("site:p=0.5,n=3;site2") at Application construction.
        # FAULTS_SEED keys every site's deterministic schedule.
        self.FAULTS: Dict[str, dict] = {}
        self.FAULTS_SEED = 0

        # observability: span tracer (util/tracing.py). Enabled at
        # startup when True; always toggleable at runtime via the admin
        # `trace` endpoint. Capacity bounds the span ring buffer.
        self.TRACE_ENABLED = False
        self.TRACE_CAPACITY = 16384
        # per-slot consensus event journal (util/slot_timeline.py):
        # always on; bounds how many recent slots are retained
        self.SLOT_TIMELINE_SLOTS = 64
        # propagation cockpit (overlay/propagation_stats.py): causal
        # hop records + per-peer usefulness. On by default; False is the
        # control leg the flood scenario's overhead guard compares
        # against (ISSUE 17 acceptance)
        self.PROPAGATION_STATS_ENABLED = True
        # flight-recorder dump directory ("" = the SCT_FLIGHT_DIR env
        # override, else the system tempdir); dumps fire on unhandled
        # close exceptions and SCP-stall / slow-close watchdog triggers
        self.FLIGHT_RECORDER_DIR = ""

        # maintenance
        self.AUTOMATIC_MAINTENANCE_PERIOD = 359.0
        self.AUTOMATIC_MAINTENANCE_COUNT = 50000

        # downstream-consumer integration: stream one XDR LedgerCloseMeta
        # record per close to this path or "fd:N" (reference
        # Config.h:264 METADATA_OUTPUT_STREAM); "" disables
        self.METADATA_OUTPUT_STREAM = ""

    # -- derived ------------------------------------------------------------
    @property
    def network_id(self) -> bytes:
        return sha256(self.NETWORK_PASSPHRASE.encode())

    def node_id(self) -> PublicKey:
        assert self.NODE_SEED is not None
        return self.NODE_SEED.public_key

    def node_name(self) -> str:
        """Display name: explicit NODE_NAME, else the strkey prefix the
        simulation layer also uses for node naming."""
        if self.NODE_NAME:
            return self.NODE_NAME
        if self.NODE_SEED is not None:
            return self.NODE_SEED.strkey_public()[:5]
        return "node"

    def self_qset(self) -> SCPQuorumSet:
        return SCPQuorumSet(threshold=1, validators=[self.node_id()],
                            innerSets=[])

    # -- loading ------------------------------------------------------------
    @classmethod
    def from_toml(cls, path_or_text: str,
                  is_path: bool = True) -> "Config":
        if is_path:
            with open(path_or_text, "rb") as f:
                data = tomllib.load(f)
        else:
            data = tomllib.loads(path_or_text)
        cfg = cls()
        simple_keys = [
            "NETWORK_PASSPHRASE", "NODE_IS_VALIDATOR", "NODE_HOME_DOMAIN",
            "RUN_STANDALONE", "MANUAL_CLOSE", "FORCE_SCP", "DATABASE",
            "BUCKET_DIR_PATH", "TMP_DIR_PATH", "PEER_PORT", "HTTP_PORT",
            "PUBLIC_HTTP_PORT", "KNOWN_PEERS", "PREFERRED_PEERS",
            "PREFERRED_PEERS_ONLY", "PREFERRED_PEER_KEYS",
            "TARGET_PEER_CONNECTIONS", "UNSAFE_QUORUM", "FAILURE_SAFETY",
            "EXPECTED_LEDGER_CLOSE_TIME", "MAX_SLOTS_TO_REMEMBER",
            "CONSENSUS_STUCK_TIMEOUT_SECONDS", "LEDGER_VALIDITY_BRACKET",
            "INVARIANT_CHECKS", "WORKER_THREADS",
            "MAX_CONCURRENT_SUBPROCESSES", "SIG_VERIFY_BACKEND",
            "SIG_VERIFY_MAX_BATCH", "TRACE_ENABLED", "TRACE_CAPACITY",
            "SLOT_TIMELINE_SLOTS", "PROPAGATION_STATS_ENABLED",
            "NODE_NAME",
            "FLIGHT_RECORDER_DIR", "CHECKPOINT_FREQUENCY",
            "CATCHUP_COMPLETE", "CATCHUP_RECENT",
            "PEER_TIMEOUT", "PEER_STRAGGLER_TIMEOUT",
            "MAX_BATCH_WRITE_COUNT", "MAX_BATCH_WRITE_BYTES",
            "PEER_SEND_QUEUE_LIMIT_BYTES", "METADATA_OUTPUT_STREAM",
            "FLOOD_RATE_LIMIT_PER_PEER", "FLOOD_RATE_BURST",
            "FLOOD_BAN_SCORE_THRESHOLD",
            "SIG_VERIFY_BREAKER_THRESHOLD", "SIG_VERIFY_BREAKER_COOLDOWN",
            "HASH_BACKEND", "STATE_CHECKPOINT_INTERVAL",
            "FAULTS_SEED",
            "BUCKETDB_READS", "BUCKETDB_BLOOM_BITS_PER_KEY",
            "INGRESS_ENABLED", "INGRESS_ASYNC_INTAKE",
            "INGRESS_INTAKE_DEPTH", "INGRESS_MAX_SOURCES",
            "INGRESS_PRIORITY_ACCOUNTS", "INGRESS_UNTRUSTED_ACCOUNTS",
        ]
        for k in simple_keys:
            if k in data:
                setattr(cfg, k, data[k])
        if "NODE_SEED" in data:
            cfg.NODE_SEED = SecretKey.from_strkey_seed(data["NODE_SEED"])
        if "QUORUM_SET" in data:
            cfg.QUORUM_SET = cls._parse_qset(data["QUORUM_SET"])
        if "HISTORY" in data:
            cfg.HISTORY = data["HISTORY"]
        if "FAULTS" in data:
            cfg.FAULTS = data["FAULTS"]
        if "INGRESS_CLASSES" in data:
            cfg.INGRESS_CLASSES = data["INGRESS_CLASSES"]
        cfg.validate()
        return cfg

    @staticmethod
    def _parse_qset(d: dict) -> SCPQuorumSet:
        from ..crypto import strkey
        validators = [PublicKey.ed25519(strkey.decode_public_key(v))
                      for v in d.get("VALIDATORS", [])]
        inner = [Config._parse_qset(i) for i in d.get("INNER_SETS", [])]
        n = len(validators) + len(inner)
        if "THRESHOLD_PERCENT" in d:   # reference config convention
            pct = int(d["THRESHOLD_PERCENT"])
            threshold = max(1, -(-n * pct // 100))  # ceil
        else:
            threshold = d.get("THRESHOLD", n)
        return SCPQuorumSet(threshold=threshold, validators=validators,
                            innerSets=inner)

    def validate(self) -> None:
        if self.NODE_IS_VALIDATOR and self.NODE_SEED is None:
            raise ValueError("validator requires NODE_SEED")
        if self.QUORUM_SET is not None and not self.UNSAFE_QUORUM:
            q = self.QUORUM_SET
            n = len(q.validators) + len(q.innerSets)
            if n > 0 and q.threshold < (n + 1) // 2:
                raise ValueError(
                    "quorum threshold below majority is unsafe; set "
                    "UNSAFE_QUORUM=true to override")

    @classmethod
    def test_config(cls, n: int = 0,
                    backend: str = "cpu") -> "Config":
        """Per-instance deterministic test config (reference getTestConfig,
        src/test/test.cpp:80-131)."""
        cfg = cls()
        cfg.NODE_SEED = SecretKey.from_seed(
            sha256(b"test-node-%d" % n))
        cfg.RUN_STANDALONE = True
        cfg.MANUAL_CLOSE = True
        cfg.FORCE_SCP = True
        cfg.UNSAFE_QUORUM = True
        cfg.DATABASE = "in-memory"
        cfg.QUORUM_SET = cfg.self_qset()
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.SIG_VERIFY_BACKEND = backend
        cfg.PEER_PORT = 17000 + n
        cfg.HTTP_PORT = 18000 + n
        return cfg
