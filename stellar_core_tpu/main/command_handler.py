"""CommandHandler: the operator admin API.

Role parity: reference `src/main/CommandHandler.cpp:77-105` — HTTP
endpoints `info`, `metrics`, `peers`, `quorum`, `scp`, `tx`,
`manualclose`, `upgrades`, `ll`, `bans`, `ban`, `unban`, `connect`,
`droppeer`, `maintenance`, `dropcursor`, `setcursor`, `getcursor`,
plus test-only `generateload`. Command dispatch is a pure function
(`handle_command`) so the CLI, tests, and the HTTP server share one
implementation; the HTTP server executes each command on the main loop
(the reference's single-threaded-consensus invariant,
docs/architecture.md:23-26).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..util.log import get_log_levels, get_logger, set_log_level

log = get_logger("Overlay")


class CommandParamError(ValueError):
    """A malformed request parameter: surfaces as a 400 with an error
    dict instead of a 500 stack trace out of the HTTP thread."""


def _int_param(params: Dict[str, str], key: str,
               default: Optional[int] = None,
               minimum: Optional[int] = None) -> Optional[int]:
    """Validated numeric query param: non-numeric or below-minimum
    values raise CommandParamError (-> 400) rather than ValueError deep
    inside a handler."""
    raw = params.get(key)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except (TypeError, ValueError):
        raise CommandParamError(
            "parameter %r must be an integer, got %r" % (key, raw))
    if minimum is not None and v < minimum:
        raise CommandParamError(
            "parameter %r must be >= %d, got %d" % (key, minimum, v))
    return v


def _float_param(params: Dict[str, str], key: str,
                 default: Optional[float] = None,
                 minimum: Optional[float] = None,
                 maximum: Optional[float] = None) -> Optional[float]:
    raw = params.get(key)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except (TypeError, ValueError):
        raise CommandParamError(
            "parameter %r must be a number, got %r" % (key, raw))
    if v != v:   # NaN compares false against any bound
        raise CommandParamError("parameter %r must not be NaN" % key)
    if minimum is not None and v < minimum:
        raise CommandParamError(
            "parameter %r must be >= %g, got %g" % (key, minimum, v))
    if maximum is not None and v > maximum:
        raise CommandParamError(
            "parameter %r must be <= %g, got %g" % (key, maximum, v))
    return v


class CommandHandler:
    def __init__(self, app) -> None:
        self.app = app
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- dispatch ------------------------------------------------------------
    def handle_command(self, name: str,
                       params: Dict[str, str]) -> Tuple[int, object]:
        """Returns (http_status, body) — body is a JSON-serializable
        dict, or a plain string served as text/plain (the Prometheus
        exposition path)."""
        fn = getattr(self, "cmd_" + name.replace("-", "_"), None)
        if fn is None:
            return 404, {"error": "unknown command %r" % name,
                         "commands": self.command_names()}
        try:
            return 200, fn(params)
        except CommandParamError as e:
            return 400, {"error": str(e)}
        except Exception as e:
            return 500, {"error": "%s: %s" % (type(e).__name__, e)}

    def command_names(self):
        return sorted(m[len("cmd_"):].replace("_", "-")
                      for m in dir(self) if m.startswith("cmd_"))

    # -- introspection -------------------------------------------------------
    def cmd_info(self, params) -> dict:
        info = self.app.get_info()
        lm = self.app.ledger_manager
        info["history"] = {
            "published_checkpoints":
                self.app.history_manager.published_checkpoints,
            "publish_queue_length":
                len(self.app.history_manager.publish_queue()),
        }
        cm = self.app.catchup_manager
        info["catchup"] = {
            "running": cm.catchup_running(),
            "buffered": cm.buffered_count(),
            "started": cm.catchups_started,
        }
        info["ledger"]["synced"] = lm.is_synced()
        return info

    def cmd_metrics(self, params):
        """`metrics[?filter=<prefix>][&format=prometheus]` — with a
        filter, only metrics whose name starts with the prefix are
        serialized (operators and tests fetch `crypto.` or `ledger.`
        without paying for the registry); `format=prometheus` renders
        the same export in text exposition format for standard scrapers
        (docs/metrics.md#prometheus-exposition)."""
        prefix = params.get("filter") or None
        out = self.app.metrics.to_json(prefix=prefix)
        # crypto-boundary metrics live outside the registry (global cache,
        # per-verifier counters); merge them in medida-style names
        from ..crypto import keys as _keys
        cache = _keys.verify_cache_stats()
        out["crypto.verify.cache-hit"] = {"count": cache["hits"]}
        out["crypto.verify.cache-miss"] = {"count": cache["misses"]}
        v = getattr(self.app, "sig_verifier", None)
        inner = getattr(v, "inner", v)
        if inner is not None and hasattr(inner, "batches_dispatched"):
            out["crypto.verify.batch-dispatch"] = {
                "count": inner.batches_dispatched}
            out["crypto.verify.sigs"] = {"count": inner.sigs_verified}
        if prefix:
            out = {k: v2 for k, v2 in out.items() if k.startswith(prefix)}
        if params.get("format") == "prometheus":
            # HELP text sourced from the docs/metrics.md catalog (the
            # M1-guarded one), falling back to the metric name — real
            # Prometheus/Grafana setups get self-describing scrapes
            from ..util.metrics import load_help_catalog, render_prometheus
            return render_prometheus(out,
                                     help_catalog=load_help_catalog())
        return out

    def cmd_verifier(self, params) -> dict:
        """Device cockpit (ISSUE 6 tentpole;
        docs/observability.md#device-cockpit): the batch-verify
        boundary's operational state in one JSON blob — per-bucket
        occupancy/pad-waste histograms, drain attribution by serving
        backend, per-device fleet rows (drain/sig/pad attribution,
        inflight, breaker ring), double-buffer staging overlap,
        compile-cache + per-bucket warmup status (app-clock stamped,
        with the warm-start plan source), queue depth/inflight/
        queue-wait, breaker state, and the verify-cache counters. The
        same data is scrapeable as `sct_verifier_*` series via
        `metrics?format=prometheus`."""
        v = getattr(self.app, "sig_verifier", None)
        if v is None:
            return {"error": "no signature verifier wired"}
        out: dict = {
            "configured_backend": self.app.config.SIG_VERIFY_BACKEND,
            "verifier": v.name,
        }
        stats = getattr(v, "stats", None)
        if stats is not None:
            out.update(stats.to_json())
        breaker = getattr(v, "breaker", None)
        if breaker is not None:
            out["breaker"] = breaker.to_json()
        inner = getattr(v, "inner", v)
        # fleet rows (ISSUE 11): per-device breaker ring of the device
        # backend, read without forcing a jax device resolve — the
        # per-device drain/inflight attribution itself rides in
        # stats.to_json()["devices"] above
        fleet = getattr(inner, "_fleet_health", None)
        if fleet is not None:
            out["fleet"] = fleet.to_json()
        out["counters"] = {
            "batches_dispatched": getattr(inner, "batches_dispatched", 0),
            "sigs_verified": getattr(inner, "sigs_verified", 0),
            "pending": v.pending(),
        }
        from ..crypto import keys as _keys
        out["cache"] = _keys.verify_cache_stats()
        return out

    def cmd_hasher(self, params) -> dict:
        """Hash cockpit (ISSUE 12 tentpole;
        docs/observability.md#hash-cockpit): the batch-hash boundary's
        operational state in one JSON blob — per-drain batch-shape /
        pad-waste / occupancy histograms, per-(lanes×blocks) bucket
        dispatch stats, drain attribution by serving backend AND by
        close-path call site (txset / result-set / header /
        bucket-entries / …), double-buffer staging overlap,
        compile-cache + per-shape warmup status, oversize split-outs,
        and the breaker state. The same data is scrapeable as
        `sct_hasher_*` series via `metrics?format=prometheus`."""
        h = getattr(self.app, "batch_hasher", None)
        if h is None:
            return {"error": "no batch hasher wired"}
        out: dict = {
            "configured_backend": self.app.config.HASH_BACKEND,
            "hasher": h.name,
        }
        stats = getattr(h, "stats", None)
        if stats is not None:
            out.update(stats.to_json())
        breaker = getattr(h, "breaker", None)
        if breaker is not None:
            out["breaker"] = breaker.to_json()
        return out

    def cmd_checkpoint(self, params) -> dict:
        """State checkpoints (ISSUE 12;
        docs/observability.md#hash-cockpit): `checkpoint[?seq=N]
        [&entry=<hex LedgerKey XDR>]`. With no params, the latest
        signed StateCheckpoint {ledger seq, header hash, Merkle root,
        node signature}; `seq=N` returns that exact checkpoint from the
        ring. `entry=` additionally serves a Merkle membership proof
        for that ledger entry against the current commitment root —
        `light_client_verify(proof, checkpoint, network_id)` then
        verifies authenticity with no replay and no ledger DB."""
        sce = getattr(self.app, "state_commitment", None)
        bm = getattr(self.app, "bucket_manager", None)
        if sce is None or bm is None:
            return {"error": "state commitments require buckets enabled"}
        seq = _int_param(params, "seq", None, minimum=1)
        cp = sce.checkpoint(seq)
        out: dict = {
            "checkpoint": cp,
            "root": sce.root.hex() if sce.root is not None else None,
            "interval": self.app.config.STATE_CHECKPOINT_INTERVAL,
            "retained": len(sce.checkpoints),
        }
        if cp is None:
            out["error"] = ("no checkpoint for seq %d in the ring" % seq
                            if seq is not None else
                            "no checkpoint emitted yet")
        entry = params.get("entry")
        if entry:
            # proofs are built against the LATEST checkpoint's frozen
            # view — pairing one with an older (or evicted/never-
            # emitted) ring seq would hand a light client a
            # (proof, checkpoint) pair that can never verify, so any
            # non-latest seq+entry combination is a 400, never a
            # silent trap
            latest = sce.checkpoint()
            if seq is not None and (
                    latest is None or cp is None or
                    cp["ledger_seq"] != latest["ledger_seq"]):
                raise CommandParamError(
                    "entry proofs are served against the latest "
                    "checkpoint%s; request them without 'seq'"
                    % ("" if latest is None else
                       " (seq %d)" % latest["ledger_seq"]))
            from ..xdr import LedgerKey
            try:
                key = LedgerKey.from_xdr(bytes.fromhex(entry))
            except Exception:
                raise CommandParamError(
                    "parameter 'entry' must be a hex-encoded LedgerKey "
                    "XDR, got %r" % entry)
            proof = sce.prove_entry(key, bm.bucket_list)
            out["proof"] = proof
            if proof is None:
                out["proof_error"] = \
                    "entry not live in the bucket list"
        return out

    def cmd_applystats(self, params) -> dict:
        """Close cockpit (ISSUE 9 tentpole;
        docs/observability.md#close-cockpit): the apply path's
        operational state in one JSON blob — per-op-type counts and
        attributed milliseconds (native engine table + Python-path
        timings), native-bail forensics by classified reason, state-read
        telemetry (per-type point lookups, entry-cache hit/miss,
        prefetch coverage + getPrefetchHitRate parity, bulk-scan rows),
        bucket per-level sizes and merge durations, and the last close's
        blob. `applystats?action=reset` zeroes the cumulative aggregates
        (registry metrics keep their monotonic histories). The same data
        is scrapeable as `sct_ledger_apply_*` / `sct_bucket_*` series
        via `metrics?format=prometheus`."""
        stats = self.app.ledger_manager.apply_stats
        action = params.get("action", "status")
        if action == "reset":
            stats.reset()
            return {"status": "reset", **stats.to_json()}
        if action != "status":
            raise CommandParamError(
                "parameter 'action' must be status|reset, got %r" % action)
        return stats.to_json()

    def cmd_bucketdb(self, params) -> dict:
        """BucketDB cockpit (ISSUE 14 tentpole;
        docs/observability.md#bucketdb-cockpit): the bucket-backed read
        path's operational state in one JSON blob — point-read
        hit/miss/tombstone counts, per-level probe attribution (bloom
        skips, index hits, bloom false positives), index build/load
        timing and sidecar load failures, bloom bit density, bytes read
        from bucket files, batched-prefetch shape, and SQL-fallback
        degrades. `bucketdb?action=reset` zeroes the cumulative
        aggregates (registry metrics keep their monotonic histories).
        The same data is scrapeable as `sct_bucketdb_*` series via
        `metrics?format=prometheus`."""
        bm = getattr(self.app, "bucket_manager", None)
        bdb = getattr(bm, "bucketdb", None)
        if bdb is None:
            return {"error": "buckets not enabled"}
        action = params.get("action", "status")
        if action not in ("status", "reset"):
            raise CommandParamError(
                "parameter 'action' must be status|reset, got %r" % action)
        if action == "reset":
            bdb.stats.reset()
        root = self.app.ledger_manager.root
        out = {
            "attached": bool(getattr(root, "bucket_backed",
                                     lambda: False)()),
            **bdb.to_json(),
        }
        if action == "reset":
            out["status"] = "reset"
        return out

    def cmd_overlaystats(self, params) -> dict:
        """Wire cockpit (ISSUE 10 tentpole;
        docs/observability.md#overlay-cockpit): the overlay's
        operational state in one JSON blob — per-message-type
        send/recv counters and byte totals, per-peer top-K bandwidth
        attribution, flood dedup (unique vs duplicate receipts +
        duplication ratio, the O(n²) flood waste), send-queue pressure,
        envelope pipeline latency by verify backend, and the
        tx-lifecycle funnel (submit→queue→include→externalize→apply
        stage latencies whose stages sum to total by construction,
        plus per-tx outcomes). `overlaystats?action=reset` zeroes the
        cumulative aggregates (registry metrics keep their monotonic
        histories). The same data is scrapeable as `sct_overlay_*` /
        `sct_herder_tx_*` series via `metrics?format=prometheus`; the
        `fleet` field is the compact shape util/fleet.py aggregates."""
        om = self.app.overlay_manager
        stats = getattr(om, "stats", None) if om is not None else None
        lc = getattr(self.app.herder, "tx_lifecycle", None)
        action = params.get("action", "status")
        if action not in ("status", "reset"):
            raise CommandParamError(
                "parameter 'action' must be status|reset, got %r" % action)
        if action == "reset":
            if stats is not None:
                stats.reset()
            if lc is not None:
                lc.reset()
        if stats is not None and om is not None and \
                hasattr(om, "send_queue_depth"):
            stats.set_queue_depth(*om.send_queue_depth())
        out: dict = {
            "overlay": stats.to_json() if stats is not None else None,
            "tx_lifecycle": lc.to_json() if lc is not None else None,
            "fleet": {
                "overlay": stats.fleet_json()
                if stats is not None else None,
                "tx": lc.fleet_json() if lc is not None else None,
            },
        }
        if action == "reset":
            out["status"] = "reset"
        return out

    def cmd_propagation(self, params) -> dict:
        """Propagation cockpit (ISSUE 17 tentpole;
        docs/observability.md#propagation-cockpit): causal flood tracing
        in one JSON blob — per-peer usefulness rankings (first-delivery
        vs redundant-edge counts, wasted bytes, top-K/bottom-K), hop-
        ring occupancy, and the fleet-wide redundant bandwidth share.
        `propagation?hash=H` returns one message's full hop trace (H a
        unique hash-hex prefix); `?peer=P` one peer's score (P a node-id
        hex prefix); `?action=reset` zeroes the aggregates (registry
        metrics keep their monotonic histories). The same data is
        scrapeable as `sct_overlay_prop_*` series via
        `metrics?format=prometheus`; the `fleet` field is the compact
        shape util/fleet.py merges into relay trees."""
        om = self.app.overlay_manager
        prop = getattr(om, "prop_stats", None) if om is not None else None
        if prop is None:
            return {"error": "propagation stats disabled "
                             "(PROPAGATION_STATS_ENABLED=false)"}
        action = params.get("action", "status")
        if action not in ("status", "reset"):
            raise CommandParamError(
                "parameter 'action' must be status|reset, got %r" % action)
        h = params.get("hash")
        if h:
            trace = prop.hash_trace(h)
            if trace is None:
                raise CommandParamError(
                    "no hop record for hash prefix %r" % h)
            return trace
        p = params.get("peer")
        if p:
            detail = prop.peer_detail(p)
            if detail is None:
                raise CommandParamError(
                    "no usefulness record for peer prefix %r" % p)
            return detail
        if action == "reset":
            prop.reset()
        out = prop.to_json()
        out["fleet"] = prop.fleet_json()
        if action == "reset":
            out["status"] = "reset"
        return out

    def cmd_scpstats(self, params) -> dict:
        """Consensus cockpit (ISSUE 19 tentpole;
        docs/observability.md#consensus-cockpit): SCP's own attribution
        in one JSON blob — per-slot phase latencies derived from the
        slot-timeline stamps (nominate→prepare→confirm→externalize,
        reconciling with `timeline` by construction), nomination/ballot
        round counts, timer-fire attribution (which timer, which round,
        fired vs cancelled), per-statement-type envelopes-per-slot
        (sent AND received — the O(n²) flood baseline), per-peer
        envelope lag, and quorum health. `scpstats?slot=N` returns one
        slot's full record; `?action=reset` zeroes the aggregates
        (registry metrics keep their monotonic histories). The same
        data is scrapeable as `sct_scp_*` series via
        `metrics?format=prometheus`; the `fleet` field is the compact
        shape util/fleet.py merges into the fleet-wide
        envelopes-per-slot baseline."""
        herder = self.app.herder
        ss = getattr(herder, "scp_stats", None)
        if ss is None:
            return {"error": "consensus cockpit unavailable"}
        action = params.get("action", "status")
        if action not in ("status", "reset"):
            raise CommandParamError(
                "parameter 'action' must be status|reset, got %r" % action)
        slot = _int_param(params, "slot", None, minimum=0)
        if slot is not None:
            rep = ss.slot_report(slot)
            if rep is None:
                raise CommandParamError(
                    "no consensus record for slot %d (ring retains %d "
                    "slots)" % (slot, ss.MAX_SLOTS))
            return rep
        if action == "reset":
            ss.reset()
        from ..herder.herder import HerderState
        out = ss.to_json()
        out["health"] = ss.health(
            herder.current_slot(),
            include_open=herder.state != HerderState.HERDER_TRACKING_STATE)
        out["fleet"] = ss.fleet_json()
        if action == "reset":
            out["status"] = "reset"
        return out

    def cmd_footprint(self, params) -> dict:
        """Node footprint census (ISSUE 19 tentpole;
        docs/observability.md#node-footprint): the per-node overhead
        table — every registered bounded structure's occupancy /
        capacity / approx bytes (hop rings, LRU caches, ingress intake,
        tx-lifecycle tracker, timelines, SCP state, send queues) plus
        process RSS / thread count / fd count. `over_capacity` is
        always empty unless a declared bound is broken. Scrapeable as
        `sct_footprint_*` series via `metrics?format=prometheus`; the
        fleet aggregator consumes this endpoint on live nodes for the
        N-vs-RSS scaling curve (`bench.py --fleet-scale`)."""
        fp = getattr(self.app, "footprint", None)
        if fp is None:
            return {"error": "footprint census unavailable"}
        return fp.to_json()

    def cmd_health(self, params) -> dict:
        """Seven-cockpit health rollup (ISSUE 17 satellite, consensus
        leg ISSUE 19;
        docs/observability.md#propagation-cockpit): the single scrape a
        fleet operator watches — device breaker states (verify + hash)
        with their recovery episodes, flood duplication ratio, native
        apply bails, bucketdb SQL fallbacks, the worst peer's
        propagation usefulness, and the consensus leg (stuck slots with
        absent-member diagnosis, quorum gaps, ballot-round inflation) —
        condensed to a coarse `status: ok|degraded|critical`.
        Degraded = a breaker not closed, SQL-fallback degrades, the
        node out of sync, or a consensus problem; critical = every
        wired device breaker open."""
        app = self.app
        problems: list = []
        out: dict = {}
        breakers: dict = {}
        open_states = []
        for name, owner in (("verifier",
                             getattr(app, "sig_verifier", None)),
                            ("hasher", getattr(app, "batch_hasher", None))):
            b = getattr(owner, "breaker", None)
            if b is None:
                continue
            j = b.to_json()
            breakers[name] = {"state": j["state"], "trips": j["trips"],
                              "recoveries": j["recoveries"]}
            open_states.append(j["state"])
            if j["state"] != "closed":
                problems.append("%s breaker %s" % (name, j["state"]))
        out["breakers"] = breakers
        out["recovery_episodes"] = sum(
            b["recoveries"] for b in breakers.values())
        st = getattr(app.ledger_manager, "apply_stats", None)
        out["native_bails"] = sum(
            getattr(st, "bails", {}).values()) if st is not None else 0
        bdb = getattr(getattr(app, "bucket_manager", None),
                      "bucketdb", None)
        sql = getattr(getattr(bdb, "stats", None), "sql_fallbacks", 0) \
            if bdb is not None else 0
        out["bucketdb_sql_fallbacks"] = sql
        if sql:
            problems.append("bucketdb degraded to SQL (%d reads)" % sql)
        om = app.overlay_manager
        ostats = getattr(om, "stats", None) if om is not None else None
        if ostats is not None:
            fl = ostats.to_json()["flood"]
            out["flood_duplication_ratio"] = fl["duplication_ratio"]
        prop = getattr(om, "prop_stats", None) if om is not None else None
        if prop is not None:
            pj = prop.to_json()
            out["worst_peer_usefulness"] = \
                pj["peers"]["worst_usefulness"]
            out["redundant_bandwidth_share"] = \
                pj["redundant_bandwidth_share"]
        # consensus leg (ISSUE 19): stuck slots name the absent
        # quorum-slice members; the in-flight slot only counts once the
        # herder has lost sync (mid-nomination is not stuck)
        ss = getattr(app.herder, "scp_stats", None)
        if ss is not None:
            from ..herder.herder import HerderState
            lost = app.herder.state != HerderState.HERDER_TRACKING_STATE
            ch = ss.health(app.herder.current_slot(), include_open=lost)
            out["consensus"] = ch
            for s in ch["stuck_slots"]:
                problems.append(
                    "slot %d stuck (absent: %s)" % (
                        s["slot"],
                        ", ".join(a[:8] for a in s["absent"]) or "none"))
            q = ch["quorum"]
            if q["missing"]:
                problems.append("%d quorum member(s) never heard from"
                                % len(q["missing"]))
            if q["behind"]:
                problems.append("%d quorum member(s) behind"
                                % len(q["behind"]))
            if ch["ballot_inflated"]:
                problems.append("ballot rounds inflated (worst %d)"
                                % ch["ballot_rounds_worst"])
        synced = app.ledger_manager.is_synced()
        out["synced"] = synced
        if not synced:
            problems.append("ledger out of sync")
        if open_states and all(s == "open" for s in open_states):
            status = "critical"
        elif problems:
            status = "degraded"
        else:
            status = "ok"
        out["status"] = status
        out["problems"] = problems
        return out

    def cmd_trace(self, params) -> dict:
        """Span-tracer control + export (ISSUE 2 tentpole):
        `trace?action=status|start|stop|clear|dump|flight`.
        `start` takes optional `capacity=N`; `dump` (the default action)
        returns Chrome-trace-event JSON (load in chrome://tracing or
        Perfetto), optional `limit=N` for the last N spans; `flight`
        forces a flight-recorder dump and returns its path."""
        tracer = self.app.tracer
        action = params.get("action", "dump")
        if action == "start":
            cap = _int_param(params, "capacity", None, minimum=1)
            tracer.enable(capacity=cap)
            return {"status": "tracing", "capacity": tracer.capacity}
        if action == "stop":
            tracer.disable()
            return {"status": "stopped", "spans": len(tracer.spans())}
        if action == "clear":
            tracer.clear()
            return {"status": "cleared"}
        if action == "status":
            return {"enabled": tracer.enabled,
                    "spans": len(tracer.spans()),
                    "capacity": tracer.capacity,
                    "dropped": tracer.dropped,
                    "flight_dumps": self.app.flight_recorder.dumps,
                    "flight_suppressed": self.app.flight_recorder.suppressed,
                    "last_flight_path": self.app.flight_recorder.last_path}
        if action == "flight":
            # operator-requested: bypasses the per-reason dump cooldown
            path = self.app.flight_recorder.dump(
                params.get("reason", "manual"), force=True)
            return {"status": "dumped", "path": path}
        if action == "dump":
            limit = _int_param(params, "limit", None, minimum=0)
            return tracer.to_chrome_trace(last_n=limit)
        return {"error": "action must be "
                         "status|start|stop|clear|dump|flight"}

    def cmd_faults(self, params) -> dict:
        """Fault-injection control (ISSUE 3 tentpole; docs/robustness.md):
        `faults?action=status|set|clear`. `set` arms one site:
        `faults?action=set&site=device.dispatch&p=1.0&n=3&after=2`
        (probability, max fire count, evaluations to skip first); `clear`
        disarms one `site` or, with no site, everything. `status` (the
        default) reports every armed site's schedule and fire counts,
        the verify breaker, and archive health."""
        faults = self.app.faults
        action = params.get("action", "status")
        if action == "set":
            site = params.get("site")
            if not site:
                return {"error": "missing 'site' param"}
            from ..util.faults import KNOWN_SITES
            if site not in KNOWN_SITES:
                # arming a typo'd site would silently no-op forever:
                # validate against the F1 registry (docs/robustness.md)
                raise CommandParamError(
                    "unknown fault site %r; known sites: %s"
                    % (site, ", ".join(sorted(KNOWN_SITES))))
            p = _float_param(params, "p", 1.0, minimum=0.0, maximum=1.0)
            if p == 0.0:
                # p=0 would arm a site that can never fire — the same
                # silent-no-op class the unknown-site 400 prevents
                raise CommandParamError(
                    "parameter 'p' must be > 0 (use action=clear to "
                    "disarm a site)")
            faults.configure(
                site, probability=p,
                count=_int_param(params, "n", None, minimum=1),
                after=_int_param(params, "after", 0, minimum=0))
            return {"status": "armed", **faults.to_json()}
        if action == "clear":
            faults.clear(params.get("site"))
            return {"status": "cleared", **faults.to_json()}
        if action == "status":
            out = faults.to_json()
            v = getattr(self.app, "sig_verifier", None)
            breaker = getattr(v, "breaker", None)
            if breaker is not None:
                out["verify_breaker"] = breaker.to_json()
            hm = self.app.history_manager
            pool = hm.readable_pool() if hm is not None else None
            if pool is not None:
                out["archives"] = pool.to_json()
            return out
        return {"error": "action must be status|set|clear"}

    def cmd_peers(self, params) -> dict:
        om = self.app.overlay_manager
        return om.get_peers_info() if om is not None else {"peers": []}

    def cmd_quorum(self, params) -> dict:
        return self.app.herder.get_json_info()

    def cmd_checkquorum(self, params) -> dict:
        """Run the quorum-intersection checker over the transitive quorum
        map (reference `check-quorum` / periodic reanalysis); pass
        critical=true to also list intersection-critical groups; pass
        background=true to run it on a worker thread (poll `quorum` for
        the result) so a slow enumeration never blocks the main loop."""
        crit = params.get("critical", "") in ("true", "1")
        h = self.app.herder
        if params.get("background", "") in ("true", "1"):
            started = h.start_quorum_intersection_check(critical=crit)
            return {"status": "started" if started
                    else "already recalculating"}
        return h.check_quorum_intersection(critical=crit)

    def cmd_scp(self, params) -> dict:
        """`scp[?limit=N][&slot=N&timeline=true]` — SCP slot
        introspection; with `slot` + `timeline=true` the response also
        carries that slot's consensus event journal
        (util/slot_timeline.py, docs/observability.md#fleet-view)."""
        h = self.app.herder
        limit = _int_param(params, "limit", 2, minimum=0)
        scp = getattr(h, "scp", None)
        out = scp.get_json_info(limit) if scp is not None else {}
        out["tracking"] = h.current_slot()
        slot = _int_param(params, "slot", None, minimum=0)
        if slot is not None and params.get("timeline") in ("true", "1"):
            out["timeline"] = self.app.slot_timeline.events(slot)
        return out

    def cmd_timeline(self, params) -> dict:
        """`timeline[?slot=N]` — the per-slot consensus event journal:
        one slot's events, or every retained slot. Events are stamped
        with the app clock (`t`) and `perf_counter` (`pc`); `node` names
        the sending node where applicable. The fleet aggregator
        (util/fleet.py) consumes this endpoint on live nodes."""
        slot = _int_param(params, "slot", None, minimum=0)
        out = self.app.slot_timeline.to_json(slot)
        out["node"] = self.app.config.node_name()
        out["node_id"] = self.app.config.node_id().key_bytes.hex()
        return out

    # -- transactions --------------------------------------------------------
    def cmd_tx(self, params) -> dict:
        """Submit a hex- (or base64-) encoded TransactionEnvelope
        (reference CommandHandler.cpp:543-578). A TRY_AGAIN_LATER
        answer carries `retry_after` (seconds) — the ingress tier's
        backpressure hint (docs/robustness.md#ingress--overload).
        Malformed blobs are 400s, not 500s out of the HTTP thread."""
        from ..transactions.transaction_frame import TransactionFrame
        from ..xdr import TransactionEnvelope
        blob = params.get("blob")
        if not blob:
            return {"status": "ERROR", "detail": "missing 'blob' param"}
        try:
            raw = bytes.fromhex(blob)
        except ValueError:
            import base64
            import binascii
            try:
                raw = base64.b64decode(blob, validate=True)
            except (ValueError, binascii.Error):
                raise CommandParamError(
                    "parameter 'blob' is neither hex nor base64")
        try:
            env = TransactionEnvelope.from_xdr(raw)
            frame = TransactionFrame.make_from_wire(
                self.app.config.network_id, env)
        except Exception:
            raise CommandParamError(
                "parameter 'blob' does not decode to a "
                "TransactionEnvelope")
        status = self.app.submit_transaction(frame)
        names = {0: "PENDING", 1: "DUPLICATE", 2: "ERROR", 3: "TRY_AGAIN_LATER"}
        out = {"status": names.get(status, str(status))}
        if status == 2 and frame.result is not None:
            out["detail"] = str(frame.result.code)
        if status == 3:
            herder = self.app.herder
            retry = getattr(herder, "last_retry_after", None)
            out["retry_after"] = round(
                retry if retry is not None
                else self.app.config.EXPECTED_LEDGER_CLOSE_TIME, 3)
        return out

    def cmd_ingress(self, params) -> dict:
        """`ingress[?action=status|set-class|reset]` — the admission
        tier's cockpit (docs/robustness.md#ingress--overload):
        `status` (default) dumps the class table, bounded-intake depth,
        tracked sources and per-class admit/throttle/shed counters;
        `set-class&account=<strkey>&class=priority|default|untrusted`
        re-pins a source account at runtime; `reset` zeroes the
        counters. 400 on unknown actions/classes/accounts."""
        ing = getattr(self.app.herder, "ingress", None)
        if ing is None:
            return {"enabled": False}
        action = params.get("action", "status")
        if action == "status":
            out = ing.to_json()
            out["enabled"] = True
            return out
        if action == "set-class":
            from ..crypto import strkey
            acct = params.get("account")
            cls = params.get("class")
            if not acct or not cls:
                raise CommandParamError(
                    "set-class needs 'account' and 'class' params")
            try:
                raw = strkey.decode_public_key(acct)
            except Exception:
                raise CommandParamError(
                    "parameter 'account' is not a valid strkey "
                    "account id")
            try:
                ing.set_class(raw, cls)
            except ValueError as e:
                raise CommandParamError(str(e))
            return {"status": "ok", "account": acct, "class": cls}
        if action == "reset":
            ing.reset_counters()
            return {"status": "reset"}
        raise CommandParamError(
            "action must be status|set-class|reset, got %r" % action)

    def cmd_manualclose(self, params) -> dict:
        self.app.manual_close()
        return {"status": "ok",
                "ledger": self.app.ledger_manager.last_closed_ledger_num()}

    # -- upgrades ------------------------------------------------------------
    def cmd_upgrades(self, params) -> dict:
        """mode=get|set|clear; set takes protocolversion/basefee/
        basereserve/maxtxsetsize + upgradetime (reference `upgrades`)."""
        from ..herder.upgrades import UpgradeParameters
        ups = self.app.herder.upgrades
        mode = params.get("mode", "get")
        if mode == "get":
            return ups.params.to_json()
        if mode == "clear":
            ups.set_parameters(UpgradeParameters())
            self.app.herder.update_upgrades_status()
            return {"status": "cleared"}
        if mode == "set":
            p = UpgradeParameters()
            # default the schedule to "now": a 0 default would read as
            # epoch and the 12h expiration (remove_applied_and_expired)
            # would silently disarm at the very next close
            p.upgrade_time = int(self.app.clock.now())
            if "upgradetime" in params:
                p.upgrade_time = int(params["upgradetime"])
            if "protocolversion" in params:
                p.protocol_version = int(params["protocolversion"])
            if "basefee" in params:
                p.base_fee = int(params["basefee"])
            if "basereserve" in params:
                p.base_reserve = int(params["basereserve"])
            if "maxtxsetsize" in params:
                p.max_tx_set_size = int(params["maxtxsetsize"])
            ups.set_parameters(p)
            self.app.herder.update_upgrades_status()
            return p.to_json()
        return {"error": "mode must be get|set|clear"}

    # -- logging -------------------------------------------------------------
    def cmd_ll(self, params) -> dict:
        """Set log level: ?level=debug[&partition=Herder]
        (reference `ll`)."""
        if "level" in params:
            set_log_level(params.get("partition"), params["level"])
        return get_log_levels()

    # -- peers ---------------------------------------------------------------
    def cmd_connect(self, params) -> dict:
        om = self.app.overlay_manager
        peer = params.get("peer", "")
        port = int(params.get("port", 0) or 0)
        if not peer:
            return {"error": "missing 'peer' param"}
        if ":" in peer and not port:
            peer, p = peer.rsplit(":", 1)
            port = int(p)
        om.connect_to(peer, port)
        return {"status": "connecting to %s:%d" % (peer, port)}

    def cmd_droppeer(self, params) -> dict:
        om = self.app.overlay_manager
        node = params.get("node", "")
        ban = params.get("ban", "0") == "1"
        for key in list(om.authenticated_peer_ids()):
            p = om.get_peer(key)
            if p is None:
                continue
            if p.peer_id is not None and \
                    p.peer_id.key_bytes.hex().startswith(node):
                if ban:
                    om.ban_manager.ban_node(p.peer_id)
                p.drop("dropped by admin")
                return {"status": "dropped"}
        return {"error": "peer not found"}

    def _parse_node_param(self, node: str):
        """A `node` param as hex-XDR PublicKey or strkey (G...); raises
        CommandParamError (-> 400) on anything else."""
        from ..xdr import PublicKey
        if not node:
            raise CommandParamError("missing 'node' param")
        try:
            if node.startswith("G"):
                from ..crypto import strkey
                return PublicKey.ed25519(strkey.decode_public_key(node))
            return PublicKey.from_xdr(bytes.fromhex(node))
        except Exception:
            raise CommandParamError(
                "parameter 'node' must be a hex-encoded PublicKey XDR "
                "or a G... strkey, got %r" % node)

    def cmd_bans(self, params) -> dict:
        """BanManager operator surface (ISSUE 8 satellite):
        `bans[?action=list|unban|unban_all]` — list the banned node ids
        (flood-control escalation and `droppeer?ban=1` feed this set),
        lift one ban (`action=unban&node=<hex-or-strkey>`), or clear
        them all. Bad params are 400s via CommandParamError."""
        bm = self.app.overlay_manager.ban_manager
        action = params.get("action", "list")
        if action == "list":
            return {"bans": bm.banned()}
        if action == "unban":
            bm.unban_node(self._parse_node_param(params.get("node", "")))
            return {"status": "ok", "bans": bm.banned()}
        if action == "unban_all":
            n = bm.unban_all()
            return {"status": "ok", "unbanned": n, "bans": bm.banned()}
        raise CommandParamError(
            "parameter 'action' must be list|unban|unban_all, got %r"
            % action)

    def cmd_unban(self, params) -> dict:
        bm = self.app.overlay_manager.ban_manager
        bm.unban_node(self._parse_node_param(params.get("node", "")))
        return {"status": "ok"}

    # -- survey / load -------------------------------------------------------
    def cmd_surveytopology(self, params) -> dict:
        """Start (or extend) a topology survey (reference
        `surveytopology`)."""
        sm = self.app.overlay_manager.survey_manager
        duration = float(params.get("duration", 60))
        node = params.get("node")
        sm.start_survey(duration)
        if node:
            from ..xdr import PublicKey
            sm.add_node_to_backlog(
                PublicKey.ed25519(bytes.fromhex(node)))
        return {"status": "started", "duration": duration}

    def cmd_stopsurvey(self, params) -> dict:
        self.app.overlay_manager.survey_manager.stop_survey()
        return {"status": "stopped"}

    def cmd_getsurveyresult(self, params) -> dict:
        sm = self.app.overlay_manager.survey_manager
        # "stats" is the compact shape the fleet aggregator stores for
        # every node (util/fleet.py add_http mirrors add_app.get_stats)
        return {**sm.get_results(), "stats": sm.get_stats()}

    def cmd_loadinfo(self, params) -> dict:
        return {"load": self.app.overlay_manager.load_manager
                .get_json_info()}

    # -- maintenance / cursors ----------------------------------------------
    def cmd_maintenance(self, params) -> dict:
        count = int(params.get("count", 50000))
        n = self.app.maintainer.perform_maintenance(count) \
            if self.app.maintainer else 0
        return {"status": "ok", "rows_deleted": n}

    def cmd_setcursor(self, params) -> dict:
        self.app.external_queue.set_cursor(params["id"],
                                           int(params["cursor"]))
        return {"status": "ok"}

    def cmd_getcursor(self, params) -> dict:
        rid = params.get("id")
        return self.app.external_queue.get_cursors(rid)

    def cmd_dropcursor(self, params) -> dict:
        self.app.external_queue.delete_cursor(params["id"])
        return {"status": "ok"}

    # -- test-only -----------------------------------------------------------
    def _require_test_mode(self):
        """Gate shared by every test-only endpoint."""
        if not self.app.config.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING:
            return {"error":
                    "set ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING to use"}
        return None

    @staticmethod
    def _named_test_key(name: str):
        """reference txtest::getAccount (TxTests.cpp:379): the name
        stretched with '.' to a 32-byte seed; "root" is the network
        root key."""
        from ..crypto.keys import SecretKey
        seed = name.encode()
        seed += b"." * (32 - len(seed)) if len(seed) < 32 else b""
        return SecretKey.from_seed(seed[:32])

    def _test_key_for(self, name: str):
        if name == "root":
            return self.app.network_root_key()
        return self._named_test_key(name)

    def cmd_testacc(self, params) -> dict:
        """reference CommandHandler::testAcc (test-only,
        CommandHandler.cpp:103-105): balance/seqnum of a name-derived
        test account."""
        gated = self._require_test_mode()
        if gated is not None:
            return gated
        name = params.get("name")
        if not name:
            return {"status": "error",
                    "detail": "Bad HTTP GET: try testacc?name=bob"}
        from ..crypto import strkey
        from ..xdr import LedgerKey
        key = self._test_key_for(name)
        e = self.app.ledger_manager.ltx_root().get_entry(
            LedgerKey.account(key.public_key))
        if e is None:
            return {"status": "error", "detail": "account does not exist"}
        ae = e.data.value
        return {"name": name,
                "id": strkey.encode_public_key(ae.accountID.key_bytes),
                "balance": ae.balance, "seqnum": ae.seqNum}

    def cmd_testtx(self, params) -> dict:
        """reference CommandHandler::testTx (test-only): submit a payment
        (or create-account with create=true) between name-derived test
        accounts."""
        gated = self._require_test_mode()
        if gated is not None:
            return gated
        frm, to = params.get("from"), params.get("to")
        amount = params.get("amount")
        if not (frm and to and amount):
            return {"status": "error",
                    "detail": "try testtx?from=root&to=bob&amount=N"
                              "[&create=true]"}
        from ..crypto import strkey
        from ..testing import AppLedgerAdapter, TestAccount
        ad = AppLedgerAdapter(self.app)
        from_acct = TestAccount(ad, self._test_key_for(frm))
        to_key = self._test_key_for(to)
        amt = int(amount)
        if params.get("create") == "true":
            op = from_acct.op_create_account(to_key.public_key, amt)
        else:
            op = from_acct.op_payment(to_key.public_key, amt)
        frame = from_acct.tx([op])
        status = self.app.submit_transaction(frame)
        return {"from_name": frm, "to_name": to,
                "from_id": strkey.encode_public_key(
                    from_acct.account_id.key_bytes),
                "to_id": strkey.encode_public_key(
                    to_key.public_key.key_bytes),
                "amount": amt, "create": params.get("create") == "true",
                "status": int(status)}

    def cmd_generateload(self, params) -> dict:
        """reference CommandHandler.cpp:103 (test-only)."""
        gated = self._require_test_mode()
        if gated is not None:
            return gated
        lg = self.app.load_generator
        accounts = int(params.get("accounts", 10))
        txs = int(params.get("txs", 10))
        if accounts:
            lg.generate_accounts(accounts)
        if txs:
            lg.generate_payments(txs)
        return lg.status()

    # -- HTTP front-end ------------------------------------------------------
    def start_http(self, port: Optional[int] = None) -> int:
        """Serve the admin API; returns the bound port. Handlers hop to the
        main loop and wait (bounded) for the result."""
        app = self
        clock = self.app.clock
        public = self.app.config.PUBLIC_HTTP_PORT
        host = "" if public else "127.0.0.1"

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:
                u = urlparse(self.path)
                name = u.path.strip("/")
                params = {k: v[0] for k, v in parse_qs(u.query).items()}
                done = threading.Event()
                result: list = [None]

                def run() -> None:
                    result[0] = app.handle_command(name, params)
                    done.set()

                clock.post_to_main(run)
                if not done.wait(timeout=30.0):
                    self._reply(504, {"error": "main loop busy"})
                    return
                status, body = result[0]
                self._reply(status, body)

            def _reply(self, status: int, body) -> None:
                if isinstance(body, str):
                    # Prometheus exposition (and any future text body):
                    # version=0.0.4 is the text-format content type
                    # scrapers negotiate on
                    data = body.encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    data = json.dumps(body, indent=1).encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, fmt, *args) -> None:
                pass  # route through our logger, not stderr

        port = port if port is not None else self.app.config.HTTP_PORT
        try:
            self._server = ThreadingHTTPServer((host, port), Handler)
        except OSError:
            self._server = ThreadingHTTPServer((host, 0), Handler)
        bound = self._server.server_address[1]
        self.app.config.HTTP_PORT = bound
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("admin HTTP API on port %d", bound)
        return bound

    def stop_http(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
