"""Command-line interface.

Role parity: reference `src/main/CommandLine.cpp:1039-1093` — subcommand
dispatch for node operation (`run`, `new-db`, `force-scp`, `catchup`,
`publish`, `offline-info`), key tooling (`gen-seed`, `sec-to-pub`,
`convert-id`, `sign-transaction`), debugging (`print-xdr`, `dump-xdr`,
`http-command`), and `version`. Invoked via
`python -m stellar_core_tpu <command>`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from ..util.timer import ClockMode, VirtualClock
from .config import Config


def _load_config(args) -> Config:
    if getattr(args, "conf", None):
        cfg = Config.from_toml(args.conf)
    else:
        cfg = Config()
    return cfg


def _make_app(cfg: Config, real_time: bool = True):
    from .application import Application
    clock = VirtualClock(ClockMode.REAL_TIME if real_time
                         else ClockMode.VIRTUAL_TIME)
    app = Application(clock, cfg)
    app.enable_buckets()
    return app


# -- commands ----------------------------------------------------------------

def cmd_run(args) -> int:
    """Run a node (reference `run` → ApplicationUtils::runWithConfig)."""
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.start()
    app.command_handler.start_http()
    print("node %s up; admin API on port %d"
          % (cfg.NODE_SEED.public_key.key_bytes.hex()[:8]
             if cfg.NODE_SEED else "?", cfg.HTTP_PORT))
    try:
        while True:
            if app.crank(False) == 0:
                time.sleep(0.001)
    except KeyboardInterrupt:
        app.stop()
    return 0


def cmd_new_db(args) -> int:
    """Reset the DB to genesis (reference `new-db`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.start_new_ledger()
    print("new ledger: genesis %s"
          % app.ledger_manager.lcl_hash.hex())
    return 0


def cmd_force_scp(args) -> int:
    """Set/clear the DB flag that makes the next `run` start SCP
    immediately from the LCL (reference `force-scp`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    if app.persistent_state is None:
        print("force-scp needs a persistent database", file=sys.stderr)
        return 1
    app.persistent_state.set_force_scp(not args.reset)
    print("force-scp %s" % ("cleared" if args.reset else "set"))
    return 0


def cmd_catchup(args) -> int:
    """Offline catchup `<to>/<count>` (reference `catchup`)."""
    from ..catchup import CURRENT, CatchupConfiguration
    from ..work.basic_work import State
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.start()
    spec = args.destination
    to_s, _, count_s = spec.partition("/")
    to = CURRENT if to_s == "current" else int(to_s)
    count = CURRENT if count_s in ("", "max") else int(count_s)
    work = app.catchup_manager.start_catchup(
        CatchupConfiguration(to, count))
    if work is None:
        print("no readable history archive configured", file=sys.stderr)
        return 1
    while not work.is_done():
        if app.crank(False) == 0:
            time.sleep(0.001)
    print("catchup %s at ledger %d"
          % (work.state.name,
             app.ledger_manager.last_closed_ledger_num()))
    return 0 if work.state == State.SUCCESS else 1


def cmd_publish(args) -> int:
    """Publish any queued checkpoints (reference `publish`)."""
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.ledger_manager.load_last_known_ledger()
    n = app.history_manager.publish_queued_history()
    print("published %d checkpoint(s)" % n)
    return 0


def cmd_new_hist(args) -> int:
    """Initialize a history archive with the genesis HAS (reference
    `new-hist`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.start_new_ledger()
    hm = app.history_manager
    ok = True
    for name in args.archives:
        arch = hm.archives.get(name)
        if arch is None or not arch.has_put():
            print("archive %r not configured/writable" % name,
                  file=sys.stderr)
            ok = False
            continue
        from ..history.archive import WELL_KNOWN
        from ..history.archive_state import HistoryArchiveState
        import tempfile, os
        # initializing an EXISTING history store must fail (reference
        # HistoryTests.cpp:1221 "initialize existing history store fails")
        fd, probe = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            exists = arch.has_get() and \
                arch.get_file_sync(WELL_KNOWN, probe) and \
                os.path.getsize(probe) > 0
        finally:
            os.unlink(probe)
        if exists:
            print("archive %r already initialized; refusing to overwrite"
                  % name, file=sys.stderr)
            ok = False
            continue
        has = HistoryArchiveState(
            app.ledger_manager.last_closed_ledger_num())
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            f.write(has.to_json())
        if arch.put_file_sync(f.name, WELL_KNOWN):
            print("initialized archive %s" % name)
        else:
            ok = False
        os.unlink(f.name)
    return 0 if ok else 1


def cmd_offline_info(args) -> int:
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.load_last_known_ledger()
    print(json.dumps(app.get_info(), indent=2))
    return 0


def cmd_gen_seed(args) -> int:
    """Generate a random node seed (reference `gen-seed`)."""
    import os as _os
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    sk = SecretKey.from_seed(_os.urandom(32))
    print("Secret seed:", strkey.encode_seed(sk.seed))
    print("Public:", strkey.encode_public_key(sk.public_key.key_bytes))
    return 0


def cmd_sec_to_pub(args) -> int:
    """Print the public key for a secret seed read from stdin
    (reference `sec-to-pub`)."""
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    seed = (args.seed or sys.stdin.readline().strip())
    sk = SecretKey.from_seed(strkey.decode_seed(seed))
    print(strkey.encode_public_key(sk.public_key.key_bytes))
    return 0


def cmd_convert_id(args) -> int:
    """Display an identifier in all known forms (reference
    `convert-id`)."""
    from ..crypto import strkey
    s = args.id
    out = {}
    try:
        raw = strkey.decode_public_key(s)
        out = {"type": "public_key", "strkey": s, "hex": raw.hex()}
    except Exception:
        try:
            raw = bytes.fromhex(s)
            out = {"type": "hex", "hex": s,
                   "strkey": strkey.encode_public_key(raw)}
        except ValueError:
            print("unrecognized id %r" % s, file=sys.stderr)
            return 1
    print(json.dumps(out, indent=2))
    return 0


def cmd_sign_transaction(args) -> int:
    """Add a signature to a transaction envelope read from a file
    (reference `sign-transaction`)."""
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    from ..crypto.hashing import sha256
    from ..transactions.transaction_frame import TransactionFrame
    from ..xdr import TransactionEnvelope
    cfg = _load_config(args)
    if args.netid:
        network_id = sha256(args.netid.encode())
    else:
        network_id = cfg.network_id
    raw = open(args.txfile, "rb").read()
    try:
        raw = bytes.fromhex(raw.decode().strip())
    except Exception:
        pass
    env = TransactionEnvelope.from_xdr(raw)
    seed = args.seed or sys.stdin.readline().strip()
    sk = SecretKey.from_seed(strkey.decode_seed(seed))
    frame = TransactionFrame.make_from_wire(network_id, env)
    frame.add_signature(sk)
    print(frame.envelope.to_xdr().hex())
    return 0


def cmd_print_xdr(args) -> int:
    """Pretty-print one XDR value (reference `print-xdr`)."""
    import stellar_core_tpu.xdr as X
    raw = open(args.file, "rb").read()
    try:
        raw = bytes.fromhex(raw.decode().strip())
    except Exception:
        pass
    t = getattr(X, args.filetype, None)
    if t is None:
        print("unknown XDR type %r" % args.filetype, file=sys.stderr)
        return 1
    v = t.from_xdr(raw)
    print(_xdr_to_jsonable(v))
    return 0


def _xdr_to_jsonable(v, depth: int = 0):
    if depth > 24:
        return "..."
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (int, str, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_xdr_to_jsonable(x, depth + 1) for x in v]
    fields = getattr(type(v), "xdr_fields", None)
    if fields is not None:
        return {n: _xdr_to_jsonable(getattr(v, n), depth + 1)
                for n, _t in fields}
    if hasattr(v, "disc") and hasattr(v, "value"):
        return {"disc": v.disc,
                "value": _xdr_to_jsonable(v.value, depth + 1)}
    return str(v)


def cmd_http_command(args) -> int:
    """Send a command to a running node's admin port (reference
    `http-command`)."""
    import urllib.request
    cfg = _load_config(args)
    url = "http://127.0.0.1:%d/%s" % (cfg.HTTP_PORT, args.command)
    with urllib.request.urlopen(url, timeout=35) as r:
        print(r.read().decode())
    return 0


def cmd_version(args) -> int:
    cfg = Config()
    print(cfg.VERSION_STR)
    return 0


def cmd_test(args) -> int:
    """Run the test suite (reference `test`)."""
    import pytest
    return pytest.main(["-q"] + (args.pytest_args or []))


def _harvest_inferred_quorum(cfg, first: int, last: int):
    """Shared harvest loop of infer-quorum/write-quorum: mine SCP history
    from every readable configured archive."""
    from ..history.archive import HistoryArchive
    from ..history.inferred_quorum import InferredQuorum
    iq = InferredQuorum()
    total = 0
    for name, d in cfg.HISTORY.items():
        arch = HistoryArchive.from_config(name, d)
        if not arch.has_get():
            continue
        total += iq.harvest_archive(arch, first, last,
                                    cfg.CHECKPOINT_FREQUENCY)
    return iq, total


def cmd_infer_quorum(args) -> int:
    """Mine quorum sets from published SCP history (reference infer-quorum,
    src/history/InferredQuorum.cpp)."""
    import json

    from .config import Config

    cfg = Config.from_toml(args.conf) if args.conf else Config()
    iq, total = _harvest_inferred_quorum(cfg, args.first, args.last)
    out = iq.to_json()
    out["entries"] = total
    out["quorum_intersection"] = iq.check_quorum_intersection()
    print(json.dumps(out, indent=1))
    return 0


def cmd_fuzz(args) -> int:
    """Mutational fuzz run over an untrusted intake surface (reference
    `fuzz` AFL mode, src/test/FuzzerImpl.cpp; docs/fuzzing.md). With
    --input, runs that single input and exits (the reference `fuzz`
    contract for AFL integration)."""
    import json
    import logging

    from .fuzz import fuzz_overlay, fuzz_tx, run_one
    logging.disable(logging.ERROR)
    if args.input:
        data = open(args.input, "rb").read()
        stats = run_one(args.mode, data)
    else:
        fn = fuzz_tx if args.mode == "tx" else fuzz_overlay
        stats = fn(iterations=args.iterations, seed=args.seed)
    print(json.dumps({"mode": args.mode, **stats}))
    return 0


def cmd_gen_fuzz(args) -> int:
    """Write a random fuzzer input file (reference `gen-fuzz`)."""
    from .fuzz import gen_input
    data = gen_input(args.mode, args.seed)
    with open(args.output, "wb") as f:
        f.write(data)
    print("wrote %d-byte %s fuzz input to %s"
          % (len(data), args.mode, args.output))
    return 0


def cmd_check_quorum(args) -> int:
    """Check quorum intersection of the last network activity (reference
    `check-quorum`): builds the node→qset map from the newest SCP history
    rows in the local DB and runs the enumeration checker
    (QuorumIntersectionCheckerImpl role)."""
    from ..herder.pending_envelopes import statement_qset_hash
    from ..herder.quorum_intersection import QuorumIntersectionChecker
    from ..xdr import SCPEnvelope, SCPQuorumSet

    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    db = getattr(app, "database", None)
    if db is None:
        print("check-quorum needs a persistent database", file=sys.stderr)
        return 1
    row = db.execute("SELECT MAX(ledgerseq) FROM scphistory").fetchone()
    if row is None or row[0] is None:
        print(json.dumps({"error": "no SCP history rows"}))
        return 1
    seq = row[0]
    qmap = {}
    for (blob,) in db.execute(
            "SELECT envelope FROM scphistory WHERE ledgerseq = ?", (seq,)):
        env = SCPEnvelope.from_xdr(blob)
        node = env.statement.nodeID.key_bytes
        qh = statement_qset_hash(env.statement)
        qrow = db.execute("SELECT qset FROM scpquorums WHERE qsethash = ?",
                          (qh.hex(),)).fetchone()
        qmap[node] = SCPQuorumSet.from_xdr(qrow[0]) if qrow else None
    checker = QuorumIntersectionChecker(qmap)
    ok = checker.network_enjoys_quorum_intersection()
    out = {"ledger": seq, "nodes": len(qmap), "intersection": bool(ok)}
    if getattr(args, "critical", False):
        from ..herder.quorum_intersection import (
            intersection_critical_groups_strkey,
        )
        out["intersection_critical"] = \
            intersection_critical_groups_strkey(qmap)
    print(json.dumps(out, indent=1))
    return 0 if ok else 2


def cmd_write_quorum(args) -> int:
    """Print the quorum graph mined from history (reference
    `write-quorum`): per-node qsets in jsonable form."""
    cfg = _load_config(args)
    iq, _total = _harvest_inferred_quorum(cfg, args.first, args.last)
    from ..crypto.strkey import encode_public_key
    out = iq.to_json()
    out["graph"] = {encode_public_key(node): _xdr_to_jsonable(
                        iq.get_qset(node))
                    for node in sorted(iq.node_qset)}
    print(json.dumps(out, indent=1))
    return 0


def cmd_dump_xdr(args) -> int:
    """Dump a STREAM FILE of XDR records, one JSON document per record
    (reference `dump-xdr`; print-xdr handles single values)."""
    import stellar_core_tpu.xdr as X
    from ..util.xdrstream import XDRInputFileStream

    t = getattr(X, args.filetype, None)
    if t is None:
        print("unknown XDR type %r" % args.filetype, file=sys.stderr)
        return 1
    n = 0
    with XDRInputFileStream(args.file) as ins:
        for rec in ins.read_all(t):
            print(json.dumps(_xdr_to_jsonable(rec)))
            n += 1
    print("-- %d record(s)" % n, file=sys.stderr)
    return 0


def cmd_report_last_history_checkpoint(args) -> int:
    """Fetch and print the most recent HistoryArchiveState from each
    readable archive (reference `report-last-history-checkpoint`)."""
    import os
    import tempfile

    from ..history.archive import HistoryArchive, WELL_KNOWN

    cfg = _load_config(args)
    ok = False
    for name, d in cfg.HISTORY.items():
        arch = HistoryArchive.from_config(name, d)
        if not arch.has_get():
            continue
        fd, tmp = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            if arch.get_file_sync(WELL_KNOWN, tmp):
                print(json.dumps({"archive": name,
                                  "state": json.load(open(tmp))}, indent=1))
                ok = True
            else:
                print("archive %s: fetch failed" % name, file=sys.stderr)
        finally:
            os.unlink(tmp)
    return 0 if ok else 1


def cmd_upgrade_db(args) -> int:
    """Apply any pending DB schema migrations (reference `upgrade-db`);
    opening the database runs the migration hook."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    db = getattr(app, "database", None)
    if db is None:
        print("no persistent database configured", file=sys.stderr)
        return 1
    print("database schema at version %s" % db.get_state("databaseschema"))
    return 0


def cmd_load_xdr(args) -> int:
    """Load an XDR bucket file directly into the ledger state, for
    debugging (reference `load-xdr`). Since SQL is a write-behind query
    index (ISSUE 14, docs/db-schema.md), the entries are applied BOTH
    into the DB tables and into the bucket list (+ persisted local HAS)
    — an SQL-only injection would be invisible to BucketDB-routed point
    reads."""
    from ..bucket.applicator import BucketApplicator
    from ..bucket.bucket import Bucket
    from ..xdr import BucketEntryType

    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.load_last_known_ledger()
    b = Bucket.read_from(args.file)
    applicator = BucketApplicator(app.ledger_manager.ltx_root(), b)
    n = 0
    while applicator:
        n += applicator.advance()
    bm = app.bucket_manager
    if bm is not None:
        from ..crypto.hashing import sha256
        lm = app.ledger_manager
        live = [e.value for e in b.payload_entries()
                if e.disc in (BucketEntryType.LIVEENTRY,
                              BucketEntryType.INITENTRY)]
        dead = [e.value for e in b.payload_entries()
                if e.disc == BucketEntryType.DEADENTRY]
        hdr = lm.lcl_header
        bm.add_batch(hdr.ledgerSeq, hdr.ledgerVersion, [], live, dead)
        # restamp the stored LCL header's bucketListHash over the
        # mutated list and re-derive the LCL hash: otherwise the next
        # start's restore check (list hash != header) would wipe the
        # bucket list and the injected entries would be invisible to
        # bucket-backed reads. Offline state surgery already forks this
        # node from any network; the restamp just keeps it locally
        # coherent.
        hdr.bucketListHash = bm.get_hash()
        lm.lcl_hash = sha256(hdr.to_xdr())
        lm._store_header(hdr)
        lm._store_local_has()
    print("applied %d entr%s from %s (bucket hash %s)"
          % (n, "y" if n == 1 else "ies", args.file,
             b.get_hash().hex()[:16]))
    return 0


def cmd_rebuild_ledger_from_buckets(args) -> int:
    """Rebuild the SQL ledger state from the current bucket files
    (reference `rebuild-ledger-from-buckets`): clears entry tables, then
    streams the bucket list newest-first (level 0 curr, snap, level 1 …)
    into the DB — the first bucket to mention a key wins."""
    from ..bucket.applicator import apply_buckets

    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    if not app.ledger_manager.load_last_known_ledger():
        print("no last-known ledger in DB", file=sys.stderr)
        return 1
    bm = getattr(app, "bucket_manager", None)
    db = getattr(app, "database", None)
    if bm is None or db is None:
        print("needs bucket directory + persistent DB", file=sys.stderr)
        return 1
    # refuse to wipe the SQL state unless the on-disk bucket list hashes
    # to exactly what the LCL header committed to — an empty or stale list
    # would otherwise destroy the only copy of the ledger
    header = app.ledger_manager.lcl_header
    if bm.get_hash() != header.bucketListHash:
        print("bucket list hash %s does not match header %s; refusing"
              % (bm.get_hash().hex()[:16],
                 header.bucketListHash.hex()[:16]), file=sys.stderr)
        return 1
    root = app.ledger_manager.ltx_root()
    for table in ("accounts", "trustlines", "offers", "accountdata"):
        db.execute("DELETE FROM %s" % table)
    db.commit()
    root._cache.clear()   # raw DELETEs bypassed the root's entry cache
    buckets = []
    for lev in bm.bucket_list.levels:
        buckets.append(lev.curr)
        buckets.append(lev.snap)
    n = apply_buckets(root, buckets)
    print("rebuilt %d ledger entr%s from %d bucket level(s)"
          % (n, "y" if n == 1 else "ies", len(bm.bucket_list.levels)))
    return 0


def cmd_simulate(args) -> int:
    """Simulate applying synthetic payment ledgers offline and report the
    close rate (reference `simulate`)."""
    from ..crypto.keys import SecretKey
    from ..testing import AppLedgerAdapter
    cfg = _load_config(args)
    cfg.RUN_STANDALONE = True
    cfg.MANUAL_CLOSE = True
    cfg.FORCE_SCP = True
    cfg.UNSAFE_QUORUM = True
    cfg.DATABASE = "in-memory"
    if cfg.NODE_SEED is None:
        import os as _os
        cfg.NODE_SEED = SecretKey.from_seed(_os.urandom(32))
    cfg.QUORUM_SET = cfg.self_qset()
    import tempfile
    cfg.BUCKET_DIR_PATH = tempfile.mkdtemp(prefix="sct-simulate-")
    app = _make_app(cfg, real_time=False)
    app.start()
    ad = AppLedgerAdapter(app)
    root = ad.root_account()
    senders = [root.create(10**10) for _ in range(args.txs)]
    app.clock.set_virtual_time(
        app.clock.now() + app.ledger_manager.last_closed_ledger_num())
    t0 = time.perf_counter()
    for _ in range(args.ledgers):
        for s in senders:
            app.submit_transaction(
                s.tx([s.op_payment(root.account_id, 1)]))
        app.clock.set_virtual_time(app.clock.now() + 1.0)
        app.manual_close()
    dt = time.perf_counter() - t0
    print(json.dumps({
        "ledgers": args.ledgers, "txs_per_ledger": args.txs,
        "wall_s": round(dt, 3),
        "ledgers_per_sec": round(args.ledgers / dt, 2),
        "txs_per_sec": round(args.ledgers * args.txs / dt, 1)}))
    return 0


# -- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="stellar-core-tpu",
        description="TPU-native replicated ledger node")
    sub = ap.add_subparsers(dest="command", required=True)

    def add(name, fn, help_, conf=True):
        p = sub.add_parser(name, help=help_)
        if conf:
            p.add_argument("--conf", help="TOML config file")
        p.set_defaults(fn=fn)
        return p

    add("run", cmd_run, "run a node")
    p = add("fuzz", cmd_fuzz, "fuzz an intake surface (tx|overlay)",
            conf=False)
    p.add_argument("--mode", choices=("tx", "overlay"), default="tx")
    p.add_argument("--iterations", type=int, default=10000)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--input", help="run this single input file and exit")
    p = add("gen-fuzz", cmd_gen_fuzz, "generate a random fuzzer input",
            conf=False)
    p.add_argument("output")
    p.add_argument("--mode", choices=("tx", "overlay"), default="tx")
    p.add_argument("--seed", type=int, default=1)
    p = add("check-quorum", cmd_check_quorum,
            "check quorum intersection of last network activity")
    p.add_argument("--critical", action="store_true",
                   help="also search for intersection-critical groups")
    p = add("write-quorum", cmd_write_quorum,
            "print a quorum graph mined from history")
    p.add_argument("--first", type=int, default=1)
    p.add_argument("--last", type=int, default=2**31 - 1)
    p = add("dump-xdr", cmd_dump_xdr, "dump an XDR stream file",
            conf=False)
    p.add_argument("file")
    p.add_argument("--filetype", default="LedgerHeaderHistoryEntry")
    add("report-last-history-checkpoint",
        cmd_report_last_history_checkpoint,
        "print each archive's latest HistoryArchiveState")
    add("upgrade-db", cmd_upgrade_db,
        "upgrade database schema to the current version")
    p = add("load-xdr", cmd_load_xdr,
            "load an XDR bucket file into the DB, for testing")
    p.add_argument("file")
    add("rebuild-ledger-from-buckets", cmd_rebuild_ledger_from_buckets,
        "rebuild SQL ledger state from the current bucket files")
    p = add("simulate", cmd_simulate, "simulate applying ledgers")
    p.add_argument("--ledgers", type=int, default=32)
    p.add_argument("--txs", type=int, default=16)
    add("new-db", cmd_new_db, "reset DB to the genesis ledger")
    p = add("force-scp", cmd_force_scp,
            "start SCP from the LCL on next run")
    p.add_argument("--reset", action="store_true")
    p = add("catchup", cmd_catchup, "catch up from history archives")
    p.add_argument("destination",
                   help="<to>/<count>, e.g. current/max or 100000/64")
    add("publish", cmd_publish, "publish queued checkpoints")
    p = add("infer-quorum", cmd_infer_quorum,
            "infer the network quorum structure from SCP history")
    p.add_argument("--first", type=int, default=1)
    p.add_argument("--last", type=int, default=2**31 - 1)
    p = add("new-hist", cmd_new_hist, "initialize history archives")
    p.add_argument("archives", nargs="+")
    add("offline-info", cmd_offline_info, "info for an offline instance")
    add("gen-seed", cmd_gen_seed, "generate a random node seed",
        conf=False)
    p = add("sec-to-pub", cmd_sec_to_pub,
            "public key for a secret seed", conf=False)
    p.add_argument("--seed", help="seed (otherwise read from stdin)")
    p = add("convert-id", cmd_convert_id,
            "display an ID in all known forms", conf=False)
    p.add_argument("id")
    p = add("sign-transaction", cmd_sign_transaction,
            "add a signature to a transaction envelope")
    p.add_argument("txfile")
    p.add_argument("--netid", help="network passphrase")
    p.add_argument("--seed", help="signing seed (else stdin)")
    p = add("print-xdr", cmd_print_xdr, "pretty-print one XDR value",
            conf=False)
    p.add_argument("file")
    p.add_argument("--filetype", default="TransactionEnvelope")
    p = add("http-command", cmd_http_command,
            "send a command to a running node")
    p.add_argument("command")
    add("version", cmd_version, "print version", conf=False)
    p = add("test", cmd_test, "run the test suite", conf=False)
    p.add_argument("pytest_args", nargs="*")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    import sys
    sys.exit(main())
