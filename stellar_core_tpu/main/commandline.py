"""Command-line interface.

Role parity: reference `src/main/CommandLine.cpp:1039-1093` — subcommand
dispatch for node operation (`run`, `new-db`, `force-scp`, `catchup`,
`publish`, `offline-info`), key tooling (`gen-seed`, `sec-to-pub`,
`convert-id`, `sign-transaction`), debugging (`print-xdr`, `dump-xdr`,
`http-command`), and `version`. Invoked via
`python -m stellar_core_tpu <command>`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

from ..util.timer import ClockMode, VirtualClock
from .config import Config


def _load_config(args) -> Config:
    if getattr(args, "conf", None):
        cfg = Config.from_toml(args.conf)
    else:
        cfg = Config()
    return cfg


def _make_app(cfg: Config, real_time: bool = True):
    from .application import Application
    clock = VirtualClock(ClockMode.REAL_TIME if real_time
                         else ClockMode.VIRTUAL_TIME)
    app = Application(clock, cfg)
    app.enable_buckets()
    return app


# -- commands ----------------------------------------------------------------

def cmd_run(args) -> int:
    """Run a node (reference `run` → ApplicationUtils::runWithConfig)."""
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.start()
    app.command_handler.start_http()
    print("node %s up; admin API on port %d"
          % (cfg.NODE_SEED.public_key.key_bytes.hex()[:8]
             if cfg.NODE_SEED else "?", cfg.HTTP_PORT))
    try:
        while True:
            if app.crank(False) == 0:
                time.sleep(0.001)
    except KeyboardInterrupt:
        app.stop()
    return 0


def cmd_new_db(args) -> int:
    """Reset the DB to genesis (reference `new-db`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.start_new_ledger()
    print("new ledger: genesis %s"
          % app.ledger_manager.lcl_hash.hex())
    return 0


def cmd_force_scp(args) -> int:
    """Set/clear the DB flag that makes the next `run` start SCP
    immediately from the LCL (reference `force-scp`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    if app.persistent_state is None:
        print("force-scp needs a persistent database", file=sys.stderr)
        return 1
    app.persistent_state.set_force_scp(not args.reset)
    print("force-scp %s" % ("cleared" if args.reset else "set"))
    return 0


def cmd_catchup(args) -> int:
    """Offline catchup `<to>/<count>` (reference `catchup`)."""
    from ..catchup import CURRENT, CatchupConfiguration
    from ..work.basic_work import State
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.start()
    spec = args.destination
    to_s, _, count_s = spec.partition("/")
    to = CURRENT if to_s == "current" else int(to_s)
    count = CURRENT if count_s in ("", "max") else int(count_s)
    work = app.catchup_manager.start_catchup(
        CatchupConfiguration(to, count))
    if work is None:
        print("no readable history archive configured", file=sys.stderr)
        return 1
    while not work.is_done():
        if app.crank(False) == 0:
            time.sleep(0.001)
    print("catchup %s at ledger %d"
          % (work.state.name,
             app.ledger_manager.last_closed_ledger_num()))
    return 0 if work.state == State.SUCCESS else 1


def cmd_publish(args) -> int:
    """Publish any queued checkpoints (reference `publish`)."""
    cfg = _load_config(args)
    app = _make_app(cfg)
    app.ledger_manager.load_last_known_ledger()
    n = app.history_manager.publish_queued_history()
    print("published %d checkpoint(s)" % n)
    return 0


def cmd_new_hist(args) -> int:
    """Initialize a history archive with the genesis HAS (reference
    `new-hist`)."""
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.start_new_ledger()
    hm = app.history_manager
    ok = True
    for name in args.archives:
        arch = hm.archives.get(name)
        if arch is None or not arch.has_put():
            print("archive %r not configured/writable" % name,
                  file=sys.stderr)
            ok = False
            continue
        from ..history.archive import WELL_KNOWN
        from ..history.archive_state import HistoryArchiveState
        import tempfile, os
        has = HistoryArchiveState(
            app.ledger_manager.last_closed_ledger_num())
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            f.write(has.to_json())
        if arch.put_file_sync(f.name, WELL_KNOWN):
            print("initialized archive %s" % name)
        else:
            ok = False
        os.unlink(f.name)
    return 0 if ok else 1


def cmd_offline_info(args) -> int:
    cfg = _load_config(args)
    app = _make_app(cfg, real_time=False)
    app.ledger_manager.load_last_known_ledger()
    print(json.dumps(app.get_info(), indent=2))
    return 0


def cmd_gen_seed(args) -> int:
    """Generate a random node seed (reference `gen-seed`)."""
    import os as _os
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    sk = SecretKey.from_seed(_os.urandom(32))
    print("Secret seed:", strkey.encode_seed(sk.seed))
    print("Public:", strkey.encode_public_key(sk.public_key.key_bytes))
    return 0


def cmd_sec_to_pub(args) -> int:
    """Print the public key for a secret seed read from stdin
    (reference `sec-to-pub`)."""
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    seed = (args.seed or sys.stdin.readline().strip())
    sk = SecretKey.from_seed(strkey.decode_seed(seed))
    print(strkey.encode_public_key(sk.public_key.key_bytes))
    return 0


def cmd_convert_id(args) -> int:
    """Display an identifier in all known forms (reference
    `convert-id`)."""
    from ..crypto import strkey
    s = args.id
    out = {}
    try:
        raw = strkey.decode_public_key(s)
        out = {"type": "public_key", "strkey": s, "hex": raw.hex()}
    except Exception:
        try:
            raw = bytes.fromhex(s)
            out = {"type": "hex", "hex": s,
                   "strkey": strkey.encode_public_key(raw)}
        except ValueError:
            print("unrecognized id %r" % s, file=sys.stderr)
            return 1
    print(json.dumps(out, indent=2))
    return 0


def cmd_sign_transaction(args) -> int:
    """Add a signature to a transaction envelope read from a file
    (reference `sign-transaction`)."""
    from ..crypto.keys import SecretKey
    from ..crypto import strkey
    from ..crypto.hashing import sha256
    from ..transactions.transaction_frame import TransactionFrame
    from ..xdr import TransactionEnvelope
    cfg = _load_config(args)
    if args.netid:
        network_id = sha256(args.netid.encode())
    else:
        network_id = cfg.network_id
    raw = open(args.txfile, "rb").read()
    try:
        raw = bytes.fromhex(raw.decode().strip())
    except Exception:
        pass
    env = TransactionEnvelope.from_xdr(raw)
    seed = args.seed or sys.stdin.readline().strip()
    sk = SecretKey.from_seed(strkey.decode_seed(seed))
    frame = TransactionFrame.make_from_wire(network_id, env)
    frame.add_signature(sk)
    print(frame.envelope.to_xdr().hex())
    return 0


def cmd_print_xdr(args) -> int:
    """Pretty-print one XDR value (reference `print-xdr`)."""
    import stellar_core_tpu.xdr as X
    raw = open(args.file, "rb").read()
    try:
        raw = bytes.fromhex(raw.decode().strip())
    except Exception:
        pass
    t = getattr(X, args.filetype, None)
    if t is None:
        print("unknown XDR type %r" % args.filetype, file=sys.stderr)
        return 1
    v = t.from_xdr(raw)
    print(_xdr_to_jsonable(v))
    return 0


def _xdr_to_jsonable(v, depth: int = 0):
    if depth > 24:
        return "..."
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, (int, str, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_xdr_to_jsonable(x, depth + 1) for x in v]
    fields = getattr(type(v), "xdr_fields", None)
    if fields is not None:
        return {n: _xdr_to_jsonable(getattr(v, n), depth + 1)
                for n, _t in fields}
    if hasattr(v, "disc") and hasattr(v, "value"):
        return {"disc": v.disc,
                "value": _xdr_to_jsonable(v.value, depth + 1)}
    return str(v)


def cmd_http_command(args) -> int:
    """Send a command to a running node's admin port (reference
    `http-command`)."""
    import urllib.request
    cfg = _load_config(args)
    url = "http://127.0.0.1:%d/%s" % (cfg.HTTP_PORT, args.command)
    with urllib.request.urlopen(url, timeout=35) as r:
        print(r.read().decode())
    return 0


def cmd_version(args) -> int:
    cfg = Config()
    print(cfg.VERSION_STR)
    return 0


def cmd_test(args) -> int:
    """Run the test suite (reference `test`)."""
    import pytest
    return pytest.main(["-q"] + (args.pytest_args or []))


def cmd_infer_quorum(args) -> int:
    """Mine quorum sets from published SCP history (reference infer-quorum,
    src/history/InferredQuorum.cpp)."""
    import json

    from ..history.archive import HistoryArchive
    from ..history.inferred_quorum import InferredQuorum
    from .config import Config

    cfg = Config.from_toml(args.conf) if args.conf else Config()
    iq = InferredQuorum()
    total = 0
    for name, d in cfg.HISTORY.items():
        arch = HistoryArchive.from_config(name, d)
        if not arch.has_get():
            continue
        total += iq.harvest_archive(arch, args.first, args.last,
                                    cfg.CHECKPOINT_FREQUENCY)
    out = iq.to_json()
    out["entries"] = total
    out["quorum_intersection"] = iq.check_quorum_intersection()
    print(json.dumps(out, indent=1))
    return 0


def cmd_fuzz(args) -> int:
    """Mutational fuzz run over an untrusted intake surface (reference
    `fuzz` AFL mode, src/test/FuzzerImpl.cpp; docs/fuzzing.md)."""
    import json
    import logging

    from .fuzz import fuzz_overlay, fuzz_tx
    logging.disable(logging.ERROR)
    fn = fuzz_tx if args.mode == "tx" else fuzz_overlay
    stats = fn(iterations=args.iterations, seed=args.seed)
    print(json.dumps({"mode": args.mode, **stats}))
    return 0


# -- parser ------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="stellar-core-tpu",
        description="TPU-native replicated ledger node")
    sub = ap.add_subparsers(dest="command", required=True)

    def add(name, fn, help_, conf=True):
        p = sub.add_parser(name, help=help_)
        if conf:
            p.add_argument("--conf", help="TOML config file")
        p.set_defaults(fn=fn)
        return p

    add("run", cmd_run, "run a node")
    p = add("fuzz", cmd_fuzz, "fuzz an intake surface (tx|overlay)",
            conf=False)
    p.add_argument("--mode", choices=("tx", "overlay"), default="tx")
    p.add_argument("--iterations", type=int, default=10000)
    p.add_argument("--seed", type=int, default=1)
    add("new-db", cmd_new_db, "reset DB to the genesis ledger")
    p = add("force-scp", cmd_force_scp,
            "start SCP from the LCL on next run")
    p.add_argument("--reset", action="store_true")
    p = add("catchup", cmd_catchup, "catch up from history archives")
    p.add_argument("destination",
                   help="<to>/<count>, e.g. current/max or 100000/64")
    add("publish", cmd_publish, "publish queued checkpoints")
    p = add("infer-quorum", cmd_infer_quorum,
            "infer the network quorum structure from SCP history")
    p.add_argument("--first", type=int, default=1)
    p.add_argument("--last", type=int, default=2**31 - 1)
    p = add("new-hist", cmd_new_hist, "initialize history archives")
    p.add_argument("archives", nargs="+")
    add("offline-info", cmd_offline_info, "info for an offline instance")
    add("gen-seed", cmd_gen_seed, "generate a random node seed",
        conf=False)
    p = add("sec-to-pub", cmd_sec_to_pub,
            "public key for a secret seed", conf=False)
    p.add_argument("--seed", help="seed (otherwise read from stdin)")
    p = add("convert-id", cmd_convert_id,
            "display an ID in all known forms", conf=False)
    p.add_argument("id")
    p = add("sign-transaction", cmd_sign_transaction,
            "add a signature to a transaction envelope")
    p.add_argument("txfile")
    p.add_argument("--netid", help="network passphrase")
    p.add_argument("--seed", help="signing seed (else stdin)")
    p = add("print-xdr", cmd_print_xdr, "pretty-print one XDR value",
            conf=False)
    p.add_argument("file")
    p.add_argument("--filetype", default="TransactionEnvelope")
    p = add("http-command", cmd_http_command,
            "send a command to a running node")
    p.add_argument("command")
    add("version", cmd_version, "print version", conf=False)
    p = add("test", cmd_test, "run the test suite", conf=False)
    p.add_argument("pytest_args", nargs="*")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    import sys
    sys.exit(main())
