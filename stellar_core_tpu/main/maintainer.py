"""Maintainer + ExternalQueue: bounded retention of historical rows.

Role parity: reference `src/main/Maintainer.{h,cpp}` (periodic deletion
of old `scphistory`/`txhistory`/`txfeehistory` rows, timer-driven by
AUTOMATIC_MAINTENANCE_PERIOD/COUNT) and `src/main/ExternalQueue.{h,cpp}`
(the `pubsub` cursor table: downstream consumers advance a cursor per
resource id, and maintenance never deletes rows a consumer has not
acknowledged). Rows still needed by queued history publishes are also
retained.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..history.checkpoints import first_in_checkpoint
from ..util.log import get_logger
from ..util.timer import VirtualTimer

log = get_logger("History")


class ExternalQueue:
    """Cursor registry gating row GC (reference ExternalQueue.cpp)."""

    def __init__(self, app) -> None:
        self.app = app

    def _db(self):
        return getattr(self.app, "database", None)

    def set_cursor(self, resid: str, cursor: int) -> None:
        assert cursor >= 0
        db = self._db()
        if db is None:
            return
        db.execute("INSERT OR REPLACE INTO pubsub (resid, lastread) "
                   "VALUES (?,?)", (resid, cursor))
        db.commit()

    def get_cursors(self, resid: Optional[str] = None) -> Dict[str, int]:
        db = self._db()
        if db is None:
            return {}
        if resid:
            row = db.execute("SELECT lastread FROM pubsub WHERE resid=?",
                             (resid,)).fetchone()
            return {resid: row[0]} if row else {}
        return {r: c for r, c in
                db.execute("SELECT resid, lastread FROM pubsub")}

    def delete_cursor(self, resid: str) -> None:
        db = self._db()
        if db is None:
            return
        db.execute("DELETE FROM pubsub WHERE resid=?", (resid,))
        db.commit()

    def min_cursor(self) -> Optional[int]:
        cursors = self.get_cursors()
        return min(cursors.values()) if cursors else None


class Maintainer:
    def __init__(self, app) -> None:
        self.app = app
        self._timer = VirtualTimer(app.clock)
        self.rows_deleted = 0

    def start(self) -> None:
        """Arm periodic maintenance (reference Maintainer::start)."""
        period = self.app.config.AUTOMATIC_MAINTENANCE_PERIOD
        count = self.app.config.AUTOMATIC_MAINTENANCE_COUNT
        if period <= 0 or count <= 0:
            return

        def tick() -> None:
            self.perform_maintenance(count)
            self._timer.expires_from_now(period)
            self._timer.async_wait(tick)

        self._timer.expires_from_now(period)
        self._timer.async_wait(tick)

    def _retention_bound(self) -> int:
        """Highest ledgerseq (exclusive) safe to delete below."""
        app = self.app
        lcl = app.ledger_manager.last_closed_ledger_num()
        freq = app.config.CHECKPOINT_FREQUENCY
        # never delete rows a future checkpoint snapshot still needs
        bound = first_in_checkpoint(
            ((lcl // freq) * freq + freq - 1), freq)
        # nor rows a queued-but-unpublished checkpoint needs
        hm = getattr(app, "history_manager", None)
        if hm is not None:
            q = hm.publish_queue()
            if q:
                bound = min(bound, first_in_checkpoint(q[0], freq))
        # nor rows a downstream consumer hasn't read
        eq = getattr(app, "external_queue", None)
        if eq is not None:
            mc = eq.min_cursor()
            if mc is not None:
                bound = min(bound, mc + 1)
        return bound

    def perform_maintenance(self, count: int) -> int:
        """Delete up to `count` rows per table below the retention bound
        (reference Maintainer::performMaintenance)."""
        db = getattr(self.app, "database", None)
        if db is None:
            return 0
        bound = self._retention_bound()
        deleted = 0
        for table in ("scphistory", "txhistory", "txfeehistory"):
            cur = db.execute(
                "DELETE FROM %s WHERE ledgerseq < ? AND ledgerseq IN "
                "(SELECT ledgerseq FROM %s WHERE ledgerseq < ? "
                "ORDER BY ledgerseq LIMIT ?)" % (table, table),
                (bound, bound, count))
            deleted += cur.rowcount if cur.rowcount > 0 else 0
        db.commit()
        self.rows_deleted += deleted
        if deleted:
            log.debug("maintenance deleted %d rows below %d", deleted,
                      bound)
        return deleted
