"""Multi-chip sharding of the verify batch.

TPU-first design (SURVEY.md §2.3): consensus traffic between mutually
untrusting validators stays on TCP — collectives don't apply there. ICI
parallelism lives INSIDE the crypto backend: a verify batch is sharded
pure-data-parallel over the `dp` mesh axis (ed25519 verifies are
embarrassingly parallel — SURVEY.md §5 "long-context" note), XLA partitions
the kernel, and the only cross-chip traffic is the result gather.

No tensor/pipeline/sequence/expert axes exist in this domain: the model is
a fixed-function crypto pipeline per batch element, not a layered network —
so the mesh is 1-D. This module also provides the multi-chip "training
step" used by __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("dp",))


def sharded_verify_fn(mesh: Mesh):
    """jit-compiled batched ed25519 verify with inputs/outputs sharded over
    the dp axis. Batch size must be a multiple of the mesh size."""
    from ..ops.ed25519 import verify_kernel

    data = NamedSharding(mesh, P("dp"))

    @partial(jax.jit,
             in_shardings=(data,) * 6,
             out_shardings=data)
    def fn(ay, a_sign, ry, r_sign, s_nibs, k_nibs):
        return verify_kernel(ay, a_sign, ry, r_sign, s_nibs, k_nibs)

    return fn


def pad_batch_to(prep: dict, size: int) -> dict:
    """Pad host-prepared arrays up to `size` (invalid padding lanes verify
    False and are masked by pre_ok)."""
    n = prep["ay"].shape[0]
    assert size >= n
    pad = size - n
    out = {}
    for k, v in prep.items():
        if k == "pre_ok":
            out[k] = np.concatenate([v, np.zeros(pad, bool)])
        else:
            out[k] = np.concatenate(
                [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
    return out


def multichip_verify(pubs, sigs, msgs, mesh: Optional[Mesh] = None):
    """End-to-end sharded verify: host prep → dp-sharded kernel → gather."""
    from ..ops.ed25519 import prepare_batch
    mesh = mesh or make_mesh()
    ndev = mesh.devices.size
    prep = prepare_batch(pubs, sigs, msgs)
    n = prep["ay"].shape[0]
    padded = -(-n // ndev) * ndev
    prep = pad_batch_to(prep, padded)
    fn = sharded_verify_fn(mesh)
    ok = np.asarray(fn(
        jnp.asarray(prep["ay"]), jnp.asarray(prep["a_sign"]),
        jnp.asarray(prep["ry"]), jnp.asarray(prep["r_sign"]),
        jnp.asarray(prep["s_nibs"]), jnp.asarray(prep["k_nibs"])))
    return ok[:n] & prep["pre_ok"][:n]
