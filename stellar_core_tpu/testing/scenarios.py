"""Scenario lab (ISSUE 8): declarative robustness scenarios.

Each scenario composes what PRs 3-6 built — the seeded fault injector,
ChaosTransport, the fleet observability stack — with this PR's node
lifecycle (Simulation.stop_node / restart_node / add_late_node), the
Herder's self-healing out-of-sync recovery, the overlay flood defense,
and the tx-queue surge eviction, into one deterministic, asserted run
that emits a **fleet bench block**: slot latency p50/p95, externalize
skew, and scenario-specific numbers (recovery time-to-tracking, flood
latency ratio, surge evictions) plus normalized `records` for
`bench/history.jsonl` under scenario-specific platform keys
(`scenario-churn`, `scenario-flood`, ...) — scenario regressions gate
exactly like perf regressions (`bench.py --scenario NAME`,
tools/bench_compare.py).

Every schedule runs on seeded RNG streams and virtual app clocks only
(no wall clock, no unseeded randomness — the sctlint D1/D2 contract),
so one (scenario, seed, scale) triple replays identically.

Catalog: docs/robustness.md#scenario-catalog. Tier-1 runs the small
seeded variants (tests/test_scenarios.py); full soaks ride the `slow`
marker.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Callable, Dict, List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..history.archive import HistoryArchive
from ..main.config import Config
from ..simulation.geography import LatencyMatrix
from ..simulation.simulation import Simulation
from ..util import rnd
from ..util.log import get_logger
from ..xdr import (
    Memo, MessageType, MuxedAccount, SCPQuorumSet, StellarMessage,
    Transaction, TransactionEnvelope, _Ext,
)
from . import AppLedgerAdapter, TestAccount

log = get_logger("LoadGen")


# --------------------------------------------------------------------------
# shared plumbing

def _record(metric: str, unit: str, value: float, platform: str,
            direction: str, source: str) -> dict:
    """One normalized bench record (tools/bench_compare.py schema)."""
    return {"metric": metric, "unit": unit, "value": value,
            "platform": platform, "direction": direction, "source": source,
            "round": None, "at_unix": None, "commit": None}


def _keys(n: int, tag: bytes, seed: int) -> List[SecretKey]:
    return [SecretKey.from_seed(sha256(tag + b"-%d-" % seed + bytes([i])))
            for i in range(n)]


def _clear_verify_cache() -> None:
    from ..crypto import keys as _keys_mod
    _keys_mod.flush_verify_cache()


def _header_hashes(app) -> Dict[int, str]:
    rows = app.database.execute(
        "SELECT ledgerseq, ledgerhash FROM ledgerheaders").fetchall()
    return dict(rows)


def _assert_header_equality(apps: List, min_common: int = 2) -> int:
    """Per-height header-hash equality across every app's DB; returns the
    number of common heights compared."""
    maps = [_header_hashes(a) for a in apps]
    common = set.intersection(*(set(m) for m in maps))
    assert len(common) >= min_common, \
        "too few common heights: %d" % len(common)
    for seq in sorted(common):
        hashes = {m[seq] for m in maps}
        assert len(hashes) == 1, "fork at ledger %d: %r" % (seq, hashes)
    return len(common)


def _fleet_block(agg) -> dict:
    """The fleet summary sub-block every scenario emits."""
    summary = agg.fleet_stats()["summary"]
    return {
        "slot_count": summary["slot_count"],
        "slot_latency_p50_ms": round(
            summary["slot_latency_p50_s"] * 1e3, 3),
        "slot_latency_p95_ms": round(
            summary["slot_latency_p95_s"] * 1e3, 3),
        "externalize_skew_p50_ms": round(
            summary["externalize_skew_p50_s"] * 1e3, 3),
        "externalize_skew_max_ms": round(
            summary["externalize_skew_max_s"] * 1e3, 3),
        "stragglers": summary["stragglers"],
    }


def _crank_until(sim: Simulation, pred: Callable[[], bool],
                 max_rounds: int, what: str) -> None:
    assert sim.crank_until(pred, max_rounds), \
        "scenario stalled waiting for %s: %r" % (
            what, {n: v.app.ledger_manager.last_closed_ledger_num()
                   for n, v in sim.nodes.items()})


def _common_records(name: str, fleet: dict, source: str) -> List[dict]:
    plat = "scenario-%s" % name
    return [
        _record("scenario_slot_latency_p95", "ms",
                fleet["slot_latency_p95_ms"], plat, "lower", source),
        _record("scenario_externalize_skew_max", "ms",
                fleet["externalize_skew_max_ms"], plat, "lower", source),
    ]


def _overlay_records(name: str, ob: Optional[dict],
                     source: str) -> List[dict]:
    """Direction-aware records from an `overlay_breakdown` (ISSUE 10):
    flood duplication ratio (lower = less O(n²) waste) and end-to-end
    tx latency p50/p95. Delegated to tools/bench_compare.py so the
    emission rules (skip idle-run zeros) live in one place."""
    if ob is None:
        return []
    return _bench_compare().overlay_breakdown_records(
        ob, "scenario-%s" % name, source)


def _bench_compare():
    """tools/bench_compare.py as a module WITHOUT touching sys.path
    (library code must not graft the repo root onto the process-wide
    import path); loaded once by file location, cached in sys.modules."""
    import importlib.util
    import sys
    mod = sys.modules.get("_sct_tools_bench_compare")
    if mod is not None:
        return mod
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "bench_compare.py")
    spec = importlib.util.spec_from_file_location(
        "_sct_tools_bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_sct_tools_bench_compare"] = mod
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------
# churn: kill / restart under load, rejoin via recovery + archive catchup

def run_churn(seed: int, scale: str, workdir: str) -> dict:
    """Churn soak: a 4-node fleet closes ledgers under payment load and
    publishes checkpoints; one tracking node is killed mid-run, the
    survivors advance past the victim's validity bracket, the victim
    restarts over its persisted DB/buckets, loses sync (stuck timer),
    and self-heals: externalize hints locate the network, recovery
    triggers CatchupWork against the archive, and tracking resumes —
    asserted per-height header-hash-equal with the survivors."""
    freq = 4
    bracket = 12
    cycles = 1 if scale == "tier1" else 2
    archive_root = os.path.join(workdir, "archive")
    os.makedirs(archive_root, exist_ok=True)

    def tweak_for(i: int):
        def tweak(cfg: Config) -> None:
            cfg.DATABASE = "sqlite3://%s" % os.path.join(
                workdir, "node%d.db" % i)
            cfg.BUCKET_DIR_PATH = os.path.join(workdir, "buckets-%d" % i)
            cfg.CHECKPOINT_FREQUENCY = freq
            cfg.LEDGER_VALIDITY_BRACKET = bracket
            cfg.CONSENSUS_STUCK_TIMEOUT_SECONDS = 2.0
            cfg.CATCHUP_COMPLETE = True   # replay every height: the
            # hash-equality assertion covers the victim's whole gap
            arch = HistoryArchive.local_dir("lab", archive_root)
            d = {"get": arch.get_tmpl, "mkdir": arch.mkdir_tmpl}
            if i == 0:
                d["put"] = arch.put_tmpl
            cfg.HISTORY = {"lab": d}
        return tweak

    sim = Simulation(Simulation.OVER_LOOPBACK)
    keys = _keys(4, b"churn", seed)
    qset = SCPQuorumSet(threshold=3,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = []
    for i, k in enumerate(keys):
        node = sim.add_node(k, qset, name="n%d" % i,
                            cfg_tweak=tweak_for(i))
        node.app.enable_buckets()
        names.append(node.name)
    for i in range(4):
        for j in range(i + 1, 4):
            sim.connect(names[i], names[j])
    sim.apply_latency_matrix(LatencyMatrix(names, "single-dc", seed))
    sim.start_all_nodes()
    victim_name = names[-1]
    n0 = sim.nodes[names[0]].app

    _crank_until(sim, lambda: sim.have_all_externalized(3), 40000,
                 "initial convergence")
    # payment load: a couple of funded accounts ping-ponging
    adapter = AppLedgerAdapter(n0)
    root = adapter.root_account()
    accounts = _keys(2, b"churn-acct", seed)
    n0.submit_transaction(root.tx(
        [root.op_create_account(k.public_key, 10**10) for k in accounts]))
    payers = [TestAccount(adapter, k) for k in accounts]
    pay_seq: Dict[bytes, int] = {}
    pump_state = {"lcl": 0}

    def pump_load(n_txs: int = 2) -> None:
        # throttled to one burst per closed ledger: steady load, not a
        # per-crank firehose
        lcl = n0.ledger_manager.last_closed_ledger_num()
        if lcl == pump_state["lcl"]:
            return
        pump_state["lcl"] = lcl
        for i in range(n_txs):
            p = payers[i % len(payers)]
            seqk = p.sk.seed
            try:
                seq = pay_seq.get(seqk) or p.next_seq()
                st = n0.submit_transaction(p.tx(
                    [p.op_payment(root.account_id, 100 + i)], seq=seq))
                if st == 0:
                    pay_seq[seqk] = seq + 1
                else:
                    pay_seq.pop(seqk, None)  # resync from the ledger
            except AssertionError:
                pay_seq.pop(seqk, None)   # account not yet created

    recovery_times: List[float] = []
    for cycle in range(cycles):
        victim = sim.nodes[victim_name]
        lcl_at_kill = victim.app.ledger_manager.last_closed_ledger_num()
        sim.stop_node(victim_name)
        # survivors advance past the victim's validity bracket AND past
        # the next checkpoint boundaries, pumping load the whole way
        down_target = lcl_at_kill + bracket + 2 * freq

        def survivors_ahead() -> bool:
            pump_load()
            return sim.have_all_externalized(down_target)
        _crank_until(sim, survivors_ahead, 120000,
                     "survivors past the bracket")
        # drain the publish queue so the archive covers the gap
        _crank_until(
            sim, lambda: n0.history_manager.publish_queue() == [],
            60000, "publish queue drain")

        sim.restart_node(victim_name)
        victim = sim.nodes[victim_name]
        h = victim.app.herder
        from ..herder.herder import HerderState

        def victim_recovered() -> bool:
            pump_load(1)
            return (h.recoveries >= 1 and
                    h.state == HerderState.HERDER_TRACKING_STATE and
                    victim.app.ledger_manager.last_closed_ledger_num() >=
                    down_target)
        _crank_until(sim, victim_recovered, 200000,
                     "victim recovery to TRACKING")
        mjson = victim.app.metrics.to_json()
        assert mjson["herder.recovery.lost-sync"]["count"] >= 1
        assert mjson["herder.recovery.attempt"]["count"] >= 1
        assert mjson["herder.recovery.catchup-triggered"]["count"] >= 1, \
            "recovery never routed through CatchupWork"
        ttt = mjson["herder.recovery.time-to-tracking"]
        assert ttt["count"] >= 1
        recovery_times.append(ttt["mean"])

    # everyone advances together after the final heal
    tip = max(v.app.ledger_manager.last_closed_ledger_num()
              for v in sim.nodes.values())
    _crank_until(sim, lambda: sim.have_all_externalized(tip + 2), 60000,
                 "post-recovery convergence")
    common = _assert_header_equality(
        [v.app for v in sim.nodes.values()], min_common=8)
    fleet = _fleet_block(sim.fleet())
    sim.stop_all_nodes()

    source = "bench.py --scenario churn"
    ttt_s = round(max(recovery_times), 6)
    records = _common_records("churn", fleet, source)
    records.append(_record("scenario_recovery_time_to_tracking", "s",
                           ttt_s, "scenario-churn", "lower", source))
    return {
        "metric": "scenario_churn", "unit": "ms",
        "value": fleet["slot_latency_p95_ms"],
        "platform": "scenario-churn",
        "scenario": "churn", "seed": seed, "scale": scale,
        "topology": {"nodes": 4, "threshold": 3, "mode": "loopback",
                     "profile": "single-dc",
                     "checkpoint_frequency": freq, "bracket": bracket},
        "fault_schedule": ["kill %s x%d, restart after bracket+2*freq "
                           "slots" % (victim_name, cycles)],
        "assertions": {
            "recovery_cycles": cycles,
            "recovery_time_to_tracking_s": ttt_s,
            "common_heights_hash_equal": common,
        },
        "fleet": fleet,
        "records": records,
    }


# --------------------------------------------------------------------------
# flood: adversarial envelope/tx flood vs the per-peer rate limiter

def _junk_tx_message(network_id: bytes, i: int) -> StellarMessage:
    """Distinct, cheap-to-reject flood payload: an unsigned payment from
    a nonexistent account (every honest node drops it at checkValid)."""
    from ..xdr import Asset, Operation, OperationBody, OperationType, \
        PaymentOp
    sk = SecretKey.from_seed(sha256(b"flood-src" + network_id))
    dst = SecretKey.from_seed(sha256(b"flood-dst" + network_id))
    op = Operation(sourceAccount=None, body=OperationBody(
        OperationType.PAYMENT,
        PaymentOp(destination=MuxedAccount.from_account_id(dst.public_key),
                  asset=Asset.native(), amount=1 + i)))
    t = Transaction(
        sourceAccount=MuxedAccount.from_account_id(sk.public_key),
        fee=100, seqNum=i + 1, timeBounds=None, memo=Memo.none(),
        operations=[op], ext=_Ext.v0())
    return StellarMessage(MessageType.TRANSACTION,
                          TransactionEnvelope.for_tx(t))


def run_flood(seed: int, scale: str, workdir: str) -> dict:
    """Adversarial flood: 3 honest validators plus one flooder peer over
    the real overlay stack. The baseline leg closes ledgers clean; the
    flood leg has the flooder spray distinct junk transactions until the
    per-peer token bucket caps it and ban-score escalation bans + drops
    it — honest slot latency p95 must stay within tolerance of the
    baseline."""
    slots = 6 if scale == "tier1" else 20
    burst_msgs = 60 if scale == "tier1" else 200

    def leg(flood_on: bool, prop_on: bool = True) -> dict:
        rnd.reseed(seed)
        _clear_verify_cache()
        sim = Simulation(Simulation.OVER_PEERS)
        hkeys = _keys(3, b"flood-honest", seed)
        fkey = _keys(1, b"flood-adversary", seed)[0]
        qset = SCPQuorumSet(threshold=2,
                            validators=[k.public_key for k in hkeys],
                            innerSets=[])

        def tweak(cfg: Config) -> None:
            cfg.DATABASE = "sqlite3://:memory:"
            # tight defense so the scenario caps within a short run:
            # ~burst tokens, slow refill, quick ban escalation
            cfg.FLOOD_RATE_LIMIT_PER_PEER = 50.0
            cfg.FLOOD_RATE_BURST = 30
            cfg.FLOOD_BAN_SCORE_THRESHOLD = 40
            # real-cadence virtual slots (1 s apart): honest per-slot SCP
            # traffic stays under the refill rate, while the flooder's
            # burst lands inside one instant and caps — accelerated
            # closes would make EVERY peer look like a flooder
            cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = False
            cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
            # the propagation-disabled control leg measures the hop-
            # recording overhead (ISSUE 17 acceptance: close-latency
            # p95 within 5% of this leg)
            cfg.PROPAGATION_STATS_ENABLED = prop_on
        honest = [sim.add_node(k, qset, name="h%d" % i, cfg_tweak=tweak)
                  for i, k in enumerate(hkeys)]
        flooder = sim.add_node(fkey, qset, name="adv", cfg_tweak=tweak)
        for i in range(3):
            for j in range(i + 1, 3):
                sim.connect_peers(honest[i].name, honest[j].name)
        for h in honest:
            sim.connect_peers(flooder.name, h.name)
        sim.start_all_nodes()
        honest_apps = [n.app for n in honest]

        def honest_at(seq: int) -> bool:
            return all(a.ledger_manager.last_closed_ledger_num() >= seq
                       for a in honest_apps)
        _crank_until(sim, lambda: honest_at(2), 60000, "flood-leg start")
        # honest payment traffic through the real overlay: the wire
        # cockpit's tx-lifecycle funnel measures submit→applied latency
        # under flood vs baseline (ISSUE 10)
        ad = AppLedgerAdapter(honest_apps[0])
        root = ad.root_account()
        base_seq = ad.seq_num(root.account_id)
        for i in range(3):
            st = honest_apps[0].submit_transaction(root.tx(
                [root.op_payment(root.account_id, 1 + i)],
                seq=base_seq + 1 + i))
            assert st == 0, "honest payment rejected at submit"
        base = max(a.ledger_manager.last_closed_ledger_num()
                   for a in honest_apps)

        flood_stats = {}
        if flood_on:
            net = flooder.app.config.network_id
            sent = 0
            adv_key = flooder.app.config.node_id().to_xdr()

            def flooder_banned() -> bool:
                return any(adv_key not in a.overlay_manager
                           .authenticated_peers and
                           a.overlay_manager.ban_manager.is_banned(
                               flooder.app.config.node_id())
                           for a in honest_apps)
            for _ in range(40):
                if flooder_banned():
                    break
                for _ in range(burst_msgs):
                    flooder.app.overlay_manager.broadcast_message(
                        _junk_tx_message(net, sent), False)
                    sent += 1
                sim.crank_all_nodes(4)
            assert flooder_banned(), \
                "flood never escalated into a BanManager ban"
            m0 = honest_apps[0].metrics.to_json()
            limited = m0.get("overlay.flood.rate-limited",
                             {}).get("count", 0)
            bans = sum(a.metrics.to_json().get("overlay.flood.ban",
                                               {}).get("count", 0)
                       for a in honest_apps)
            assert limited > 0, "rate limiter never capped the flooder"
            assert bans >= 1
            flood_stats = {"junk_sent": sent, "limited_at_h0": limited,
                           "bans": bans}

        _crank_until(sim, lambda: honest_at(base + slots), 200000,
                     "honest liveness%s" % (" under flood"
                                            if flood_on else ""))
        # the honest payments actually completed the funnel
        def payments_applied() -> bool:
            lc = honest_apps[0].herder.tx_lifecycle
            return lc.fleet_json()["count"] >= 3
        _crank_until(sim, payments_applied, 60000,
                     "honest payments applied")
        _assert_header_equality(honest_apps, min_common=2)
        from ..util.fleet import FleetAggregator
        agg = FleetAggregator()
        for n in honest:
            agg.add_app(n.name, n.app)
        fleet = _fleet_block(agg)
        overlay = agg.overlay_breakdown()
        propagation = agg.propagation_summary()
        sim.stop_all_nodes()
        return {"fleet": fleet, "flood": flood_stats,
                "overlay_breakdown": overlay,
                "propagation": propagation}

    off = leg(False)
    on = leg(True)
    # propagation-disabled control: same flood, hop recording off —
    # the ISSUE 17 overhead guard compares honest slot p95 against it
    ctrl = leg(True, prop_on=False)
    assert ctrl["propagation"] is None, \
        "control leg still recorded propagation hops"
    p95_off = max(off["fleet"]["slot_latency_p95_ms"], 0.001)
    ratio = round(on["fleet"]["slot_latency_p95_ms"] / p95_off, 3)
    p95_ctrl = max(ctrl["fleet"]["slot_latency_p95_ms"], 0.001)
    prop_overhead = round(on["fleet"]["slot_latency_p95_ms"] / p95_ctrl, 3)
    source = "bench.py --scenario flood"
    records = _common_records("flood", on["fleet"], source)
    records.append(_record("scenario_flood_latency_ratio", "x", ratio,
                           "scenario-flood", "lower", source))
    records.append(_record("scenario_flood_prop_overhead_ratio", "x",
                           prop_overhead, "scenario-flood", "lower",
                           source))
    # wire-cockpit gates (ISSUE 10): flood duplication ratio + honest
    # tx latency under flood
    records.extend(_overlay_records("flood", on["overlay_breakdown"],
                                    source))
    # propagation cockpit gates (ISSUE 17): hop latency, tree depth,
    # redundant bandwidth share — and the cross-cockpit reconciliation
    # (duplicates/firsts over merged hop records IS the flood
    # duplication ratio; both cockpits count at Floodgate.add_record)
    bc = _bench_compare()
    records.extend(bc.propagation_records(
        on["propagation"], "scenario-flood", source))
    errs = bc.validate_propagation(
        on["propagation"], where="flood",
        flood=(on["overlay_breakdown"] or {}).get("flood"))
    assert not errs, "propagation block failed validation: %r" % errs
    assert on["overlay_breakdown"] is not None
    assert on["overlay_breakdown"]["flood"]["unique"] > 0
    assert on["overlay_breakdown"]["tx_latency_ms"]["count"] >= 3
    assert on["propagation"] is not None
    assert on["propagation"]["trees"] > 0
    assert on["propagation"]["redundant_bandwidth_share"] > 0
    return {
        "metric": "scenario_flood", "unit": "ms",
        "value": on["fleet"]["slot_latency_p95_ms"],
        "platform": "scenario-flood",
        "scenario": "flood", "seed": seed, "scale": scale,
        "topology": {"nodes": 3, "threshold": 2, "mode": "peers",
                     "adversaries": 1},
        "fault_schedule": ["flooder sprays %d-msg junk-tx bursts until "
                           "banned" % (60 if scale == "tier1" else 200)],
        "assertions": {
            "flooder_banned": True,
            "limited_at_h0": on["flood"]["limited_at_h0"],
            "bans": on["flood"]["bans"],
            "junk_sent": on["flood"]["junk_sent"],
            "p95_ratio_on_vs_off": ratio,
            "prop_overhead_ratio": prop_overhead,
        },
        "fleet": on["fleet"],
        "baseline_fleet": off["fleet"],
        "control_fleet": ctrl["fleet"],
        "overlay_breakdown": on["overlay_breakdown"],
        "baseline_overlay_breakdown": off["overlay_breakdown"],
        "propagation": on["propagation"],
        "records": records,
    }


# --------------------------------------------------------------------------
# partition: region severed and healed; minority self-heals via SCP state

def run_partition(seed: int, scale: str, workdir: str) -> dict:
    """Partitioned-region heal: 4 validators across a three-region
    latency matrix over chaos links; one region (1 node) is severed, the
    majority keeps externalizing, the minority's stuck timer fires and
    recovery polls; after heal, the recovery path re-learns the live
    slots via GET_SCP_STATE solicitation (no archive needed inside the
    remember window) and tracking resumes hash-equal."""
    part_slots = 4 if scale == "tier1" else 8

    def tweak(cfg: Config) -> None:
        cfg.DATABASE = "sqlite3://:memory:"
        # cross-region slots take several virtual seconds (latency +
        # nomination rounds): 10 s only fires for the genuinely severed
        # node, not for a slow-but-alive majority
        cfg.CONSENSUS_STUCK_TIMEOUT_SECONDS = 10.0
        # the partitioned node's virtual clock jumps ahead on its own
        # timers; idle/straggler drops would disconnect it permanently
        # (sim links have no redial) — the scenario tests SCP recovery,
        # not the peer book, so park the peer-liveness timeouts
        cfg.PEER_TIMEOUT = 10**6
        cfg.PEER_STRAGGLER_TIMEOUT = 10**6

    sim = Simulation(Simulation.OVER_PEERS)
    keys = _keys(4, b"partition", seed)
    qset = SCPQuorumSet(threshold=3,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = [sim.add_node(k, qset, name="p%d" % i, cfg_tweak=tweak).name
             for i, k in enumerate(keys)]
    sim.apply_latency_matrix(LatencyMatrix(names, "three-region", seed))
    for i in range(4):
        for j in range(i + 1, 4):
            sim.connect_peers(names[i], names[j], chaos=True)
    sim.start_all_nodes()
    _crank_until(sim, lambda: sim.have_all_externalized(3), 80000,
                 "pre-partition convergence")

    minority = names[3]
    majority = names[:3]
    for other in majority:
        sim.set_partition(minority, other, True)
    maj_apps = [sim.nodes[n].app for n in majority]
    min_app = sim.nodes[minority].app
    base = max(a.ledger_manager.last_closed_ledger_num() for a in maj_apps)

    def majority_ahead() -> bool:
        return all(a.ledger_manager.last_closed_ledger_num() >=
                   base + part_slots for a in maj_apps)
    _crank_until(sim, majority_ahead, 200000, "majority under partition")
    for other in majority:
        sim.heal_partition(minority, other)
        # the frames the partition ate advanced the senders' HMAC
        # sequences: the healed link is cryptographically dead, like a
        # real partition killing TCP — reconnect with a fresh handshake
        sim.reconnect_peers(minority, other, chaos=True)

    h = min_app.herder
    from ..herder.herder import HerderState

    def minority_healed() -> bool:
        return (h.recoveries >= 1 and
                h.state == HerderState.HERDER_TRACKING_STATE and
                min_app.ledger_manager.last_closed_ledger_num() >=
                base + part_slots)
    _crank_until(sim, minority_healed, 200000, "minority heal")
    mjson = min_app.metrics.to_json()
    assert mjson["herder.recovery.lost-sync"]["count"] >= 1
    assert mjson["herder.recovery.scp-state-request"]["count"] >= 1, \
        "recovery never solicited SCP state"
    ttt = mjson["herder.recovery.time-to-tracking"]
    assert ttt["count"] >= 1
    tip = max(v.app.ledger_manager.last_closed_ledger_num()
              for v in sim.nodes.values())
    _crank_until(sim, lambda: sim.have_all_externalized(tip + 2), 80000,
                 "post-heal convergence")
    common = _assert_header_equality([v.app for v in sim.nodes.values()],
                                     min_common=4)
    fleet = _fleet_block(sim.fleet())
    matrix = sim.latency_matrix.to_json()
    sim.stop_all_nodes()

    source = "bench.py --scenario partition"
    heal_s = round(ttt["mean"], 6)
    records = _common_records("partition", fleet, source)
    records.append(_record("scenario_recovery_time_to_tracking", "s",
                           heal_s, "scenario-partition", "lower", source))
    return {
        "metric": "scenario_partition", "unit": "ms",
        "value": fleet["slot_latency_p95_ms"],
        "platform": "scenario-partition",
        "scenario": "partition", "seed": seed, "scale": scale,
        "topology": {"nodes": 4, "threshold": 3, "mode": "peers",
                     "profile": "three-region",
                     "regions": matrix["regions"]},
        "fault_schedule": ["sever %s from all for %d slots, then heal"
                           % (minority, part_slots)],
        "assertions": {
            "recovery_time_to_tracking_s": heal_s,
            "scp_state_requests":
                mjson["herder.recovery.scp-state-request"]["count"],
            "common_heights_hash_equal": common,
        },
        "fleet": fleet,
        "records": records,
    }


# --------------------------------------------------------------------------
# surge: pool saturation with hot-account contention + fee-bid eviction

def run_surge(seed: int, scale: str, workdir: str) -> dict:
    """Surge: a 3-node fleet with a deliberately small tx pool is hit
    with 3 rounds of low-fee payments (every round pays the SAME hot
    destination) until the pool saturates, then a burst of high-fee
    bids — each admission must evict a lowest-fee-rate chain tail
    (`herder.tx-queue.surge-evicted`), the pool stays bounded, and
    consensus keeps closing hash-equal."""
    n_low = 10 if scale == "tier1" else 20
    n_high = 5 if scale == "tier1" else 10
    cap_ops = 3 * n_low

    def tweak(cfg: Config) -> None:
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.TESTING_UPGRADE_MAX_TX_SET_SIZE = cap_ops
        cfg.POOL_LEDGER_MULTIPLIER = 1

    sim = Simulation(Simulation.OVER_LOOPBACK)
    keys = _keys(3, b"surge", seed)
    qset = SCPQuorumSet(threshold=2,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = [sim.add_node(k, qset, name="s%d" % i, cfg_tweak=tweak).name
             for i, k in enumerate(keys)]
    for i in range(3):
        for j in range(i + 1, 3):
            sim.connect(names[i], names[j])
    sim.start_all_nodes()
    n0 = sim.nodes[names[0]].app
    _crank_until(sim, lambda: sim.have_all_externalized(2), 40000,
                 "surge start")

    adapter = AppLedgerAdapter(n0)
    root = adapter.root_account()
    low_keys = _keys(n_low, b"surge-low", seed)
    high_keys = _keys(n_high, b"surge-high", seed)
    n0.submit_transaction(root.tx(
        [root.op_create_account(k.public_key, 10**10)
         for k in low_keys + high_keys]))
    hot = root.account_id   # every payment hits ONE hot destination

    def accounts_exist() -> bool:
        return adapter.account_exists(low_keys[0].public_key) and \
            adapter.account_exists(high_keys[-1].public_key)
    _crank_until(sim, accounts_exist, 40000, "surge accounts")

    # saturate: 3 rounds of low-fee chains, no cranking in between so the
    # pool actually fills instead of draining into txsets
    lows = [TestAccount(adapter, k) for k in low_keys]
    for rnd_i in range(3):
        for acc in lows:
            seq = acc.next_seq() + rnd_i
            st = n0.submit_transaction(acc.tx(
                [acc.op_payment(hot, 50 + rnd_i)], seq=seq, fee=100))
            assert st == 0, "low-fee fill rejected (round %d)" % rnd_i
    q = n0.herder.tx_queue
    assert q.size_ops() == cap_ops, (q.size_ops(), cap_ops)

    # the pool is full: every further same-rate bid must bounce...
    bounced = n0.submit_transaction(
        lows[0].tx([lows[0].op_payment(hot, 999)],
                   seq=lows[0].next_seq() + 3, fee=100))
    assert bounced != 0, "same-rate bid admitted into a full pool"
    # ...while strictly-better bids evict lowest-rate tails
    highs = [TestAccount(adapter, k) for k in high_keys]
    for acc in highs:
        st = n0.submit_transaction(acc.tx(
            [acc.op_payment(hot, 77)], seq=acc.next_seq(), fee=2000))
        assert st == 0, "high-fee bid rejected despite eviction room"
    assert q.size_ops() <= cap_ops
    evicted = n0.metrics.to_json()[
        "herder.tx-queue.surge-evicted"]["count"]
    assert evicted >= n_high, (evicted, n_high)

    # remember the high bids' hashes before consensus consumes them
    high_hashes = {f.contents_hash().hex()
                   for chain in q._pending.values()
                   for f in chain if f.fee_bid >= 2000}
    assert len(high_hashes) == n_high
    tip = n0.ledger_manager.last_closed_ledger_num()
    _crank_until(sim, lambda: sim.have_all_externalized(tip + 4), 80000,
                 "surge drain")
    # the high bids actually made it into closed ledgers
    applied = {row[0] for row in n0.database.execute(
        "SELECT txid FROM txhistory").fetchall()}
    assert high_hashes <= applied, \
        "surge-admitted high-fee txs never applied"
    assert q.size_ops() <= cap_ops
    common = _assert_header_equality([v.app for v in sim.nodes.values()],
                                     min_common=4)
    agg = sim.fleet()
    fleet = _fleet_block(agg)
    # loopback mode has no wire stats (the overlay shim), but the
    # tx-lifecycle half still measures the surge's submit→apply funnel
    # incl. the evictions the fee-market defense performed (ISSUE 10)
    overlay = agg.overlay_breakdown()
    sim.stop_all_nodes()

    source = "bench.py --scenario surge"
    records = _common_records("surge", fleet, source)
    records.extend(_overlay_records("surge", overlay, source))
    assert overlay is not None
    assert overlay["tx_latency_ms"]["count"] > 0
    assert overlay["outcomes"].get("evicted", 0) >= n_high
    return {
        "metric": "scenario_surge", "unit": "ms",
        "value": fleet["slot_latency_p95_ms"],
        "platform": "scenario-surge",
        "scenario": "surge", "seed": seed, "scale": scale,
        "topology": {"nodes": 3, "threshold": 2, "mode": "loopback",
                     "pool_cap_ops": cap_ops},
        "fault_schedule": ["%d low-fee chains x3 rounds to a hot "
                           "destination, then %d high-fee bids"
                           % (n_low, n_high)],
        "assertions": {
            "surge_evicted": evicted,
            "pool_bounded": True,
            "applied_tx_rows": len(applied),
            "common_heights_hash_equal": common,
        },
        "fleet": fleet,
        "overlay_breakdown": overlay,
        "records": records,
    }


# --------------------------------------------------------------------------
# checkpoint: one validator serving signed state checkpoints + membership
# proofs to a fleet of light clients while validating (ISSUE 12)

def run_checkpoint(seed: int, scale: str, workdir: str) -> dict:
    """Checkpoint-serving: a 3-node fleet closes ledgers under payment
    load; node 0 maintains the incremental Merkle state commitment
    (asserted equal to the from-scratch oracle at EVERY close) and
    emits signed checkpoints on a short interval. After the load phase
    a fleet of light clients round-robins membership proofs for the
    touched accounts and verifies each against the served checkpoint
    with `light_client_verify` — a pure function over proof bytes, no
    ledger DB, no replay — under the <10 ms acceptance bound; one
    tampered proof and one forged checkpoint signature must be
    rejected."""
    from ..ledger.state_commitment import light_client_verify
    from ..util.timer import real_perf_counter
    from ..xdr import LedgerKey
    slots = 9 if scale == "tier1" else 24
    n_clients = 50 if scale == "tier1" else 1000
    interval = 3

    def tweak(cfg: Config) -> None:
        cfg.DATABASE = "sqlite3://:memory:"
        cfg.STATE_CHECKPOINT_INTERVAL = interval

    sim = Simulation(Simulation.OVER_LOOPBACK)
    keys = _keys(3, b"checkpoint", seed)
    qset = SCPQuorumSet(threshold=2,
                        validators=[k.public_key for k in keys],
                        innerSets=[])
    names = []
    for i, k in enumerate(keys):
        node = sim.add_node(k, qset, name="c%d" % i, cfg_tweak=tweak)
        # every validator runs the bucket list (consensus commits to
        # bucketListHash); node 0 is additionally the checkpoint SERVER
        node.app.enable_buckets(os.path.join(workdir,
                                             "cp-buckets-%d" % i))
        names.append(node.name)
    server = sim.nodes[names[0]].app
    for i in range(3):
        for j in range(i + 1, 3):
            sim.connect(names[i], names[j])
    sim.start_all_nodes()
    _crank_until(sim, lambda: sim.have_all_externalized(2), 40000,
                 "checkpoint-scenario start")

    adapter = AppLedgerAdapter(server)
    root = adapter.root_account()
    accounts = _keys(6, b"checkpoint-acct", seed)
    server.submit_transaction(root.tx(
        [root.op_create_account(k.public_key, 10**10) for k in accounts]))
    sce = server.state_commitment
    bl = server.bucket_manager.bucket_list
    oracle_state = {"lcl": 0, "checked": 0}

    def oracle_each_close() -> None:
        # the 30-ledger-replay acceptance's live twin: every NEW close
        # on the serving node must keep incremental == from-scratch
        lcl = server.ledger_manager.last_closed_ledger_num()
        if lcl == oracle_state["lcl"] or sce.root is None:
            return
        oracle_state["lcl"] = lcl
        assert sce.root == sce.from_scratch_root(bl), \
            "incremental Merkle root diverged from oracle at %d" % lcl
        oracle_state["checked"] += 1

    payers = [TestAccount(adapter, k) for k in accounts]
    pay_seq: Dict[bytes, int] = {}
    pump_state = {"lcl": 0}

    def pump_load() -> None:
        lcl = server.ledger_manager.last_closed_ledger_num()
        oracle_each_close()
        if lcl == pump_state["lcl"]:
            return
        pump_state["lcl"] = lcl
        for i, p in enumerate(payers[:3]):
            seqk = p.sk.seed
            try:
                seq = pay_seq.get(seqk) or p.next_seq()
                st = server.submit_transaction(p.tx(
                    [p.op_payment(root.account_id, 10 + i)], seq=seq))
                if st == 0:
                    pay_seq[seqk] = seq + 1
                else:
                    pay_seq.pop(seqk, None)
            except AssertionError:
                pay_seq.pop(seqk, None)

    base = server.ledger_manager.last_closed_ledger_num()

    def load_done() -> bool:
        pump_load()
        return sim.have_all_externalized(base + slots) and \
            sce.checkpoint() is not None
    _crank_until(sim, load_done, 200000, "checkpoint load phase")
    assert oracle_state["checked"] >= slots - 2, oracle_state

    # --- the serving side: checkpoint + per-client proofs -------------
    cp = sce.checkpoint()
    assert cp is not None
    prove_keys = [LedgerKey.account(root.account_id)] + \
        [LedgerKey.account(k.public_key) for k in accounts]
    proofs = []
    for k in prove_keys:
        p = sce.prove_entry(k)
        assert p is not None, "no proof for a live account"
        proofs.append(p)
    import json as _json
    proof_bytes = max(len(_json.dumps(p)) for p in proofs)

    # --- the light-client fleet: verify without replay or DB ----------
    net = server.config.network_id
    verify_s: List[float] = []
    for c in range(n_clients):
        p = proofs[c % len(proofs)]
        t0 = real_perf_counter()
        ok, reason = light_client_verify(p, cp, net)
        verify_s.append(real_perf_counter() - t0)
        assert ok, "light client %d rejected a valid proof: %s" % (
            c, reason)
    verify_s.sort()
    p50_ms = round(verify_s[len(verify_s) // 2] * 1e3, 4)
    p95_ms = round(verify_s[int(len(verify_s) * 0.95)] * 1e3, 4)
    assert p95_ms < 10.0, "light-client verify p95 %.3f ms over the " \
        "10 ms acceptance bound" % p95_ms

    # tampering must be caught: a flipped entry byte and a forged
    # checkpoint signature
    bad = _json.loads(_json.dumps(proofs[0]))
    flip = "00" if bad["entry"][-2:] != "00" else "01"
    bad["entry"] = bad["entry"][:-2] + flip
    assert not light_client_verify(bad, cp, net)[0], \
        "tampered entry accepted"
    forged = dict(cp)
    forged["signature"] = "00" * 64
    assert not light_client_verify(proofs[0], forged, net)[0], \
        "forged checkpoint signature accepted"

    emitted = server.metrics.to_json()[
        "commitment.checkpoint.emitted"]["count"]
    assert emitted >= 1
    common = _assert_header_equality([v.app for v in sim.nodes.values()],
                                     min_common=4)
    fleet = _fleet_block(sim.fleet())
    sim.stop_all_nodes()

    source = "bench.py --scenario checkpoint"
    records = _common_records("checkpoint", fleet, source)
    records.append(_record("scenario_checkpoint_verify_p95", "ms",
                           p95_ms, "scenario-checkpoint", "lower",
                           source))
    records.append(_record("checkpoint_proof_bytes", "bytes",
                           proof_bytes, "scenario-checkpoint", "lower",
                           source))
    return {
        "metric": "scenario_checkpoint", "unit": "ms",
        "value": fleet["slot_latency_p95_ms"],
        "platform": "scenario-checkpoint",
        "scenario": "checkpoint", "seed": seed, "scale": scale,
        "topology": {"nodes": 3, "threshold": 2, "mode": "loopback",
                     "checkpoint_interval": interval,
                     "light_clients": n_clients},
        "fault_schedule": ["none (proof-integrity scenario: tampered "
                           "proof + forged signature must be rejected)"],
        "assertions": {
            "oracle_checked_closes": oracle_state["checked"],
            "checkpoints_emitted": emitted,
            "light_clients": n_clients,
            "verify_p50_ms": p50_ms,
            "verify_p95_ms": p95_ms,
            "proof_bytes": proof_bytes,
            "tampered_rejected": True,
            "common_heights_hash_equal": common,
        },
        "fleet": fleet,
        "records": records,
    }


# --------------------------------------------------------------------------
# overload: 5x open-loop oversubscription vs the ingress admission tier

def run_overload(seed: int, scale: str, workdir: str) -> dict:
    """Million-submitter overload (ISSUE 18): a 3-node loopback fleet
    whose pool capacity is deliberately small is oversubscribed 5x —
    untrusted-class flooder accounts spraying chained payments, plus a
    seeded open-loop Zipf flood from a 10^6-key submitter keyspace —
    while a handful of priority-class accounts submit honest traffic.
    Three legs: `unloaded` (priority only — the latency baseline),
    `control` (full overload, INGRESS_ENABLED=False — the pool absorbs
    everything and degrades), `ingress` (full overload through the
    admission tier). Gates: the ingress leg keeps applied-tx p95 within
    2x the unloaded baseline with priority goodput >= 90%, while the
    control leg visibly degrades on both axes; every ingress queue/map
    stays bounded."""
    slots = 6 if scale == "tier1" else 12
    txset_cap = 20
    n_pri, n_flood = 4, 10
    pri_per_slot, flood_per_slot = 2, 10     # 100/slot vs 20 capacity
    junk_rate = 30.0                          # open-loop keyspace flood
    oversub = round(
        (n_flood * flood_per_slot + junk_rate + n_pri * pri_per_slot)
        / float(txset_cap), 1)

    pri_keys = _keys(n_pri, b"overload-pri", seed)
    flood_keys = _keys(n_flood, b"overload-flood", seed)

    def leg(name: str, ingress_on: bool, loaded: bool) -> dict:
        rnd.reseed(seed)
        _clear_verify_cache()
        from ..crypto import strkey as _strkey
        sim = Simulation(Simulation.OVER_LOOPBACK)
        vkeys = _keys(3, b"overload-val", seed)
        qset = SCPQuorumSet(threshold=2,
                            validators=[k.public_key for k in vkeys],
                            innerSets=[])

        def tweak(cfg: Config) -> None:
            cfg.DATABASE = "sqlite3://:memory:"
            cfg.TESTING_UPGRADE_MAX_TX_SET_SIZE = txset_cap
            cfg.POOL_LEDGER_MULTIPLIER = 3
            cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = False
            cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
            cfg.INGRESS_ENABLED = ingress_on
            if ingress_on:
                cfg.INGRESS_ASYNC_INTAKE = True
                # roughly one close's worth of intake plus slack: the
                # Zipf keyspace flood must overflow it every slot so
                # shed-lowest-class-first is actually exercised
                cfg.INGRESS_INTAKE_DEPTH = txset_cap + 4
                cfg.INGRESS_MAX_SOURCES = 4096
                # tight classes so 5x oversubscription throttles and
                # sheds within a short run: priority unlimited, the
                # junk keyspace (default) and the untrusted flooders
                # capped far below their spray rates
                cfg.INGRESS_CLASSES = {
                    "default": {"rate": 0.5, "burst": 2.0,
                                "max_inflight": 2},
                    "untrusted": {"rate": 0.3, "burst": 1.0,
                                  "max_inflight": 2},
                }
                # the genesis root (account factory) rides the priority
                # class too — the operator pins its own keys
                cfg.INGRESS_PRIORITY_ACCOUNTS = [
                    _strkey.encode_public_key(k.public_key.key_bytes)
                    for k in pri_keys] + [
                    SecretKey.from_seed(
                        sha256(cfg.network_id)).strkey_public()]
                cfg.INGRESS_UNTRUSTED_ACCOUNTS = [
                    _strkey.encode_public_key(k.public_key.key_bytes)
                    for k in flood_keys]
        names = [sim.add_node(k, qset, name="o%d" % i,
                              cfg_tweak=tweak).name
                 for i, k in enumerate(vkeys)]
        for i in range(3):
            for j in range(i + 1, 3):
                sim.connect(names[i], names[j])
        sim.start_all_nodes()
        n0 = sim.nodes[names[0]].app
        _crank_until(sim, lambda: sim.have_all_externalized(2), 40000,
                     "overload start (%s)" % name)

        adapter = AppLedgerAdapter(n0)
        root = adapter.root_account()
        # chunked creates: a tx wider than maxTxSetSize ops could never
        # fit a txset with this deliberately tiny capacity
        all_keys = pri_keys + flood_keys
        rseq = root.next_seq() - 1
        for i in range(0, len(all_keys), 10):
            rseq += 1
            st = n0.submit_transaction(root.tx(
                [root.op_create_account(k.public_key, 10**10)
                 for k in all_keys[i:i + 10]], seq=rseq))
            assert st == 0, "account-creation tx refused at submit"

        def accounts_exist() -> bool:
            return adapter.account_exists(pri_keys[0].public_key) and \
                adapter.account_exists(flood_keys[-1].public_key)
        _crank_until(sim, accounts_exist, 80000, "overload accounts")

        pris = [TestAccount(adapter, k) for k in pri_keys]
        floods = [TestAccount(adapter, k) for k in flood_keys]
        seqs: Dict[bytes, int] = {}
        dest = root.account_id

        def burst(accts, per_acct, counters, amt, hashes=None) -> None:
            """One per-slot submission burst of chained payments; local
            seq tracking resyncs from the ledger after hard rejects so
            bounced chains resume instead of gapping forever. `amt`
            varies per slot so a bounced-then-retried payment is a
            distinct tx, not a lifecycle duplicate."""
            for acc in accts:
                k = acc.sk.seed
                seq = seqs.get(k)
                if seq is None:
                    seq = acc.next_seq() - 1
                for _ in range(per_acct):
                    frame = acc.tx([acc.op_payment(dest, amt)],
                                   seq=seq + 1, fee=100)
                    status = n0.submit_transaction(frame)
                    counters["submitted"] += 1
                    if status == 0:
                        seq += 1
                        counters["accepted"] += 1
                        if hashes is not None:
                            hashes.add(frame.contents_hash().hex())
                    elif status == 3:
                        counters["backpressured"] += 1
                    else:
                        counters["rejected"] += 1
                        seq = acc.next_seq() - 1
                seqs[k] = seq

        pri_counts = {"submitted": 0, "accepted": 0, "backpressured": 0,
                      "rejected": 0}
        flood_counts = dict(pri_counts)
        pri_hashes: set = set()
        if loaded:
            # the open-loop 10^6-keyspace junk flood rides app-clock
            # timers for the whole measurement window
            n0.load_generator.start_open_loop(
                junk_rate, duration_s=float(slots), submitters=10**6,
                zipf_s=1.1, seed=seed, tick=0.5)
        base = n0.ledger_manager.last_closed_ledger_num()
        for s in range(slots):
            # flooders race ahead of honest traffic every slot — in the
            # control leg they fill the pool before priority arrives
            if loaded:
                burst(floods, flood_per_slot, flood_counts, 1 + s)
            burst(pris, pri_per_slot, pri_counts, 1 + s, pri_hashes)
            _crank_until(sim,
                         lambda: sim.have_all_externalized(base + s + 1),
                         200000, "overload slot %d (%s)" % (s, name))
        ol = n0.load_generator.open_loop_status()
        n0.load_generator.stop_open_loop()
        # drain: a few unloaded closes so in-flight priority txs land
        _crank_until(sim,
                     lambda: sim.have_all_externalized(base + slots + 3),
                     200000, "overload drain (%s)" % name)

        applied = {row[0] for row in n0.database.execute(
            "SELECT txid FROM txhistory").fetchall()}
        pri_applied = len(pri_hashes & applied)
        _assert_header_equality([v.app for v in sim.nodes.values()],
                                min_common=2)
        agg = sim.fleet()
        fleet = _fleet_block(agg)
        overlay = agg.overlay_breakdown()
        ing = n0.herder.ingress
        ing_json = ing.to_json() if ing is not None else None
        lc = n0.herder.tx_lifecycle.to_json()
        sim.stop_all_nodes()
        return {"fleet": fleet, "overlay_breakdown": overlay,
                "ingress_json": ing_json, "lifecycle": lc,
                "open_loop": ol, "pri": pri_counts,
                "pri_applied": pri_applied, "flood": flood_counts}

    unloaded = leg("unloaded", ingress_on=True, loaded=False)
    control = leg("control", ingress_on=False, loaded=True)
    on = leg("ingress", ingress_on=True, loaded=True)

    def p95(legb: dict) -> float:
        ob = legb["overlay_breakdown"]
        assert ob is not None and ob["tx_latency_ms"]["count"] > 0
        return max(ob["tx_latency_ms"]["p95"], 0.001)

    p95_unloaded, p95_control, p95_on = p95(unloaded), p95(control), \
        p95(on)
    p95_ratio = round(p95_on / p95_unloaded, 3)
    control_ratio = round(p95_control / p95_unloaded, 3)
    goodput = round(on["pri_applied"] /
                    max(1, on["pri"]["submitted"]), 6)
    goodput_control = round(control["pri_applied"] /
                            max(1, control["pri"]["submitted"]), 6)
    cj = on["ingress_json"]["counters"]
    admitted = sum(c["admitted"] for c in cj.values())
    throttled = sum(c["throttled"] for c in cj.values())
    shed = sum(c["shed"] for c in cj.values())
    decided = admitted + throttled + shed
    shed_ratio = round(shed / max(1, decided), 6)
    ingress_block = {
        "oversubscription": oversub,
        "decided": decided, "admitted": admitted,
        "throttled": throttled, "shed": shed,
        "shed_ratio": shed_ratio,
        "priority": {"submitted": on["pri"]["submitted"],
                     "applied": on["pri_applied"],
                     "goodput": goodput},
        "intake": on["ingress_json"]["intake"],
        "sources": on["ingress_json"]["sources"],
        "outcomes": on["lifecycle"]["outcomes"],
        "tx_latency_p95_ms": round(p95_on, 3),
        "unloaded_p95_ms": round(p95_unloaded, 3),
        "p95_ratio": p95_ratio,
    }

    # acceptance gates (ISSUE 18): bounded latency + priority goodput
    # through the admission tier, visible degradation without it
    assert p95_ratio <= 2.0, \
        "ingress leg p95 %.1fms exceeds 2x unloaded %.1fms" \
        % (p95_on, p95_unloaded)
    assert goodput >= 0.9, \
        "priority goodput %.3f under overload with ingress on" % goodput
    assert goodput_control < goodput, \
        "control leg did not degrade priority goodput (%.3f vs %.3f)" \
        % (goodput_control, goodput)
    assert p95_control > p95_on, \
        "control leg p95 %.1fms not worse than ingress leg %.1fms" \
        % (p95_control, p95_on)
    assert shed > 0 and throttled > 0, (shed, throttled)
    # bounded memory: intake and per-source maps never exceed their caps
    assert on["ingress_json"]["intake"]["depth"] <= \
        on["ingress_json"]["intake"]["cap"]
    assert on["ingress_json"]["sources"]["tracked"] <= \
        on["ingress_json"]["sources"]["cap"]
    # the lifecycle funnel counted the sheds (sum contract: funnel
    # outcomes are a subset of ingress decisions — duplicates decided
    # more than once are tracked once)
    oc = on["lifecycle"]["outcomes"]
    assert oc.get("shed", 0) + oc.get("throttled", 0) > 0
    assert oc.get("shed", 0) <= shed
    assert oc.get("throttled", 0) <= throttled
    # the open-loop flood actually spanned a wide keyspace and was
    # backpressured rather than absorbed
    assert on["open_loop"]["distinct_submitters"] > 50
    assert on["open_loop"]["backpressured"] > 0
    assert on["open_loop"]["last_retry_after"] is not None

    source = "bench.py --scenario overload"
    plat = "scenario-overload"
    records = _common_records("overload", on["fleet"], source)
    bc = _bench_compare()
    records.extend(bc.ingress_records(ingress_block, plat, source))
    records.append(_record("overload_control_p95_ratio", "x",
                           control_ratio, plat, "higher", source))
    errs = bc.validate_ingress(ingress_block, where="overload")
    assert not errs, "ingress block failed validation: %r" % errs
    return {
        "metric": "scenario_overload", "unit": "ms",
        "value": p95_on,
        "platform": plat,
        "scenario": "overload", "seed": seed, "scale": scale,
        "topology": {"nodes": 3, "threshold": 2, "mode": "loopback",
                     "txset_cap": txset_cap, "pool_multiplier": 3,
                     "priority_accounts": n_pri,
                     "flooder_accounts": n_flood,
                     "junk_keyspace": 10**6},
        "fault_schedule": [
            "%d untrusted flooders x%d chained payments per slot + "
            "%.0f tx/s Zipf(1.1) open-loop junk from a 10^6-key "
            "keyspace (%.1fx oversubscribed) for %d slots"
            % (n_flood, flood_per_slot, junk_rate, oversub, slots)],
        "assertions": {
            "p95_ratio_vs_unloaded": p95_ratio,
            "control_p95_ratio_vs_unloaded": control_ratio,
            "priority_goodput": goodput,
            "control_priority_goodput": goodput_control,
            "shed": shed, "throttled": throttled,
            "intake_bounded": True, "sources_bounded": True,
            "open_loop_distinct_submitters":
                on["open_loop"]["distinct_submitters"],
        },
        "fleet": on["fleet"],
        "baseline_fleet": unloaded["fleet"],
        "control_fleet": control["fleet"],
        "ingress": ingress_block,
        "overlay_breakdown": on["overlay_breakdown"],
        "records": records,
    }


# --------------------------------------------------------------------------
# registry + runner

SCENARIOS: Dict[str, dict] = {
    "churn": {
        "fn": run_churn,
        "description": "kill/restart a tracking node under load + "
                       "archive failover; self-healing recovery to "
                       "TRACKING (time-to-tracking gated)",
    },
    "flood": {
        "fn": run_flood,
        "description": "adversarial junk-tx flood vs the per-peer token "
                       "bucket + ban-score escalation; honest p95 vs "
                       "no-flood baseline",
    },
    "partition": {
        "fn": run_partition,
        "description": "three-region latency matrix, one region severed "
                       "and healed; minority self-heals via solicited "
                       "SCP state",
    },
    "surge": {
        "fn": run_surge,
        "description": "tx-pool saturation with hot-account contention; "
                       "fee-bid surge eviction keeps the pool bounded",
    },
    "overload": {
        "fn": run_overload,
        "description": "5x+ open-loop oversubscription from a 10^6-key "
                       "Zipf submitter keyspace vs the ingress admission "
                       "tier; priority goodput + bounded p95 gated "
                       "against an ingress-off control leg",
    },
    "checkpoint": {
        "fn": run_checkpoint,
        "description": "one validator maintains the incremental Merkle "
                       "state commitment under load (oracle-checked "
                       "every close) and serves signed checkpoints + "
                       "membership proofs to a light-client fleet that "
                       "verifies without replay (<10 ms p95 gated)",
    },
}


def run_scenario(name: str, seed: int = 1, scale: str = "tier1",
                 workdir: Optional[str] = None) -> dict:
    """Run one scenario deterministically; returns its fleet bench block
    (see module docstring). Raises AssertionError when a scenario
    invariant does not hold."""
    if name not in SCENARIOS:
        raise ValueError("unknown scenario %r; known: %s"
                         % (name, ", ".join(sorted(SCENARIOS))))
    assert scale in ("tier1", "soak"), scale
    rnd.reseed(seed)
    _clear_verify_cache()
    own_dir = workdir is None
    if own_dir:
        workdir = tempfile.mkdtemp(prefix="sct-scenario-%s-" % name)
    try:
        block = SCENARIOS[name]["fn"](seed, scale, workdir)
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)
    block["description"] = SCENARIOS[name]["description"]
    return block


def run_suite(seed: int = 1, scale: str = "tier1") -> dict:
    """All scenarios, one artifact: the shape committed as
    BENCH_r*_scenarios.json and ingested into bench/history.jsonl."""
    blocks = {name: run_scenario(name, seed=seed, scale=scale)
              for name in sorted(SCENARIOS)}
    records: List[dict] = []
    for b in blocks.values():
        records.extend(b["records"])
    return {
        "metric": "scenario_suite", "unit": "scenarios",
        "value": len(blocks), "platform": "scenario-suite",
        "seed": seed, "scale": scale,
        "scenarios": blocks,
        "records": records,
    }
