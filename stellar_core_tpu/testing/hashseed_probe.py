"""Hash-seed differential probe: the runtime twin of sctlint's S1 rule
(ISSUE 20; docs/static-analysis.md#hash-seed-gate).

S1 statically bans set-ordered iteration from feeding consensus-visible
values, because CPython randomizes str/bytes hashing per process
(`PYTHONHASHSEED`) and set iteration order with it. This probe is the
empirical check that the static net has no holes: it runs a seeded
3-node loopback consensus simulation (buckets enabled, a funded account
created mid-run so txsets are non-empty), records every node's
per-height header hash, bucket-list hash and txset apply-order, and
prints the whole record as canonical JSON on stdout.

The differential gate (tests/test_hashseed_differential.py) runs this
module in two subprocesses under DIFFERENT `PYTHONHASHSEED` values and
asserts byte-identical output — any set-order leak into hashing, XDR
serialization or txset ordering shows up as a diff between the two
runs. Inside one run the three nodes must also agree height-by-height,
which the probe asserts itself before printing.

Run directly: `python -m stellar_core_tpu.testing.hashseed_probe
[--heights N]`.
"""

from __future__ import annotations

import argparse
import json
import sys


def collect(heights: int = 4, max_rounds: int = 200000) -> dict:
    """Drive the sim and return {node: {height: record}} where record =
    {header, bucket_list, txs}. Hashes are hex; txs is the apply-order
    list of full tx hashes in the externalized txset."""
    from ..crypto.keys import SecretKey
    from ..simulation import topologies
    from ..testing import AppLedgerAdapter

    sim = topologies.core(3, 2)
    for node in sim.nodes.values():
        node.app.enable_buckets()
    sim.start_all_nodes()

    records: dict = {name: {} for name in sim.nodes}

    def poll() -> None:
        for name, node in sim.nodes.items():
            lm = node.app.ledger_manager
            seq = lm.last_closed_ledger_num()
            d = records[name]
            if seq in d or seq < 1:
                continue
            header = lm.lcl_header
            txs = []
            ts = node.app.herder.pending.get_tx_set(
                header.scpValue.txSetHash)
            if ts is not None:
                txs = [f.full_hash().hex() for f in ts.sort_for_apply()]
            d[seq] = {"header": lm.lcl_hash.hex(),
                      "bucket_list": header.bucketListHash.hex(),
                      "txs": txs}

    def done_through(target: int):
        def pred() -> bool:
            poll()
            return sim.have_all_externalized(target)
        return pred

    if not sim.crank_until(done_through(2), max_rounds):
        raise SystemExit("probe: consensus never reached height 2")

    # a deterministic payment so at least one txset is non-empty (the
    # seeded test key stream, not os.urandom — the probe's output must
    # be identical across runs)
    first = next(iter(sim.nodes.values()))
    root = AppLedgerAdapter(first.app).root_account()
    alice = SecretKey.pseudo_random_for_testing()
    frame = root.tx([root.op_create_account(alice.public_key, 10 ** 9)])
    if first.app.submit_transaction(frame) != 0:
        raise SystemExit("probe: payment submission refused")

    if not sim.crank_until(done_through(heights), max_rounds):
        raise SystemExit("probe: consensus never reached height %d"
                         % heights)
    poll()
    sim.stop_all_nodes()

    # intra-run agreement first: the three nodes must already match
    # height-by-height, otherwise the diff against the other hash seed
    # would blame the wrong thing
    names = sorted(records)
    for h in range(1, heights + 1):
        per = [(n, records[n].get(h)) for n in names]
        vals = {json.dumps(r, sort_keys=True) for (_, r) in per
                if r is not None}
        if len(vals) > 1:
            raise SystemExit("probe: nodes diverged at height %d: %r"
                             % (h, per))
    if not any(records[n].get(h, {}).get("txs")
               for n in names for h in records[n]):
        raise SystemExit("probe: no non-empty txset was externalized")

    # heights past `heights` may differ per node (whoever closed last);
    # trim so both subprocess runs compare a common prefix
    return {n: {str(h): r for h, r in records[n].items()
                if h <= heights}
            for n in names}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="hashseed_probe")
    ap.add_argument("--heights", type=int, default=4)
    args = ap.parse_args(argv)
    out = collect(args.heights)
    json.dump(out, sys.stdout, indent=1, sort_keys=True)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
