"""Test DSL: in-memory ledger + account helpers for building/applying txs.

Role parity: reference `src/test/TxTests.{h,cpp}`, `src/test/TestAccount.h`,
`src/test/TestMarket.h` — the fixtures every transactions/herder test uses.
Used by tests/ and by the LoadGenerator.
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto.hashing import sha256
from ..crypto.keys import SecretKey
from ..ledger.ledgertxn import InMemoryLedgerTxnRoot, LedgerTxn
from ..transactions.transaction_frame import TransactionFrame
from ..xdr import (
    Asset, LedgerHeader, LedgerKey, Memo, MuxedAccount, Operation,
    OperationBody, OperationType, Price, PublicKey, StellarValue,
    StellarValueExt, TimeBounds, Transaction, TransactionEnvelope, _Ext,
)

TESTING_NETWORK_ID = sha256(b"(sct) testing network")
GENESIS_TOTAL_COINS = 10**17

# Default protocol for TestLedger/genesis_header. The pytest harness's
# --protocol-version option rewrites this (tests/conftest.py), re-running
# every version-agnostic suite at another protocol — the reference's
# `--all-versions` re-run (src/test/test.cpp:213-217). Tests pinning an
# explicit ledger_version are unaffected.
DEFAULT_LEDGER_VERSION = 13


def genesis_header(base_fee=100, base_reserve=5_000_000,
                   max_tx_set_size=100, ledger_version=None) -> LedgerHeader:
    if ledger_version is None:
        ledger_version = DEFAULT_LEDGER_VERSION
    return LedgerHeader(
        ledgerVersion=ledger_version, previousLedgerHash=b"\x00" * 32,
        scpValue=StellarValue(txSetHash=b"\x00" * 32, closeTime=1,
                              upgrades=[], ext=StellarValueExt(0, None)),
        txSetResultHash=b"\x00" * 32, bucketListHash=b"\x00" * 32,
        ledgerSeq=2, totalCoins=GENESIS_TOTAL_COINS, feePool=0,
        inflationSeq=0, idPool=0, baseFee=base_fee,
        baseReserve=base_reserve, maxTxSetSize=max_tx_set_size,
        skipList=[b"\x00" * 32] * 4, ext=_Ext.v0())


def root_secret_key(network_id: bytes = TESTING_NETWORK_ID) -> SecretKey:
    """Deterministic root (genesis) key derived from the network id
    (reference txtest::getRoot role)."""
    return SecretKey.from_seed(sha256(network_id))


class TestLedger:
    """An in-memory ledger with a funded root account; applies transactions
    directly (fee+seq then apply), without consensus."""

    __test__ = False    # not a pytest collection target

    def __init__(self, network_id: bytes = TESTING_NETWORK_ID,
                 verifier=None,
                 ledger_version: Optional[int] = None) -> None:
        self.network_id = network_id
        self.root = InMemoryLedgerTxnRoot(
            genesis_header(ledger_version=ledger_version))
        self.verifier = verifier
        root_sk = root_secret_key(network_id)
        from ..transactions.account_helpers import make_account_entry
        ltx = LedgerTxn(self.root)
        ltx.create(make_account_entry(
            root_sk.public_key, GENESIS_TOTAL_COINS,
            (ltx.load_header().ledgerSeq - 1) << 32))
        ltx.commit()
        self.root_account = TestAccount(self, root_sk)

    # -- state access -------------------------------------------------------
    def header(self) -> LedgerHeader:
        return self.root.get_header()

    def balance(self, account_id: PublicKey) -> int:
        e = self.root.get_entry(LedgerKey.account(account_id))
        assert e is not None, "no such account"
        return e.data.value.balance

    def account_exists(self, account_id: PublicKey) -> bool:
        return self.root.get_entry(LedgerKey.account(account_id)) is not None

    def trust_balance(self, account_id: PublicKey, asset: Asset) -> int:
        e = self.root.get_entry(LedgerKey.trustline(account_id, asset))
        assert e is not None, "no trustline"
        return e.data.value.balance

    def seq_num(self, account_id: PublicKey) -> int:
        e = self.root.get_entry(LedgerKey.account(account_id))
        return e.data.value.seqNum

    # -- applying -----------------------------------------------------------
    def advance_ledger(self) -> None:
        """Bump ledgerSeq/closeTime as a real close would."""
        ltx = LedgerTxn(self.root)
        h = ltx.load_header()
        h.ledgerSeq += 1
        h.scpValue.closeTime += 5
        ltx.commit()

    def apply_frame(self, frame: TransactionFrame) -> bool:
        """check → charge fee/seq → apply, mirroring ledger close for a
        single tx."""
        self.advance_ledger()
        # `with` rolls back on an exception mid-apply (common in failing
        # tests) so the root's child slot isn't left registered
        with LedgerTxn(self.root) as ltx:
            ok = frame.check_valid(ltx, 0, self.verifier)
            if not ok:
                ltx.rollback()
                return False
            frame.process_fee_seq_num(ltx, None)
            applied = frame.apply(ltx, self.verifier)
            ltx.commit()  # fees/seq consumed even on failed apply
        return applied

    def close_with(self, frames: List[TransactionFrame]) -> List[bool]:
        """Apply a batch like a ledger close: all fees/seqs first, then all
        ops (reference LedgerManagerImpl::closeLedger ordering)."""
        self.advance_ledger()
        with LedgerTxn(self.root) as ltx:
            for f in frames:
                f.process_fee_seq_num(ltx, None)
            results = [f.apply(ltx, self.verifier) for f in frames]
        return results


class AppLedgerAdapter:
    """Adapts a full Application to the TestLedger account-DSL surface:
    txs are submitted through the Herder and applied by consensus closes
    (MANUAL_CLOSE)."""

    def __init__(self, app) -> None:
        self.app = app
        self.network_id = app.config.network_id

    def header(self) -> LedgerHeader:
        return self.app.ledger_manager.lcl_header

    def _root(self):
        return self.app.ledger_manager.ltx_root()

    def balance(self, account_id: PublicKey) -> int:
        e = self._root().get_entry(LedgerKey.account(account_id))
        assert e is not None, "no such account"
        return e.data.value.balance

    def account_exists(self, account_id: PublicKey) -> bool:
        return self._root().get_entry(
            LedgerKey.account(account_id)) is not None

    def trust_balance(self, account_id, asset):
        e = self._root().get_entry(
            LedgerKey.trustline(account_id, asset))
        assert e is not None
        return e.data.value.balance

    def seq_num(self, account_id: PublicKey) -> int:
        e = self._root().get_entry(LedgerKey.account(account_id))
        return e.data.value.seqNum if e is not None else 0

    def apply_frame(self, frame) -> bool:
        status = self.app.submit_transaction(frame)
        if status != 0:
            return False
        self.app.manual_close()
        from ..xdr import TransactionResultCode
        return frame.result.code == TransactionResultCode.txSUCCESS

    def root_account(self) -> "TestAccount":
        return TestAccount(self, self.app.network_root_key())


class TestAccount:
    __test__ = False    # not a pytest collection target

    def __init__(self, ledger: TestLedger, sk: SecretKey) -> None:
        self.ledger = ledger
        self.sk = sk

    @property
    def account_id(self) -> PublicKey:
        return self.sk.public_key

    @property
    def muxed(self) -> MuxedAccount:
        return MuxedAccount.from_account_id(self.account_id)

    def next_seq(self) -> int:
        return self.ledger.seq_num(self.account_id) + 1

    def balance(self) -> int:
        return self.ledger.balance(self.account_id)

    # -- op builders --------------------------------------------------------
    @staticmethod
    def op(body: OperationBody,
           source: Optional[PublicKey] = None) -> Operation:
        return Operation(
            sourceAccount=(MuxedAccount.from_account_id(source)
                           if source else None),
            body=body)

    def op_create_account(self, dest: PublicKey, balance: int) -> Operation:
        from ..xdr import CreateAccountOp
        return self.op(OperationBody(
            OperationType.CREATE_ACCOUNT,
            CreateAccountOp(destination=dest, startingBalance=balance)))

    def op_payment(self, dest: PublicKey, amount: int,
                   asset: Optional[Asset] = None) -> Operation:
        from ..xdr import PaymentOp
        return self.op(OperationBody(
            OperationType.PAYMENT,
            PaymentOp(destination=MuxedAccount.from_account_id(dest),
                      asset=asset or Asset.native(), amount=amount)))

    def op_change_trust(self, asset: Asset, limit: int) -> Operation:
        from ..xdr import ChangeTrustOp
        return self.op(OperationBody(
            OperationType.CHANGE_TRUST,
            ChangeTrustOp(line=asset, limit=limit)))

    def op_manage_sell_offer(self, selling: Asset, buying: Asset,
                             amount: int, n: int, d: int,
                             offer_id: int = 0) -> Operation:
        from ..xdr import ManageSellOfferOp
        return self.op(OperationBody(
            OperationType.MANAGE_SELL_OFFER,
            ManageSellOfferOp(selling=selling, buying=buying, amount=amount,
                              price=Price(n=n, d=d), offerID=offer_id)))

    def op_manage_buy_offer(self, selling: Asset, buying: Asset,
                            buy_amount: int, n: int, d: int,
                            offer_id: int = 0) -> Operation:
        from ..xdr import ManageBuyOfferOp
        return self.op(OperationBody(
            OperationType.MANAGE_BUY_OFFER,
            ManageBuyOfferOp(selling=selling, buying=buying,
                             buyAmount=buy_amount, price=Price(n=n, d=d),
                             offerID=offer_id)))

    def op_create_passive_sell_offer(self, selling: Asset, buying: Asset,
                                     amount: int, n: int, d: int
                                     ) -> Operation:
        from ..xdr import CreatePassiveSellOfferOp
        return self.op(OperationBody(
            OperationType.CREATE_PASSIVE_SELL_OFFER,
            CreatePassiveSellOfferOp(selling=selling, buying=buying,
                                     amount=amount, price=Price(n=n, d=d))))

    def op_set_options(self, inflation_dest=None, clear_flags=None,
                       set_flags=None, master_weight=None, low=None,
                       med=None, high=None, home_domain=None,
                       signer=None) -> Operation:
        from ..xdr import SetOptionsOp
        return self.op(OperationBody(
            OperationType.SET_OPTIONS,
            SetOptionsOp(inflationDest=inflation_dest,
                         clearFlags=clear_flags, setFlags=set_flags,
                         masterWeight=master_weight, lowThreshold=low,
                         medThreshold=med, highThreshold=high,
                         homeDomain=home_domain, signer=signer)))

    def op_add_signer(self, key_bytes32: bytes, weight: int = 1) -> Operation:
        from ..xdr import Signer, SignerKey
        return self.op_set_options(
            signer=Signer(key=SignerKey.ed25519(key_bytes32), weight=weight))

    def op_allow_trust(self, trustor: PublicKey, code: bytes = b"USD\x00",
                       authorize: int = 1) -> Operation:
        from ..xdr import AllowTrustAsset, AllowTrustOp
        return self.op(OperationBody(
            OperationType.ALLOW_TRUST,
            AllowTrustOp(trustor=trustor, asset=AllowTrustAsset(1, code),
                         authorize=authorize)))

    def op_manage_data(self, name: str,
                       value: Optional[bytes]) -> Operation:
        from ..xdr import ManageDataOp
        return self.op(OperationBody(
            OperationType.MANAGE_DATA,
            ManageDataOp(dataName=name, dataValue=value)))

    # -- tx builders --------------------------------------------------------
    def tx(self, ops: List[Operation], seq: Optional[int] = None,
           fee: Optional[int] = None,
           time_bounds: Optional[TimeBounds] = None,
           extra_signers: Optional[List[SecretKey]] = None,
           memo: Optional[Memo] = None,
           ) -> TransactionFrame:
        header = self.ledger.header()
        t = Transaction(
            sourceAccount=self.muxed,
            fee=fee if fee is not None else header.baseFee * len(ops),
            seqNum=seq if seq is not None else self.next_seq(),
            timeBounds=time_bounds, memo=memo or Memo.none(),
            operations=ops, ext=_Ext.v0())
        frame = TransactionFrame(
            self.ledger.network_id, TransactionEnvelope.for_tx(t))
        frame.add_signature(self.sk)
        for sk in (extra_signers or []):
            frame.add_signature(sk)
        return frame

    # -- high-level actions (apply immediately) -----------------------------
    def create(self, balance: int,
               sk: Optional[SecretKey] = None) -> "TestAccount":
        sk = sk or SecretKey.pseudo_random_for_testing()
        frame = self.tx([self.op_create_account(sk.public_key, balance)])
        assert self.ledger.apply_frame(frame), frame.result
        return TestAccount(self.ledger, sk)

    def pay(self, dest: "TestAccount", amount: int,
            asset: Optional[Asset] = None) -> bool:
        frame = self.tx([self.op_payment(dest.account_id, amount, asset)])
        return self.ledger.apply_frame(frame)

    def change_trust(self, asset: Asset, limit: int) -> bool:
        return self.ledger.apply_frame(
            self.tx([self.op_change_trust(asset, limit)]))
