"""TxSetFrame: the content of a consensus value.

Role parity: reference `src/herder/TxSetFrame.{h,cpp}`:
- canonical order: sort by full envelope hash (TxSetFrame.cpp:61)
- apply order: per-account sequence order, accounts interleaved by a
  hash-XOR shuffle so apply order isn't gameable (TxSetFrame.cpp:101-148)
- surge pricing: when over capacity, keep the highest fee-per-op txs
  (TxSetFrame.cpp:150-275)
- validity: per-tx checkValid + per-account seq chains + fee balance
  (checkOrTrim, TxSetFrame.cpp:277-359) — a TPU batch-verify hot caller
- contents hash: SHA256(previousLedgerHash ‖ sorted envelopes)
  (TxSetFrame.cpp:418-434)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..ledger.ledgertxn import LedgerTxn
from ..transactions.transaction_frame import (
    FeeBumpTransactionFrame, TransactionFrame,
)
from ..xdr import TransactionEnvelope, TransactionSet

AnyFrame = object  # TransactionFrame | FeeBumpTransactionFrame


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


class TxSetFrame:
    def __init__(self, network_id: bytes, previous_ledger_hash: bytes,
                 frames: Optional[List[AnyFrame]] = None) -> None:
        self.network_id = network_id
        self.previous_ledger_hash = previous_ledger_hash
        self.frames: List[AnyFrame] = list(frames or [])
        self._hash: Optional[bytes] = None

    @classmethod
    def from_wire(cls, network_id: bytes,
                  xdr_set: TransactionSet) -> "TxSetFrame":
        frames = [TransactionFrame.make_from_wire(network_id, env)
                  for env in xdr_set.txs]
        return cls(network_id, xdr_set.previousLedgerHash, frames)

    def to_wire(self) -> TransactionSet:
        return TransactionSet(
            previousLedgerHash=self.previous_ledger_hash,
            txs=[f.envelope for f in self.sorted_for_hash()])

    # -- ordering -----------------------------------------------------------
    def sorted_for_hash(self) -> List[AnyFrame]:
        return sorted(self.frames, key=lambda f: f.full_hash())

    @staticmethod
    def _chains_by_seq_account(frames) -> Dict[bytes, List[AnyFrame]]:
        """Per-account chains keyed by the sequence-owning account, each
        chain in seqNum order — shared by apply ordering, surge pricing,
        and validation."""
        by_acc: Dict[bytes, List[AnyFrame]] = {}
        for f in frames:
            by_acc.setdefault(f.seq_account_id().key_bytes, []).append(f)
        for chain in by_acc.values():
            chain.sort(key=lambda f: f.seq_num)
        return by_acc

    def sort_for_apply(self) -> List[AnyFrame]:
        """Deterministic shuffled apply order: group per source account in
        seq order, then round-robin accounts ordered by
        (account_id XOR set_hash)."""
        by_acc = self._chains_by_seq_account(self.sorted_for_hash())
        h = self.get_contents_hash()
        order = sorted(by_acc, key=lambda acc: _xor(acc, h))
        out: List[AnyFrame] = []
        queues = {acc: list(chain) for acc, chain in by_acc.items()}
        while queues:
            for acc in list(order):
                chain = queues.get(acc)
                if not chain:
                    queues.pop(acc, None)
                    continue
                out.append(chain.pop(0))
        return out

    # -- size / fees --------------------------------------------------------
    def size_ops(self) -> int:
        return sum(f.num_operations() for f in self.frames)

    def size_txs(self) -> int:
        return len(self.frames)

    # largest op count one tx can carry (reference MAX_OPS_PER_TX)
    MAX_OPS_PER_TX = 100

    @staticmethod
    def _cap_units(f: AnyFrame, header) -> int:
        """Capacity unit: OPERATIONS from protocol 11, whole TRANSACTIONS
        before (reference TxSetFrame::size, TxSetFrame.cpp:449-453)."""
        return max(1, f.num_operations()) if header.ledgerVersion >= 11 \
            else 1

    def size_for_cap(self, header) -> int:
        return sum(self._cap_units(f, header) for f in self.frames)

    def base_fee(self, header) -> Optional[int]:
        """Per-set effective base fee (reference getBaseFee
        TxSetFrame.cpp:466-495): from protocol 11, when the set is within
        MAX_OPS_PER_TX of capacity, every tx pays the LOWEST
        ceil(feeBid/numOps) bid in the set; otherwise (and always pre-11)
        the protocol base fee applies (returned as None)."""
        if header.ledgerVersion < 11:
            return None
        ops = 0
        lowest = None
        for f in self.frames:
            n = max(1, f.num_operations())
            ops += n
            bid = -(-f.fee_bid // n)  # ROUND_UP
            if lowest is None or bid < lowest:
                lowest = bid
        cutoff = max(0, header.maxTxSetSize - self.MAX_OPS_PER_TX)
        if ops > cutoff and lowest is not None:
            return lowest
        return None

    def total_fees(self, header) -> int:
        """Σ feeCharged at this set's effective base fee from protocol 11;
        pre-11 the full fee bids (reference TxSetFrame::getTotalFees,
        used by combineCandidates' tiebreak)."""
        if header.ledgerVersion < 11:
            return sum(f.fee_bid for f in self.frames)
        bf = self.base_fee(header)
        return sum(f.fee_charged(header, bf) for f in self.frames)

    def _fee_rate_key(self, f: AnyFrame, header) -> Tuple:
        # higher fee per OPERATION first regardless of protocol (reference
        # SurgeCompare, TxSetFrame.cpp:150-186); tie-break by full hash
        return (f.fee_bid * 2**32 // max(1, f.num_operations()),
                f.full_hash())

    def surge_pricing_filter(self, header) -> None:
        """Trim to maxTxSetSize units keeping highest fee-per-unit, whole
        account chains at a time (reference surgePricingFilter)."""
        max_ops = header.maxTxSetSize
        if self.size_for_cap(header) <= max_ops:
            return
        by_acc = self._chains_by_seq_account(self.frames)
        # a chain's priority is its lowest fee-rate tx (can't include later
        # txs without earlier ones)
        included: List[AnyFrame] = []
        ops_used = 0
        chains = list(by_acc.values())
        # greedy: repeatedly take the head tx with best fee rate
        heads = [(c, 0) for c in chains]
        import heapq
        heap = []
        for ci, (c, idx) in enumerate(heads):
            f = c[0]
            heapq.heappush(
                heap, (tuple(-x if isinstance(x, int) else x
                             for x in self._fee_rate_key(f, header)[:1]) +
                       (f.full_hash(),), ci, 0))
        heads_idx = [0] * len(chains)
        while heap:
            _, ci, idx = heapq.heappop(heap)
            if idx != heads_idx[ci]:
                continue
            f = chains[ci][idx]
            if ops_used + self._cap_units(f, header) > max_ops:
                break
            included.append(f)
            ops_used += self._cap_units(f, header)
            heads_idx[ci] += 1
            if heads_idx[ci] < len(chains[ci]):
                nf = chains[ci][heads_idx[ci]]
                heapq.heappush(
                    heap,
                    (tuple(-x if isinstance(x, int) else x
                           for x in self._fee_rate_key(nf, header)[:1]) +
                     (nf.full_hash(),), ci, heads_idx[ci]))
        self.frames = included
        self._hash = None

    # -- validity -----------------------------------------------------------
    def check_or_trim(self, ltx_parent, verifier=None,
                      trim: bool = False) -> Tuple[bool, List[AnyFrame]]:
        """Validate every tx (seq chains per account, checkValid, whole-
        chain fee balance). trim=True removes invalid txs (and their
        dependents); returns (all_valid, trimmed)."""
        removed: List[AnyFrame] = []
        self._prewarm_signatures(ltx_parent, verifier)
        by_acc = self._chains_by_seq_account(self.frames)
        keep: List[AnyFrame] = []
        for acc, chain in sorted(by_acc.items()):
            ltx = LedgerTxn(ltx_parent)
            try:
                from ..xdr import LedgerKey, PublicKey
                acc_entry = ltx.load_without_record(
                    LedgerKey.account(PublicKey.ed25519(acc)))
                if acc_entry is None:
                    removed.extend(chain)
                    continue
                cur_seq = acc_entry.data.value.seqNum
                chain_ok: List[AnyFrame] = []
                bad = False
                for f in chain:
                    if bad or not f.check_valid(ltx, cur_seq, verifier):
                        removed.append(f)
                        bad = True  # later txs have broken seq chain
                        continue
                    cur_seq = f.seq_num
                    chain_ok.append(f)
                keep.extend(chain_ok)
            finally:
                ltx.rollback()
        # whole-set fee balance per FEE SOURCE (reference accountFeeMap
        # keyed by getFeeSourceID — for fee bumps the sponsor, which can
        # differ from the seq account; reference TxSetFrame.cpp:325-356)
        keep = self._check_fee_balances(ltx_parent, keep, removed)
        if trim:
            self.frames = keep
            self._hash = None
            return (not removed), removed
        return (not removed), removed

    def _check_fee_balances(self, ltx_parent, keep: List[AnyFrame],
                            removed: List[AnyFrame]) -> List[AnyFrame]:
        """Drop every tx whose fee source cannot cover the SUM of fees it
        sponsors across the set."""
        from ..transactions.account_helpers import (
            account_available_balance,
        )
        from ..xdr import LedgerKey, PublicKey
        ltx = LedgerTxn(ltx_parent)
        try:
            header = ltx.load_header()
            fees: Dict[bytes, int] = {}
            for f in keep:
                k = f.fee_account_id().key_bytes
                fees[k] = fees.get(k, 0) + f.fee_charged(header)
            bad_sources = set()
            for k, total in fees.items():
                entry = ltx.load_without_record(
                    LedgerKey.account(PublicKey.ed25519(k)))
                if entry is None or account_available_balance(
                        header, entry.data.value) < total:
                    bad_sources.add(k)
            if not bad_sources:
                return keep
            out = []
            broken_chains: Dict[bytes, int] = {}  # seq acc -> first bad seq
            for f in keep:
                if f.fee_account_id().key_bytes in bad_sources:
                    removed.append(f)
                    k = f.seq_account_id().key_bytes
                    broken_chains[k] = min(
                        broken_chains.get(k, f.seq_num), f.seq_num)
                else:
                    out.append(f)
            if broken_chains:
                # later-seq txs of a broken chain can no longer apply
                out2 = []
                for f in out:
                    k = f.seq_account_id().key_bytes
                    if k in broken_chains and                             f.seq_num > broken_chains[k]:
                        removed.append(f)
                    else:
                        out2.append(f)
                out = out2
            return out
        finally:
            ltx.rollback()

    def _prewarm_signatures(self, ltx_parent, verifier) -> None:
        """Two-phase validation (TPU batch hot caller #3): collect every
        hint-matching signature triple for the WHOLE set and verify them in
        one device dispatch; the per-tx walk below then completes entirely
        off the warm verify cache. Reference walks tx-by-tx
        (TxSetFrame.cpp:277-359); batching is the TPU-native reshape."""
        if verifier is None or not getattr(verifier, "wants_prewarm", False):
            return
        if len(self.frames) <= 1:
            return
        from ..transactions.transaction_frame import frames_sig_triples
        ltx = LedgerTxn(ltx_parent)
        try:
            triples = frames_sig_triples(ltx, self.frames)
        finally:
            ltx.rollback()
        if triples:
            verifier.prewarm_many(triples)

    def trim_invalid(self, ltx_parent, verifier=None) -> List[AnyFrame]:
        _, removed = self.check_or_trim(ltx_parent, verifier, trim=True)
        return removed

    def check_valid(self, ltx_parent, verifier=None) -> bool:
        lcl_hash = getattr(ltx_parent, "lcl_hash", None)
        ok, _ = self.check_or_trim(ltx_parent, verifier, trim=False)
        return ok

    # -- hashing ------------------------------------------------------------
    def get_contents_hash(self, hasher=None) -> bytes:
        """SHA256(previousLedgerHash ‖ sorted envelopes), streamed as
        one whole-txset digest through the bounded-join stream path
        (crypto/batch_hasher.stream_digest, ISSUE 12) — identical bytes
        to the incremental-context path, one C-level update per ~1 MiB
        of envelopes instead of one Python call per tx. Callers with an
        app context (herder intake, the close's value check) pass the
        app's BatchHasher so the computation lands in the hash cockpit
        under the `txset` site; cache hits never re-attribute."""
        if self._hash is None:
            from itertools import chain
            chunks = chain(
                (self.previous_ledger_hash,),
                (f.envelope_bytes() for f in self.sorted_for_hash()))
            if hasher is not None:
                self._hash = hasher.hash_stream(chunks, site="txset")
            else:
                from ..crypto.batch_hasher import stream_digest
                self._hash = stream_digest(chunks)
        return self._hash
