"""TransactionQueue: the pending transaction pool.

Role parity: reference `src/herder/TransactionQueue.{h,cpp}:25-227`:
- per-account chains sorted by sequence number
- age-based expiry: txs not included within pendingDepth (4) ledgers are
  dropped and banned for banDepth (10) ledgers
- replace-by-fee requires >= 10x the old fee (FEE_MULTIPLIER)
- pool cap: maxTxSetSize * poolLedgerMultiplier ops
- tryAdd runs full checkValid — TPU batch-verify hot caller #2
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..ledger.ledgertxn import LedgerTxn
from ..util.log import get_logger
from ..util.threads import main_thread_only
from .txset import TxSetFrame

log = get_logger("Herder")


class TxQueueResult:
    ADD_STATUS_PENDING = 0
    ADD_STATUS_DUPLICATE = 1
    ADD_STATUS_ERROR = 2
    ADD_STATUS_TRY_AGAIN_LATER = 3
    ADD_STATUS_FILTERED = 4


class TransactionQueue:
    FEE_MULTIPLIER = 10

    def __init__(self, ledger_access, pending_depth: int = 4,
                 ban_depth: int = 10, pool_ledger_multiplier: int = 2,
                 verifier=None, metrics=None, lifecycle=None) -> None:
        """ledger_access: object exposing .ltx_root() and .header()."""
        self._ledger = ledger_access
        self.pending_depth = pending_depth
        self.ban_depth = ban_depth
        self.pool_multiplier = pool_ledger_multiplier
        self.verifier = verifier
        self.metrics = metrics
        # tx-lifecycle cockpit (ISSUE 10): evict/expire/ban/replace
        # outcomes complete the submit→apply funnel
        self.lifecycle = lifecycle
        # account -> list[frame] sorted by seq; ages are PER ACCOUNT
        # (reference AccountState.mAge: ledgers since the account last
        # had a tx applied — the whole chain expires together)
        self._pending: Dict[bytes, List[object]] = {}
        self._ages: Dict[bytes, int] = {}
        self._known_hashes: Dict[bytes, bytes] = {}  # full hash -> acc
        self._banned: List[set] = [set() for _ in range(ban_depth)]
        # running fee-bid total per FEE source (reference per-account
        # mTotalFees): O(1) admission checks instead of pool scans
        self._fee_totals: Dict[bytes, int] = {}

    def _note_add(self, frame) -> None:
        k = frame.fee_account_id().key_bytes
        self._fee_totals[k] = self._fee_totals.get(k, 0) + frame.fee_bid

    def _note_outcome(self, frame, kind: str) -> None:
        if self.lifecycle is not None:
            self.lifecycle.outcome(frame.full_hash(), kind)

    def _note_remove(self, frame) -> None:
        k = frame.fee_account_id().key_bytes
        left = self._fee_totals.get(k, 0) - frame.fee_bid
        if left > 0:
            self._fee_totals[k] = left
        else:
            self._fee_totals.pop(k, None)

    # -- queries ------------------------------------------------------------
    def size_ops(self) -> int:
        return sum(f.num_operations() for chain in self._pending.values()
                   for f in chain)

    def is_banned(self, tx_hash: bytes) -> bool:
        return any(tx_hash in b for b in self._banned)

    def pool_cap_ops(self) -> int:
        return self._ledger.header().maxTxSetSize * self.pool_multiplier

    # -- add ----------------------------------------------------------------
    @main_thread_only
    def try_add(self, frame) -> int:
        h = frame.full_hash()
        if h in self._known_hashes:
            return TxQueueResult.ADD_STATUS_DUPLICATE
        if self.is_banned(h):
            return TxQueueResult.ADD_STATUS_TRY_AGAIN_LATER
        acc = frame.seq_account_id().key_bytes
        chain = self._pending.get(acc, [])
        # replace-by-fee: same seqnum present?
        replace_idx = None
        for i, f in enumerate(chain):
            if f.seq_num == frame.seq_num:
                if frame.fee_bid < f.fee_bid * self.FEE_MULTIPLIER:
                    return TxQueueResult.ADD_STATUS_ERROR
                replace_idx = i
                break
        # sequence continuity: must extend the chain (or replace)
        cur_seq = self._account_seq(acc)
        if replace_idx is None and \
                frame.seq_num != cur_seq + 1 + len(chain):
            return TxQueueResult.ADD_STATUS_ERROR

        # pool-cap check with surge eviction: a replacement frees its own
        # ops, so it must not count them twice. Victims are only SELECTED
        # here (a hopeless low bid bounces before costing any signature
        # verifies); the eviction COMMITS after the frame proves valid —
        # an invalid tx must never flush honest pending txs for free
        need = self.size_ops() + frame.num_operations() - self.pool_cap_ops()
        if replace_idx is not None:
            need -= chain[replace_idx].num_operations()
        victims = self._surge_victims(frame, need) if need > 0 else []
        if victims is None:
            return TxQueueResult.ADD_STATUS_TRY_AGAIN_LATER

        # full validity check against current ledger — hot verify site
        ltx = LedgerTxn(self._ledger.ltx_root())
        try:
            if getattr(self.verifier, "wants_prewarm", False):
                # ONE batched dispatch for every candidate signature pair
                # of this tx; the per-signer walk inside check_valid then
                # completes off the warm verify cache (hot caller #2,
                # batched the TPU way — same gate as txset.py's
                # check_or_trim). Required for async backends: their
                # enqueue futures complete on the main loop, never inside
                # a synchronous admission call.
                self.verifier.prewarm_many(frame.candidate_sig_triples(ltx))
            seq_base = frame.seq_num - 1
            if not frame.check_valid(ltx, seq_base, self.verifier):
                return TxQueueResult.ADD_STATUS_ERROR
            # the fee source must cover this full fee BID on top of every
            # bid it already sponsors in the pool (reference
            # TransactionQueue.cpp:196-205 accumulates fee bids; fee
            # source != seq account for fee bumps). A replacement nets
            # out the bid of the tx it replaces.
            header = ltx.load_header()
            fee_acc = frame.fee_account_id().key_bytes
            pending_fees = self._fee_totals.get(fee_acc, 0) + frame.fee_bid
            if replace_idx is not None:
                old = chain[replace_idx]
                if old.fee_account_id().key_bytes == fee_acc:
                    pending_fees -= old.fee_bid
            from ..xdr import LedgerKey, PublicKey
            from ..transactions.account_helpers import (
                account_available_balance,
            )
            entry = ltx.load_without_record(
                LedgerKey.account(PublicKey.ed25519(fee_acc)))
            if entry is None or account_available_balance(
                    header, entry.data.value) < pending_fees:
                return TxQueueResult.ADD_STATUS_ERROR
        finally:
            ltx.rollback()

        if victims:
            self._surge_evict(victims, frame)
        if replace_idx is not None:
            old = chain[replace_idx]
            del self._known_hashes[old.full_hash()]
            # ban the replaced tx directly — ban() would drop the chain
            # tail, but later txs still chain off the replacement
            self._banned[0].add(old.full_hash())
            self._note_remove(old)
            self._note_outcome(old, "replaced")
            chain[replace_idx] = frame
        else:
            chain.append(frame)
            chain.sort(key=lambda f: f.seq_num)
        self._pending[acc] = chain
        self._ages.setdefault(acc, 0)
        self._known_hashes[h] = acc
        self._note_add(frame)
        return TxQueueResult.ADD_STATUS_PENDING

    def _surge_victims(self, frame, need):
        """Pool saturated: pick the lowest-fee-rate pending txs whose
        eviction would admit a strictly better bid (reference
        TransactionQueue::canFitWithEviction role; ISSUE 8 surge
        scenario). Only chain TAILS are eligible — an inner eviction
        would break the account's sequence continuity — and a victim
        qualifies only when the incoming fee-per-op strictly beats its
        own. Selection does NOT mutate the pool: None means the incoming
        bid cannot fit even with eviction (nothing is shed for a tx that
        bounces anyway); a list means evicting exactly those tails frees
        `need` ops."""
        # fee rates compared as integer cross-products (a/b < c/d ⇔
        # a*d < c*b for positive denominators) — eviction order is
        # consensus-visible, so no float division here (FL1)
        in_fee = frame.fee_bid
        in_ops = max(1, frame.num_operations())
        own = frame.seq_account_id().key_bytes
        # per-account count of not-yet-selected tail positions: one chain
        # can donate several tails, deepest-first
        tails = {acc: len(chain) for acc, chain in self._pending.items()}
        victims = []
        while need > 0:
            victim_acc = None
            victim_fee, victim_ops = in_fee, in_ops
            victim_tail = None
            for acc, chain in self._pending.items():
                if acc == own or tails[acc] == 0:
                    continue
                tail = chain[tails[acc] - 1]
                t_fee = tail.fee_bid
                t_ops = max(1, tail.num_operations())
                if t_fee * victim_ops < victim_fee * t_ops:
                    victim_acc, victim_tail = acc, tail
                    victim_fee, victim_ops = t_fee, t_ops
            if victim_acc is None:
                return None
            tails[victim_acc] -= 1
            victims.append((victim_acc, victim_tail))
            need -= victim_tail.num_operations()
        return victims

    def _surge_evict(self, victims, frame) -> None:
        """Commit a `_surge_victims` selection: runs only after the
        incoming frame passed full validation, so an invalid tx can never
        flush honest pending txs. Evicted txs are NOT banned: they may be
        resubmitted once the surge clears."""
        m = self.metrics
        for acc, tail in victims:
            chain = self._pending[acc]
            popped = chain.pop()
            assert popped is tail, "pool mutated between select and evict"
            self._known_hashes.pop(popped.full_hash(), None)
            self._note_remove(popped)
            self._note_outcome(popped, "evicted")
            if m is not None:
                m.new_meter("herder.tx-queue.surge-evicted").mark()
            log.debug("surge-evicted tx %s (fee %d over %d op(s) "
                      "underbids %d over %d)",
                      popped.full_hash().hex()[:8],
                      popped.fee_bid, max(1, popped.num_operations()),
                      frame.fee_bid, max(1, frame.num_operations()))
            if not chain:
                self._pending.pop(acc, None)
                self._ages.pop(acc, None)

    def _account_seq(self, acc: bytes) -> int:
        from ..xdr import LedgerKey, PublicKey
        e = self._ledger.ltx_root().get_entry(
            LedgerKey.account(PublicKey.ed25519(acc)))
        return e.data.value.seqNum if e is not None else 0

    # -- ledger-close maintenance -------------------------------------------
    def remove_applied(self, frames: List) -> None:
        for f in frames:
            h = f.full_hash()
            acc = self._known_hashes.pop(h, None)
            if acc is None:
                # also drop any pending tx with same (acc, seq<=applied)
                acc = f.seq_account_id().key_bytes
            chain = self._pending.get(acc)
            if not chain:
                continue
            new_chain = [g for g in chain if g.seq_num > f.seq_num]
            for g in chain:
                if g.seq_num <= f.seq_num:
                    self._note_remove(g)
                    if g.full_hash() != h:
                        self._known_hashes.pop(g.full_hash(), None)
                        # a chain-mate invalidated by the applied tx's
                        # seq advance (the applied tx itself finalizes
                        # via TxLifecycle.applied)
                        self._note_outcome(g, "dropped")
            if new_chain:
                self._pending[acc] = new_chain
                # the account saw a tx applied this ledger: age resets
                self._ages[acc] = 0
            else:
                self._pending.pop(acc, None)
                self._ages.pop(acc, None)

    def shift(self) -> None:
        """Age every account one ledger; an account reaching
        pending_depth has its WHOLE chain banned at once (reference
        shift: per-account mAge, TransactionQueue.cpp:490-530)."""
        self._banned.pop()
        self._banned.insert(0, set())
        for acc in list(self._pending):
            age = self._ages.get(acc, 0) + 1
            if age >= self.pending_depth:
                for f in self._pending[acc]:
                    self._banned[0].add(f.full_hash())
                    self._known_hashes.pop(f.full_hash(), None)
                    self._note_remove(f)
                    self._note_outcome(f, "expired")
                self._pending.pop(acc, None)
                self._ages.pop(acc, None)
            else:
                self._ages[acc] = age

    def ban(self, hashes: List[bytes]) -> None:
        """Ban the listed txs AND drop them from the pool; everything
        chained after a banned tx in its account's chain no longer has a
        valid seq position, so it is dropped and banned too (reference
        TransactionQueue::ban bans the matched tx and its tail)."""
        hs = set(hashes)
        self._banned[0].update(hs)
        # _known_hashes maps hash -> account: jump straight to the one
        # affected chain instead of scanning the whole pool
        for h in hashes:
            acc = self._known_hashes.get(h)
            if acc is None:
                continue
            chain = self._pending.get(acc)
            if not chain:
                continue
            cut = next((i for i, f in enumerate(chain)
                        if f.full_hash() in hs), None)
            if cut is None:
                continue
            for f in chain[cut:]:
                self._banned[0].add(f.full_hash())
                self._known_hashes.pop(f.full_hash(), None)
                self._note_remove(f)
                self._note_outcome(f, "banned")
            if cut:
                self._pending[acc] = chain[:cut]
            else:
                self._pending.pop(acc, None)
                self._ages.pop(acc, None)

    # -- txset construction ---------------------------------------------------
    def to_txset(self, lcl_hash: bytes, network_id: bytes) -> TxSetFrame:
        frames = [f for chain in self._pending.values()
                  for f in chain]
        return TxSetFrame(network_id, lcl_hash, frames)
