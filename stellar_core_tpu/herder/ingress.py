"""TxIngress: admission control in front of the TransactionQueue
(ISSUE 18 tentpole; ROADMAP item 2, the million-user front door).

Role parity: the reference absorbs submission overload inside the pool
(surge pricing + eviction) and per-peer flood buckets; DSig (PAPERS.md,
arXiv:2406.07215) argues datacenter-scale signature services live or die
on admission/backpressure discipline *in front of* the batch path, and
the EdDSA committee study (2302.00418) shows per-source load shaping is
what keeps verification batches well-formed under adversarial mixes.
This module is that front door:

- **Rate classes**: every source account maps to a class — `priority` /
  `default` / `untrusted` — each with a token-bucket `rate`/`burst` and
  a `max_inflight` cap (admissions per close window). Membership is
  config-declared (`INGRESS_PRIORITY_ACCOUNTS` / `_UNTRUSTED_ACCOUNTS`)
  and runtime-tunable (admin `ingress?action=set-class`), bounded at
  MAX_CLASS_OVERRIDES entries.
- **Per-source buckets** live in a RandomEvictionCache capped at
  `max_sources` entries, so 10^6 distinct submitters cost a fixed-size
  map, not 10^6 states (the soak test asserts this).
- **Decisions**: ADMIT (hand the frame to the queue), THROTTLE (the
  source's bucket or inflight cap is exhausted — `TRY_AGAIN_LATER` with
  a computed retry-after hint), SHED (overload: the bounded intake is
  full and the arrival does not outrank anything queued, or the
  `ingress.shed-storm` fault forced it). Shed/throttle land in the
  tx-lifecycle funnel as `herder.tx.outcome.shed` / `.throttled`.
- **Bounded async intake** (`async_intake`): admitted frames park in
  per-class deques (total depth capped) and drain in class-rank order
  on `pump()` — priority first, so a default/untrusted backlog can
  never starve the priority class. When the intake is full an arrival
  only enters by evicting the tail of the *worst-ranked* non-empty
  class strictly below it; otherwise the arrival itself is shed —
  lowest class first, always.
- Fault sites `ingress.admit-stall` (admission decision delayed: the
  caller is told to retry) and `ingress.shed-storm` (forced shed burst)
  make both degraded paths deterministically drivable
  (docs/robustness.md#fault-points).

Everything runs on the injected app clock (virtual in tests — sctlint
D1) and the cache's own seeded RNG (D2); metrics ride a private
registry when none is injected, keeping the `new_*` literals visible to
the M1 catalog scanner. Operator surface: docs/robustness.md
"Ingress & overload", metrics in docs/metrics.md, admin `ingress`
endpoint in docs/admin.md.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ..util.cache import RandomEvictionCache
from ..util.faults import check_faults
from ..util.log import get_logger
from ..util.metrics import MetricsRegistry
from ..util.timer import real_monotonic

log = get_logger("Herder")

# admission decisions
ADMIT = 0      # caller must hand the frame to TransactionQueue now
PARKED = 1     # accepted into the bounded async intake; pump() delivers
THROTTLE = 2   # per-source rate/inflight exceeded -> TRY_AGAIN_LATER
SHED = 3       # overload shed -> TRY_AGAIN_LATER

# class ranks: lower rank = better; shed order walks ranks downward
CLASS_RANKS = {"priority": 0, "default": 1, "untrusted": 2}

# the config-overridable class table. rate <= 0 means unlimited (the
# flood-control convention); the defaults are deliberately generous so
# a node that never configures ingress behaves exactly like one without
# it — admission only bites when an operator declares tighter classes.
DEFAULT_CLASSES: Dict[str, dict] = {
    "priority":  {"rate": 0.0,    "burst": 0.0,     "max_inflight": 0},
    "default":   {"rate": 5000.0, "burst": 100000.0, "max_inflight": 0},
    "untrusted": {"rate": 50.0,   "burst": 200.0,   "max_inflight": 1000},
}


class RateClass:
    __slots__ = ("name", "rank", "rate", "burst", "max_inflight")

    def __init__(self, name: str, rate: float, burst: float,
                 max_inflight: int) -> None:
        self.name = name
        self.rank = CLASS_RANKS[name]
        self.rate = float(rate)
        self.burst = float(burst)
        self.max_inflight = int(max_inflight)

    def to_json(self) -> dict:
        return {"rank": self.rank, "rate": self.rate, "burst": self.burst,
                "max_inflight": self.max_inflight}


class _SourceState:
    __slots__ = ("tokens", "last_refill", "inflight")

    def __init__(self, tokens: float, now: float) -> None:
        self.tokens = tokens
        self.last_refill = now
        self.inflight = 0


class TxIngress:
    """Admission layer; see module docstring."""

    # explicit class assignments are operator input; cap them so a
    # misbehaving driver cannot grow the override map without bound
    MAX_CLASS_OVERRIDES = 4096
    # floor for computed retry-after hints (seconds)
    MIN_RETRY_AFTER = 0.05
    # retry-after when the hint is not rate-derived (shed / stall):
    # "come back after roughly one close drains the backlog"
    DEFAULT_RETRY_AFTER = 1.0

    def __init__(self, metrics=None, now_fn=None, faults=None,
                 classes: Optional[Dict[str, dict]] = None,
                 priority=(), untrusted=(),
                 intake_depth: int = 512, max_sources: int = 65536,
                 async_intake: bool = False,
                 sink: Optional[Callable] = None,
                 shed_cb: Optional[Callable[[bytes], None]] = None) -> None:
        self._now = now_fn or real_monotonic
        # private registry when none is injected: direct constructions
        # (unit tests, the soak harness) stay app-free while every
        # registration below uses the new_* idiom the M1 scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.faults = faults
        self.classes: Dict[str, RateClass] = {}
        for name, defaults in DEFAULT_CLASSES.items():
            spec = dict(defaults)
            spec.update((classes or {}).get(name, {}))
            self.classes[name] = RateClass(
                name, spec["rate"], spec["burst"], spec["max_inflight"])
        self._class_of: Dict[bytes, str] = {}
        for acct in priority:
            self.set_class(acct, "priority")
        for acct in untrusted:
            self.set_class(acct, "untrusted")
        self.intake_depth = int(intake_depth)
        self.async_intake = bool(async_intake)
        self._sink = sink
        self._shed_cb = shed_cb
        # per-source token buckets, bounded; the cache's own seeded RNG
        # keeps eviction deterministic (sctlint D2)
        self._sources: RandomEvictionCache = RandomEvictionCache(
            max(1, int(max_sources)))
        # bounded async intake: one FIFO per class, drained priority-first
        self._intake: Dict[str, deque] = {n: deque() for n in CLASS_RANKS}
        self._intake_total = 0
        self.last_retry_after: Optional[float] = None
        m = self.metrics
        self._m_admitted = m.new_meter("herder.ingress.admitted")
        self._m_parked = m.new_meter("herder.ingress.parked")
        self._m_throttled = m.new_meter("herder.ingress.throttled")
        self._m_shed = m.new_meter("herder.ingress.shed")
        self._m_pumped = m.new_meter("herder.ingress.pumped")
        self._g_depth = m.new_gauge("herder.ingress.intake-depth")
        self._g_sources = m.new_gauge("herder.ingress.sources")
        self.reset_counters()

    # -- class table ---------------------------------------------------------
    def set_class(self, account: bytes, class_name: str) -> None:
        """Pin `account` (32 raw key bytes) to a rate class; assigning
        "default" removes the override. The override map is bounded."""
        if class_name not in self.classes:
            raise ValueError("unknown ingress class %r (known: %s)"
                             % (class_name,
                                ", ".join(sorted(self.classes))))
        if class_name == "default":
            self._class_of.pop(account, None)
            return
        if account not in self._class_of and \
                len(self._class_of) >= self.MAX_CLASS_OVERRIDES:
            raise ValueError("ingress class override map is full "
                             "(%d entries)" % self.MAX_CLASS_OVERRIDES)
        self._class_of[account] = class_name

    def class_of(self, account: bytes) -> RateClass:
        return self.classes[self._class_of.get(account, "default")]

    # -- admission -----------------------------------------------------------
    def _state(self, account: bytes, rc: RateClass,
               now: float) -> _SourceState:
        st = self._sources.maybe_get(account)
        if st is None:
            st = _SourceState(rc.burst, now)
            self._sources.put(account, st)
        return st

    def _retry_after(self, rc: RateClass, st: _SourceState) -> float:
        if rc.rate <= 0:
            return self.DEFAULT_RETRY_AFTER
        deficit = max(0.0, 1.0 - st.tokens)
        return max(self.MIN_RETRY_AFTER,
                   round(deficit / rc.rate, 3) or self.MIN_RETRY_AFTER)

    def admit(self, frame, tx_hash: Optional[bytes] = None,
              fresh: bool = True) -> Tuple[int, Optional[float]]:
        """Admission decision for one frame. Returns (decision,
        retry_after): ADMIT means the caller must queue the frame now,
        PARKED means the bounded intake took it (`pump()` delivers),
        THROTTLE/SHED carry a retry-after hint for the submitter."""
        account = frame.source_account_id().key_bytes
        return self.admit_source(account, frame=frame, tx_hash=tx_hash,
                                 fresh=fresh)

    def admit_source(self, account: bytes, frame=None,
                     tx_hash: Optional[bytes] = None,
                     fresh: bool = True) -> Tuple[int, Optional[float]]:
        """Core admission on raw source-account bytes (the soak test
        drives this directly with synthetic keys)."""
        rc = self.class_of(account)
        now = self._now()
        st = self._state(account, rc, now)
        self._g_sources.set(len(self._sources))
        self.last_retry_after = None
        if check_faults(self, "ingress.shed-storm"):
            return self._shed(rc, "shed-storm")
        if check_faults(self, "ingress.admit-stall"):
            # the admission decision itself is delayed: tell the caller
            # to come back, without charging the source's bucket
            return self._throttle(rc, self.DEFAULT_RETRY_AFTER, "stall")
        if rc.rate > 0:
            st.tokens = min(rc.burst,
                            st.tokens + (now - st.last_refill) * rc.rate)
            st.last_refill = now
            if st.tokens < 1.0:
                return self._throttle(rc, self._retry_after(rc, st))
        if rc.max_inflight > 0 and st.inflight >= rc.max_inflight:
            return self._throttle(rc, self.DEFAULT_RETRY_AFTER,
                                  "inflight")
        if self.async_intake and self._sink is not None and \
                frame is not None:
            parked = self._park(rc, frame, tx_hash, fresh)
            if not parked:
                return self._shed(rc, "intake-full")
            if rc.rate > 0:
                st.tokens -= 1.0
            st.inflight += 1
            return (PARKED, None)
        if rc.rate > 0:
            st.tokens -= 1.0
        st.inflight += 1
        self._m_admitted.mark()
        self.counters[rc.name]["admitted"] += 1
        return (ADMIT, None)

    def _throttle(self, rc: RateClass, retry_after: float,
                  why: str = "rate") -> Tuple[int, float]:
        self._m_throttled.mark()
        self.counters[rc.name]["throttled"] += 1
        self.last_retry_after = retry_after
        log.debug("ingress throttled a %s-class tx (%s); retry in %.3fs",
                  rc.name, why, retry_after)
        return (THROTTLE, retry_after)

    def _shed(self, rc: RateClass, why: str) -> Tuple[int, float]:
        self._m_shed.mark()
        self.counters[rc.name]["shed"] += 1
        self.last_retry_after = self.DEFAULT_RETRY_AFTER
        log.debug("ingress shed a %s-class tx (%s)", rc.name, why)
        return (SHED, self.DEFAULT_RETRY_AFTER)

    # -- bounded async intake ------------------------------------------------
    def _park(self, rc: RateClass, frame, tx_hash, fresh) -> bool:
        """Park an admitted frame in its class FIFO. When the intake is
        at depth, the arrival only enters by shedding the tail of the
        worst-ranked non-empty class strictly below it (lowest class
        first, never the other way around)."""
        if self._intake_total >= self.intake_depth:
            victim_class = None
            for name in sorted(self.classes,
                               key=lambda n: -self.classes[n].rank):
                if self.classes[name].rank <= rc.rank:
                    break
                if self._intake[name]:
                    victim_class = name
                    break
            if victim_class is None:
                return False
            _, vh, vfresh = self._intake[victim_class].pop()
            self._intake_total -= 1
            self._m_shed.mark()
            self.counters[victim_class]["shed"] += 1
            if vfresh and vh is not None and self._shed_cb is not None:
                self._shed_cb(vh)
        self._intake[rc.name].append((frame, tx_hash, fresh))
        self._intake_total += 1
        self._m_parked.mark()
        self.counters[rc.name]["admitted"] += 1
        self._g_depth.set(self._intake_total)
        return True

    def pump(self, max_n: Optional[int] = None) -> int:
        """Drain up to `max_n` parked frames (all, when None) into the
        sink in class-rank order — priority first, so a lower-class
        backlog can never starve the priority class."""
        if self._sink is None or self._intake_total == 0:
            return 0
        budget = self._intake_total if max_n is None \
            else min(max_n, self._intake_total)
        pumped = 0
        for name in sorted(self.classes,
                           key=lambda n: self.classes[n].rank):
            q = self._intake[name]
            while q and pumped < budget:
                frame, tx_hash, fresh = q.popleft()
                self._intake_total -= 1
                pumped += 1
                self._sink(frame, tx_hash, fresh)
            if pumped >= budget:
                break
        if pumped:
            self._m_pumped.mark(pumped)
        self._g_depth.set(self._intake_total)
        return pumped

    def intake_depth_now(self) -> int:
        return self._intake_total

    # -- lifecycle hooks -----------------------------------------------------
    def ledger_closed(self) -> None:
        """A close drains the pool: reset the per-source inflight
        window (max_inflight caps admissions per close window) and reap
        sources whose buckets have fully refilled."""
        now = self._now()
        for key in self._sources.keys():
            st = self._sources.get(key)
            st.inflight = 0
            rc = self.class_of(key)
            if rc.rate > 0:
                st.tokens = min(rc.burst, st.tokens +
                                (now - st.last_refill) * rc.rate)
                st.last_refill = now
                if st.tokens >= rc.burst:
                    self._sources.erase(key)
            else:
                self._sources.erase(key)
        self._g_sources.set(len(self._sources))

    def reset_counters(self) -> None:
        self.counters: Dict[str, Dict[str, int]] = {
            n: {"admitted": 0, "throttled": 0, "shed": 0}
            for n in CLASS_RANKS}

    # -- introspection -------------------------------------------------------
    def to_json(self) -> dict:
        """The admin `ingress?action=status` blob."""
        return {
            "async_intake": self.async_intake,
            "intake": {"depth": self._intake_total,
                       "cap": self.intake_depth,
                       "per_class": {n: len(q)
                                     for n, q in self._intake.items()}},
            "sources": {"tracked": len(self._sources),
                        "cap": self._sources._max,
                        "evictions": self._sources.evictions},
            "classes": {n: rc.to_json()
                        for n, rc in sorted(self.classes.items())},
            "overrides": len(self._class_of),
            "counters": {n: dict(c)
                         for n, c in sorted(self.counters.items())},
        }
