"""TxLifecycle: end-to-end transaction latency attribution (ISSUE 10
tentpole; docs/observability.md#overlay-cockpit).

Answers "how long does a user's transaction take from submit to
applied?" by stamping each locally-received transaction at four
boundaries, all on the injected app clock (sctlint D1 — virtual-clock
simulations stay deterministic):

    submit      Herder.recv_transaction entry (HTTP `tx` or overlay flood)
    queue       TransactionQueue.try_add admission (signature checks paid)
    include     txset construction at nomination (trigger_next_ledger)
    externalize the slot's value externalizing
    apply       the close completing for that slot

Consecutive stamps become the stage histograms
`herder.tx.latency.submit-to-queue` / `queue-to-include` /
`include-to-externalize` / `externalize-to-apply`, and
`herder.tx.latency.total` is computed as the SUM of the four stage
durations — the stages sum to total *by construction*, the same
sum-contract style as the close cockpit's `apply_breakdown`
(tools/bench_compare.py validates it in committed artifacts). A stage
that never happened locally (another node's txset won nomination, so
`include` was never stamped here) is backfilled at the next stamp and
contributes a zero-width stage, keeping the contract exact.

The funnel completes with per-tx outcomes (`herder.tx.outcome.<kind>`):
`applied`, `rejected` (admission failed), `replaced` (replace-by-fee),
`evicted` (surge eviction), `expired` (aged out of the pool), `banned`
(trimmed invalid), `dropped` (chain-mate invalidated by an applied tx),
`deferred` (externalized into a catchup gap), `untracked` (tracking-map
overflow), `shed` / `throttled` (the ingress tier refused it before
queue admission — herder/ingress.py, ISSUE 18). Only locally-observed
transactions are tracked, and the map is bounded at MAX_TRACKED
entries.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic

# stage index in the stamp vector -> stage metric segment
STAGES = ("submit-to-queue", "queue-to-include",
          "include-to-externalize", "externalize-to-apply")


class TxLifecycle:
    """Tx-lifecycle aggregation; see module docstring."""

    MAX_TRACKED = 8192

    def __init__(self, metrics=None, tracer=None, now_fn=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, harnesses) app-registry-free while
        # letting every registration below use the new_* idiom the M1
        # metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self._lock = TrackedLock("herder.tx-lifecycle")
        m = self.metrics
        self._h_stage = {
            s: m.new_histogram("herder.tx.latency.%s" % s) for s in STAGES}
        self._h_total = m.new_histogram("herder.tx.latency.total")
        self._m_outcome: Dict[str, object] = {}
        self.reset()

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        """Zero the cumulative aggregates (admin
        `overlaystats?action=reset`; registry metrics keep their
        monotonic histories)."""
        with self._lock:
            # tx hash -> [t_submit, t_queue, t_include, t_ext] stamps
            self._pending: Dict[bytes, list] = {}
            self.stage_seconds: Dict[str, float] = {s: 0.0 for s in STAGES}
            self.total_seconds = 0.0
            self.applied_count = 0
            self.outcomes: Dict[str, int] = {}
            self.last_slot: Optional[dict] = None
            self._slot_outcomes: Dict[str, int] = {}

    # -- stamps --------------------------------------------------------------
    def submit(self, tx_hash: bytes) -> bool:
        """Stamp a tx at submission; False when the hash is already
        tracked (a re-flooded duplicate must not clobber the original
        stamps)."""
        now = self._now()
        shed = False
        with self._lock:
            if tx_hash in self._pending:
                return False
            if len(self._pending) >= self.MAX_TRACKED:
                # bounded: shed the oldest entry (insertion order)
                oldest = next(iter(self._pending))
                del self._pending[oldest]
                self._outcome_locked("untracked", 1)
                shed = True
            self._pending[tx_hash] = [now, None, None, None]
        if shed:
            self._outcome_meter("untracked").mark()
        return True

    def _stamp(self, tx_hash: bytes, idx: int) -> None:
        now = self._now()
        with self._lock:
            st = self._pending.get(tx_hash)
            if st is None:
                return
            if st[idx] is None:
                st[idx] = now
            # backfill skipped stages so every stage duration stays
            # defined (zero-width) and the sum contract holds
            for i in range(idx):
                if st[i] is None:
                    st[i] = st[idx]

    def queued(self, tx_hash: bytes) -> None:
        self._stamp(tx_hash, 1)

    def included(self, tx_hashes: Iterable[bytes]) -> None:
        for h in tx_hashes:
            self._stamp(h, 2)

    def externalized(self, tx_hashes: Iterable[bytes]) -> None:
        for h in tx_hashes:
            self._stamp(h, 3)

    # -- funnel outcomes -----------------------------------------------------
    def _outcome_meter(self, kind: str):
        m = self._m_outcome.get(kind)
        if m is None:
            m = self.metrics.new_meter("herder.tx.outcome.%s" % kind)
            self._m_outcome[kind] = m
        return m

    def _outcome_locked(self, kind: str, n: int = 1) -> None:
        self.outcomes[kind] = self.outcomes.get(kind, 0) + n
        self._slot_outcomes[kind] = self._slot_outcomes.get(kind, 0) + n

    def outcome(self, tx_hash: bytes, kind: str) -> bool:
        """Terminal outcome for a tracked tx (evicted/expired/...);
        no-op for hashes this node never tracked — remote txsets must
        not inflate the funnel."""
        with self._lock:
            if self._pending.pop(tx_hash, None) is None:
                return False
            self._outcome_locked(kind)
        self._outcome_meter(kind).mark()
        return True

    # -- completion ----------------------------------------------------------
    def applied(self, tx_hashes: Iterable[bytes], slot: int) -> int:
        """The close for `slot` committed: finalize every tracked tx in
        its txset — stage histograms, the by-construction total, and the
        per-slot funnel blob. Returns the number finalized."""
        now = self._now()
        finalized = 0
        with self._lock:
            for h in tx_hashes:
                st = self._pending.pop(h, None)
                if st is None:
                    continue
                stamps = list(st) + [now]
                # backfill any stage the local node never saw
                for i in range(len(stamps) - 2, -1, -1):
                    if stamps[i] is None:
                        stamps[i] = stamps[i + 1]
                durations = [max(0.0, stamps[i + 1] - stamps[i])
                             for i in range(len(STAGES))]
                total = 0.0
                for s, d in zip(STAGES, durations):
                    self._h_stage[s].update(d)
                    self.stage_seconds[s] += d
                    total += d
                # total is the SUM of the stage durations — the sum
                # contract is exact by construction, not approximate
                self._h_total.update(total)
                self.total_seconds += total
                self.applied_count += 1
                self._outcome_locked("applied")
                finalized += 1
            slot_funnel = dict(self._slot_outcomes)
            self._slot_outcomes = {}
            self.last_slot = {"slot": slot, **slot_funnel}
        if finalized:
            self._outcome_meter("applied").mark(finalized)
        if self.tracer is not None and self.tracer.enabled and finalized:
            self.tracer.instant("herder.tx.applied", cat="herder",
                                slot=slot, txs=finalized)
        return finalized

    # -- exports -------------------------------------------------------------
    def to_json(self) -> dict:
        """The admin `overlaystats` cockpit blob (tx-lifecycle half)."""
        total = self._h_total.snapshot()
        stage_p95 = {s: round(self._h_stage[s].snapshot()["p95"] * 1e3, 3)
                     for s in STAGES}
        with self._lock:
            return {
                "applied": self.applied_count,
                "pending_tracked": len(self._pending),
                "stage_seconds": {s: round(self.stage_seconds[s], 6)
                                  for s in STAGES},
                "total_seconds": round(self.total_seconds, 6),
                "stage_p95_ms": stage_p95,
                "total_ms": {"count": total["count"],
                             "p50": round(total["median"] * 1e3, 3),
                             "p95": round(total["p95"] * 1e3, 3),
                             "mean": round(total["mean"] * 1e3, 3)},
                "outcomes": dict(sorted(self.outcomes.items())),
                "last_slot": self.last_slot,
            }

    def fleet_json(self) -> dict:
        """Compact per-node export for the FleetAggregator: cumulative
        stage/total seconds (the sum contract travels with them) plus
        the total-latency reservoir in ms, so the fleet view can compute
        true cross-node percentiles instead of merging per-node ones."""
        with self._lock:
            count = self.applied_count
            stage = {s: round(self.stage_seconds[s], 9) for s in STAGES}
            total = round(self.total_seconds, 9)
            outcomes = dict(sorted(self.outcomes.items()))
        samples = [round(v * 1e3, 3) for v in self._h_total._samples]
        return {"count": count, "stage_seconds": stage,
                "total_seconds": total, "samples_ms": samples,
                "outcomes": outcomes}
