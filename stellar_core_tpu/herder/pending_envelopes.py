"""PendingEnvelopes: buffer SCP envelopes until dependencies arrive.

Role parity: reference `src/herder/PendingEnvelopes.{h,cpp}:26-153` —
per-slot state sets (discarded/fetching/ready/processed), LRU caches of
txsets and quorum sets, two ItemFetchers (txset, qset), QuorumTracker
feeding.  The fetch transport is injected (overlay ItemFetcher in a full
node; direct delivery in simulation).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from ..crypto.hashing import sha256
from ..util.cache import RandomEvictionCache
from ..util.log import get_logger
from ..xdr import SCPEnvelope, SCPQuorumSet, SCPStatementType

log = get_logger("Herder")


def statement_txset_hashes(st) -> List[bytes]:
    """TxSet hashes referenced by a statement's StellarValue payloads."""
    from ..xdr import StellarValue
    values = []
    t = st.pledges.disc
    p = st.pledges.value
    if t == SCPStatementType.SCP_ST_NOMINATE:
        values = list(p.votes) + list(p.accepted)
    elif t == SCPStatementType.SCP_ST_PREPARE:
        if p.ballot.counter:
            values.append(p.ballot.value)
        if p.prepared is not None:
            values.append(p.prepared.value)
        if p.preparedPrime is not None:
            values.append(p.preparedPrime.value)
    elif t == SCPStatementType.SCP_ST_CONFIRM:
        values.append(p.ballot.value)
    else:
        values.append(p.commit.value)
    out = []
    for v in values:
        try:
            sv = StellarValue.from_xdr(v)
            out.append(sv.txSetHash)
        except Exception as e:
            # a peer can pledge arbitrary bytes; an unparseable value
            # simply names no txset to fetch — but say so (E1: no silent
            # swallows in consensus code)
            log.debug("ignoring unparseable StellarValue in statement: %s", e)
    return out


def statement_qset_hash(st) -> bytes:
    t = st.pledges.disc
    if t == SCPStatementType.SCP_ST_EXTERNALIZE:
        return st.pledges.value.commitQuorumSetHash
    return st.pledges.value.quorumSetHash


class PendingEnvelopes:
    QSET_CACHE_SIZE = 10000
    TXSET_CACHE_SIZE = 10000

    def __init__(self, herder,
                 fetch_txset: Optional[Callable[[bytes], None]] = None,
                 fetch_qset: Optional[Callable[[bytes], None]] = None
                 ) -> None:
        self.herder = herder
        self.fetch_txset_fn = fetch_txset
        self.fetch_qset_fn = fetch_qset
        self.txsets: Dict[bytes, object] = {}
        self.qsets: Dict[bytes, SCPQuorumSet] = {}
        # slot -> list of envelopes waiting on deps
        self.fetching: Dict[int, List[SCPEnvelope]] = {}
        self.processed: Dict[int, Set[bytes]] = {}
        self.discarded: Dict[int, Set[bytes]] = {}
        # slot -> envelope hashes whose signature verify is in flight on
        # the batch backend (async analog of "fetching": buffered until
        # the device batch completes on the main loop)
        self.verifying: Dict[int, Set[bytes]] = {}

    def set_fetchers(self, fetch_txset, fetch_qset) -> None:
        self.fetch_txset_fn = fetch_txset
        self.fetch_qset_fn = fetch_qset

    # -- caches -------------------------------------------------------------
    def add_tx_set(self, h: bytes, txset) -> None:
        self.txsets[h] = txset
        self._retry_fetching()

    def add_quorum_set(self, h: bytes, qset: SCPQuorumSet) -> None:
        self.qsets[h] = qset
        self._retry_fetching()

    def get_tx_set(self, h: bytes):
        return self.txsets.get(h)

    def get_quorum_set(self, h: bytes) -> Optional[SCPQuorumSet]:
        return self.qsets.get(h)

    # -- intake -------------------------------------------------------------
    def _missing_deps(self, env: SCPEnvelope) -> List[tuple]:
        missing = []
        st = env.statement
        qh = statement_qset_hash(st)
        if qh not in self.qsets:
            missing.append(("qset", qh))
        for th in statement_txset_hashes(st):
            if th not in self.txsets:
                missing.append(("txset", th))
        return missing

    def begin_verify(self, env: SCPEnvelope,
                     eh: Optional[bytes] = None) -> bool:
        """Enter the 'verifying' state. False when the envelope is already
        known (processed / discarded / verify in flight) — callers skip
        re-verification and re-flooding."""
        slot = env.statement.slotIndex
        eh = eh or sha256(env.to_xdr())
        if eh in self.processed.get(slot, set()) or \
                eh in self.discarded.get(slot, set()) or \
                eh in self.verifying.get(slot, set()):
            return False
        self.verifying.setdefault(slot, set()).add(eh)
        return True

    def finish_verify(self, env: SCPEnvelope, ok: bool,
                      eh: Optional[bytes] = None) -> bool:
        """Resolve a verify: promote to the normal intake path or discard."""
        slot = env.statement.slotIndex
        eh = eh or sha256(env.to_xdr())
        vs = self.verifying.get(slot)
        if vs is not None:
            vs.discard(eh)
            if not vs:
                del self.verifying[slot]
        if not ok:
            self.discarded.setdefault(slot, set()).add(eh)
            return False
        return self.recv_scp_envelope(env, eh)

    def recv_scp_envelope(self, env: SCPEnvelope,
                          eh: Optional[bytes] = None) -> bool:
        """Returns True if the envelope became ready (delivered to SCP
        queue); False if buffered/discarded."""
        slot = env.statement.slotIndex
        eh = eh or sha256(env.to_xdr())
        if eh in self.processed.get(slot, set()) or \
                eh in self.discarded.get(slot, set()):
            return False
        missing = self._missing_deps(env)
        if missing:
            self.fetching.setdefault(slot, []).append(env)
            for kind, h in missing:
                # the envelope rides along so trackers know which slots
                # still depend on the item (ItemFetcher GC keys off it)
                if kind == "qset" and self.fetch_qset_fn:
                    self.fetch_qset_fn(h, env)
                elif kind == "txset" and self.fetch_txset_fn:
                    self.fetch_txset_fn(h, env)
            return False
        self.processed.setdefault(slot, set()).add(eh)
        self.herder.envelope_ready(env)
        return True

    def _retry_fetching(self) -> None:
        for slot in sorted(self.fetching):
            still: List[SCPEnvelope] = []
            for env in self.fetching[slot]:
                if self._missing_deps(env):
                    still.append(env)
                else:
                    eh = sha256(env.to_xdr())
                    self.processed.setdefault(slot, set()).add(eh)
                    self.herder.envelope_ready(env)
            if still:
                self.fetching[slot] = still
            else:
                del self.fetching[slot]

    def discard_envelope(self, env: SCPEnvelope) -> None:
        slot = env.statement.slotIndex
        self.discarded.setdefault(slot, set()).add(sha256(env.to_xdr()))

    # -- GC -----------------------------------------------------------------
    def erase_below(self, slot: int) -> None:
        for d in (self.fetching, self.processed, self.discarded,
                  self.verifying):
            for s in [s for s in d if s < slot]:
                del d[s]
