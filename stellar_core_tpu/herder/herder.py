"""Herder: binds SCP to the ledger.

Role parity: reference `src/herder/HerderImpl.{h,cpp}` +
`HerderSCPDriver.{h,cpp}`:
- slot = ledger sequence, value = XDR StellarValue(txset hash, closeTime,
  upgrades)
- envelope signature verify/sign (verifyEnvelope HerderImpl.cpp:1474 —
  TPU batch hot caller #1, routed through the injected BatchSigVerifier)
- tracking / not-tracking state machine with a consensus-stuck watchdog
  (herder/readme.md)
- triggerNextLedger (HerderImpl.cpp:743-832): queue → txset → trim →
  surge → nominate
- valueExternalized: persist SCP history, hand LedgerCloseData to the
  ledger manager, update the tx queue, re-arm the trigger timer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..crypto.hashing import sha256
from ..scp.driver import SCPDriver, ValidationLevel
from ..scp.scp import SCP
from ..util.log import get_logger
from ..util.threads import main_thread_only
from ..util.timer import VirtualTimer
from ..xdr import (
    EnvelopeType, LedgerCloseValueSignature, LedgerUpgrade, SCPEnvelope,
    SCPQuorumSet, SCPStatementType, StellarValue, StellarValueExt, Uint32,
    Uint64, Packer,
)
from ..ledger.ledger_manager import LedgerCloseData
from .pending_envelopes import PendingEnvelopes, statement_qset_hash
from .tx_queue import TransactionQueue, TxQueueResult
from .txset import TxSetFrame, _xor
from .upgrades import Upgrades

log = get_logger("Herder")


class HerderState:
    HERDER_SYNCING_STATE = 0
    HERDER_TRACKING_STATE = 1


class HerderSCPDriver(SCPDriver):
    """SCPDriver bound to a Herder (reference HerderSCPDriver.cpp)."""

    def __init__(self, herder: "Herder") -> None:
        self.herder = herder
        # SCPDriver trace hooks (scp/driver.py) emit ballot/nomination
        # instants against the application tracer, and journal the same
        # progression into the per-slot timeline (always on)
        self.tracer = getattr(herder.app, "tracer", None)
        self.timeline = getattr(herder.app, "slot_timeline", None)
        # consensus cockpit: the envelope/round hook sites in scp/
        # read this attribute off the driver (Herder builds it first)
        self.scp_stats = getattr(herder, "scp_stats", None)

    # -- envelope signing ----------------------------------------------------
    def _envelope_sign_bytes(self, st) -> bytes:
        p = Packer()
        p.put(self.herder.app.config.network_id)
        Uint32.pack(p, EnvelopeType.ENVELOPE_TYPE_SCP)
        p.put(st.to_xdr())
        return sha256(p.bytes())

    def sign_envelope(self, envelope: SCPEnvelope) -> None:
        sk = self.herder.app.config.NODE_SEED
        envelope.signature = sk.sign(
            self._envelope_sign_bytes(envelope.statement))

    def emit_envelope(self, envelope: SCPEnvelope) -> None:
        self.herder.emit_envelope(envelope)

    # -- values --------------------------------------------------------------
    def _check_close_time(self, sv: StellarValue, slot_index: int) -> bool:
        lm = self.herder.app.ledger_manager
        lcl = lm.lcl_header
        if slot_index == lcl.ledgerSeq + 1:
            if sv.closeTime <= lcl.scpValue.closeTime:
                return False
        # reject implausible future close times (reference: MAX_TIME_SLIP)
        now = self.herder.app.clock.system_now()
        if sv.closeTime > now + 60:
            return False
        return True

    def validate_value(self, slot_index: int, value: bytes,
                       nomination: bool) -> ValidationLevel:
        try:
            sv = StellarValue.from_xdr(value)
        except Exception:
            return ValidationLevel.INVALID
        if sv.ext.disc == StellarValueExt.STELLAR_VALUE_SIGNED:
            # signed values are nomination-only, and the embedded
            # signature must verify (reference validateValueHelper:203)
            if not nomination or \
                    not self.herder.verify_stellar_value_signature(sv):
                return ValidationLevel.INVALID
        if not self._check_close_time(sv, slot_index):
            return ValidationLevel.INVALID
        lm = self.herder.app.ledger_manager
        if slot_index != lm.lcl_header.ledgerSeq + 1:
            # not the slot we can fully validate against
            return ValidationLevel.MAYBE_VALID
        lclh = lm.lcl_header
        if (not nomination or lclh.ledgerVersion < 11) and \
                sv.ext.disc != 0:
            # ballot protocol (and pre-11 entirely) only supports BASIC
            return ValidationLevel.INVALID
        if nomination and lclh.ledgerVersion >= 11 and \
                sv.ext.disc != StellarValueExt.STELLAR_VALUE_SIGNED:
            # v11+ requires SIGNED for nomination (reference :327-334)
            return ValidationLevel.INVALID
        txset = self.herder.pending.get_tx_set(sv.txSetHash)
        if txset is None:
            return ValidationLevel.MAYBE_VALID
        if nomination:
            if txset.previous_ledger_hash != lm.lcl_hash:
                return ValidationLevel.INVALID
            ltx_root = lm.ltx_root()
            ok, _removed = txset.check_or_trim(
                ltx_root, self.herder.verifier, trim=False)
            if not ok:
                return ValidationLevel.INVALID
        if not self._upgrades_valid(sv, nomination):
            return ValidationLevel.INVALID
        return ValidationLevel.FULLY_VALIDATED

    def _upgrades_valid(self, sv: StellarValue, nomination: bool) -> bool:
        """Reference HerderSCPDriver::validateValue:390-414: every upgrade
        must be apply-valid (within OUR supported protocol), strictly
        type-ordered, and — when nominating — match an armed local
        parameter, so foreign upgrades are voted down and stripped by
        extract_valid_value but still applied once externalized."""
        lm = self.herder.app.ledger_manager
        cfg = self.herder.app.config
        last_type = None
        for raw in sv.upgrades:
            if not Upgrades.is_valid_for_apply(
                    raw, lm.lcl_header, cfg.LEDGER_PROTOCOL_VERSION):
                return False
            if nomination and not self.herder.upgrades.is_valid_for_nomination(
                    raw, lm.lcl_header, lm.lcl_header.scpValue.closeTime):
                return False
            t = LedgerUpgrade.from_xdr(raw).disc
            if last_type is not None and last_type >= t:
                return False
            last_type = t
        return True

    def extract_valid_value(self, slot_index: int,
                            value: bytes) -> Optional[bytes]:
        try:
            sv = StellarValue.from_xdr(value)
        except Exception:
            return None
        lm = self.herder.app.ledger_manager
        cfg = self.herder.app.config
        # strip upgrades we would not nominate ourselves (reference
        # extractValidValue:450 runs isValid in nomination mode: foreign
        # or stale upgrades drop out, the rest of the value survives)
        upgrades = [
            u for u in sv.upgrades
            if Upgrades.is_valid_for_apply(
                u, lm.lcl_header, cfg.LEDGER_PROTOCOL_VERSION)
            and self.herder.upgrades.is_valid_for_nomination(
                u, lm.lcl_header, lm.lcl_header.scpValue.closeTime)]
        sv2 = StellarValue(txSetHash=sv.txSetHash, closeTime=sv.closeTime,
                           upgrades=upgrades, ext=sv.ext)
        v2 = sv2.to_xdr()
        if self.validate_value(slot_index, v2, True) == \
                ValidationLevel.FULLY_VALIDATED:
            return v2
        return None

    def combine_candidates(self, slot_index: int,
                           candidates: List[bytes]) -> Optional[bytes]:
        """Best LCL-based txset by (size, total fees from v11, xored-hash
        tiebreak), max closeTime, per-type max of upgrades (reference
        HerderSCPDriver::combineCandidates:608 + compareTxSets +
        lessThanXored)."""
        best_sv: Optional[StellarValue] = None
        max_close = 0
        merged_upgrades: Dict[int, bytes] = {}
        candidates_hash = bytes(32)
        parsed: List[StellarValue] = []
        from ..xdr import LedgerUpgrade
        for raw in candidates:
            try:
                sv = StellarValue.from_xdr(raw)
            except Exception:
                continue
            candidates_hash = _xor(candidates_hash, sha256(raw))
            max_close = max(max_close, sv.closeTime)
            for u in sv.upgrades:
                try:
                    up = LedgerUpgrade.from_xdr(u)
                except Exception:
                    continue
                cur = merged_upgrades.get(up.disc)
                if cur is None or u > cur:
                    merged_upgrades[up.disc] = u
            parsed.append(sv)

        lm = self.herder.app.ledger_manager
        header = lm.lcl_header

        def xored(h: bytes) -> bytes:
            # salting the tiebreak with the candidates hash keeps the
            # winner unpredictable across rounds (reference lessThanXored)
            return _xor(h, candidates_hash)

        usable = []
        for sv in parsed:
            txset = self.herder.pending.get_tx_set(sv.txSetHash)
            if txset is not None and \
                    txset.previous_ledger_hash == lm.lcl_hash:
                fees = txset.total_fees(header)
                usable.append(((txset.size_for_cap(header), fees,
                                xored(sv.txSetHash)), sv))
        if usable:
            best_sv = max(usable, key=lambda t: t[0])[1]
        elif parsed:
            # no candidate txset is known/LCL-based (fetch still in
            # flight): converge on the highest xored hash
            best_sv = max(parsed, key=lambda sv: xored(sv.txSetHash))
        if best_sv is None:
            return None
        out = StellarValue(
            txSetHash=best_sv.txSetHash, closeTime=max_close,
            upgrades=[merged_upgrades[k] for k in sorted(merged_upgrades)],
            ext=StellarValueExt(0, None))
        return out.to_xdr()

    # -- infrastructure ------------------------------------------------------
    def get_qset(self, qset_hash: bytes) -> Optional[SCPQuorumSet]:
        return self.herder.pending.get_quorum_set(qset_hash)

    def setup_timer(self, slot_index: int, timer_id: int, timeout: float,
                    cb) -> None:
        self.herder.setup_scp_timer(slot_index, timer_id, timeout, cb)

    def compute_timeout(self, round_number: int) -> int:
        return min(round_number, 30 * 60)

    def value_externalized(self, slot_index: int, value: bytes) -> None:
        self.herder.value_externalized(slot_index, value)

    def ballot_did_hear_from_quorum(self, slot_index, ballot) -> None:
        super().ballot_did_hear_from_quorum(slot_index, ballot)
        self.herder.track_heartbeat()


class Herder:
    # how far ahead of the current slot envelopes are accepted
    # (overridable via Config.LEDGER_VALIDITY_BRACKET)
    LEDGER_VALIDITY_BRACKET = 100
    # cadence of the self-healing poll while out of sync (app-clock
    # seconds; virtual in tests/simulation)
    OUT_OF_SYNC_RECOVERY_INTERVAL = 2.0
    # newest out-of-bracket externalize-hint slots retained while syncing
    MAX_EXT_HINT_SLOTS = 32

    def __init__(self, app) -> None:
        self.app = app
        cfg = app.config
        self.verifier = app.sig_verifier
        # consensus cockpit (ISSUE 19): per-slot phase/round/envelope
        # attribution + quorum health, built BEFORE the driver so the
        # driver's hook sites see it (docs/observability.md
        # #consensus-cockpit)
        from ..scp.local_node import all_nodes_of
        from ..scp.scp_stats import ScpStats
        self.scp_stats = ScpStats(
            metrics=getattr(app, "metrics", None),
            tracer=getattr(app, "tracer", None),
            now_fn=app.clock.now,
            self_id=cfg.node_id().key_bytes.hex(),
            timeline=getattr(app, "slot_timeline", None))
        self.scp_stats.set_quorum(
            nb.hex() for nb in all_nodes_of(cfg.QUORUM_SET))
        self.scp_driver = HerderSCPDriver(self)
        self.scp = SCP(self.scp_driver, cfg.node_id(),
                       cfg.NODE_IS_VALIDATOR, cfg.QUORUM_SET)
        self.pending = PendingEnvelopes(self)
        # tx-lifecycle cockpit (ISSUE 10): submit → queue → include →
        # externalize → apply latency attribution on the app clock,
        # wired before the queue so eviction/expiry outcomes land in the
        # same funnel (docs/observability.md#overlay-cockpit)
        from .tx_lifecycle import TxLifecycle
        self.tx_lifecycle = TxLifecycle(
            metrics=getattr(app, "metrics", None),
            tracer=getattr(app, "tracer", None),
            now_fn=app.clock.now)
        self.tx_queue = TransactionQueue(
            app.ledger_manager, cfg.TRANSACTION_QUEUE_PENDING_DEPTH,
            cfg.TRANSACTION_QUEUE_BAN_DEPTH, cfg.POOL_LEDGER_MULTIPLIER,
            self.verifier, metrics=getattr(app, "metrics", None),
            lifecycle=self.tx_lifecycle)
        # ingress admission tier (ISSUE 18): per-source rate classes +
        # bounded intake in FRONT of the queue, so overload sheds before
        # paying signature validation (docs/robustness.md#ingress--overload)
        self.ingress = None
        self.last_retry_after: Optional[float] = None
        if cfg.INGRESS_ENABLED:
            from ..crypto import strkey
            from .ingress import TxIngress
            self.ingress = TxIngress(
                metrics=getattr(app, "metrics", None),
                now_fn=app.clock.now,
                faults=getattr(app, "faults", None),
                classes=cfg.INGRESS_CLASSES,
                priority=[strkey.decode_public_key(a)
                          for a in cfg.INGRESS_PRIORITY_ACCOUNTS],
                untrusted=[strkey.decode_public_key(a)
                           for a in cfg.INGRESS_UNTRUSTED_ACCOUNTS],
                intake_depth=cfg.INGRESS_INTAKE_DEPTH,
                max_sources=cfg.INGRESS_MAX_SOURCES,
                async_intake=cfg.INGRESS_ASYNC_INTAKE,
                sink=self._queue_tx,
                shed_cb=lambda h: self.tx_lifecycle.outcome(h, "shed"))
        self.upgrades = Upgrades()
        self.state = HerderState.HERDER_SYNCING_STATE
        self.tracking_slot: Optional[int] = None
        self._scp_timers: Dict[Tuple[int, int], VirtualTimer] = {}
        self.trigger_timer = VirtualTimer(app.clock)
        self.stuck_timer = VirtualTimer(app.clock)
        # self-healing recovery (out_of_sync_recovery): poll timer,
        # episode start stamp (None = not recovering), episode counter,
        # and the buffer of externalize statements seen for slots beyond
        # the validity bracket — the evidence of where the network is
        self.LEDGER_VALIDITY_BRACKET = getattr(
            cfg, "LEDGER_VALIDITY_BRACKET", self.LEDGER_VALIDITY_BRACKET)
        self.out_of_sync_timer = VirtualTimer(app.clock)
        self.recovery_started_at: Optional[float] = None
        self.recoveries = 0
        self._recovery_counted = False
        self._ext_hints: Dict[int, set] = {}
        self.ledger_close_meta = None
        # register own qset
        q = cfg.QUORUM_SET
        self.pending.add_quorum_set(sha256(q.to_xdr()), q)
        # transitive quorum map (reference QuorumTracker)
        from .quorum_intersection import QuorumTracker
        self.quorum_tracker = QuorumTracker(
            cfg.node_id(), lambda: self.app.config.QUORUM_SET)
        self._nominate_started: dict = {}
        self.last_quorum_intersection: Optional[dict] = None
        # in-flight background intersection check (reference
        # QuorumMapIntersectionState): the main loop owns these fields;
        # the worker thread only reads `checker` via its own reference
        self._qic_checker = None      # live QuorumIntersectionChecker
        self._qic_thread = None
        self.quorum_check_recalculating = False

    # -- state machine -------------------------------------------------------
    def bootstrap(self) -> None:
        """FORCE_SCP start (reference Herder::bootstrap)."""
        cfg = self.app.config
        assert cfg.FORCE_SCP
        self.set_tracking(self.app.ledger_manager.last_closed_ledger_num())
        self.app.ledger_manager.state = 1  # synced
        if not cfg.MANUAL_CLOSE:
            self._arm_trigger_timer()

    def update_upgrades_status(self) -> None:
        """Status line while upgrade parameters are armed (reference
        HerderImpl upgrades status, :843-860)."""
        from ..util.status_manager import StatusCategory
        sm = getattr(self.app, "status_manager", None)
        if sm is None:
            return
        p = self.upgrades.params
        armed = {k: v for k, v in p.to_json().items()
                 if k != "time" and v is not None}
        if armed:
            sm.set_status_message(
                StatusCategory.REQUIRES_UPGRADES,
                "Armed with network upgrades: %s" % armed)
        else:
            sm.remove_status_message(StatusCategory.REQUIRES_UPGRADES)

    def set_tracking(self, slot: int) -> None:
        was_recovering = self.recovery_started_at is not None
        self.state = HerderState.HERDER_TRACKING_STATE
        self.tracking_slot = slot
        if was_recovering:
            # a recovery episode ends the moment consensus tracks again:
            # stop the poll, stamp time-to-tracking (the scenario suite's
            # headline recovery number), and journal the moment
            dt = max(0.0, self.app.clock.now() - self.recovery_started_at)
            self.recovery_started_at = None
            self._recovery_counted = False
            self.out_of_sync_timer.cancel()
            m = self._metrics()
            if m is not None:
                m.new_meter("herder.recovery.resumed").mark()
                m.new_timer("herder.recovery.time-to-tracking").update(dt)
            tl = getattr(self.app, "slot_timeline", None)
            if tl is not None:
                tl.record(slot, "recovery.tracked", dedupe=True,
                          time_to_tracking_s=round(dt, 6))
            log.info("consensus sync recovered at slot %d after %.3fs",
                     slot, dt)
        self.track_heartbeat()

    def track_heartbeat(self) -> None:
        cfg = self.app.config
        self.stuck_timer.expires_from_now(
            cfg.CONSENSUS_STUCK_TIMEOUT_SECONDS)
        self.stuck_timer.async_wait(self._lost_sync)

    def _lost_sync(self) -> None:
        log.warning("lost consensus sync (stuck timer fired)")
        m = self._metrics()
        if m is not None:
            m.new_meter("herder.recovery.lost-sync").mark()
        # SCP-stall flight dump: the spans/metrics leading into the stall
        # are the evidence that outlives the wedge (ISSUE 2: a stalled
        # relay went unexplained for a round)
        recorder = getattr(self.app, "flight_recorder", None)
        if recorder is not None:
            recorder.dump("scp-stall",
                          extra={"tracking_slot": self.tracking_slot,
                                 "state": "syncing"})
        self.state = HerderState.HERDER_SYNCING_STATE
        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None:
            tl.record(self.current_slot(), "recovery.lost-sync",
                      dedupe=True)
        # one anchor per recovery episode (ISSUE 19 satellite): the
        # clock stamp lands HERE, at the same moment the journal's
        # `recovery.lost-sync` record does, so time-to-tracking and the
        # timeline measure the same episode. The first poll used to
        # stamp it a poll-dispatch later — the two surfaces disagreed by
        # that skew. Episode COUNTING stays with the default poll path
        # (an app-installed hook overrides recovery, not the anchor).
        if self.recovery_started_at is None:
            self.recovery_started_at = self.app.clock.now()
        # an app-installed hook still overrides (test/operator hook
        # contract); the default is the real self-healing path below
        hook = getattr(self.app, "out_of_sync_recovery", None)
        if hook is not None:
            hook()
        else:
            self.out_of_sync_recovery()

    # -- self-healing recovery (ISSUE 8) -------------------------------------
    def _note_externalize_hint(self, envelope: SCPEnvelope) -> None:
        """Remember EXTERNALIZE statements for slots beyond the validity
        bracket instead of dropping them blind: they are the evidence of
        where the network is when we are far behind. Only statements from
        transitive-quorum nodes WITH a valid envelope signature count —
        hints steer catchup and the recovery loop, so one forged envelope
        claiming an absurd slot under a quorum member's id must not
        poison network_tracked_slot — and the buffer holds the newest
        MAX_EXT_HINT_SLOTS slots."""
        st = envelope.statement
        if st.pledges.disc != SCPStatementType.SCP_ST_EXTERNALIZE:
            return
        if not self.quorum_tracker.is_node_definitely_in_quorum(st.nodeID):
            return
        slot, node_key = st.slotIndex, st.nodeID.key_bytes
        if node_key in self._ext_hints.get(slot, ()):
            return   # already counted: no repeat verify work
        fut = self.verifier.enqueue(
            st.nodeID, envelope.signature,
            self.scp_driver._envelope_sign_bytes(st))

        def done(ok: bool) -> None:
            if not ok:
                log.debug("bad signature on externalize hint for slot %d",
                          slot)
                return
            self._ext_hints.setdefault(slot, set()).add(node_key)
            while len(self._ext_hints) > self.MAX_EXT_HINT_SLOTS:
                del self._ext_hints[min(self._ext_hints)]

        if fut.done():
            done(fut.result())
        else:
            fut.add_done_callback(done)

    def network_tracked_slot(self) -> Optional[int]:
        """Best estimate of the slot the network currently externalizes:
        max over (a) buffered out-of-bracket externalize hints, (b)
        EXTERNALIZE statements sitting in live SCP slots, (c) ledgers the
        catchup manager has buffered. None = no evidence."""
        best: Optional[int] = None
        if self._ext_hints:
            best = max(self._ext_hints)
        for idx in sorted(self.scp.known_slots, reverse=True):
            if best is not None and idx <= best:
                break
            for env in self.scp.known_slots[idx].get_current_state():
                if env.statement.pledges.disc == \
                        SCPStatementType.SCP_ST_EXTERNALIZE:
                    best = idx if best is None else max(best, idx)
                    break
        cm = getattr(self.app, "catchup_manager", None)
        if cm is not None:
            mb = cm.max_buffered_seq()
            if mb is not None:
                best = mb if best is None else max(best, mb)
        return best

    @main_thread_only
    def out_of_sync_recovery(self) -> None:
        """The self-healing path (reference HerderImpl::outOfSyncRecovery
        + getMoreSCPState): on each poll while not tracking, shed SCP
        state for slots that can no longer close, locate the network's
        tracked slot from buffered externalize evidence, solicit fresh
        SCP state from a few peers, and — when the gap needs history —
        trigger catchup through the CatchupWork/ArchivePool machinery.
        Tracking resumes via set_tracking when a slot externalizes."""
        if self.state == HerderState.HERDER_TRACKING_STATE:
            return
        m = self._metrics()
        clock = self.app.clock
        if self.recovery_started_at is None:
            # direct invocation (tests, operator): no _lost_sync ran, so
            # the episode anchors at the first poll
            self.recovery_started_at = clock.now()
        first = not self._recovery_counted
        if first:
            self._recovery_counted = True
            self.recoveries += 1
        if m is not None:
            m.new_meter("herder.recovery.attempt").mark()
        cur = self.current_slot()
        net_slot = self.network_tracked_slot()

        # 1. shed stale SCP slots: anything below the open slot can never
        # close anymore, and dropping it speeds envelope processing
        stale = [s for s in self.scp.known_slots if s < max(1, cur - 1)]
        if stale:
            keep_from = max(1, cur - 1)
            self.scp.purge_slots(keep_from)
            self.pending.erase_below(keep_from)
            if m is not None:
                m.new_counter("herder.recovery.purged-slots").inc(
                    len(stale))

        # 2. solicit current SCP state from a few random peers (reference
        # getMoreSCPState): a partitioned-and-healed node re-learns the
        # live slots without waiting for the next natural flood
        overlay = getattr(self.app, "overlay_manager", None)
        asked = 0
        if overlay is not None and \
                hasattr(overlay, "random_authenticated_peers"):
            from ..xdr import MessageType, StellarMessage
            for peer in overlay.random_authenticated_peers(3):
                peer.send_message(StellarMessage(
                    MessageType.GET_SCP_STATE, max(0, cur - 1)))
                asked += 1
        if m is not None and asked:
            m.new_meter("herder.recovery.scp-state-request").mark(asked)

        # 3. the ledger gap needs history: run catchup via the existing
        # CatchupWork/ArchivePool machinery (multi-archive failover and
        # all — docs/robustness.md#archive-domain)
        cm = getattr(self.app, "catchup_manager", None)
        hm = getattr(self.app, "history_manager", None)
        triggered = False
        if net_slot is not None and net_slot > cur and cm is not None \
                and not cm.catchup_running() and hm is not None \
                and hm.readable_archive() is not None:
            if cm.start_catchup() is not None:
                triggered = True
                if m is not None:
                    m.new_meter("herder.recovery.catchup-triggered").mark()

        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None:
            tl.record(cur, "recovery.attempt",
                      net_slot=net_slot, catchup=triggered)
        if first:
            recorder = getattr(self.app, "flight_recorder", None)
            if recorder is not None:
                # recovery-correlated dump: the state of the node at the
                # moment self-healing started (rate-limited per reason)
                recorder.dump("out-of-sync-recovery",
                              extra={"net_slot": net_slot,
                                     "current_slot": cur,
                                     "catchup_triggered": triggered,
                                     "ext_hint_slots":
                                         sorted(self._ext_hints)[-8:]})
        log.info("out-of-sync recovery: slot %d, network at %s, "
                 "purged %d stale slots, asked %d peers, catchup=%s",
                 cur, net_slot, len(stale), asked, triggered)

        # 4. keep polling until tracking resumes
        self.out_of_sync_timer.expires_from_now(
            self.OUT_OF_SYNC_RECOVERY_INTERVAL)
        self.out_of_sync_timer.async_wait(self.out_of_sync_recovery)

    # -- signed close values (v11+) ------------------------------------------
    def _stellar_value_sign_bytes(self, sv: StellarValue) -> bytes:
        """networkID ‖ ENVELOPE_TYPE_SCPVALUE ‖ txSetHash ‖ closeTime
        (reference signStellarValue/verifyStellarValueSignature,
        HerderImpl.cpp:1498-1516). The signature deliberately excludes
        upgrades so extractValidValue can strip them."""
        p = Packer()
        p.put(self.app.config.network_id)
        Uint32.pack(p, EnvelopeType.ENVELOPE_TYPE_SCPVALUE)
        p.put(sv.txSetHash)
        Uint64.pack(p, sv.closeTime)
        return p.bytes()

    def sign_stellar_value(self, sv: StellarValue) -> None:
        sk = self.app.config.NODE_SEED
        sv.ext = StellarValueExt(
            StellarValueExt.STELLAR_VALUE_SIGNED,
            LedgerCloseValueSignature(
                nodeID=sk.public_key,
                signature=sk.sign(self._stellar_value_sign_bytes(sv))))

    def verify_stellar_value_signature(self, sv: StellarValue) -> bool:
        from ..crypto.keys import PubKeyUtils
        lcs = sv.ext.value
        return PubKeyUtils.verify_sig(
            lcs.nodeID, lcs.signature, self._stellar_value_sign_bytes(sv))

    def current_slot(self) -> int:
        return self.app.ledger_manager.last_closed_ledger_num() + 1

    # -- transaction intake --------------------------------------------------
    def _metrics(self):
        return getattr(self.app, "metrics", None)

    def recv_transaction(self, frame) -> int:
        """HOT CALLER #2 via TransactionQueue.try_add → checkValid.
        The ingress tier (ISSUE 18) decides first: a throttled or shed
        tx returns TRY_AGAIN_LATER *before* any signature validation is
        paid, with `last_retry_after` carrying the hint `cmd_tx`
        surfaces to the submitter."""
        m = self._metrics()
        if m is not None:
            m.new_meter("herder.tx.received").mark()
        # lifecycle stamp: submit at entry, queue on admission — the
        # submit→queue stage is the admission (signature-check) cost. A
        # re-flooded duplicate must not clobber the original's stamps.
        h = frame.full_hash()
        fresh = self.tx_lifecycle.submit(h)
        self.last_retry_after = None
        ing = self.ingress
        if ing is not None:
            from . import ingress as _ing
            decision, retry_after = ing.admit(frame, tx_hash=h,
                                              fresh=fresh)
            if decision in (_ing.THROTTLE, _ing.SHED):
                if fresh:
                    self.tx_lifecycle.outcome(
                        h, "shed" if decision == _ing.SHED
                        else "throttled")
                self.last_retry_after = retry_after
                return TxQueueResult.ADD_STATUS_TRY_AGAIN_LATER
            if decision == _ing.PARKED:
                # accepted into the bounded intake; the pump delivers it
                # to the queue at the next trigger (optimistic PENDING —
                # open-loop submitters treat it as accepted)
                return TxQueueResult.ADD_STATUS_PENDING
        status = self._queue_tx(frame, h, fresh)
        if status == TxQueueResult.ADD_STATUS_TRY_AGAIN_LATER:
            # pool-side backpressure (source limit / fee floor): a close
            # drains the pool, so that is the honest retry horizon
            self.last_retry_after = \
                self.app.config.EXPECTED_LEDGER_CLOSE_TIME
        return status

    def _queue_tx(self, frame, h: bytes, fresh: bool) -> int:
        """Queue-admission tail shared by the direct path and the
        ingress intake pump."""
        status = self.tx_queue.try_add(frame)
        if status == TxQueueResult.ADD_STATUS_PENDING:
            self.tx_lifecycle.queued(h)
        elif fresh and status != TxQueueResult.ADD_STATUS_DUPLICATE:
            self.tx_lifecycle.outcome(h, "rejected")
        if status == 0:
            m = self._metrics()
            if m is not None:
                m.new_meter("herder.tx.accepted").mark()
        return status

    # -- SCP envelope intake -------------------------------------------------
    @main_thread_only
    def recv_scp_envelope(self, envelope: SCPEnvelope,
                          on_verified=None) -> int:
        """HOT CALLER #1. The signature verify is enqueued on the batch
        backend; with an async backend (tpu/tpu-async) verifies accumulate
        across envelopes into one device dispatch and complete on the main
        loop (the PendingEnvelopes 'verifying' state — async analog of the
        reference's fetch-before-feed buffering). `on_verified(ok)` fires
        when the decision lands (immediately on the sync backend)."""
        m = self._metrics()
        if m is not None:
            m.new_meter("scp.envelope.receive").mark()
        st = envelope.statement
        slot = st.slotIndex
        cur = self.current_slot()
        if slot < max(1, cur - 1) or \
                slot > cur + self.LEDGER_VALIDITY_BRACKET:
            if slot > cur:
                # too far ahead to process, but not to learn from: an
                # externalize statement up there is recovery's evidence
                # of where the network is (out_of_sync_recovery)
                self._note_externalize_hint(envelope)
            return SCP.EnvelopeState.INVALID
        # in-quorum filtering: envelopes from nodes outside the local
        # TRANSITIVE quorum are discarded — they can't affect consensus
        # and dropping them here also saves their signature verifies
        # (reference PendingEnvelopes::recvSCPEnvelope "not in quorum",
        # PendingEnvelopes.cpp:268-273; HerderTests "In quorum filtering")
        if not self.quorum_tracker.is_node_definitely_in_quorum(st.nodeID):
            log.debug("dropping envelope from %s (not in quorum)",
                      st.nodeID.value.hex()[:8])
            return SCP.EnvelopeState.INVALID
        eh = sha256(envelope.to_xdr())
        if not self.pending.begin_verify(envelope, eh):
            # duplicate (processed / discarded / already verifying)
            return SCP.EnvelopeState.INVALID
        # envelope pipeline latency (ISSUE 10): receive → verify →
        # herder process, app-clock stamped, attributed to the verify
        # backend — the envelope-verify cost ROADMAP item 3's BLS
        # tradeoff study needs on the same axis as bandwidth
        ostats = getattr(getattr(self.app, "overlay_manager", None),
                         "stats", None)
        t_recv = self.app.clock.now()
        fut = self.verifier.enqueue(
            st.nodeID, envelope.signature,
            self.scp_driver._envelope_sign_bytes(st))

        def done(ok: bool) -> None:
            if not ok:
                log.debug("bad envelope signature")
            t_verified = self.app.clock.now()
            self.pending.finish_verify(envelope, ok, eh)
            if ostats is not None:
                ostats.record_envelope(
                    t_verified - t_recv,
                    self.app.clock.now() - t_verified,
                    getattr(self.verifier, "name", "none"), ok)
            if on_verified is not None:
                on_verified(ok)

        if fut.done():
            done(fut.result())
            return (SCP.EnvelopeState.VALID if fut.result()
                    else SCP.EnvelopeState.INVALID)
        fut.add_done_callback(done)
        # batch backends: make sure a dispatch happens even outside the
        # app crank loop (flush coalesces: one dispatch per burst)
        self.verifier.flush()
        if fut.done():
            return (SCP.EnvelopeState.VALID if fut.result()
                    else SCP.EnvelopeState.INVALID)
        return SCP.EnvelopeState.PENDING

    def envelope_ready(self, envelope: SCPEnvelope) -> None:
        """Called by PendingEnvelopes when deps are present."""
        self._update_quorum_tracker(envelope)
        self.scp.receive_envelope(envelope)

    def _update_quorum_tracker(self, envelope: SCPEnvelope) -> None:
        """Keep the transitive quorum map current (reference
        HerderImpl::updateTransitiveQuorum via QuorumTracker::expand,
        rebuilding from the qset cache when expansion fails)."""
        from .pending_envelopes import statement_qset_hash
        st = envelope.statement
        qh = statement_qset_hash(st)
        qset = self.pending.get_quorum_set(qh)
        if qset is None:
            return
        if not self.quorum_tracker.expand(st.nodeID, qset):
            known = {st.nodeID.key_bytes: qset}
            self.quorum_tracker.rebuild(
                lambda node_id: known.get(node_id.key_bytes) or
                self._lookup_node_qset(node_id))

    def _lookup_node_qset(self, node_id):
        """Best-effort qset lookup for rebuild: latest SCP statement this
        node has seen from `node_id` names its qset hash."""
        from .pending_envelopes import statement_qset_hash
        for slot in self.scp.known_slots.values():
            for env in slot.get_current_state():
                if env.statement.nodeID.to_xdr() == node_id.to_xdr():
                    return self.pending.get_quorum_set(
                        statement_qset_hash(env.statement))
        return None

    def check_quorum_intersection(self, critical: bool = False) -> dict:
        """Run the intersection checker over the transitive quorum map
        (reference HerderImpl::checkAndMaybeReanalyzeQuorumMap); with
        critical=True also search for intersection-critical groups
        (reference getIntersectionCriticalGroups)."""
        from .quorum_intersection import QuorumIntersectionChecker
        qmap = self.quorum_tracker.get_quorum()
        checker = QuorumIntersectionChecker(qmap)
        out = self._run_intersection_check(checker, qmap, critical)
        self.last_quorum_intersection = out
        return out

    @staticmethod
    def _run_intersection_check(checker, qmap, critical: bool) -> dict:
        """The computation itself — safe on any thread (touches only the
        checker and the snapshotted qmap). Raises InterruptedError when
        the main loop sets checker.interrupted."""
        from .quorum_intersection import intersection_critical_groups_strkey
        ok = checker.network_enjoys_quorum_intersection()
        out = {
            "node_count": checker.n,
            "intersection": ok,
            "quorums_seen": checker.quorums_seen,
        }
        if checker.last_split is not None:
            out["last_good_split"] = [
                [x.hex() for x in side] for side in checker.last_split]
        if critical:
            # share the checker's interrupt flag with every throwaway
            # checker the criticality scan builds, so a shutdown-time
            # interrupt lands mid-scan too, not just mid-enumeration
            out["intersection_critical"] = \
                intersection_critical_groups_strkey(qmap, parent=checker)
        return out

    def start_quorum_intersection_check(self, critical: bool = False) -> bool:
        """Kick the intersection check onto a worker thread so a slow
        enumeration never stalls ledger close (reference
        checkAndMaybeReanalyzeQuorumMap posts the checker to a background
        thread and keeps mRecalculating state). Returns False if a check
        is already in flight. The result lands in
        last_quorum_intersection via post_to_main on a later crank."""
        import threading
        from .quorum_intersection import QuorumIntersectionChecker
        if self.quorum_check_recalculating:
            return False
        qmap = dict(self.quorum_tracker.get_quorum())
        checker = QuorumIntersectionChecker(qmap)
        self._qic_checker = checker
        self.quorum_check_recalculating = True
        clock = self.app.clock

        def work() -> None:
            try:
                out = self._run_intersection_check(checker, qmap, critical)
            except InterruptedError:
                out = {"node_count": checker.n, "interrupted": True}
            except Exception as e:   # never kill the process from a worker
                out = {"node_count": checker.n, "error": str(e)}

            def install() -> None:
                self.last_quorum_intersection = out
                self.quorum_check_recalculating = False
                self._qic_checker = None
            clock.post_to_main(install)

        self._qic_thread = threading.Thread(
            target=work, name="quorum-intersection", daemon=True)
        self._qic_thread.start()
        return True

    def interrupt_quorum_intersection(self) -> None:
        """Ask an in-flight background check to bail at its next branch
        (reference HerderImpl.cpp:140-144: shutdown sets mInterruptFlag
        to avoid a long pause joining worker threads). Safe to call with
        no check running."""
        checker = self._qic_checker
        if checker is not None:
            checker.interrupted = True

    def recv_tx_set(self, h: bytes, txset: TxSetFrame) -> bool:
        if txset.get_contents_hash(
                hasher=getattr(self.app, "batch_hasher", None)) != h:
            return False
        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None and txset.previous_ledger_hash == \
                self.app.ledger_manager.lcl_hash:
            # journal only txsets actually pinned to the OPEN slot
            # (previous_ledger_hash == LCL): a late fetch for an
            # already-closed slot must not be misfiled under the next
            # one. Dedupe by hash, not sender — two competing nominated
            # txsets are two distinct fetch records.
            tl.record(self.current_slot(), "txset.fetched", dedupe=True,
                      dedupe_key=h.hex(),
                      hash=h.hex()[:8], txs=len(txset.frames))
        self.pending.add_tx_set(h, txset)
        return True

    def recv_scp_quorum_set(self, h: bytes, qset: SCPQuorumSet) -> bool:
        if sha256(qset.to_xdr()) != h:
            return False
        self.pending.add_quorum_set(h, qset)
        return True

    def emit_envelope(self, envelope: SCPEnvelope) -> None:
        # persist our pledges BEFORE they hit the wire: a crash mid-slot
        # must not forget ballots other nodes may hold us to (reference
        # persistSCPState in emitEnvelope, HerderImpl.cpp:302)
        m = self._metrics()
        if m is not None:
            m.new_meter("scp.envelope.emit").mark()
        # consensus cockpit: our half of the O(n²) flood baseline
        from ..scp.scp_stats import STATEMENT_KIND
        st = envelope.statement
        self.scp_stats.envelope_sent(st.slotIndex,
                                     STATEMENT_KIND[st.pledges.disc])
        self.persist_latest_scp_state(envelope.statement.slotIndex)
        overlay = getattr(self.app, "overlay_manager", None)
        if overlay is not None:
            from ..xdr import MessageType, StellarMessage
            overlay.broadcast_message(
                StellarMessage(MessageType.SCP_MESSAGE, envelope), False)

    # -- nomination ----------------------------------------------------------
    @main_thread_only
    def trigger_next_ledger(self, ledger_seq_to_trigger: int) -> None:
        from ..util.tracing import app_span
        lm = self.app.ledger_manager
        cfg = self.app.config
        lcl = lm.lcl_header
        slot = lcl.ledgerSeq + 1
        if ledger_seq_to_trigger != slot:
            log.debug("stale trigger for %d (slot %d)",
                      ledger_seq_to_trigger, slot)
            return
        with app_span(self.app, "herder.trigger", cat="scp",
                      slot=slot) as tsp:
            if self.ingress is not None:
                # drain the bounded intake (priority class first) into
                # the queue so this trigger's txset sees parked txs
                self.ingress.pump()
            txset = self.tx_queue.to_txset(lm.lcl_hash, cfg.network_id)
            removed = txset.trim_invalid(lm.ltx_root(), self.verifier)
            if removed:
                self.tx_queue.ban([f.full_hash() for f in removed])
            txset.surge_pricing_filter(lcl)
            tsp.set_tag("txs", len(txset.frames))
            h = txset.get_contents_hash(
                hasher=getattr(self.app, "batch_hasher", None))
            self.pending.add_tx_set(h, txset)
            # lifecycle stamp: txset inclusion at nomination (the slot's
            # externalized set may differ; missed stages backfill)
            self.tx_lifecycle.included(
                [f.full_hash() for f in txset.frames])

        close_time = max(self.app.clock.system_now(),
                         lcl.scpValue.closeTime + 1)
        upgrades = self.upgrades.create_upgrades_for(lcl, close_time)
        value = StellarValue(txSetHash=h, closeTime=close_time,
                             upgrades=upgrades,
                             ext=StellarValueExt(0, None))
        if lcl.ledgerVersion >= 11:
            # v11+ nominates SIGNED values (reference signStellarValue,
            # HerderImpl.cpp:828,1508: sig over networkID ‖
            # ENVELOPE_TYPE_SCPVALUE ‖ txSetHash ‖ closeTime)
            self.sign_stellar_value(value)
        prev = lcl.scpValue.to_xdr()
        self._nominate_started[slot] = self.app.clock.now()
        m = self._metrics()
        if m is not None:
            m.new_meter("scp.value.nominated").mark()
        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None:
            tl.record(slot, "nominate.trigger", dedupe=True,
                      txs=len(txset.frames))
        self.scp.nominate(slot, value.to_xdr(), prev)

    def _arm_trigger_timer(self) -> None:
        cfg = self.app.config
        seconds = 0.001 if cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING \
            else cfg.EXPECTED_LEDGER_CLOSE_TIME
        slot = self.current_slot()
        self.trigger_timer.expires_from_now(seconds)
        self.trigger_timer.async_wait(
            lambda: self.trigger_next_ledger(slot))

    # -- externalization -----------------------------------------------------
    def slot_latency_anchor(self, slot_index: int) -> Optional[float]:
        """THE slot-latency anchor (ISSUE 19 satellite;
        docs/observability.md#slot-latency-anchor): the slot's
        `nominate.trigger` timeline stamp, falling back to the in-memory
        nomination-start clock when no journal is attached. The
        timeline's externalize tag, ScpStats' phase wall, and the
        recovery telemetry all measure slot latency from this one
        definition."""
        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None:
            ev = tl.first(slot_index, "nominate.trigger")
            if ev is not None:
                return ev["t"]
        return self._nominate_started.get(slot_index)

    @main_thread_only
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        t0 = self.slot_latency_anchor(slot_index)
        self._nominate_started.pop(slot_index, None)
        self._nominate_started = {
            s: t for s, t in self._nominate_started.items()
            if s > slot_index}   # drop stale never-externalized slots
        m = self._metrics()
        lat = (max(0.0, self.app.clock.now() - t0)
               if t0 is not None else None)
        if m is not None:
            m.new_meter("scp.value.externalized").mark()
            if lat is not None:
                # reference scp.timing.externalized: nomination-start →
                # externalize latency per slot
                m.new_timer("scp.timing.externalized").update(lat)
        tracer = getattr(self.app, "tracer", None)
        if tracer is not None and tracer.enabled:
            # round timing rides as a tag: the latency is measured on the
            # app clock, not the tracer clock, so it can't be a span
            tracer.instant("scp.externalize", cat="scp", slot=slot_index,
                           **({} if lat is None else
                              {"nominate_to_externalize_s": round(lat, 6)}))
        tl = getattr(self.app, "slot_timeline", None)
        if tl is not None:
            tl.record(slot_index, "externalize", dedupe=True,
                      **({} if lat is None else
                         {"nominate_to_externalize_s": round(lat, 6)}))
        # consensus cockpit: derive phase latencies from the stamps the
        # timeline just completed, latch the slot's round/envelope/lag
        # attribution (must run AFTER the `externalize` record above)
        self.scp_stats.slot_externalized(slot_index)
        sv = StellarValue.from_xdr(value)
        txset = self.pending.get_tx_set(sv.txSetHash)
        assert txset is not None, "externalized unknown txset"
        self.set_tracking(slot_index)
        self.persist_latest_scp_state(slot_index)
        self.save_scp_history(slot_index)

        # lifecycle stamps around the close: externalize before, apply
        # after the ledger manager returns — externalize→apply is the
        # local close cost the funnel separates from consensus latency
        tx_hashes = [f.full_hash() for f in txset.frames]
        self.tx_lifecycle.externalized(tx_hashes)
        lm = self.app.ledger_manager
        lcd = LedgerCloseData(slot_index, txset, sv)
        lm.value_externalized(lcd)
        if lm.last_closed_ledger_num() >= slot_index:
            self.tx_lifecycle.applied(tx_hashes, slot_index)
        else:
            # buffered into a catchup gap: the close happens later via
            # replay — don't fabricate an apply stamp now
            for h in tx_hashes:
                self.tx_lifecycle.outcome(h, "deferred")

        # disarm upgrade parameters that just externalized or whose
        # scheduled time expired (reference HerderImpl::valueExternalized →
        # Upgrades::removeUpgrades; stale nodes must not keep pushing)
        if self.upgrades.remove_applied_and_expired(
                sv.upgrades, sv.closeTime):
            log.info("upgrades: armed parameters now %s",
                     self.upgrades.params.to_json())
        self.update_upgrades_status()

        # tx queue maintenance
        self.tx_queue.remove_applied(list(txset.frames))
        self.tx_queue.shift()
        if self.ingress is not None:
            # a close drains the pool: reset per-source inflight windows
            # and reap fully-refilled bucket states
            self.ingress.ledger_closed()
        if m is not None:
            m.new_counter("herder.pending-ops.count").set_count(
                self.tx_queue.size_ops())

        # GC old slots + pending state + overlay flood records
        keep_from = max(1, slot_index -
                        self.app.config.MAX_SLOTS_TO_REMEMBER + 1)
        self.scp.purge_slots(keep_from)
        self.pending.erase_below(keep_from)
        # externalize hints at-or-below the closed slot are consumed
        self._ext_hints = {s: v for s, v in self._ext_hints.items()
                           if s > slot_index}
        overlay = getattr(self.app, "overlay_manager", None)
        if overlay is not None and hasattr(overlay, "ledger_closed"):
            overlay.ledger_closed(slot_index)
        self.scp_stats.slot_closed(slot_index)

        if not self.app.config.MANUAL_CLOSE:
            self._arm_trigger_timer()

    # -- SCP timers ----------------------------------------------------------
    def setup_scp_timer(self, slot_index: int, timer_id: int,
                        timeout: float, cb) -> None:
        key = (slot_index, timer_id)
        t = self._scp_timers.get(key)
        if t is None:
            t = VirtualTimer(self.app.clock)
            self._scp_timers[key] = t
        t.cancel()
        ss = self.scp_stats
        if cb is None:
            ss.timer_cancelled(slot_index, timer_id)
            return
        # consensus cockpit: attribute every fire to (timer, round) —
        # arming over a pending schedule counts the implicit cancel
        ss.timer_armed(slot_index, timer_id)

        def fired() -> None:
            ss.timer_fired(slot_index, timer_id)
            cb()

        t.expires_from_now(timeout)
        t.async_wait(fired)

    # -- persistence ---------------------------------------------------------
    def save_scp_history(self, slot_index: int) -> None:
        """Write the slot's SCP envelopes + quorum sets to the history
        tables feeding checkpoint publication (reference
        HerderPersistence::saveSCPHistory, called from
        HerderImpl::valueExternalized at HerderImpl.cpp:183)."""
        db = getattr(self.app, "database", None)
        if db is None:
            return
        from ..crypto.hashing import sha256
        from .pending_envelopes import statement_qset_hash
        envs = self.scp.get_externalizing_state(slot_index)
        db.execute("DELETE FROM scphistory WHERE ledgerseq = ?",
                   (slot_index,))
        for env in envs:
            db.execute(
                "INSERT INTO scphistory (nodeid, ledgerseq, envelope) "
                "VALUES (?, ?, ?)",
                (env.statement.nodeID.key_bytes.hex(), slot_index,
                 env.to_xdr()))
            qh = statement_qset_hash(env.statement)
            qset = self.pending.qsets.get(qh)
            if qset is None and self.app.config.QUORUM_SET is not None:
                local = self.app.config.QUORUM_SET
                if sha256(local.to_xdr()) == qh:
                    qset = local
            if qset is not None:
                db.execute(
                    "INSERT OR REPLACE INTO scpquorums "
                    "(qsethash, lastledgerseq, qset) VALUES (?, ?, ?)",
                    (qh.hex(), slot_index, qset.to_xdr()))
        db.commit()

    def persist_latest_scp_state(self, slot_index: int) -> None:
        db = getattr(self.app, "database", None)
        if db is None:
            return
        import base64
        envs = self.scp.get_latest_messages_send(slot_index)
        blob = b"".join(len(e.to_xdr()).to_bytes(4, "big") + e.to_xdr()
                        for e in envs)
        db.set_state("scphistory", base64.b64encode(blob).decode())
        db.commit()

    def restore_scp_state(self) -> None:
        db = getattr(self.app, "database", None)
        if db is None:
            return
        import base64
        raw = db.get_state("scphistory")
        if not raw:
            return
        blob = base64.b64decode(raw)
        i = 0
        while i + 4 <= len(blob):
            n = int.from_bytes(blob[i:i + 4], "big")
            i += 4
            try:
                env = SCPEnvelope.from_xdr(blob[i:i + n])
                self.scp.set_state_from_envelope(env)
            except Exception as e:
                # persisted-state corruption loses one envelope, not the
                # restart; log it so an operator can see the decay (E1)
                log.warning("discarding corrupt persisted SCP envelope "
                            "at offset %d: %s", i, e)
            i += n

    # -- introspection -------------------------------------------------------
    def get_json_info(self) -> dict:
        return {
            "you": self.app.config.NODE_SEED.strkey_public(),
            "state": ("tracking" if self.state ==
                      HerderState.HERDER_TRACKING_STATE else "syncing"),
            "slot": self.tracking_slot,
            "queue_ops": self.tx_queue.size_ops(),
            "recovery": {
                "recovering": self.recovery_started_at is not None,
                "recoveries": self.recoveries,
                "network_tracked_slot": self.network_tracked_slot(),
            },
            "scp": self.scp.get_json_info(),
            "transitive": {
                "node_count": len(self.quorum_tracker.get_quorum()),
                "intersection": self.last_quorum_intersection,
                "recalculating": self.quorum_check_recalculating,
            },
        }
