"""Upgrades: protocol/fee/size/reserve upgrade voting.

Role parity: reference `src/herder/Upgrades.{h,cpp}` — armed via config or
the HTTP admin endpoint, nominated inside StellarValue.upgrades, validated
against scheduled parameters, applied at ledger close (after txs).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..xdr import LedgerHeader, LedgerUpgrade, LedgerUpgradeType

# armed parameters expire this long after their scheduled time, so nodes
# restarted with stale configs don't try to change the network (reference
# Upgrades::UPDGRADE_EXPIRATION_HOURS)
UPGRADE_EXPIRATION_SECONDS = 12 * 3600


class UpgradeValidity:
    VALID = 0
    XDR_INVALID = 1
    INVALID = 2


class UpgradeParameters:
    def __init__(self) -> None:
        self.upgrade_time: int = 0
        self.protocol_version: Optional[int] = None
        self.base_fee: Optional[int] = None
        self.max_tx_set_size: Optional[int] = None
        self.base_reserve: Optional[int] = None

    def to_json(self) -> dict:
        return {
            "time": self.upgrade_time,
            "version": self.protocol_version,
            "fee": self.base_fee,
            "maxtxsize": self.max_tx_set_size,
            "reserve": self.base_reserve,
        }


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None) -> None:
        self.params = params or UpgradeParameters()

    def set_parameters(self, params: UpgradeParameters) -> None:
        self.params = params

    def create_upgrades_for(self, header: LedgerHeader,
                            close_time: int) -> List[bytes]:
        """Upgrades to nominate, given the current header (reference
        createUpgradesFor)."""
        out: List[bytes] = []
        p = self.params
        if close_time < p.upgrade_time:
            return out
        if p.protocol_version is not None and \
                p.protocol_version != header.ledgerVersion:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_VERSION,
                p.protocol_version).to_xdr())
        if p.base_fee is not None and p.base_fee != header.baseFee:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE,
                p.base_fee).to_xdr())
        if p.max_tx_set_size is not None and \
                p.max_tx_set_size != header.maxTxSetSize:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE,
                p.max_tx_set_size).to_xdr())
        if p.base_reserve is not None and \
                p.base_reserve != header.baseReserve:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE,
                p.base_reserve).to_xdr())
        return out

    def is_valid_for_nomination(self, raw: bytes, header: LedgerHeader,
                                close_time: int) -> bool:
        """Would we vote for this upgrade? (reference isValid w/ nomination
        mode)."""
        try:
            up = LedgerUpgrade.from_xdr(raw)
        except Exception:
            return False
        p = self.params
        if close_time < p.upgrade_time:
            return False
        t = up.disc
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            return up.value == p.protocol_version
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return up.value == p.base_fee
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return up.value == p.max_tx_set_size
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return up.value == p.base_reserve
        return False

    @staticmethod
    def validity_for_apply(raw: bytes, header: LedgerHeader,
                           max_ledger_version: int) -> int:
        """Full apply-validity (reference isValidForApply): version
        upgrades must be strictly monotonic and within the supported
        protocol; fee/reserve must be nonzero; unknown types are invalid.
        Close-time behavior: a non-VALID upgrade in an externalized value
        fails the close (LedgerManagerImpl.cpp:617-634)."""
        try:
            up = LedgerUpgrade.from_xdr(raw)
        except Exception:
            return UpgradeValidity.XDR_INVALID
        t = up.disc
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            ok = header.ledgerVersion < up.value <= max_ledger_version
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            ok = up.value != 0
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            ok = True
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            ok = up.value != 0
        else:
            ok = False
        return UpgradeValidity.VALID if ok else UpgradeValidity.INVALID

    @staticmethod
    def is_valid_for_apply(raw: bytes, header: LedgerHeader,
                           max_ledger_version: int = 2**32 - 1) -> bool:
        return Upgrades.validity_for_apply(
            raw, header, max_ledger_version) == UpgradeValidity.VALID

    @staticmethod
    def remove_upgrades(value_upgrades: List[bytes],
                        header: LedgerHeader) -> List[bytes]:
        return [u for u in value_upgrades
                if Upgrades.is_valid_for_apply(u, header)]

    def remove_applied_and_expired(self, value_upgrades: List[bytes],
                                   close_time: int) -> bool:
        """Reset armed parameters that (a) just externalized — each upgrade
        in the closed value clears a matching armed target — or (b) whose
        scheduled time passed more than UPGRADE_EXPIRATION_SECONDS ago
        (reference Upgrades::removeUpgrades). Returns True if anything was
        reset (callers persist the new parameters)."""
        p = self.params
        updated = False
        if p.upgrade_time + UPGRADE_EXPIRATION_SECONDS <= close_time:
            for field in ("protocol_version", "base_fee",
                          "max_tx_set_size", "base_reserve"):
                if getattr(p, field) is not None:
                    setattr(p, field, None)
                    updated = True
            return updated
        by_type = {
            LedgerUpgradeType.LEDGER_UPGRADE_VERSION: "protocol_version",
            LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: "base_fee",
            LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
                "max_tx_set_size",
            LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE: "base_reserve",
        }
        for raw in value_upgrades:
            try:
                up = LedgerUpgrade.from_xdr(raw)
            except Exception:
                continue
            field = by_type.get(up.disc)
            if field is not None and getattr(p, field) == up.value:
                setattr(p, field, None)
                updated = True
        return updated

    @staticmethod
    def apply_to(ltx, up: LedgerUpgrade) -> None:
        """Apply one externalized upgrade inside `ltx` (reference
        Upgrades::applyTo). Version and reserve upgrades can rewrite
        ledger ENTRIES, not just the header: crossing into protocol 10
        (or raising the reserve at >=10) recomputes every offer owner's
        liabilities via prepare_liabilities."""
        header = ltx.load_header()
        t = up.disc
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            prev = header.ledgerVersion
            header.ledgerVersion = up.value
            if prev < 10 <= header.ledgerVersion:
                prepare_liabilities(ltx, header)
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            header.baseFee = up.value
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            header.maxTxSetSize = up.value
        elif t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            increased = up.value > header.baseReserve
            header.baseReserve = up.value
            if header.ledgerVersion >= 10 and increased:
                prepare_liabilities(ltx, header)


def prepare_liabilities(ltx, header: LedgerHeader) -> None:
    """Bring offers and liabilities into a valid state (reference
    Upgrades.cpp prepareLiabilities:611-762). For every account with
    offers: (1) total the liabilities its offers imply per asset, (2)
    erase ALL offers buying an asset whose initial buying total exceeds
    the available limit (and likewise selling vs available balance) —
    deletion decisions use only the INITIAL totals so offer order can't
    matter, (3) round remaining offers to exchange-representable amounts,
    (4) write the recomputed totals into the account/trustline liability
    extensions."""
    from ..transactions.account_helpers import (
        INT64_MAX, change_subentries, get_buying_liabilities,
        get_selling_liabilities, load_account, load_trustline, min_balance,
        trustline_authorized_to_maintain,
    )
    from ..transactions.offer_exchange import adjust_offer, offer_liabilities
    from ..xdr import LedgerKey

    offers = ltx.load_all_offers()
    by_account: dict = {}
    for e in offers:
        by_account.setdefault(e.data.value.sellerID.key_bytes, []).append(e)

    for _seller, acct_offers in sorted(by_account.items()):
        seller = acct_offers[0].data.value.sellerID

        # (1) initial per-asset totals; None marks int64 overflow (legacy
        # offers predate liability caps). Issuer-owned sides total 0 but
        # the asset key must exist for the deletion check below.
        init_buying: dict = {}
        init_selling: dict = {}

        def add_init(table, asset, amount):
            k = asset.to_xdr()
            cur = table.setdefault(k, 0)
            if not asset.is_native and seller == asset.issuer:
                return
            if cur is not None:
                cur += amount
                table[k] = cur if cur <= INT64_MAX else None

        for e in acct_offers:
            o = e.data.value
            buying_liab, selling_liab = offer_liabilities(
                o.price.n, o.price.d, o.amount)
            add_init(init_buying, o.buying, buying_liab)
            add_init(init_selling, o.selling, selling_liab)

        acc_entry = load_account(ltx, seller)
        assert acc_entry is not None, "offer owner account missing"
        acc = acc_entry.data.value
        balance = acc.balance
        balance_above_reserve = balance - min_balance(
            header, acc.numSubEntries)

        def available_balance(asset):
            # capacity to DELIVER asset, liabilities excluded (reference
            # getAvailableBalanceExcludingLiabilities)
            if asset.is_native:
                return balance_above_reserve
            if seller == asset.issuer:
                return INT64_MAX
            tl = ltx.load_without_record(LedgerKey.trustline(seller, asset))
            if tl is not None and \
                    trustline_authorized_to_maintain(tl.data.value):
                return tl.data.value.balance
            return 0

        def available_limit(asset):
            # capacity to RECEIVE asset (reference
            # getAvailableLimitExcludingLiabilities)
            if asset.is_native:
                return INT64_MAX - balance
            if seller == asset.issuer:
                return INT64_MAX
            tl = ltx.load_without_record(LedgerKey.trustline(seller, asset))
            if tl is not None and \
                    trustline_authorized_to_maintain(tl.data.value):
                return tl.data.value.limit - tl.data.value.balance
            return 0

        def excess(table, asset, cap_fn):
            total = table[asset.to_xdr()]
            return total is None or total > cap_fn(asset)

        # (2)+(3) erase/adjust each offer; recompute surviving totals.
        # `final` only gains entries from SURVIVING offers — matching the
        # reference, whose updateOffer touches its liabilities map only in
        # the non-erase branch: an asset that loses every offer keeps its
        # previously-recorded liabilities (at the v10 crossing they are 0
        # by construction; at a reserve raise the excess stays recorded,
        # conservatively — same quirk as the reference).
        final: dict = {}   # asset xdr -> [buying, selling]
        for e in acct_offers:
            o = e.data.value
            erase = excess(init_selling, o.selling, available_balance) or \
                excess(init_buying, o.buying, available_limit)
            adj = adjust_offer(o.price.n, o.price.d, o.amount, INT64_MAX)
            if erase or adj == 0:
                ltx.erase(LedgerKey.offer(seller, o.offerID))
                assert change_subentries(header, acc_entry, -1)
                continue
            o.amount = adj   # load_all_offers loads for update: sticks
            buying_liab, selling_liab = offer_liabilities(
                o.price.n, o.price.d, o.amount)
            if o.buying.is_native or seller != o.buying.issuer:
                final.setdefault(o.buying.to_xdr(), [0, 0])[0] += buying_liab
            if o.selling.is_native or seller != o.selling.issuer:
                final.setdefault(o.selling.to_xdr(), [0, 0])[1] += \
                    selling_liab

        # (4) set account/trustline liabilities to the recomputed totals
        from ..transactions.account_helpers import (
            add_buying_liabilities, add_selling_liabilities,
        )
        from ..xdr import Asset
        for asset_x, (buying, selling) in sorted(final.items()):
            asset = Asset.from_xdr(asset_x)
            if asset.is_native:
                target = acc_entry
            else:
                target = load_trustline(ltx, seller, asset)
                assert target is not None, \
                    "offer survived without its trustline"
            d_sell = selling - get_selling_liabilities(header, target)
            d_buy = buying - get_buying_liabilities(header, target)
            if header.ledgerVersion > 10 and (d_sell > 0 or d_buy > 0):
                raise RuntimeError(
                    "invalid liabilities delta above protocol 10")
            if not add_selling_liabilities(header, target, d_sell):
                raise RuntimeError(
                    "invalid selling liabilities during upgrade")
            if not add_buying_liabilities(header, target, d_buy):
                raise RuntimeError(
                    "invalid buying liabilities during upgrade")
