"""Upgrades: protocol/fee/size/reserve upgrade voting.

Role parity: reference `src/herder/Upgrades.{h,cpp}` — armed via config or
the HTTP admin endpoint, nominated inside StellarValue.upgrades, validated
against scheduled parameters, applied at ledger close (after txs).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..xdr import LedgerHeader, LedgerUpgrade, LedgerUpgradeType


class UpgradeParameters:
    def __init__(self) -> None:
        self.upgrade_time: int = 0
        self.protocol_version: Optional[int] = None
        self.base_fee: Optional[int] = None
        self.max_tx_set_size: Optional[int] = None
        self.base_reserve: Optional[int] = None

    def to_json(self) -> dict:
        return {
            "time": self.upgrade_time,
            "version": self.protocol_version,
            "fee": self.base_fee,
            "maxtxsize": self.max_tx_set_size,
            "reserve": self.base_reserve,
        }


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None) -> None:
        self.params = params or UpgradeParameters()

    def set_parameters(self, params: UpgradeParameters) -> None:
        self.params = params

    def create_upgrades_for(self, header: LedgerHeader,
                            close_time: int) -> List[bytes]:
        """Upgrades to nominate, given the current header (reference
        createUpgradesFor)."""
        out: List[bytes] = []
        p = self.params
        if close_time < p.upgrade_time:
            return out
        if p.protocol_version is not None and \
                p.protocol_version != header.ledgerVersion:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_VERSION,
                p.protocol_version).to_xdr())
        if p.base_fee is not None and p.base_fee != header.baseFee:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE,
                p.base_fee).to_xdr())
        if p.max_tx_set_size is not None and \
                p.max_tx_set_size != header.maxTxSetSize:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE,
                p.max_tx_set_size).to_xdr())
        if p.base_reserve is not None and \
                p.base_reserve != header.baseReserve:
            out.append(LedgerUpgrade(
                LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE,
                p.base_reserve).to_xdr())
        return out

    def is_valid_for_nomination(self, raw: bytes, header: LedgerHeader,
                                close_time: int) -> bool:
        """Would we vote for this upgrade? (reference isValid w/ nomination
        mode)."""
        try:
            up = LedgerUpgrade.from_xdr(raw)
        except Exception:
            return False
        p = self.params
        if close_time < p.upgrade_time:
            return False
        t = up.disc
        if t == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            return up.value == p.protocol_version
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            return up.value == p.base_fee
        if t == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return up.value == p.max_tx_set_size
        if t == LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE:
            return up.value == p.base_reserve
        return False

    @staticmethod
    def is_valid_for_apply(raw: bytes, header: LedgerHeader) -> bool:
        """Structurally applicable? (applied even if we didn't vote for it,
        once consensus accepts it)."""
        try:
            up = LedgerUpgrade.from_xdr(raw)
        except Exception:
            return False
        if up.disc == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            return up.value >= header.ledgerVersion
        return up.value > 0

    @staticmethod
    def remove_upgrades(value_upgrades: List[bytes],
                        header: LedgerHeader) -> List[bytes]:
        return [u for u in value_upgrades
                if Upgrades.is_valid_for_apply(u, header)]
