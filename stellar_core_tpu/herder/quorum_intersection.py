"""Quorum intersection checker + transitive quorum tracker.

Role parity: reference `src/herder/QuorumIntersectionCheckerImpl.{h,cpp}`
(min-quorum enumeration with SCC pruning, contraction to maximal quorums,
half-space cutoff, perimeter look-ahead, max-indegree branching heuristic
— algorithm documented at QuorumIntersectionCheckerImpl.h:7-300, after
Lachowski arXiv:1902.06493) and `src/herder/QuorumTracker.{h,cpp}`
(transitive closure of the local qset over received SCP traffic).

Sets of nodes are Python ints used as bitmasks — the Python-idiomatic
analogue of the reference's BitSet, giving O(1)-word intersection /
containment over networks of hundreds of validators.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..util.log import get_logger
from ..xdr import PublicKey, SCPQuorumSet

log = get_logger("SCP")


class QuorumIntersectionChecker:
    def __init__(self, qmap: Dict[bytes, Optional[SCPQuorumSet]],
                 parent: "QuorumIntersectionChecker" = None) -> None:
        """qmap: node id (raw 32B ed25519) -> its quorum set (None if
        unknown; unknown nodes can never be satisfied, matching the
        reference's treatment of missing qsets).

        `parent` shares its interrupt flag with this checker (the
        criticality scan builds one throwaway checker per candidate
        group; the reference threads one shared interrupt flag through
        all of them — HerderImpl.cpp:140-144)."""
        self._parent = parent
        self.ids: List[bytes] = sorted(qmap)
        self.index: Dict[bytes, int] = {v: i for i, v in enumerate(self.ids)}
        self.n = len(self.ids)
        self.full: int = (1 << self.n) - 1
        self._qsets: List[Optional[SCPQuorumSet]] = [
            qmap[v] for v in self.ids]
        # dependency edges i -> j (j appears in i's qset, transitively
        # through inner sets)
        self._deps: List[int] = [self._dep_mask(qs) for qs in self._qsets]
        self.interrupted = False
        self.last_split: Optional[Tuple[List[bytes], List[bytes]]] = None
        self.quorums_seen = 0
        # compiled qset forms: pubnet-scale maps share qset structure
        # heavily (every org validator carries the same top-level set), so
        # satisfaction is evaluated per DISTINCT compiled set and memoized
        # per (set, mask) — this is what makes ~100-org transitive maps
        # finish (reference compiles to TBitSet structures similarly,
        # QuorumIntersectionCheckerImpl.h:7-60)
        self._compiled: List[Tuple[int, int, Tuple[int, ...]]] = []
        self._compile_by_id: Dict[int, int] = {}
        self._compile_by_val: Dict[tuple, int] = {}
        self._compile_keepalive: List[SCPQuorumSet] = []
        self._node_cq: List[Optional[int]] = [
            None if qs is None else self._compile_qs(qs)
            for qs in self._qsets]
        self._sat_cache: Dict[Tuple[int, int], bool] = {}
        # nodes grouped by compiled qset: a contraction pass evaluates
        # each DISTINCT qset once instead of once per node (nodes with no
        # qset are never satisfied, so they simply have no group)
        groups: Dict[int, int] = {}
        for i, ci in enumerate(self._node_cq):
            if ci is not None:
                groups[ci] = groups.get(ci, 0) | (1 << i)
        self._cq_groups: List[Tuple[int, int]] = sorted(groups.items())

    # -- qset satisfaction ---------------------------------------------------
    def _dep_mask(self, qs: Optional[SCPQuorumSet]) -> int:
        m = 0
        if qs is None:
            return m
        for v in qs.validators:
            i = self.index.get(v.key_bytes)
            if i is not None:
                m |= 1 << i
        for inner in qs.innerSets:
            m |= self._dep_mask(inner)
        return m

    def _compile_qs(self, qs: SCPQuorumSet) -> int:
        key = id(qs)
        hit = self._compile_by_id.get(key)
        if hit is not None:
            return hit
        # keep the object alive: the id-keyed memo must never serve a
        # freed object's recycled id to a different qset
        self._compile_keepalive.append(qs)
        direct = 0
        for v in qs.validators:
            i = self.index.get(v.key_bytes)
            if i is not None:
                direct |= 1 << i
        children = tuple(self._compile_qs(inner) for inner in qs.innerSets)
        vkey = (qs.threshold, direct, children)
        idx = self._compile_by_val.get(vkey)
        if idx is None:
            idx = len(self._compiled)
            self._compiled.append(vkey)
            self._compile_by_val[vkey] = idx
        self._compile_by_id[key] = idx
        return idx

    def _sat(self, ci: int, mask: int) -> bool:
        ck = (ci, mask)
        cached = self._sat_cache.get(ck)
        if cached is not None:
            return cached
        thr, direct, children = self._compiled[ci]
        hits = (direct & mask).bit_count()
        if hits < thr:
            for ch in children:
                if self._sat(ch, mask):
                    hits += 1
                    if hits >= thr:
                        break
        r = hits >= thr
        if len(self._sat_cache) > 4_000_000:
            self._sat_cache.clear()
        self._sat_cache[ck] = r
        return r

    def _qset_satisfied(self, qs: SCPQuorumSet, mask: int) -> bool:
        return self._sat(self._compile_qs(qs), mask)

    def _node_satisfied(self, i: int, mask: int) -> bool:
        ci = self._node_cq[i]
        return ci is not None and self._sat(ci, mask)

    # -- quorum machinery (refinement 2) ------------------------------------
    def contract_to_maximal_quorum(self, mask: int) -> int:
        """Largest quorum within `mask`, or 0 (reference
        contractToMaximalQuorum). Each fixpoint pass walks the distinct
        compiled qsets, not the individual nodes."""
        while True:
            next_mask = 0
            for ci, gmask in self._cq_groups:
                gm = gmask & mask
                if gm and self._sat(ci, mask):
                    next_mask |= gm
            if next_mask == mask:
                return mask
            mask = next_mask
            if mask == 0:
                return 0

    def is_a_quorum(self, mask: int) -> bool:
        return mask != 0 and self.contract_to_maximal_quorum(mask) == mask

    def is_minimal_quorum(self, mask: int) -> bool:
        """A quorum none of whose one-smaller subsets contains a quorum
        (reference isMinimalQuorum)."""
        m = mask
        while m:
            low = m & -m
            if self.contract_to_maximal_quorum(mask & ~low) != 0:
                return False
            m ^= low
        return True

    # -- SCC analysis (the outer pruning) ------------------------------------
    def _sccs(self) -> List[int]:
        """Tarjan over the dependency graph; returns SCC masks."""
        idx = [0] * self.n
        low = [0] * self.n
        on = [False] * self.n
        comp: List[int] = []
        stack: List[int] = []
        counter = [1]

        def strongconnect(v0: int) -> None:
            # iterative tarjan (explicit stack) to survive big nets
            work = [(v0, 0)]
            while work:
                v, pi = work.pop()
                if pi == 0:
                    idx[v] = low[v] = counter[0]
                    counter[0] += 1
                    stack.append(v)
                    on[v] = True
                recurse = False
                deps = self._deps[v]
                m = deps >> pi
                shift = pi
                while m:
                    if m & 1:
                        w = shift
                        if idx[w] == 0:
                            work.append((v, shift + 1))
                            work.append((w, 0))
                            recurse = True
                            break
                        elif on[w]:
                            low[v] = min(low[v], idx[w])
                    m >>= 1
                    shift += 1
                if recurse:
                    continue
                if low[v] == idx[v]:
                    c = 0
                    while True:
                        w = stack.pop()
                        on[w] = False
                        c |= 1 << w
                        if w == v:
                            break
                    comp.append(c)
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])

        for v in range(self.n):
            if idx[v] == 0:
                strongconnect(v)
        return comp

    # -- the enumeration (refinements 3-7) -----------------------------------
    def network_enjoys_quorum_intersection(self) -> bool:
        if self.n == 0:
            return True
        sccs = self._sccs()
        # pick the SCC containing a quorum; a quorum in any OTHER SCC is an
        # immediate disjoint pair (SCCs don't intersect by construction)
        main_scc = 0
        for c in sorted(sccs, key=lambda c: -bin(c).count("1")):
            if self.contract_to_maximal_quorum(c) != 0:
                if main_scc:
                    self._record_split(
                        self.contract_to_maximal_quorum(main_scc),
                        self.contract_to_maximal_quorum(c))
                    return False
                main_scc = c
        if not main_scc:
            log.warning("no quorum found in any SCC")
            return True    # vacuously true: no quorums at all
        self._main = main_scc
        self._maxsz = bin(main_scc).count("1") // 2 + 1
        self.quorums_seen = 0
        return self._enumerate(0, main_scc)

    def _record_split(self, a: int, b: int) -> None:
        self.last_split = ([self.ids[i] for i in _bits(a)],
                           [self.ids[i] for i in _bits(b)])
        log.warning("found disjoint quorums: %s | %s",
                    [x.hex()[:8] for x in self.last_split[0]],
                    [x.hex()[:8] for x in self.last_split[1]])

    def _enumerate(self, committed: int, remaining: int) -> bool:
        """True iff no disjoint minq pair found in this branch (reference's
        recursive enumerate with early exits #1-3)."""
        if self.interrupted or \
                (self._parent is not None and self._parent.interrupted):
            raise InterruptedError("quorum intersection check interrupted")
        if bin(committed).count("1") > self._maxsz:
            return True
        if committed != 0 and self.is_a_quorum(committed):
            self.quorums_seen += 1
            if self.is_minimal_quorum(committed):
                comp = self.contract_to_maximal_quorum(
                    self._main & ~committed)
                if comp:
                    self._record_split(committed, comp)
                    return False
            return True   # supersets of a quorum are never minqs
        if remaining == 0:
            return True
        perimeter = committed | remaining
        maxq = self.contract_to_maximal_quorum(perimeter)
        if maxq == 0 or (committed & ~maxq) != 0:
            return True   # no quorum ahead extends committed
        i = self._pick_branch_node(remaining)
        bit = 1 << i
        return (self._enumerate(committed, remaining & ~bit) and
                self._enumerate(committed | bit, remaining & ~bit))

    def _pick_branch_node(self, remaining: int) -> int:
        """Max indegree within the remaining subgraph (refinement 7)."""
        best, best_deg = -1, -1
        for i in _bits(remaining):
            deg = 0
            for j in _bits(remaining):
                if (self._deps[j] >> i) & 1:
                    deg += 1
            if deg > best_deg:
                best, best_deg = i, deg
        return best


def _bits(mask: int):
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low


class QuorumTracker:
    """Transitive quorum map rooted at the local node (reference
    QuorumTracker.h:21-51)."""

    def __init__(self, local_id: PublicKey,
                 local_qset_fn: Callable[[], SCPQuorumSet]) -> None:
        self._local_id = local_id
        self._local_qset_fn = local_qset_fn
        self._quorum: Dict[bytes, Optional[SCPQuorumSet]] = {}
        self.rebuild(lambda node_id: None)

    def is_node_definitely_in_quorum(self, node_id: PublicKey) -> bool:
        return node_id.key_bytes in self._quorum

    def _qset_nodes(self, qs: SCPQuorumSet) -> List[bytes]:
        out = [v.key_bytes for v in qs.validators]
        for inner in qs.innerSets:
            out.extend(self._qset_nodes(inner))
        return out

    def expand(self, node_id: PublicKey,
               qset: SCPQuorumSet) -> bool:
        """Add node's qset if node is already in the transitive quorum
        (reference expand); False means caller should rebuild."""
        key = node_id.key_bytes
        if key not in self._quorum:
            return False
        if self._quorum[key] is not None:
            return self._quorum[key].to_xdr() == qset.to_xdr()
        self._quorum[key] = qset
        for dep in self._qset_nodes(qset):
            self._quorum.setdefault(dep, None)
        return True

    def rebuild(self, lookup: Callable[[PublicKey],
                                       Optional[SCPQuorumSet]]) -> None:
        """Recompute the closure from the local qset via `lookup`
        (reference rebuild)."""
        self._quorum = {}
        frontier = [(self._local_id.key_bytes, self._local_qset_fn())]
        while frontier:
            key, qs = frontier.pop()
            if key in self._quorum and self._quorum[key] is not None:
                continue
            self._quorum[key] = qs
            if qs is None:
                continue
            for dep in self._qset_nodes(qs):
                if dep not in self._quorum:
                    self._quorum[dep] = None
                    got = lookup(PublicKey.ed25519(dep))
                    if got is not None:
                        frontier.append((dep, got))
        self.quorum_map_changed = True

    def get_quorum(self) -> Dict[bytes, Optional[SCPQuorumSet]]:
        return self._quorum


# -- intersection-critical group analysis ------------------------------------

def _points_to_any(qs: SCPQuorumSet, group: frozenset) -> bool:
    """Single traversal: does qs reference any member of `group`?"""
    for v in qs.validators:
        if v.key_bytes in group:
            return True
    return any(_points_to_any(i, group) for i in qs.innerSets)


def _criticality_candidates(qs: SCPQuorumSet, out: set, root: bool) -> None:
    """Reference findCriticalityCandidates: every validator as a
    singleton, plus every non-root LEAF innerSet as a group."""
    for v in qs.validators:
        out.add(frozenset((v.key_bytes,)))
    if not root and not qs.innerSets:
        out.add(frozenset(v.key_bytes for v in qs.validators))
    for i in qs.innerSets:
        _criticality_candidates(i, out, False)


def intersection_critical_groups(
        qmap: Dict[bytes, Optional[SCPQuorumSet]],
        parent: QuorumIntersectionChecker = None) -> List[set]:
    """Find "intersection-critical" node groups (reference
    QuorumIntersectionChecker::getIntersectionCriticalGroups): for each
    candidate group (leaf innerSets + singletons), install a "fickle"
    qset — threshold 2 over {the group itself, anyone pointing at the
    group} so the group goes along with anyone — and re-check
    intersection. Groups whose fickleness splits the network are the
    operators to watch."""
    candidates: set = set()
    for qs in qmap.values():
        if qs is not None:
            _criticality_candidates(qs, candidates, True)
    log.info("examining %d node groups for intersection-criticality",
             len(candidates))
    critical: List[set] = []
    # frozenset ordering is subset partial order — sort by element lists
    # for deterministic output across runs
    for group in sorted(candidates, key=sorted):
        group_qset = SCPQuorumSet(
            threshold=len(group),
            validators=[PublicKey.ed25519(k) for k in sorted(group)],
            innerSets=[])
        points_to = sorted(
            node for node, qs in qmap.items()
            if node not in group and qs is not None and
            _points_to_any(qs, group))
        fickle = SCPQuorumSet(
            threshold=2,
            validators=[],
            innerSets=[group_qset,
                       SCPQuorumSet(threshold=1,
                                    validators=[PublicKey.ed25519(k)
                                                for k in points_to],
                                    innerSets=[])])
        test_qmap = dict(qmap)
        for k in group:
            test_qmap[k] = fickle
        checker = QuorumIntersectionChecker(test_qmap, parent=parent)
        if not checker.network_enjoys_quorum_intersection():
            critical.append(set(group))
    return critical


def intersection_critical_groups_strkey(
        qmap: Dict[bytes, Optional[SCPQuorumSet]],
        parent: QuorumIntersectionChecker = None) -> List[List[str]]:
    """Criticality report in operator form (strkey lists) — shared by the
    HTTP checkquorum endpoint and the check-quorum CLI."""
    from ..crypto.strkey import encode_public_key
    return [sorted(encode_public_key(k) for k in group)
            for group in intersection_critical_groups(qmap, parent=parent)]
