"""History work units: remote file transfer, (de)compression, archive
state fetch, batched checkpoint downloads, ledger-chain verification.

Role parity: reference `src/historywork/*` — `GetRemoteFileWork` /
`PutRemoteFileWork` / `MakeRemoteDirWork` shell out through the process
manager (`GetRemoteFileWork.cpp`), `GunzipFileWork`/`GzipFileWork`
(`GunzipFileWork.cpp`), `GetAndUnzipRemoteFileWork.cpp`,
`BatchDownloadWork.cpp` (bounded-parallel per-checkpoint downloads),
`VerifyBucketWork.cpp` (hash downloaded bucket), and
`VerifyLedgerChainWork.cpp` (hash-chain back-link verification).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..crypto.hashing import sha256
from ..history.archive import (ArchivePool, HistoryArchive, bucket_path,
                               category_path)
from ..history.archive_state import HistoryArchiveState
from ..history.checkpoints import checkpoints_in_range
from ..history.snapshot import gunzip_file, gzip_file
from ..util.log import get_logger
from ..util.xdrstream import XDRInputFileStream
from ..work.basic_work import (FAILURE, RETRY_A_FEW, RETRY_NEVER, RUNNING,
                               SUCCESS, WAITING, BasicWork, State)
from ..work.work import BatchWork, WorkSequence
from ..xdr import LedgerHeaderHistoryEntry

log = get_logger("History")


class RunCommandWork(BasicWork):
    """Run one shell command through the app's ProcessManager; the work
    WAITs until the subprocess exit event fires (reference
    `historywork/RunCommandWork.cpp`)."""

    def __init__(self, app, name: str, max_retries: int = RETRY_A_FEW
                 ) -> None:
        super().__init__(app.clock, name, max_retries)
        self.app = app
        self._ev = None
        self._exit_code: Optional[int] = None

    def get_command(self) -> str:
        raise NotImplementedError

    def on_reset(self) -> None:
        self._ev = None
        self._exit_code = None

    def on_run(self) -> State:
        if self._exit_code is not None:
            return SUCCESS if self._exit_code == 0 else FAILURE
        if self._ev is None:
            cmd = self.get_command()
            if not cmd:
                return FAILURE
            self._ev = self.app.process_manager.run_process(cmd)

            def done(code: int) -> None:
                self._exit_code = code
                self.wake_up()

            self._ev.add_done_callback(done)
        return WAITING


class GetRemoteFileWork(RunCommandWork):
    """Download archive:remote -> local (reference GetRemoteFileWork).

    `archive` may be a single HistoryArchive or an ArchivePool: with a
    pool, every attempt re-picks the healthiest archive not yet tried
    for THIS file, so a retry after a transport failure (or after a
    downstream corruption detection excluded the culprit) lands on a
    different archive (docs/robustness.md failover). Fault points
    `archive.get-fail` / `archive.corrupt` / `archive.short-read`
    (util/faults.py) simulate a broken transfer, a bit-flipped file and
    a truncated file respectively."""

    def __init__(self, app, archive, remote: str, local: str) -> None:
        super().__init__(app, "get-remote-file %s" % remote)
        self.archive = archive
        self.pool = archive if isinstance(archive, ArchivePool) else None
        self.current_archive: Optional[HistoryArchive] = \
            None if self.pool is not None else archive
        self._tried: List[str] = []   # archive names tried for this file
        self.remote = remote
        self.local = local

    def get_command(self) -> str:
        if self.pool is not None:
            self.current_archive = self.pool.pick(exclude=self._tried)
        if self.current_archive is None:
            return ""
        os.makedirs(os.path.dirname(self.local) or ".", exist_ok=True)
        return self.current_archive.get_cmd(self.remote, self.local)

    def exclude_current(self) -> None:
        """Mark the archive of the last attempt as tried (called by this
        work and by parents that detect corruption downstream)."""
        if self.current_archive is not None and \
                self.current_archive.name not in self._tried:
            self._tried.append(self.current_archive.name)

    def on_run(self) -> State:
        st = super().on_run()
        if st != SUCCESS:
            return st
        faults = getattr(self.app, "faults", None)
        if faults is not None:
            if faults.should_fire("archive.get-fail"):
                return FAILURE
            if faults.should_fire("archive.corrupt") and \
                    os.path.exists(self.local):
                size = os.path.getsize(self.local)
                with open(self.local, "r+b") as f:
                    if size:
                        f.seek(size // 2)
                        b = f.read(1)
                        f.seek(size // 2)
                        f.write(bytes([b[0] ^ 0xFF]))
                    else:
                        # an empty file "corrupts" by growing garbage
                        f.write(b"\xff")
            if faults.should_fire("archive.short-read") and \
                    os.path.exists(self.local):
                with open(self.local, "r+b") as f:
                    f.truncate(os.path.getsize(self.local) // 2)
        if self.pool is not None and self.current_archive is not None:
            self.pool.report_success(self.current_archive)
        return SUCCESS

    def on_failure_retry(self) -> None:
        if os.path.exists(self.local):
            os.unlink(self.local)
        if self.pool is not None and self.current_archive is not None:
            self.pool.report_failure(self.current_archive)
            self.exclude_current()

    def on_failure_raise(self) -> None:
        self.on_failure_retry()


class PutRemoteFileWork(RunCommandWork):
    """Upload local -> archive:remote (reference PutRemoteFileWork)."""

    def __init__(self, app, archive: HistoryArchive, local: str,
                 remote: str) -> None:
        super().__init__(app, "put-remote-file %s" % remote)
        self.archive = archive
        self.local = local
        self.remote = remote

    def get_command(self) -> str:
        return self.archive.put_cmd(self.local, self.remote)


class MakeRemoteDirWork(RunCommandWork):
    """mkdir -p on the archive (reference MakeRemoteDirWork)."""

    def __init__(self, app, archive: HistoryArchive, remote_dir: str
                 ) -> None:
        super().__init__(app, "make-remote-dir %s" % remote_dir)
        self.archive = archive
        self.remote_dir = remote_dir

    def get_command(self) -> str:
        return self.archive.mkdir_cmd(self.remote_dir)


class GunzipFileWork(BasicWork):
    """Decompress foo.gz -> foo in-process (reference GunzipFileWork
    shells out to gzip; python's gzip module plays that role)."""

    def __init__(self, app, gz_path: str, keep: bool = False) -> None:
        super().__init__(app.clock, "gunzip %s" % gz_path, RETRY_NEVER)
        self.gz_path = gz_path
        self.keep = keep

    def on_run(self) -> State:
        if not os.path.exists(self.gz_path):
            return FAILURE
        gunzip_file(self.gz_path)
        if not self.keep:
            os.unlink(self.gz_path)
        return SUCCESS


class GzipFileWork(BasicWork):
    """Compress foo -> foo.gz (reference GzipFileWork)."""

    def __init__(self, app, path: str, keep: bool = False) -> None:
        super().__init__(app.clock, "gzip %s" % path, RETRY_NEVER)
        self.path = path
        self.keep = keep

    def on_run(self) -> State:
        if not os.path.exists(self.path):
            return FAILURE
        gzip_file(self.path)
        if not self.keep:
            os.unlink(self.path)
        return SUCCESS


class GetAndUnzipRemoteFileWork(WorkSequence):
    """Download then gunzip, optionally verifying the sha256 of the
    decompressed file (reference GetAndUnzipRemoteFileWork). A failure
    detected AFTER the download succeeded — gunzip error on a truncated
    file, content-hash mismatch on a corrupted one — indicts the archive
    that served the bytes: it is reported to the pool and excluded, so
    the sequence retry re-downloads from a different archive."""

    def __init__(self, app, archive, remote_gz: str,
                 local: str, expected_hash: Optional[bytes] = None) -> None:
        self.local = local
        self.expected_hash = expected_hash
        self._get = GetRemoteFileWork(app, archive, remote_gz,
                                      local + ".gz")
        seq: List[BasicWork] = [
            self._get,
            GunzipFileWork(app, local + ".gz"),
        ]
        super().__init__(app.clock, "get-and-unzip %s" % remote_gz, seq)

    def on_run(self) -> State:
        st = super().on_run()
        if st == SUCCESS and self.expected_hash is not None:
            with open(self.local, "rb") as f:
                if sha256(f.read()) != self.expected_hash:
                    log.warning("hash mismatch on %s", self.local)
                    return FAILURE
        return st

    def _blame_archive(self) -> None:
        g = self._get
        # only a post-download failure is news here; a transport failure
        # already reported itself inside GetRemoteFileWork's own retries
        if g.state == State.SUCCESS and g.pool is not None and \
                g.current_archive is not None:
            g.pool.report_failure(g.current_archive)
            g.exclude_current()

    def on_failure_retry(self) -> None:
        self._blame_archive()
        for p in (self.local, self.local + ".gz"):
            if os.path.exists(p):
                os.unlink(p)

    def on_failure_raise(self) -> None:
        self._blame_archive()


class GetHistoryArchiveStateWork(BasicWork):
    """Fetch a HistoryArchiveState JSON — the well-known (archive tip) or
    a specific checkpoint's (reference GetHistoryArchiveStateWork)."""

    def __init__(self, app, archive, local_dir: str,
                 checkpoint: Optional[int] = None) -> None:
        super().__init__(app.clock, "get-history-archive-state",
                         RETRY_A_FEW)
        self.app = app
        self.archive = archive
        self.checkpoint = checkpoint
        self.local = os.path.join(
            local_dir,
            "has-%s.json" % ("well-known" if checkpoint is None
                             else "%08x" % checkpoint))
        self.has: Optional[HistoryArchiveState] = None
        self._get: Optional[GetRemoteFileWork] = None
        # archive names to avoid, SHARED into every inner download so a
        # corrupt-HAS blame survives this work's own retries (on_reset
        # rebuilds the download work)
        self._tried: List[str] = []

    def _remote(self) -> str:
        from ..history.archive import WELL_KNOWN
        if self.checkpoint is None:
            return WELL_KNOWN
        return category_path("history", self.checkpoint, ".json")

    def on_reset(self) -> None:
        self._get = None
        self.has = None

    def on_run(self) -> State:
        if self._get is None:
            self._get = GetRemoteFileWork(self.app, self.archive,
                                          self._remote(), self.local)
            self._get._tried = self._tried
            self._get._parent = self
            self._get.start()
        if not self._get.is_done():
            self._get.crank_work()
            if not self._get.is_done():
                return RUNNING if self._get.is_crankable() else WAITING
        if self._get.state != State.SUCCESS:
            return FAILURE
        try:
            with open(self.local) as f:
                self.has = HistoryArchiveState.from_json(f.read())
        except Exception as e:
            # the bytes arrived but don't parse: the serving archive is
            # corrupt for this file — blame it so the retry (our own
            # on_reset rebuilds the download) picks a different one
            log.warning("unparseable HistoryArchiveState from %s: %s",
                        getattr(self._get.current_archive, "name", "?"), e)
            g = self._get
            if g.pool is not None and g.current_archive is not None:
                g.pool.report_failure(g.current_archive)
                g.exclude_current()
            return FAILURE
        return SUCCESS


class BatchDownloadWork(BatchWork):
    """Download-and-unzip one category file per checkpoint over a ledger
    range, bounded-parallel (reference BatchDownloadWork.cpp)."""

    def __init__(self, app, archive, category: str,
                 first_ledger: int, last_ledger: int, download_dir: str,
                 max_concurrent: int = 8) -> None:
        super().__init__(app.clock, "batch-download %s [%d..%d]"
                         % (category, first_ledger, last_ledger),
                         max_concurrent)
        self.app = app
        self.archive = archive
        self.category = category
        self.download_dir = download_dir
        freq = app.config.CHECKPOINT_FREQUENCY
        self._checkpoints = list(checkpoints_in_range(
            first_ledger, last_ledger, freq))
        self._idx = 0

    def local_path(self, checkpoint: int) -> str:
        return os.path.join(self.download_dir, "%s-%08x.xdr"
                            % (self.category, checkpoint))

    def do_reset(self) -> None:
        self._idx = 0

    def yield_more_work(self) -> Optional[BasicWork]:
        if self._idx >= len(self._checkpoints):
            return None
        c = self._checkpoints[self._idx]
        self._idx += 1
        return GetAndUnzipRemoteFileWork(
            self.app, self.archive,
            category_path(self.category, c, ".xdr.gz"),
            self.local_path(c))


class VerifyBucketWork(BasicWork):
    """Hash a downloaded bucket file and compare to its content address
    (reference VerifyBucketWork runs the hash on a worker thread; one
    bucket per crank keeps the loop responsive here)."""

    def __init__(self, app, path: str, expected_hash: bytes) -> None:
        super().__init__(app.clock, "verify-bucket %s"
                         % expected_hash.hex()[:8], RETRY_NEVER)
        self.path = path
        self.expected_hash = expected_hash

    def on_run(self) -> State:
        from ..bucket.bucket import Bucket
        b = Bucket.read_from(self.path)
        if b.get_hash() != self.expected_hash:
            log.warning("bucket %s hash mismatch",
                        self.expected_hash.hex()[:8])
            return FAILURE
        return SUCCESS


class DownloadBucketsWork(BatchWork):
    """Fetch + verify + adopt every bucket a HAS references (reference
    DownloadBucketsWork.cpp). Buckets already in the local store are
    skipped (content addressing makes this safe)."""

    def __init__(self, app, archive, hashes: List[str],
                 download_dir: str, max_concurrent: int = 8) -> None:
        super().__init__(app.clock, "download-buckets(%d)" % len(hashes),
                         max_concurrent)
        self.app = app
        self.archive = archive
        self.download_dir = download_dir
        self._hashes = list(dict.fromkeys(hashes))  # dedup, keep order
        self._idx = 0

    def local_path(self, hash_hex: str) -> str:
        return os.path.join(self.download_dir,
                            "bucket-%s.xdr" % hash_hex)

    def do_reset(self) -> None:
        self._idx = 0

    def yield_more_work(self) -> Optional[BasicWork]:
        bm = self.app.bucket_manager
        while self._idx < len(self._hashes):
            hh = self._hashes[self._idx]
            self._idx += 1
            if bm is not None and \
                    bm.get_bucket_by_hash(bytes.fromhex(hh)) is not None:
                continue                      # already have it
            local = self.local_path(hh)
            seq: List[BasicWork] = [
                GetAndUnzipRemoteFileWork(self.app, self.archive,
                                          bucket_path(hh), local),
                VerifyBucketWork(self.app, local, bytes.fromhex(hh)),
            ]
            return WorkSequence(self.clock, "fetch-bucket %s" % hh[:8],
                                seq)
        return None

    def do_work(self) -> State:
        # adopt everything downloaded into the content-addressed store
        from ..bucket.bucket import Bucket
        bm = self.app.bucket_manager
        if bm is None:
            return SUCCESS
        for hh in self._hashes:
            if bm.get_bucket_by_hash(bytes.fromhex(hh)) is not None:
                continue
            path = self.local_path(hh)
            if os.path.exists(path):
                bm.adopt_bucket(Bucket.read_from(path))
        return SUCCESS


class VerifyLedgerChainWork(BasicWork):
    """Walk downloaded ledger-header files verifying the hash chain:
    every entry's hash must equal SHA256(header) and every header's
    previousLedgerHash must back-link the prior entry (reference
    VerifyLedgerChainWork.cpp; it walks newest→oldest, one checkpoint
    per crank — mirrored here oldest→newest, same predicate). An
    optional trusted (seq, hash) pins the top of the chain."""

    def __init__(self, app, download_dir: str, first_ledger: int,
                 last_ledger: int,
                 trusted: Optional[tuple] = None,
                 local_genesis: Optional[tuple] = None) -> None:
        super().__init__(app.clock, "verify-ledger-chain", RETRY_NEVER)
        self.app = app
        self.download_dir = download_dir
        self.first_ledger = first_ledger
        self.last_ledger = last_ledger
        self.trusted = trusted            # (seq, hash) to match exactly
        self.local_genesis = local_genesis  # (lcl_seq, lcl_hash) link check
        freq = app.config.CHECKPOINT_FREQUENCY
        self._checkpoints = list(checkpoints_in_range(
            first_ledger, last_ledger, freq))
        self._ci = 0
        self._prev: Optional[LedgerHeaderHistoryEntry] = None
        self._trusted_matched = False

    def on_reset(self) -> None:
        self._ci = 0
        self._prev = None
        self._trusted_matched = False

    def _entry_ok(self, e: LedgerHeaderHistoryEntry) -> bool:
        if sha256(e.header.to_xdr()) != e.hash:
            log.warning("header %d self-hash mismatch", e.header.ledgerSeq)
            return False
        if self._prev is not None:
            if e.header.ledgerSeq != self._prev.header.ledgerSeq + 1:
                # a seq gap would let a forged segment skip the back-link
                # check entirely — reject it outright
                log.warning("ledger seq gap: %d after %d",
                            e.header.ledgerSeq, self._prev.header.ledgerSeq)
                return False
            if e.header.previousLedgerHash != self._prev.hash:
                log.warning("chain break at %d", e.header.ledgerSeq)
                return False
        if self.local_genesis is not None:
            seq, hsh = self.local_genesis
            if e.header.ledgerSeq == seq + 1 and \
                    e.header.previousLedgerHash != hsh:
                log.warning("chain does not link local LCL %d", seq)
                return False
        return True

    def on_run(self) -> State:
        if self._ci >= len(self._checkpoints):
            if self.trusted is not None and not self._trusted_matched and \
                    self.first_ledger <= self.trusted[0] <= self.last_ledger:
                # the consensus anchor was inside the range but never seen
                log.warning("trusted hash %d absent from chain",
                            self.trusted[0])
                return FAILURE
            return SUCCESS
        c = self._checkpoints[self._ci]
        self._ci += 1
        path = os.path.join(self.download_dir, "ledger-%08x.xdr" % c)
        if not os.path.exists(path):
            return FAILURE
        with XDRInputFileStream(path) as ins:
            for e in ins.read_all(LedgerHeaderHistoryEntry):
                if not self._entry_ok(e):
                    return FAILURE
                if self.trusted is not None and \
                        e.header.ledgerSeq == self.trusted[0]:
                    if e.hash != self.trusted[1]:
                        log.warning("trusted hash mismatch at %d",
                                    e.header.ledgerSeq)
                        return FAILURE
                    self._trusted_matched = True
                self._prev = e
        return RUNNING
