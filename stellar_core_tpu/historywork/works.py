"""History work units: remote file transfer, (de)compression, archive
state fetch, batched checkpoint downloads, ledger-chain verification.

Role parity: reference `src/historywork/*` — `GetRemoteFileWork` /
`PutRemoteFileWork` / `MakeRemoteDirWork` shell out through the process
manager (`GetRemoteFileWork.cpp`), `GunzipFileWork`/`GzipFileWork`
(`GunzipFileWork.cpp`), `GetAndUnzipRemoteFileWork.cpp`,
`BatchDownloadWork.cpp` (bounded-parallel per-checkpoint downloads),
`VerifyBucketWork.cpp` (hash downloaded bucket), and
`VerifyLedgerChainWork.cpp` (hash-chain back-link verification).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..crypto.hashing import sha256
from ..history.archive import HistoryArchive, category_path, bucket_path
from ..history.archive_state import HistoryArchiveState
from ..history.checkpoints import checkpoints_in_range
from ..history.snapshot import gunzip_file, gzip_file
from ..util.log import get_logger
from ..util.xdrstream import XDRInputFileStream
from ..work.basic_work import (FAILURE, RETRY_A_FEW, RETRY_NEVER, RUNNING,
                               SUCCESS, WAITING, BasicWork, State)
from ..work.work import BatchWork, WorkSequence
from ..xdr import LedgerHeaderHistoryEntry

log = get_logger("History")


class RunCommandWork(BasicWork):
    """Run one shell command through the app's ProcessManager; the work
    WAITs until the subprocess exit event fires (reference
    `historywork/RunCommandWork.cpp`)."""

    def __init__(self, app, name: str, max_retries: int = RETRY_A_FEW
                 ) -> None:
        super().__init__(app.clock, name, max_retries)
        self.app = app
        self._ev = None
        self._exit_code: Optional[int] = None

    def get_command(self) -> str:
        raise NotImplementedError

    def on_reset(self) -> None:
        self._ev = None
        self._exit_code = None

    def on_run(self) -> State:
        if self._exit_code is not None:
            return SUCCESS if self._exit_code == 0 else FAILURE
        if self._ev is None:
            cmd = self.get_command()
            if not cmd:
                return FAILURE
            self._ev = self.app.process_manager.run_process(cmd)

            def done(code: int) -> None:
                self._exit_code = code
                self.wake_up()

            self._ev.add_done_callback(done)
        return WAITING


class GetRemoteFileWork(RunCommandWork):
    """Download archive:remote -> local (reference GetRemoteFileWork)."""

    def __init__(self, app, archive: HistoryArchive, remote: str,
                 local: str) -> None:
        super().__init__(app, "get-remote-file %s" % remote)
        self.archive = archive
        self.remote = remote
        self.local = local

    def get_command(self) -> str:
        os.makedirs(os.path.dirname(self.local) or ".", exist_ok=True)
        return self.archive.get_cmd(self.remote, self.local)

    def on_failure_retry(self) -> None:
        if os.path.exists(self.local):
            os.unlink(self.local)


class PutRemoteFileWork(RunCommandWork):
    """Upload local -> archive:remote (reference PutRemoteFileWork)."""

    def __init__(self, app, archive: HistoryArchive, local: str,
                 remote: str) -> None:
        super().__init__(app, "put-remote-file %s" % remote)
        self.archive = archive
        self.local = local
        self.remote = remote

    def get_command(self) -> str:
        return self.archive.put_cmd(self.local, self.remote)


class MakeRemoteDirWork(RunCommandWork):
    """mkdir -p on the archive (reference MakeRemoteDirWork)."""

    def __init__(self, app, archive: HistoryArchive, remote_dir: str
                 ) -> None:
        super().__init__(app, "make-remote-dir %s" % remote_dir)
        self.archive = archive
        self.remote_dir = remote_dir

    def get_command(self) -> str:
        return self.archive.mkdir_cmd(self.remote_dir)


class GunzipFileWork(BasicWork):
    """Decompress foo.gz -> foo in-process (reference GunzipFileWork
    shells out to gzip; python's gzip module plays that role)."""

    def __init__(self, app, gz_path: str, keep: bool = False) -> None:
        super().__init__(app.clock, "gunzip %s" % gz_path, RETRY_NEVER)
        self.gz_path = gz_path
        self.keep = keep

    def on_run(self) -> State:
        if not os.path.exists(self.gz_path):
            return FAILURE
        gunzip_file(self.gz_path)
        if not self.keep:
            os.unlink(self.gz_path)
        return SUCCESS


class GzipFileWork(BasicWork):
    """Compress foo -> foo.gz (reference GzipFileWork)."""

    def __init__(self, app, path: str, keep: bool = False) -> None:
        super().__init__(app.clock, "gzip %s" % path, RETRY_NEVER)
        self.path = path
        self.keep = keep

    def on_run(self) -> State:
        if not os.path.exists(self.path):
            return FAILURE
        gzip_file(self.path)
        if not self.keep:
            os.unlink(self.path)
        return SUCCESS


class GetAndUnzipRemoteFileWork(WorkSequence):
    """Download then gunzip, optionally verifying the sha256 of the
    decompressed file (reference GetAndUnzipRemoteFileWork)."""

    def __init__(self, app, archive: HistoryArchive, remote_gz: str,
                 local: str, expected_hash: Optional[bytes] = None) -> None:
        self.local = local
        self.expected_hash = expected_hash
        seq: List[BasicWork] = [
            GetRemoteFileWork(app, archive, remote_gz, local + ".gz"),
            GunzipFileWork(app, local + ".gz"),
        ]
        super().__init__(app.clock, "get-and-unzip %s" % remote_gz, seq)

    def on_run(self) -> State:
        st = super().on_run()
        if st == SUCCESS and self.expected_hash is not None:
            with open(self.local, "rb") as f:
                if sha256(f.read()) != self.expected_hash:
                    log.warning("hash mismatch on %s", self.local)
                    return FAILURE
        return st


class GetHistoryArchiveStateWork(BasicWork):
    """Fetch a HistoryArchiveState JSON — the well-known (archive tip) or
    a specific checkpoint's (reference GetHistoryArchiveStateWork)."""

    def __init__(self, app, archive: HistoryArchive, local_dir: str,
                 checkpoint: Optional[int] = None) -> None:
        super().__init__(app.clock, "get-history-archive-state",
                         RETRY_A_FEW)
        self.app = app
        self.archive = archive
        self.checkpoint = checkpoint
        self.local = os.path.join(
            local_dir,
            "has-%s.json" % ("well-known" if checkpoint is None
                             else "%08x" % checkpoint))
        self.has: Optional[HistoryArchiveState] = None
        self._get: Optional[GetRemoteFileWork] = None

    def _remote(self) -> str:
        from ..history.archive import WELL_KNOWN
        if self.checkpoint is None:
            return WELL_KNOWN
        return category_path("history", self.checkpoint, ".json")

    def on_reset(self) -> None:
        self._get = None
        self.has = None

    def on_run(self) -> State:
        if self._get is None:
            self._get = GetRemoteFileWork(self.app, self.archive,
                                          self._remote(), self.local)
            self._get._parent = self
            self._get.start()
        if not self._get.is_done():
            self._get.crank_work()
            return RUNNING
        if self._get.state != State.SUCCESS:
            return FAILURE
        with open(self.local) as f:
            self.has = HistoryArchiveState.from_json(f.read())
        return SUCCESS


class BatchDownloadWork(BatchWork):
    """Download-and-unzip one category file per checkpoint over a ledger
    range, bounded-parallel (reference BatchDownloadWork.cpp)."""

    def __init__(self, app, archive: HistoryArchive, category: str,
                 first_ledger: int, last_ledger: int, download_dir: str,
                 max_concurrent: int = 8) -> None:
        super().__init__(app.clock, "batch-download %s [%d..%d]"
                         % (category, first_ledger, last_ledger),
                         max_concurrent)
        self.app = app
        self.archive = archive
        self.category = category
        self.download_dir = download_dir
        freq = app.config.CHECKPOINT_FREQUENCY
        self._checkpoints = list(checkpoints_in_range(
            first_ledger, last_ledger, freq))
        self._idx = 0

    def local_path(self, checkpoint: int) -> str:
        return os.path.join(self.download_dir, "%s-%08x.xdr"
                            % (self.category, checkpoint))

    def do_reset(self) -> None:
        self._idx = 0

    def yield_more_work(self) -> Optional[BasicWork]:
        if self._idx >= len(self._checkpoints):
            return None
        c = self._checkpoints[self._idx]
        self._idx += 1
        return GetAndUnzipRemoteFileWork(
            self.app, self.archive,
            category_path(self.category, c, ".xdr.gz"),
            self.local_path(c))


class VerifyBucketWork(BasicWork):
    """Hash a downloaded bucket file and compare to its content address
    (reference VerifyBucketWork runs the hash on a worker thread; one
    bucket per crank keeps the loop responsive here)."""

    def __init__(self, app, path: str, expected_hash: bytes) -> None:
        super().__init__(app.clock, "verify-bucket %s"
                         % expected_hash.hex()[:8], RETRY_NEVER)
        self.path = path
        self.expected_hash = expected_hash

    def on_run(self) -> State:
        from ..bucket.bucket import Bucket
        b = Bucket.read_from(self.path)
        if b.get_hash() != self.expected_hash:
            log.warning("bucket %s hash mismatch",
                        self.expected_hash.hex()[:8])
            return FAILURE
        return SUCCESS


class DownloadBucketsWork(BatchWork):
    """Fetch + verify + adopt every bucket a HAS references (reference
    DownloadBucketsWork.cpp). Buckets already in the local store are
    skipped (content addressing makes this safe)."""

    def __init__(self, app, archive: HistoryArchive, hashes: List[str],
                 download_dir: str, max_concurrent: int = 8) -> None:
        super().__init__(app.clock, "download-buckets(%d)" % len(hashes),
                         max_concurrent)
        self.app = app
        self.archive = archive
        self.download_dir = download_dir
        self._hashes = list(dict.fromkeys(hashes))  # dedup, keep order
        self._idx = 0

    def local_path(self, hash_hex: str) -> str:
        return os.path.join(self.download_dir,
                            "bucket-%s.xdr" % hash_hex)

    def do_reset(self) -> None:
        self._idx = 0

    def yield_more_work(self) -> Optional[BasicWork]:
        bm = self.app.bucket_manager
        while self._idx < len(self._hashes):
            hh = self._hashes[self._idx]
            self._idx += 1
            if bm is not None and \
                    bm.get_bucket_by_hash(bytes.fromhex(hh)) is not None:
                continue                      # already have it
            local = self.local_path(hh)
            seq: List[BasicWork] = [
                GetAndUnzipRemoteFileWork(self.app, self.archive,
                                          bucket_path(hh), local),
                VerifyBucketWork(self.app, local, bytes.fromhex(hh)),
            ]
            return WorkSequence(self.clock, "fetch-bucket %s" % hh[:8],
                                seq)
        return None

    def do_work(self) -> State:
        # adopt everything downloaded into the content-addressed store
        from ..bucket.bucket import Bucket
        bm = self.app.bucket_manager
        if bm is None:
            return SUCCESS
        for hh in self._hashes:
            if bm.get_bucket_by_hash(bytes.fromhex(hh)) is not None:
                continue
            path = self.local_path(hh)
            if os.path.exists(path):
                bm.adopt_bucket(Bucket.read_from(path))
        return SUCCESS


class VerifyLedgerChainWork(BasicWork):
    """Walk downloaded ledger-header files verifying the hash chain:
    every entry's hash must equal SHA256(header) and every header's
    previousLedgerHash must back-link the prior entry (reference
    VerifyLedgerChainWork.cpp; it walks newest→oldest, one checkpoint
    per crank — mirrored here oldest→newest, same predicate). An
    optional trusted (seq, hash) pins the top of the chain."""

    def __init__(self, app, download_dir: str, first_ledger: int,
                 last_ledger: int,
                 trusted: Optional[tuple] = None,
                 local_genesis: Optional[tuple] = None) -> None:
        super().__init__(app.clock, "verify-ledger-chain", RETRY_NEVER)
        self.app = app
        self.download_dir = download_dir
        self.first_ledger = first_ledger
        self.last_ledger = last_ledger
        self.trusted = trusted            # (seq, hash) to match exactly
        self.local_genesis = local_genesis  # (lcl_seq, lcl_hash) link check
        freq = app.config.CHECKPOINT_FREQUENCY
        self._checkpoints = list(checkpoints_in_range(
            first_ledger, last_ledger, freq))
        self._ci = 0
        self._prev: Optional[LedgerHeaderHistoryEntry] = None
        self._trusted_matched = False

    def on_reset(self) -> None:
        self._ci = 0
        self._prev = None
        self._trusted_matched = False

    def _entry_ok(self, e: LedgerHeaderHistoryEntry) -> bool:
        if sha256(e.header.to_xdr()) != e.hash:
            log.warning("header %d self-hash mismatch", e.header.ledgerSeq)
            return False
        if self._prev is not None:
            if e.header.ledgerSeq != self._prev.header.ledgerSeq + 1:
                # a seq gap would let a forged segment skip the back-link
                # check entirely — reject it outright
                log.warning("ledger seq gap: %d after %d",
                            e.header.ledgerSeq, self._prev.header.ledgerSeq)
                return False
            if e.header.previousLedgerHash != self._prev.hash:
                log.warning("chain break at %d", e.header.ledgerSeq)
                return False
        if self.local_genesis is not None:
            seq, hsh = self.local_genesis
            if e.header.ledgerSeq == seq + 1 and \
                    e.header.previousLedgerHash != hsh:
                log.warning("chain does not link local LCL %d", seq)
                return False
        return True

    def on_run(self) -> State:
        if self._ci >= len(self._checkpoints):
            if self.trusted is not None and not self._trusted_matched and \
                    self.first_ledger <= self.trusted[0] <= self.last_ledger:
                # the consensus anchor was inside the range but never seen
                log.warning("trusted hash %d absent from chain",
                            self.trusted[0])
                return FAILURE
            return SUCCESS
        c = self._checkpoints[self._ci]
        self._ci += 1
        path = os.path.join(self.download_dir, "ledger-%08x.xdr" % c)
        if not os.path.exists(path):
            return FAILURE
        with XDRInputFileStream(path) as ins:
            for e in ins.read_all(LedgerHeaderHistoryEntry):
                if not self._entry_ok(e):
                    return FAILURE
                if self.trusted is not None and \
                        e.header.ledgerSeq == self.trusted[0]:
                    if e.hash != self.trusted[1]:
                        log.warning("trusted hash mismatch at %d",
                                    e.header.ledgerSeq)
                        return FAILURE
                    self._trusted_matched = True
                self._prev = e
        return RUNNING
