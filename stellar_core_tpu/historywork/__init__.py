"""History work units (reference `src/historywork`)."""

from .apply_works import (ApplyBucketsWork, ApplyCheckpointWork,
                          DownloadApplyTxsWork, checkpoint_verify_triples)
from .works import (BatchDownloadWork, DownloadBucketsWork,
                    GetAndUnzipRemoteFileWork, GetHistoryArchiveStateWork,
                    GetRemoteFileWork, GunzipFileWork, GzipFileWork,
                    MakeRemoteDirWork, PutRemoteFileWork, RunCommandWork,
                    VerifyBucketWork, VerifyLedgerChainWork)

__all__ = [
    "ApplyBucketsWork", "ApplyCheckpointWork", "BatchDownloadWork",
    "DownloadApplyTxsWork", "DownloadBucketsWork",
    "GetAndUnzipRemoteFileWork", "GetHistoryArchiveStateWork",
    "GetRemoteFileWork", "GunzipFileWork", "GzipFileWork",
    "MakeRemoteDirWork", "PutRemoteFileWork", "RunCommandWork",
    "VerifyBucketWork", "VerifyLedgerChainWork",
    "checkpoint_verify_triples",
]
