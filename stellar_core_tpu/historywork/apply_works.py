"""Apply-side history works: bucket-state restore and checkpoint replay.

Role parity: reference `src/catchup/ApplyBucketsWork.cpp` (stream a
downloaded bucket-list snapshot into the ledger, then adopt it as the
live BucketList), `src/catchup/ApplyCheckpointWork.cpp:79-244` (stream
headers+txsets of one checkpoint, closing one ledger per crank via
`ApplyLedgerWork` → `LedgerManager::closeLedger`), and
`src/catchup/DownloadApplyTxsWork.cpp:23-104` (a BatchWork that overlaps
checkpoint N+1's download with checkpoint N's apply).

TPU batch site (SURVEY.md §3.4): before replaying a checkpoint, every
(source-key, signature, payload) triple in its txsets is drained through
`BatchSigVerifier.verify_many` in one padded device batch, pre-warming
the verify cache so the synchronous per-tx checks during apply all hit.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from ..crypto.hashing import sha256
from ..history.archive import HistoryArchive, category_path
from ..history.archive_state import HistoryArchiveState, has_level_dicts
from ..history.checkpoints import checkpoints_in_range, first_in_checkpoint
from ..util.log import get_logger
from ..util.xdrstream import XDRInputFileStream
from ..work.basic_work import (FAILURE, RETRY_NEVER, RUNNING, SUCCESS,
                               BasicWork, State)
from ..work.work import BatchWork, ConditionalWork, WorkSequence
from ..xdr import LedgerHeaderHistoryEntry, TransactionHistoryEntry
from .works import GetAndUnzipRemoteFileWork

log = get_logger("History")


class ApplyBucketsWork(BasicWork):
    """Load the bucket snapshot named by a HAS into ledger state and
    fast-forward the LCL to that checkpoint's header.

    Reference parity: `catchup/ApplyBucketsWork.cpp` + the LCL reset in
    `CatchupWork::applyBucketsAtLedger`. Divergence checks: the restored
    bucket list's hash must equal the downloaded header's bucketListHash,
    else the archive state is corrupt."""

    def __init__(self, app, has: HistoryArchiveState,
                 header_entry: LedgerHeaderHistoryEntry) -> None:
        super().__init__(app.clock, "apply-buckets@%d"
                         % header_entry.header.ledgerSeq, RETRY_NEVER)
        self.app = app
        self.has = has
        self.header_entry = header_entry

    def on_run(self) -> State:
        from ..bucket import K_NUM_LEVELS
        from ..bucket.applicator import apply_buckets
        from ..bucket.bucket import Bucket

        bm = self.app.bucket_manager
        lm = self.app.ledger_manager
        header = self.header_entry.header

        # order: level 0 curr, 0 snap, 1 curr, ... (newest first)
        ordered: List[Bucket] = []
        for lv in self.has.levels:
            for hh in (lv.curr, lv.snap):
                if hh == "0" * 64:
                    continue
                b = (bm.get_bucket_by_hash(bytes.fromhex(hh))
                     if bm is not None else None)
                if b is None:
                    log.warning("apply-buckets: missing bucket %s", hh[:8])
                    return FAILURE
                ordered.append(b)

        # validate BEFORE destroying local state: the snapshot's whole-list
        # hash must already match the header (pure computation over the
        # level hashes, no mutation)
        from ..crypto.hashing import SHA256
        whole = SHA256()
        for lv in self.has.levels:
            lh = SHA256()
            lh.add(bytes.fromhex(lv.curr))
            lh.add(bytes.fromhex(lv.snap))
            whole.add(lh.finish())
        if whole.finish() != header.bucketListHash:
            log.warning("snapshot bucket list hash mismatch at %d — "
                        "refusing to touch local state", header.ledgerSeq)
            return FAILURE

        # the snapshot IS the state: drop anything local first, else
        # entries deleted on-network during the gap would survive as
        # phantoms (reference resets ledger state before bucket apply);
        # the invalidated flag blocks direct closes until the LCL
        # fast-forward below lands (cleared in set_last_closed_ledger)
        lm.entries_invalidated = True
        lm.ltx_root().clear_entries()
        n = apply_buckets(lm.ltx_root(), ordered)
        log.info("applied %d bucket entries at ledger %d", n,
                 header.ledgerSeq)

        if bm is not None:
            bm.assume_state(has_level_dicts(self.has), header.ledgerSeq,
                            header.ledgerVersion)

        lm.set_last_closed_ledger(header, self.header_entry.hash)
        lm._store_local_has()   # restart between here and the next close
        # must re-adopt THIS bucket list, not the pre-catchup one
        return SUCCESS


def checkpoint_verify_triples(frames, ltx) -> List[Tuple]:
    """Collect (key32, sig, contents-HASH) triples for a batch of tx
    frames — the whole-ledger/checkpoint drain of SURVEY.md §2.2. The
    message is the tx contents hash, exactly what SignatureChecker later
    verifies over (reference signs/verifies sha256(networkID‖envType‖tx),
    SignatureUtils.cpp:27-36), so the prewarmed cache entries are the ones
    the apply path hits. Signer sets (master + account signers of every
    tx/op source) resolve through ledger state, so multisig txs prewarm
    too; signers added mid-checkpoint are caught by the per-ledger
    incremental prewarm (only signers added within the SAME ledger fall
    back to the sync path)."""
    from ..transactions.transaction_frame import frames_sig_triples
    return frames_sig_triples(ltx, frames)


class _PrewarmPipeline:
    """Pipelined catchup (ISSUE 13): ledger N+1's signature verification
    overlaps ledger N's apply. The MAIN thread collects the candidate
    triples (ledger reads stay single-threaded); the worker only runs
    `verifier.prewarm_many` — pure crypto whose native batch call drops
    the GIL, so it genuinely runs underneath the (also GIL-free) native
    apply. A prewarm is cache-warming only: stale or extra triples can
    never change an accept/reject decision, the apply path re-derives
    candidates against live state."""

    def __init__(self, verifier) -> None:
        import queue
        from ..util.threads import spawn_worker
        self._verifier = verifier
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._thread = spawn_worker("catchup.prewarm-pipeline", self._run)

    def submit(self, seq: int, triples) -> None:
        del seq
        self._q.put(triples)

    def close(self) -> None:
        # cancel flag first: queued-but-unstarted batches are stale
        # work the worker must skip (a reset/abort mid-checkpoint would
        # otherwise leave it verifying a whole checkpoint for nothing)
        self._closed = True
        self._q.put(None)

    def _run(self) -> None:
        while True:
            triples = self._q.get()
            if triples is None or self._closed:
                return
            try:
                self._verifier.prewarm_many(triples)
            except Exception as e:  # cache warm only: never fail catchup
                log.warning("pipelined prewarm failed: %s", e)


class ApplyCheckpointWork(BasicWork):
    """Replay one checkpoint's ledgers through LedgerManager.close_ledger,
    one ledger per crank (reference ApplyCheckpointWork.cpp:244 →
    ApplyLedgerWork.cpp:22-24). First crank drains the checkpoint's
    signatures through the batch verifier; on the cpu+native path the
    checkpoint-wide drain is replaced by the per-ledger prewarm
    pipeline (ledger N+1 verifies while N applies)."""

    def __init__(self, app, download_dir: str, checkpoint: int,
                 first_seq: int, last_seq: int) -> None:
        super().__init__(app.clock, "apply-checkpoint %08x" % checkpoint,
                         RETRY_NEVER)
        self.app = app
        self.download_dir = download_dir
        self.checkpoint = checkpoint
        self.first_seq = first_seq
        self.last_seq = last_seq
        self._loaded = False
        self._headers: Dict[int, LedgerHeaderHistoryEntry] = {}
        self._txsets: Dict[int, object] = {}
        self._frames: Dict[int, object] = {}   # seq -> TxSetFrame
        self._next: int = first_seq
        self._sig_state_dirty = False   # a signer set changed mid-checkpoint
        self._prefetch_summary: Optional[dict] = None
        self._pipeline: Optional[_PrewarmPipeline] = None

    def on_reset(self) -> None:
        self._loaded = False
        self._headers.clear()
        self._txsets.clear()
        self._frames.clear()
        self._next = self.first_seq
        self._sig_state_dirty = False
        self._prefetch_summary = None
        self._close_pipeline()

    def _close_pipeline(self) -> None:
        if self._pipeline is not None:
            self._pipeline.close()
            self._pipeline = None

    def _finish(self, st: State) -> None:
        self._close_pipeline()
        super()._finish(st)

    # -- pipelined per-ledger prewarm ---------------------------------------
    def _pipeline_enabled(self) -> bool:
        """Per-ledger pipelining replaces the checkpoint-wide drain
        exactly when that drain is redundant (sync CPU backend + native
        engine): there the verify cost sits INSIDE each close, and the
        only way to take it off the replay clock is to overlap it with
        the previous ledger's apply."""
        if not self._prewarm_redundant():
            return False
        cfg = getattr(self.app, "config", None)
        if not getattr(cfg, "CATCHUP_PIPELINE", True):
            return False
        return getattr(self.app, "sig_verifier", None) is not None

    def _range_triples(self, first: int, last: int):
        """Candidate triples for a ledger range, collected on the MAIN
        thread against current state (one ltx + one signer cache for
        the whole batch)."""
        frames = []
        for seq in range(first, last + 1):
            fr = self._frames.get(seq)
            if fr is not None:
                frames.extend(fr.frames)
        if not frames:
            return []
        from ..ledger.ledgertxn import LedgerTxn
        ltx = LedgerTxn(self.app.ledger_manager.ltx_root())
        try:
            return checkpoint_verify_triples(frames, ltx)
        finally:
            ltx.rollback()

    def _pipeline_submit(self, first: int, last: int) -> None:
        """Hand the range's signature verification to the pipeline
        worker; the closes that follow overlap it. A prewarm is
        opportunistic — whatever the worker hasn't finished when a
        close needs it, the engine verifies synchronously (sharded),
        so there is no join barrier anywhere. The
        `apply.pipeline-stall` fault degrades to sequential: the
        collection still happens, the verify runs inline right here."""
        from ..util.faults import check_faults
        metrics = getattr(self.app, "metrics", None)
        triples = self._range_triples(first, last)
        if not triples:
            return
        if check_faults(self.app, "apply.pipeline-stall"):
            if metrics is not None:
                metrics.new_meter("catchup.pipeline.stall").mark()
            self.app.sig_verifier.prewarm_many(triples)
            return
        if self._pipeline is None:
            self._pipeline = _PrewarmPipeline(self.app.sig_verifier)
        if metrics is not None:
            metrics.new_meter("catchup.pipeline.prewarm").mark()
        self._pipeline.submit(first, triples)

    def _load(self) -> bool:
        lpath = os.path.join(self.download_dir,
                             "ledger-%08x.xdr" % self.checkpoint)
        tpath = os.path.join(self.download_dir,
                             "transactions-%08x.xdr" % self.checkpoint)
        if not os.path.exists(lpath):
            return False
        with XDRInputFileStream(lpath) as ins:
            for e in ins.read_all(LedgerHeaderHistoryEntry):
                self._headers[e.header.ledgerSeq] = e
        if os.path.exists(tpath):
            with XDRInputFileStream(tpath) as ins:
                for t in ins.read_all(TransactionHistoryEntry):
                    self._txsets[t.ledgerSeq] = t.txSet
        return True

    def _prewarm_redundant(self) -> bool:
        """The checkpoint prewarm exists to batch crypto into one device
        dispatch AND to pre-resolve signer sets in Python. With the
        native apply engine active it resolves signer sets in C and
        feeds the verifier per tx, and on the synchronous CPU backend
        batching buys nothing — the whole Python collection pass is then
        pure overhead on the replay clock."""
        verifier = getattr(self.app, "sig_verifier", None)
        if getattr(verifier, "name", "") != "cpu":
            return False
        lm = self.app.ledger_manager
        if not getattr(lm, "use_native_apply", True):
            return False
        from ..native import apply_engine
        return apply_engine() is not None

    def _prewarm_frames(self, frames) -> None:
        """Collect candidate triples against CURRENT ledger state and
        drain them through the batch verifier (cached triples are skipped
        inside prewarm_many — a fully-covered call dispatches nothing)."""
        from ..util.tracing import app_span
        verifier = getattr(self.app, "sig_verifier", None)
        if verifier is None or not frames or self._prewarm_redundant():
            return
        from ..ledger.ledgertxn import LedgerTxn
        # sig-batch prep (triple collection + signer-set resolution) and
        # the verify drain trace separately: prep is host CPU, the drain
        # is the backend-attributed phase
        with app_span(self.app, "catchup.sig_prep", cat="catchup",
                      frames=len(frames)):
            ltx = LedgerTxn(self.app.ledger_manager.ltx_root())
            try:
                triples = checkpoint_verify_triples(frames, ltx)
            finally:
                ltx.rollback()
        if triples:
            verifier.prewarm_many(triples)

    def _prewarm(self) -> None:
        """One device batch for the whole checkpoint's signatures."""
        from ..herder.txset import TxSetFrame
        from ..util.tracing import app_span
        net = self.app.config.network_id
        frames = []
        with app_span(self.app, "catchup.txset_parse", cat="catchup",
                      checkpoint=self.checkpoint) as psp:
            for seq in range(self.first_seq, self.last_seq + 1):
                ts = self._txsets.get(seq)
                if ts is None:
                    continue
                fr = TxSetFrame.from_wire(net, ts)
                self._frames[seq] = fr       # reused at apply: parse once
                for f in fr.frames:          # history wire is immutable:
                    f.freeze_signatures()    # skip per-serialize fp checks
                frames.extend(fr.frames)
            psp.set_tag("txs", len(frames))
        self._prewarm_frames(frames)
        if self._pipeline_enabled():
            # cpu+native: the whole checkpoint's signature verification
            # rides the pipeline worker underneath the apply loop
            self._pipeline_submit(self.first_seq, self.last_seq)
        self._prefetch_checkpoint(frames)
        log.debug("prewarmed checkpoint %08x (%d txs)",
                  self.checkpoint, len(frames))

    def _prefetch_checkpoint(self, frames) -> None:
        """Bulk-warm the root entry cache with the whole checkpoint's
        statically-knowable touched keys (ISSUE 9 satellite: the
        prefetch() count finally lands somewhere — the
        ledger.apply.prefetch.* coverage metrics via LedgerTxnRoot)."""
        root = self.app.ledger_manager.ltx_root()
        if not frames or not hasattr(root, "prefetch"):
            return
        from ..ledger.apply_stats import txset_prefetch_keys
        keys = txset_prefetch_keys(frames)
        # prefetch() returns only NEWLY loaded keys; coverage (resident
        # after the pass / requested, already-warm included) comes from
        # the stats aggregates it feeds — delta around the call
        stats = getattr(self.app.ledger_manager, "apply_stats", None)
        before = stats.prefetch_totals() if stats is not None else None
        loaded = root.prefetch(keys)
        covered = len(keys)
        if before is not None:
            after = stats.prefetch_totals()
            covered = after["cached"] - before["cached"]
        self._prefetch_summary = {
            "keys": len(keys), "covered": covered, "loaded": loaded}

    def _log_checkpoint_summary(self) -> None:
        """One line per applied checkpoint: prefetch coverage + the
        cumulative getPrefetchHitRate-parity hit rate."""
        stats = getattr(self.app.ledger_manager, "apply_stats", None)
        ps = self._prefetch_summary
        if stats is None or ps is None:
            return
        log.info(
            "checkpoint %08x applied: prefetch coverage %d/%d keys "
            "(%d newly loaded; hit-rate %.1f%% cumulative)",
            self.checkpoint, ps["covered"], ps["keys"], ps["loaded"],
            100.0 * stats.prefetch_hit_rate())

    @staticmethod
    def _mutates_signers(txset) -> bool:
        """Does any op in the set ADD verification pairs? Only a
        SET_OPTIONS carrying a signer does (flags/threshold/home-domain
        changes and master-weight edits don't: the master key is always
        a candidate; creations/merges only add/remove master keys)."""
        from ..xdr import OperationType
        for f in txset.frames:
            tx = getattr(f, "tx", None) or f.inner.tx
            for op in tx.operations:
                if op.body.disc == OperationType.SET_OPTIONS and                         op.body.value.signer is not None:
                    return True
        return False

    def _prewarm_ledger(self, txset) -> None:
        """Re-prewarm after a signer-set mutation: the whole-checkpoint
        prewarm resolved signer sets at checkpoint start, so signatures
        from signers added mid-checkpoint missed it, and each miss would
        otherwise dispatch a tiny padded device batch from inside
        check_signature. When the dirty flag flips, ALL remaining
        checkpoint frames re-collect against current state in ONE batch
        and the flag clears (a later mutation re-arms it) — the common
        no-mutation case skips collection entirely."""
        del txset
        if not self._sig_state_dirty:
            return
        self._sig_state_dirty = False
        if self._pipeline_enabled():
            # re-collect the remaining range against post-mutation state
            self._pipeline_submit(self._next, self.last_seq)
            return
        frames = []
        for seq in range(self._next, self.last_seq + 1):
            fr = self._frames.get(seq)
            if fr is not None:
                frames.extend(fr.frames)
        self._prewarm_frames(frames)

    def on_run(self) -> State:
        from ..herder.txset import TxSetFrame
        from ..ledger.ledger_manager import LedgerCloseData

        if not self._loaded:
            from ..util.tracing import app_span
            with app_span(self.app, "catchup.load_files", cat="catchup",
                          checkpoint=self.checkpoint):
                ok = self._load()
            if not ok:
                return FAILURE
            self._prewarm()
            self._loaded = True

        lm = self.app.ledger_manager
        if self._next > self.last_seq:
            self._log_checkpoint_summary()
            return SUCCESS
        seq = self._next
        if seq <= lm.last_closed_ledger_num():
            self._next += 1           # already applied (restart overlap)
            return RUNNING
        entry = self._headers.get(seq)
        if entry is None:
            log.warning("checkpoint %08x missing header %d",
                        self.checkpoint, seq)
            return FAILURE
        net = self.app.config.network_id
        txset = self._frames.get(seq)
        if txset is None:
            ts = self._txsets.get(seq)
            txset = (TxSetFrame.from_wire(net, ts) if ts is not None else
                     TxSetFrame(net, entry.header.previousLedgerHash, []))
        self._prewarm_ledger(txset)
        lcd = LedgerCloseData(seq, txset, entry.header.scpValue)
        from ..util.tracing import app_span
        with app_span(self.app, "catchup.apply_ledger", cat="catchup",
                      seq=seq, checkpoint=self.checkpoint):
            lm.close_ledger(lcd)
        if not self._sig_state_dirty and self._mutates_signers(txset):
            self._sig_state_dirty = True
        if lm.lcl_hash != entry.hash:
            log.error("replay diverged at ledger %d: %s != %s", seq,
                      lm.lcl_hash.hex()[:8], entry.hash.hex()[:8])
            return FAILURE
        self._next += 1
        if self._next > self.last_seq:
            self._log_checkpoint_summary()
            return SUCCESS
        return RUNNING


class DownloadApplyTxsWork(BatchWork):
    """Pipelines checkpoint downloads with strictly-ordered application
    (reference DownloadApplyTxsWork.cpp:35-104): up to `max_concurrent`
    checkpoints download in parallel while applies run in checkpoint
    order behind a ConditionalWork latch."""

    def __init__(self, app, archive: HistoryArchive, download_dir: str,
                 first_seq: int, last_seq: int,
                 max_concurrent: int = 4) -> None:
        super().__init__(app.clock, "download-apply-txs [%d..%d]"
                         % (first_seq, last_seq), max_concurrent)
        self.app = app
        self.archive = archive
        self.download_dir = download_dir
        self.first_seq = first_seq
        self.last_seq = last_seq
        freq = app.config.CHECKPOINT_FREQUENCY
        self._freq = freq
        self._checkpoints = list(checkpoints_in_range(first_seq, last_seq,
                                                      freq))
        self._idx = 0
        # apply gate: checkpoints apply strictly in order
        self._applied_up_to = first_seq - 1

    def do_reset(self) -> None:
        self._idx = 0
        self._applied_up_to = self.first_seq - 1

    def yield_more_work(self) -> Optional[BasicWork]:
        if self._idx >= len(self._checkpoints):
            return None
        c = self._checkpoints[self._idx]
        self._idx += 1
        lo = max(self.first_seq, first_in_checkpoint(c, self._freq))
        hi = min(self.last_seq, c)

        gets: List[BasicWork] = []
        for cat in ("ledger", "transactions"):
            local = os.path.join(self.download_dir,
                                 "%s-%08x.xdr" % (cat, c))
            if os.path.exists(local):
                continue              # verify phase already fetched it
            gets.append(GetAndUnzipRemoteFileWork(
                self.app, self.archive, category_path(cat, c, ".xdr.gz"),
                local))

        apply_work = ApplyCheckpointWork(self.app, self.download_dir, c,
                                         lo, hi)
        gate_lo = lo

        gated = ConditionalWork(
            self.clock, "apply-gate %08x" % c,
            lambda gate_lo=gate_lo: self._applied_up_to == gate_lo - 1,
            apply_work)

        apply_work.on_success = \
            lambda hi=hi: setattr(self, "_applied_up_to", hi)
        return WorkSequence(self.clock, "download-apply %08x" % c,
                            gets + [gated])
