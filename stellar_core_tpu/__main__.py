"""`python -m stellar_core_tpu` entry point (reference src/main/main.cpp)."""

import sys

from .main.commandline import main

if __name__ == "__main__":
    sys.exit(main())
