"""`python -m stellar_core_tpu` entry point (reference src/main/main.cpp)."""

import signal
import sys

from .main.commandline import main

if __name__ == "__main__":
    # die quietly when a downstream pipe (head, less) closes, like any
    # well-behaved unix CLI
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (AttributeError, ValueError):
        pass
    sys.exit(main())
