"""The flagship device model: the batched ed25519 verification graph.

In this framework the "model" executed on TPU is not a neural network but a
fixed-function cryptographic pipeline (SURVEY.md §2.2): point
decompression + double-scalar multiplication + projective equality over a
batch axis. This module packages it with the standard model-API surface
(build inputs, forward step, sharded step) so the driver and benchmarks
treat it like any other model family.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..crypto.keys import SecretKey
from ..ops import ed25519 as E


def make_example_batch(batch: int = 256, n_keys: int = 16,
                       corrupt_every: int = 0) -> Tuple[list, list, list]:
    """Deterministic signed batch for compile checks and benches."""
    sks = [SecretKey.from_seed(bytes([i + 1] * 32)) for i in range(n_keys)]
    pubs, sigs, msgs = [], [], []
    for i in range(batch):
        sk = sks[i % n_keys]
        m = b"bench-msg-%08d" % i
        s = bytearray(sk.sign(m))
        if corrupt_every and i % corrupt_every == corrupt_every - 1:
            s[i % 64] ^= 1
        pubs.append(sk.public_key.key_bytes)
        sigs.append(bytes(s))
        msgs.append(m)
    return pubs, sigs, msgs


def device_args(pubs: List[bytes], sigs: List[bytes],
                msgs: List[bytes]) -> tuple:
    """Host (numpy) arg tuple for the jittable forward step. Staying on
    the host matters: materializing device arrays here would initialize
    the JAX backend inside the CALLER's process — and a compile-check
    harness probing `entry()` must decide for itself when (and whether)
    to touch a possibly-wedged device. jit accepts numpy directly."""
    prep = E.prepare_batch(pubs, sigs, msgs)
    return tuple(np.asarray(prep[k]) for k in
                 ("ay", "a_sign", "ry", "r_sign", "s_nibs", "k_nibs"))


def forward(ay, a_sign, ry, r_sign, s_nibs, k_nibs):
    """The jittable forward step: (B,...) int32 inputs → (B,) bool."""
    return E.verify_kernel(ay, a_sign, ry, r_sign, s_nibs, k_nibs)
