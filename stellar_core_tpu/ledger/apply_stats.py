"""ApplyStats: the close cockpit's shared aggregation (ISSUE 9 tentpole;
docs/observability.md#close-cockpit).

One instance per LedgerManager, shared by every layer that touches the
apply path — the native engine (per-op count/ns table returned by
`_sctapply.apply_close`), the Python op loop (per-op latency samples from
`TransactionFrame.apply`), the SQL root (`LedgerTxnRoot` point-lookup /
cache / prefetch telemetry) and the bucket layer (per-level sizes, merge
durations). The same aggregate objects feed four consumers:

- the admin `applystats` endpoint (`to_json`, `?action=reset`);
- the metrics registry (`ledger.apply.*` / `bucket.*` names), which makes
  the whole cockpit scrapeable as `sct_ledger_apply_*` via
  `metrics?format=prometheus`;
- the tracer: `close.apply` spans are tagged with the close's op mix and
  read-set stats so flight dumps carry close-shape forensics;
- `bench.py` replay blocks: `apply_breakdown()` emits per-op ms + bail
  reasons + state-read stats whose parts sum to the measured apply wall,
  normalized by tools/bench_compare.py into per-op regression records.

Clocks: per-op and per-merge DURATIONS are real elapsed seconds via
util.timer.real_perf_counter/real_monotonic — an op apply or a bucket
merge takes real time even when the app clock is frozen — while meter
rates run on the injected app clock (`now_fn`), so chaos soaks under a
virtual clock stay deterministic. Recording happens on the main loop and
the bucket-merge worker pool; aggregate mutation is under `_lock`,
registry metric objects are individually thread-safe.

Why no histogram sample per native op: the native engine attributes with
one (count, ns) table per close — per-op latency HISTOGRAMS only get
samples on the Python path, where each op applies in its own nested txn.
Cumulative per-op counts and seconds cover both paths identically.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..util.metrics import MetricsRegistry
from ..util.threads import TrackedLock
from ..util.timer import real_monotonic
from ..xdr import OperationType

# OperationType value -> kebab-case metric segment ("manage-sell-offer").
# Bounded: the dynamic `ledger.apply.op.<type>.*` name space can never
# exceed the 14 wire op types (+ the distinct fee-bump/muxed tx meters).
OP_TYPE_NAMES: Dict[int, str] = {
    v: k.lower().replace("_", "-")
    for k, v in vars(OperationType).items()
    if isinstance(v, int) and not k.startswith("_") and k.isupper()
}


def op_type_name(op_type: int) -> str:
    return OP_TYPE_NAMES.get(op_type, "unknown-%d" % op_type)


def frame_traits(frame) -> tuple:
    """(is_fee_bump, touches_muxed) of one tx frame — the close
    cockpit's distinct fee-bump / muxed traffic counters. Muxed means a
    med25519 (sub-id-carrying) MuxedAccount anywhere an account is
    referenced: tx source, op sources, payment-family / account-merge
    destinations."""
    from ..xdr import CryptoKeyType, MuxedAccount
    mux = CryptoKeyType.KEY_TYPE_MUXED_ED25519
    fee_bump = hasattr(frame, "inner")
    tx = getattr(frame, "tx", None)
    if tx is None:
        tx = frame.inner.tx

    def _is_mux(acct) -> bool:
        return acct is not None and getattr(acct, "disc", None) == mux

    muxed = fee_bump and _is_mux(frame.fee_bump.feeSource)
    muxed = muxed or _is_mux(tx.sourceAccount)
    if not muxed:
        for op in tx.operations:
            if _is_mux(op.sourceAccount):
                muxed = True
                break
            body = op.body.value
            if isinstance(body, MuxedAccount):   # ACCOUNT_MERGE arm
                if _is_mux(body):
                    muxed = True
                    break
            elif _is_mux(getattr(body, "destination", None)):
                muxed = True
                break
    return fee_bump, muxed


def txset_prefetch_keys(frames) -> list:
    """The txset's statically-knowable touched keys, for bulk-warming
    the root entry cache before apply (reference LedgerManagerImpl::
    prefetchTxSourceIds + prefetchTransactionData): tx + op source
    accounts, create-account / payment / account-merge destinations, and
    the src/dest trustlines of credit-asset payments. Deduplicated in
    first-touch order."""
    from ..xdr import (
        Asset, AssetType, LedgerKey, MuxedAccount, OperationType,
    )
    keys: list = []
    seen: set = set()

    def add(key) -> None:
        kb = key.to_xdr()
        if kb not in seen:
            seen.add(kb)
            key.__dict__["_kb"] = kb   # the ledgertxn map key, pre-memoized
            keys.append(key)

    def add_acc(pk) -> None:
        if pk is not None:
            add(LedgerKey.account(pk))

    for frame in frames:
        if hasattr(frame, "inner"):          # fee bump: outer fee source
            add_acc(frame.fee_bump.feeSource.account_id)
            tx = frame.inner.tx
        else:
            tx = frame.tx
        add_acc(tx.sourceAccount.account_id)
        tx_src = tx.sourceAccount.account_id
        for op in tx.operations:
            src = (op.sourceAccount.account_id
                   if op.sourceAccount is not None else tx_src)
            add_acc(src)
            t = op.body.disc
            body = op.body.value
            if t == OperationType.CREATE_ACCOUNT:
                add_acc(body.destination)
            elif t == OperationType.PAYMENT:
                dest = body.destination.account_id
                add_acc(dest)
                if body.asset.disc != AssetType.ASSET_TYPE_NATIVE:
                    add(LedgerKey.trustline(src, body.asset))
                    add(LedgerKey.trustline(dest, body.asset))
            elif t == OperationType.ACCOUNT_MERGE and \
                    isinstance(body, MuxedAccount):
                add_acc(body.account_id)
    return keys


class ApplyStats:
    """Close-cockpit aggregation; see module docstring."""

    def __init__(self, metrics=None, tracer=None, now_fn=None) -> None:
        self._now = now_fn or real_monotonic
        # a private registry when none is injected keeps direct
        # constructions (tests, differential harnesses) app-registry-free
        # while letting every registration below use the new_* idiom the
        # M1 metric-catalog scanner keys on
        self.metrics = metrics if metrics is not None \
            else MetricsRegistry(now_fn=self._now)
        self.tracer = tracer
        self._lock = TrackedLock("ledger.apply-stats")
        self.reset()
        # fixed-name registry metrics, created eagerly so the Prometheus
        # export carries the full cockpit shape from the first scrape
        m = self.metrics
        self._t_wall = m.new_timer("ledger.apply.wall")
        self._h_read = m.new_histogram("ledger.apply.read-set")
        self._h_write = m.new_histogram("ledger.apply.write-set")
        self._h_pcov = m.new_histogram("ledger.apply.prefetch.coverage-pct")
        self._m_phit = m.new_meter("ledger.apply.prefetch.hit")
        self._m_pmiss = m.new_meter("ledger.apply.prefetch.miss")
        self._m_chit = m.new_meter("ledger.apply.state.cache-hit")
        self._m_cmiss = m.new_meter("ledger.apply.state.cache-miss")
        self._m_rows = m.new_meter("ledger.apply.state.bulk-scan-rows")
        # BucketDB routing (ISSUE 14): cache misses served from the
        # bucket list (never SQL), and the root entry cache's real-LRU
        # eviction count — silent coverage loss at 10^6 accounts is a
        # visible meter, not a mystery miss rate
        self._m_bucket_read = m.new_meter("ledger.apply.state.bucket-read")
        self._m_evict = m.new_meter("ledger.apply.entry-cache.evicted")
        self._m_feebump = m.new_meter("ledger.apply.tx.fee-bump")
        self._m_muxed = m.new_meter("ledger.apply.tx.muxed")
        self._h_merge = m.new_histogram("bucket.merge.seconds")
        # conflict-graph parallel close (ISSUE 13): per-close cluster
        # shape gauges + parallel/serial path meters
        self._g_cl_count = m.new_gauge("ledger.apply.cluster.count")
        self._g_cl_width = m.new_gauge("ledger.apply.cluster.width")
        self._g_cl_workers = m.new_gauge("ledger.apply.cluster.workers")
        self._m_cl_parallel = m.new_meter(
            "ledger.apply.cluster.parallel-close")
        self._m_cl_serial = m.new_meter("ledger.apply.cluster.serial-close")
        self._m_cl_degrade = m.new_meter("ledger.apply.cluster.degraded")
        # per-entry-type / per-op-type metrics, resolved once — the hot
        # read and apply loops must not pay a name format + registry
        # lookup per event (both name spaces are small and bounded)
        self._m_lookup: Dict[str, object] = {}
        self._m_op: Dict[str, object] = {}
        self._h_op: Dict[str, object] = {}
        self._g_level: Dict[int, object] = {}

    # -- lifecycle -----------------------------------------------------------
    def reset(self) -> None:
        """Zero the cumulative aggregates (admin `applystats?action=reset`;
        registry metrics keep their monotonic histories — Prometheus
        counters must never go backwards)."""
        with self._lock:
            self.ops: Dict[str, dict] = {}      # name -> {count, seconds}
            self.bails: Dict[str, int] = {}
            self.tx = {"total": 0, "fee_bump": 0, "muxed": 0}
            self.closes = {"native": 0, "python": 0}
            self.apply_wall_s = 0.0
            self.reads = {
                "lookups": {},          # entry type -> SQL point lookups
                "cache_hits": 0, "cache_misses": 0,
                "bucket_reads": 0,      # misses served by BucketDB
                "cache_evictions": 0,
                "bulk_scans": 0, "bulk_scan_rows": 0,
                "prefetch": {"calls": 0, "requested": 0, "cached": 0,
                             "hits": 0, "misses": 0},
            }
            self.buckets = {"levels": {}, "merges": 0, "merge_seconds": 0.0}
            self.clusters = {"parallel_closes": 0, "serial_closes": 0,
                             "degraded": 0, "last_count": 0,
                             "last_width": 0, "last_workers": 0,
                             "last_apply_ms": 0.0}
            self.last_close: Optional[dict] = None
            self._close = None

    # -- per-close bracketing ------------------------------------------------
    def begin_close(self, seq: int) -> None:
        """Open the per-close window; read counters recorded until
        end_close() are attributed to this close's blob + span tags."""
        with self._lock:
            self._close = {
                "seq": seq,
                # real stamp, NOT the app clock: abort_close() needs a
                # real elapsed even under a frozen virtual clock
                "t_real": real_monotonic(),
                "ops": {}, "path": None, "bail": None,
                "reads_base": self._reads_snapshot(),
            }

    def abort_close(self) -> Optional[dict]:
        """Seal the window of a close that RAISED (ledger_manager's
        close-exception handler): the real elapsed since begin_close()
        joins the cumulative apply wall so per-op seconds already
        recorded for the doomed close can't outgrow it — the
        apply_breakdown sum contract (other_ms >= 0) survives failed
        closes. Counts under path "failed"; no-op if the window was
        already sealed."""
        with self._lock:
            c = self._close
            if c is None:
                return None
            wall_s = real_monotonic() - c["t_real"]
        return self.end_close("failed", wall_s)

    def _reads_snapshot(self) -> dict:
        r = self.reads
        return {"lookups": dict(r["lookups"]),
                "cache_hits": r["cache_hits"],
                "cache_misses": r["cache_misses"],
                "bucket_reads": r["bucket_reads"],
                "bulk_scan_rows": r["bulk_scan_rows"]}

    def end_close(self, path: str, wall_s: float,
                  write_set: int = 0) -> Optional[dict]:
        """Seal the per-close window; returns the close blob (also kept
        as `last_close`) so the caller can tag its apply span."""
        if path != "failed":
            # a failed close's wall_s spans begin_close()→raise (which
            # may include post-apply work like bucket hashing) — it must
            # join the cumulative apply_wall_s for the sum contract, but
            # feeding it to the per-close apply-latency timer would
            # spike operator p95/p99 with non-apply time
            self._t_wall.update(wall_s)
            self._h_write.update(write_set)
        with self._lock:
            self.closes[path] = self.closes.get(path, 0) + 1
            self.apply_wall_s += wall_s
            c = self._close
            self._close = None
            if c is None:
                return None
            base = c["reads_base"]
            cur = self._reads_snapshot()
            lookups = {t: n - base["lookups"].get(t, 0)
                       for t, n in cur["lookups"].items()
                       if n - base["lookups"].get(t, 0)}
            bucket_reads = cur["bucket_reads"] - base["bucket_reads"]
            read_set = sum(lookups.values()) + bucket_reads + \
                (cur["cache_hits"] - base["cache_hits"])
            blob = {
                "seq": c["seq"], "path": path, "bail": c["bail"],
                "wall_ms": round(wall_s * 1e3, 3),
                "ops": {n: {"count": d["count"],
                            "ms": round(d["seconds"] * 1e3, 3)}
                        for n, d in c["ops"].items()},
                "reads": {
                    "lookups": lookups,
                    "cache_hits": cur["cache_hits"] - base["cache_hits"],
                    "cache_misses":
                        cur["cache_misses"] - base["cache_misses"],
                    "bucket_reads": bucket_reads,
                    "bulk_scan_rows":
                        cur["bulk_scan_rows"] - base["bulk_scan_rows"],
                    "read_set": read_set,
                    "write_set": write_set,
                },
            }
            self.last_close = blob
        if path != "failed":
            # a truncated close's partial read count is not a per-close
            # read-set sample (same skew rationale as the wall timer)
            self._h_read.update(blob["reads"]["read_set"])
        return blob

    # -- per-op attribution --------------------------------------------------
    def record_op(self, name: str, count: int = 1,
                  seconds: Optional[float] = None,
                  sample: bool = False) -> None:
        """`count` applications of op type `name` costing `seconds`
        total. `sample=True` additionally feeds the per-op latency
        histogram (the Python path, where each op is individually
        timed; the native table is per-close totals)."""
        meter = self._m_op.get(name)
        if meter is None:
            meter = self.metrics.new_meter("ledger.apply.op.%s.count" % name)
            self._m_op[name] = meter
        meter.mark(count)
        if seconds is not None and sample:
            hist = self._h_op.get(name)
            if hist is None:
                hist = self.metrics.new_histogram(
                    "ledger.apply.op.%s.seconds" % name)
                self._h_op[name] = hist
            hist.update(seconds)
        with self._lock:
            d = self.ops.setdefault(name, {"count": 0, "seconds": 0.0})
            d["count"] += count
            if seconds is not None:
                d["seconds"] += seconds
            if self._close is not None:
                c = self._close["ops"].setdefault(
                    name, {"count": 0, "seconds": 0.0})
                c["count"] += count
                if seconds is not None:
                    c["seconds"] += seconds

    def record_native_op_table(self, table) -> None:
        """The native engine's per-close {op_type: (count, ns)} table."""
        for op_type, (count, ns) in table.items():
            self.record_op(op_type_name(int(op_type)), count=int(count),
                           seconds=ns / 1e9)

    def record_tx(self, fee_bump: bool, muxed: bool) -> None:
        self.record_tx_counts(1, int(fee_bump), int(muxed))

    def record_tx_counts(self, total: int, fee_bump: int,
                         muxed: int) -> None:
        """Batched tx-mix counters: one lock acquisition per txset, not
        per tx (close_ledger classifies the whole set up front)."""
        with self._lock:
            self.tx["total"] += total
            self.tx["fee_bump"] += fee_bump
            self.tx["muxed"] += muxed
        if fee_bump:
            self._m_feebump.mark(fee_bump)
        if muxed:
            self._m_muxed.mark(muxed)

    def record_clusters(self, count: int, width: int, workers: int,
                        parallel: bool, apply_ns: int = 0) -> None:
        """One native close's conflict-graph shape: cluster count, max
        cluster width (txs), worker count, whether the engine actually
        ran the clusters concurrently, and the engine's tx-execution
        wall (the phase the parallelism accelerates — parse/verify/
        fees/emission excluded)."""
        self._g_cl_count.set(count)
        self._g_cl_width.set(width)
        self._g_cl_workers.set(workers)
        (self._m_cl_parallel if parallel else self._m_cl_serial).mark()
        with self._lock:
            key = "parallel_closes" if parallel else "serial_closes"
            self.clusters[key] += 1
            self.clusters["last_count"] = count
            self.clusters["last_width"] = width
            self.clusters["last_workers"] = workers
            self.clusters["last_apply_ms"] = round(apply_ns / 1e6, 3)
            if self._close is not None:
                self._close["clusters"] = {
                    "count": count, "width": width, "workers": workers,
                    "parallel": parallel,
                    "apply_ms": round(apply_ns / 1e6, 3)}

    def record_cluster_degrade(self) -> None:
        """apply.cluster-fail fired: this close runs serial instead of
        parallel (the fault's graceful-degradation contract)."""
        self._m_cl_degrade.mark()
        with self._lock:
            self.clusters["degraded"] += 1

    # -- native-bail forensics -----------------------------------------------
    def record_bail(self, reason: str) -> None:
        """One native_apply_txset ineligibility/bailout, classified
        (ledger/native_apply.py BAIL_* reasons + the engine's own)."""
        self.metrics.new_meter("ledger.apply.native-bail.%s" % reason).mark()
        with self._lock:
            self.bails[reason] = self.bails.get(reason, 0) + 1
            if self._close is not None:
                self._close["bail"] = reason
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant("ledger.apply.native-bail", cat="ledger",
                                reason=reason)

    # -- state-read telemetry (LedgerTxnRoot hooks) --------------------------
    def _lookup_meter(self, entry_type: str):
        m = self._m_lookup.get(entry_type)
        if m is None:
            m = self.metrics.new_meter(
                "ledger.apply.state.lookup.%s" % entry_type)
            self._m_lookup[entry_type] = m
        return m

    def record_read(self, hit: bool, prefetched: bool,
                    entry_type: Optional[str] = None,
                    source: str = "sql") -> None:
        """One root entry read, folded into a single lock acquisition —
        this hook sits inside the exact path the cockpit measures.
        Covers the cache hit/miss counters, the getPrefetchHitRate-parity
        prefetch hit/miss (a warm cache hit on a never-prefetched key
        records neither; every miss counts as a prefetch miss), and — on
        a miss — the point lookup by entry type, attributed to its
        serving `source`: "sql" feeds the per-type SQL lookup meters the
        ISSUE-14 zero-SQL gate asserts on; "bucket" (BucketDB-served)
        feeds the separate bucket-read counter, so routing state reads
        off SQL visibly DRAINS `ledger.apply.state.lookup.*` instead of
        inflating it."""
        if hit:
            self._m_chit.mark()
            if prefetched:
                self._m_phit.mark()
            with self._lock:
                self.reads["cache_hits"] += 1
                if prefetched:
                    self.reads["prefetch"]["hits"] += 1
        else:
            self._m_cmiss.mark()
            self._m_pmiss.mark()
            if entry_type is not None and source == "sql":
                self._lookup_meter(entry_type).mark()
            elif source == "bucket":
                self._m_bucket_read.mark()
            with self._lock:
                self.reads["cache_misses"] += 1
                self.reads["prefetch"]["misses"] += 1
                if source == "bucket":
                    self.reads["bucket_reads"] += 1
                elif entry_type is not None:
                    lk = self.reads["lookups"]
                    lk[entry_type] = lk.get(entry_type, 0) + 1

    def record_cache_evictions(self, n: int = 1) -> None:
        """Root entry-cache LRU evictions (the bounded-cache coverage
        signal the ISSUE-14 satellite makes observable)."""
        self._m_evict.mark(n)
        with self._lock:
            self.reads["cache_evictions"] += n

    def record_bulk_scan(self, rows: int) -> None:
        self._m_rows.mark(rows)
        with self._lock:
            self.reads["bulk_scans"] += 1
            self.reads["bulk_scan_rows"] += rows

    def record_prefetch(self, requested: int, cached: int,
                        lookups: Optional[Dict[str, int]] = None,
                        bucket_loads: int = 0) -> None:
        """One prefetch() pass: `requested` keys asked for, `cached`
        resident in the entry cache afterwards (already-warm + newly
        loaded). Coverage = cached/requested — the per-txset number the
        bucket-read refactor (ROADMAP item 4 / ISSUE 14) gates on.
        `lookups` carries the pass's SQL point loads by entry type;
        `bucket_loads` counts keys the BucketDB batched pass resolved
        instead — both batched into this one acquisition."""
        cov = 100.0 * cached / requested if requested else 100.0
        self._h_pcov.update(cov)
        if lookups:
            for entry_type, n in lookups.items():
                self._lookup_meter(entry_type).mark(n)
        if bucket_loads:
            self._m_bucket_read.mark(bucket_loads)
        with self._lock:
            p = self.reads["prefetch"]
            p["calls"] += 1
            p["requested"] += requested
            p["cached"] += cached
            self.reads["bucket_reads"] += bucket_loads
            if lookups:
                lk = self.reads["lookups"]
                for entry_type, n in lookups.items():
                    lk[entry_type] = lk.get(entry_type, 0) + n

    def prefetch_totals(self) -> dict:
        """Cumulative prefetch aggregates (calls/requested/cached/
        hits/misses) — delta two snapshots to attribute one pass."""
        with self._lock:
            return dict(self.reads["prefetch"])

    def prefetch_hit_rate(self) -> float:
        """reference getPrefetchHitRate (LedgerTxn.cpp): root reads
        served from a prefetched key over those plus reads that fell
        through to SQL (warm cache hits on never-prefetched keys are
        not in the denominator)."""
        with self._lock:
            return self._hit_rate_locked()

    # -- bucket layer --------------------------------------------------------
    def record_merge(self, level: int, seconds: float,
                     out_entries: int) -> None:
        """One completed bucket merge (runs on the merge worker pool)."""
        self._h_merge.update(seconds)
        self.metrics.new_meter("bucket.merge.level.%d" % level).mark()
        with self._lock:
            self.buckets["merges"] += 1
            self.buckets["merge_seconds"] += seconds
            lv = self.buckets["levels"].setdefault(
                level, {"merges": 0, "merge_seconds": 0.0, "entries": 0})
            lv["merges"] += 1
            lv["merge_seconds"] += seconds
            lv["last_out_entries"] = out_entries

    def record_level_sizes(self, sizes) -> None:
        """Per-level curr+snap entry counts at a close (bucket_manager
        snapshot hook); levels are bounded at K_NUM_LEVELS=11. Runs every
        close — gauges are memoized and the lock taken once."""
        sizes = list(sizes)
        for level, n in sizes:
            g = self._g_level.get(level)
            if g is None:
                g = self.metrics.new_gauge("bucket.level.%d.entries" % level)
                self._g_level[level] = g
            g.set(n)
        with self._lock:
            for level, n in sizes:
                lv = self.buckets["levels"].setdefault(
                    level, {"merges": 0, "merge_seconds": 0.0, "entries": 0})
                lv["entries"] = n

    # -- exports -------------------------------------------------------------
    def to_json(self) -> dict:
        """The admin `applystats` cockpit blob."""
        with self._lock:
            return {
                "closes": dict(self.closes),
                "apply_wall_s": round(self.apply_wall_s, 6),
                "ops": {n: {"count": d["count"],
                            "ms": round(d["seconds"] * 1e3, 3)}
                        for n, d in sorted(self.ops.items())},
                "tx": dict(self.tx),
                "native_bails": dict(sorted(self.bails.items())),
                "state_reads": {
                    "lookups": dict(sorted(
                        self.reads["lookups"].items())),
                    "cache_hits": self.reads["cache_hits"],
                    "cache_misses": self.reads["cache_misses"],
                    "bucket_reads": self.reads["bucket_reads"],
                    "cache_evictions": self.reads["cache_evictions"],
                    "bulk_scans": self.reads["bulk_scans"],
                    "bulk_scan_rows": self.reads["bulk_scan_rows"],
                    "prefetch": dict(self.reads["prefetch"]),
                },
                "prefetch_hit_rate": round(self._hit_rate_locked(), 4),
                "buckets": {
                    "merges": self.buckets["merges"],
                    "merge_seconds":
                        round(self.buckets["merge_seconds"], 6),
                    "levels": {str(k): dict(v) for k, v in sorted(
                        self.buckets["levels"].items())},
                },
                "clusters": dict(self.clusters),
                "last_close": self.last_close,
            }

    def _hit_rate_locked(self) -> float:
        p = self.reads["prefetch"]
        total = p["hits"] + p["misses"]
        return p["hits"] / total if total else 0.0

    def apply_breakdown(self) -> dict:
        """The bench.py replay block: per-op ms + bail reasons +
        state-read stats whose parts sum to the measured apply wall —
        `other_ms` is the residual (fees, signature checks, parsing,
        delta serialization) so sum(per_op_ms) + other_ms ==
        apply_wall_s * 1000 by construction."""
        with self._lock:
            per_op_ms = {n: round(d["seconds"] * 1e3, 3)
                         for n, d in sorted(self.ops.items())}
            op_counts = {n: d["count"]
                         for n, d in sorted(self.ops.items())}
            wall_ms = self.apply_wall_s * 1e3
            other = wall_ms - sum(per_op_ms.values())
            return {
                "apply_wall_s": round(self.apply_wall_s, 6),
                "closes": dict(self.closes),
                "per_op_ms": per_op_ms,
                "op_counts": op_counts,
                "other_ms": round(other, 6),
                "bails": dict(sorted(self.bails.items())),
                "clusters": dict(self.clusters),
                "tx": dict(self.tx),
                "state_reads": {
                    "lookups": dict(sorted(
                        self.reads["lookups"].items())),
                    "cache_hits": self.reads["cache_hits"],
                    "cache_misses": self.reads["cache_misses"],
                    "bucket_reads": self.reads["bucket_reads"],
                    "cache_evictions": self.reads["cache_evictions"],
                    "bulk_scan_rows": self.reads["bulk_scan_rows"],
                    "prefetch": dict(self.reads["prefetch"]),
                    "prefetch_hit_rate": round(self._hit_rate_locked(), 4),
                },
            }
