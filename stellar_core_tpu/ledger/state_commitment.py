"""State commitments: an incremental Merkle tree over the bucket list,
signed succinct checkpoints, and light-client membership proofs
(ISSUE 12 tentpole; ROADMAP item 5).

The bucket list already content-addresses the whole ledger state — but
its hash chain (`SHA256(concat_i SHA256(curr_i ‖ snap_i))`) only proves
WHOLE-STATE equality: verifying that one ledger entry is part of the
committed state means replaying or downloading buckets. This module
adds the proof-carrying half:

- **Commitment tree.** One Merkle leaf per bucket slot (curr and snap
  of each of the 11 levels, 22 leaves): `leaf = SHA256(0x02 ‖
  bucket_stream_hash ‖ entry_root)`, where `entry_root` is the Merkle
  root over the bucket's entry leaves (`SHA256(0x00 ‖ entry_xdr)`).
  Interior nodes are `SHA256(0x01 ‖ left ‖ right)` with a lonely right
  edge promoted unchanged — the prefixes domain-separate the two tree
  layers from each other and from raw SHA-256 traffic.
- **Incremental update.** Buckets are immutable and content-addressed,
  so entry roots are cached by bucket hash: a close recomputes entry
  roots only for buckets that CHANGED this close (level-0 fresh every
  close, deeper levels only at their spill boundaries) — O(changed
  levels), not O(state). The 22-leaf top tree re-hashes in 21 small
  SHA-256s. A from-scratch oracle (`from_scratch_root`) ignores every
  cache; the differential tests pin incremental == oracle across
  randomized churn and whole replays.
- **Checkpoints.** Every `STATE_CHECKPOINT_INTERVAL` closes the engine
  emits a `StateCheckpoint` {ledger seq, header hash, Merkle root, node
  signature over the network-id-bound payload}, kept in a bounded ring
  and served by the admin `checkpoint[?seq=N]` endpoint. The
  `commitment.sign-fail` fault site models a sealed-key failure: the
  checkpoint for that interval is skipped (visible via
  `commitment.sign-fail`), the next interval retries.
- **Light clients.** `light_client_verify(proof, checkpoint,
  network_id)` is a pure function over the proof bytes — no ledger DB,
  no bucket files, no Application: entry leaf → entry root → commitment
  leaf → root, then the ed25519 signature over the checkpoint payload.
  The checkpoint-serving scenario (testing/scenarios.py) drives one
  validator feeding a fleet of such verifiers under load.

Entry-leaf hashing is the device-batchable load (thousands of small
messages per changed bucket): it routes through the app's BatchHasher
(`site="bucket-entries"`), so a TPU node hashes whole entry-blocks per
dispatch and a device-less node falls back to hashlib with identical
digests.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..crypto.hashing import sha256
from ..util.log import get_logger
from ..util.timer import real_monotonic

log = get_logger("Ledger")

# default closes-per-checkpoint; Config.STATE_CHECKPOINT_INTERVAL
# overrides per node (scenario/test configs run small intervals)
CHECKPOINT_INTERVAL = 8

# domain-separation prefixes (module docstring)
ENTRY_LEAF_PREFIX = b"\x00"
NODE_PREFIX = b"\x01"
BUCKET_LEAF_PREFIX = b"\x02"

# checkpoint signature payload versioning
_SIGN_DOMAIN = b"sct-state-checkpoint-v1"

ZERO_HASH = b"\x00" * 32


def _node(left: bytes, right: bytes) -> bytes:
    return sha256(NODE_PREFIX + left + right)


def merkle_root(leaves: List[bytes]) -> bytes:
    """Root over leaf hashes; a lonely right edge is promoted unchanged
    (no duplication — the path length just shortens on that edge).
    Empty input commits to the zero hash."""
    if not leaves:
        return ZERO_HASH
    level = list(leaves)
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(_node(level[i], level[i + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def merkle_path(leaves: List[bytes], index: int) -> List[dict]:
    """Inclusion path for leaves[index]: a list of {"h": sibling hex,
    "right": sibling-is-on-the-right} steps from leaf to root."""
    assert 0 <= index < len(leaves)
    path: List[dict] = []
    level = list(leaves)
    i = index
    while len(level) > 1:
        nxt = []
        for j in range(0, len(level) - 1, 2):
            nxt.append(_node(level[j], level[j + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        sib = i ^ 1
        if sib < len(level):
            path.append({"h": level[sib].hex(), "right": bool(sib > i)})
        i //= 2
        level = nxt
    return path


def merkle_climb(leaf: bytes, path: List[dict]) -> bytes:
    """Recompute the root from a leaf and its inclusion path."""
    h = leaf
    for step in path:
        sib = bytes.fromhex(step["h"])
        h = _node(h, sib) if step["right"] else _node(sib, h)
    return h


def checkpoint_sign_payload(network_id: bytes, ledger_seq: int,
                            header_hash: bytes, root: bytes) -> bytes:
    """The bytes a checkpoint signature covers: domain- and
    network-bound so a checkpoint can never be replayed across networks
    or mistaken for any other signed artifact."""
    return (_SIGN_DOMAIN + network_id +
            ledger_seq.to_bytes(4, "big") + header_hash + root)


class StateCheckpoint:
    """A signed, succinct state commitment: everything a light client
    needs to verify entry membership without replay."""

    __slots__ = ("ledger_seq", "header_hash", "merkle_root", "node_id",
                 "signature")

    def __init__(self, ledger_seq: int, header_hash: bytes,
                 merkle_root_: bytes, node_id: bytes,
                 signature: bytes) -> None:
        self.ledger_seq = ledger_seq
        self.header_hash = header_hash
        self.merkle_root = merkle_root_
        self.node_id = node_id          # 32-byte ed25519 public key
        self.signature = signature

    def to_json(self) -> dict:
        return {"v": 1, "ledger_seq": self.ledger_seq,
                "header_hash": self.header_hash.hex(),
                "merkle_root": self.merkle_root.hex(),
                "node_id": self.node_id.hex(),
                "signature": self.signature.hex()}

    @classmethod
    def from_json(cls, blob: dict) -> "StateCheckpoint":
        return cls(int(blob["ledger_seq"]),
                   bytes.fromhex(blob["header_hash"]),
                   bytes.fromhex(blob["merkle_root"]),
                   bytes.fromhex(blob["node_id"]),
                   bytes.fromhex(blob["signature"]))


def light_client_verify(proof: dict, checkpoint: dict,
                        network_id: bytes) -> Tuple[bool, str]:
    """Pure light-client verification: (ok, reason). Touches ONLY the
    proof + checkpoint blobs and the network id — no ledger DB, no
    bucket files, no Application object.

    Steps: entry leaf → entry root (entry_path) → commitment leaf
    (bucket hash binding) → commitment root (leaf_path) → root equality
    with the checkpoint → ed25519 signature over the checkpoint
    payload."""
    from ..crypto.keys import PubKeyUtils
    from ..xdr import PublicKey
    try:
        entry = bytes.fromhex(proof["entry"])
        bucket_hash = bytes.fromhex(proof["bucket_hash"])
        root = bytes.fromhex(checkpoint["merkle_root"])
        header_hash = bytes.fromhex(checkpoint["header_hash"])
        node_id = bytes.fromhex(checkpoint["node_id"])
        signature = bytes.fromhex(checkpoint["signature"])
        seq = int(checkpoint["ledger_seq"])
    except (KeyError, ValueError, TypeError) as e:
        return False, "malformed proof/checkpoint: %s" % e
    entry_leaf = sha256(ENTRY_LEAF_PREFIX + entry)
    entry_root = merkle_climb(entry_leaf, proof.get("entry_path", []))
    leaf = sha256(BUCKET_LEAF_PREFIX + bucket_hash + entry_root)
    got_root = merkle_climb(leaf, proof.get("leaf_path", []))
    if got_root != root:
        return False, "merkle root mismatch"
    payload = checkpoint_sign_payload(network_id, seq, header_hash, root)
    if not PubKeyUtils.verify_sig(PublicKey.ed25519(node_id), signature,
                                  payload):
        return False, "checkpoint signature invalid"
    return True, "ok"


class StateCommitmentEngine:
    """Per-node commitment state: leaf/entry-root caches, the live
    root, and the checkpoint ring. Driven from the close path
    (`on_close`, main thread only — mirrors the bucket list's own
    threading contract) and read by the admin `checkpoint` endpoint
    (which posts to main like every command)."""

    CHECKPOINT_RING = 64

    def __init__(self, app) -> None:
        self.app = app
        self.metrics = getattr(app, "metrics", None)
        # bucket-hash -> entry Merkle root; buckets are immutable, so
        # the cache is sound by construction. Bounded: stale entries
        # (buckets GC'd by forgetUnreferencedBuckets) age out once the
        # map exceeds twice the live slot count.
        self._entry_roots: "OrderedDict[bytes, bytes]" = OrderedDict()
        # leaf slot -> (bucket_hash, leaf_hash): the incremental state
        self._leaves: List[Optional[Tuple[bytes, bytes]]] = []
        self._root: Optional[bytes] = None
        self._closes = 0
        self.checkpoints: "OrderedDict[int, StateCheckpoint]" = \
            OrderedDict()
        # the latest checkpoint's frozen view: the bucket objects (all
        # immutable, shared with the live list) and their leaf hashes
        # at emit time — proofs are built against THIS root so a served
        # (proof, checkpoint) pair always verifies, however many closes
        # have advanced the live root since
        self._checkpoint_slots: Optional[List] = None
        self._checkpoint_leaves: Optional[List[bytes]] = None
        if self.metrics is not None:
            m = self.metrics
            self._h_changed = m.new_histogram("commitment.leaves-changed")
            self._h_update = m.new_histogram("commitment.update-ms")
        else:
            self._h_changed = self._h_update = None

    # -- entry roots ---------------------------------------------------------
    def _entry_leaves(self, bucket) -> List[bytes]:
        """Entry leaf hashes for one bucket — the device-batchable
        drain: whole entry-blocks per dispatch through the app's
        BatchHasher (`site="bucket-entries"`), hashlib when no hasher
        is wired."""
        # entry_record is the memoized framed record the bucket's own
        # hash serialized; [4:] strips the RFC 5531 mark back to the
        # XDR body, so leaf hashing never re-serializes an entry
        from ..bucket.bucket import entry_record
        msgs = [ENTRY_LEAF_PREFIX + entry_record(e)[4:]
                for e in bucket.entries]
        hasher = getattr(self.app, "batch_hasher", None)
        if hasher is not None and msgs:
            return hasher.hash_many(msgs, site="bucket-entries")
        return [sha256(m) for m in msgs]

    def entry_root(self, bucket) -> bytes:
        """Merkle root over a bucket's entry leaves, cached by the
        bucket's identity hash (immutable content)."""
        bh = bucket.get_hash()
        got = self._entry_roots.get(bh)
        if got is not None:
            self._entry_roots.move_to_end(bh)
            return got
        root = merkle_root(self._entry_leaves(bucket))
        self._entry_roots[bh] = root
        limit = max(64, 4 * max(1, len(self._leaves)))
        while len(self._entry_roots) > limit:
            self._entry_roots.popitem(last=False)
        return root

    @staticmethod
    def _slots(bucket_list) -> List:
        """The fixed leaf order: level 0 curr, level 0 snap, level 1
        curr, ... — matching the bucket list's own hash-chain order."""
        out = []
        for lev in bucket_list.levels:
            out.append(lev.curr)
            out.append(lev.snap)
        return out

    def _leaf_hash(self, bucket) -> Tuple[bytes, bytes]:
        bh = bucket.get_hash()
        if bh == ZERO_HASH:
            return bh, sha256(BUCKET_LEAF_PREFIX + bh + ZERO_HASH)
        return bh, sha256(BUCKET_LEAF_PREFIX + bh + self.entry_root(bucket))

    # -- the incremental update ---------------------------------------------
    def update_root(self, bucket_list) -> bytes:
        """Refresh the commitment root after a close: only leaves whose
        bucket hash changed recompute their entry root (cache hit
        otherwise); the 22-leaf top tree re-hashes unconditionally (21
        small SHA-256s — cheaper than tracking its internal nodes)."""
        t0 = real_monotonic()
        slots = self._slots(bucket_list)
        if len(self._leaves) != len(slots):
            self._leaves = [None] * len(slots)
        changed = 0
        for i, b in enumerate(slots):
            bh = b.get_hash()
            cached = self._leaves[i]
            if cached is not None and cached[0] == bh:
                continue
            self._leaves[i] = self._leaf_hash(b)
            changed += 1
        self._root = merkle_root([lf[1] for lf in self._leaves])
        if self._h_changed is not None:
            self._h_changed.update(changed)
            self._h_update.update((real_monotonic() - t0) * 1e3)
        return self._root

    def from_scratch_root(self, bucket_list) -> bytes:
        """The differential oracle: the same root computed with every
        cache bypassed (entry leaves re-hashed via plain hashlib)."""
        leaves = []
        for b in self._slots(bucket_list):
            bh = b.get_hash()
            if bh == ZERO_HASH:
                er = ZERO_HASH
            else:
                er = merkle_root([sha256(ENTRY_LEAF_PREFIX + e.to_xdr())
                                  for e in b.entries])
            leaves.append(sha256(BUCKET_LEAF_PREFIX + bh + er))
        return merkle_root(leaves)

    @property
    def root(self) -> Optional[bytes]:
        return self._root

    # -- the close hook ------------------------------------------------------
    def on_close(self, bucket_list, ledger_seq: int,
                 header_hash: bytes) -> Optional[StateCheckpoint]:
        """Called once per committed close (main thread): incremental
        root update, then a signed checkpoint every
        STATE_CHECKPOINT_INTERVAL closes. Returns the checkpoint when
        one was emitted."""
        self.update_root(bucket_list)
        self._closes += 1
        interval = getattr(getattr(self.app, "config", None),
                           "STATE_CHECKPOINT_INTERVAL",
                           CHECKPOINT_INTERVAL)
        if interval <= 0 or self._closes % interval:
            return None
        return self._emit_checkpoint(ledger_seq, header_hash,
                                     self._slots(bucket_list))

    def _emit_checkpoint(self, ledger_seq: int, header_hash: bytes,
                         slots: List) -> Optional[StateCheckpoint]:
        cfg = getattr(self.app, "config", None)
        seed = getattr(cfg, "NODE_SEED", None)
        if seed is None or self._root is None:
            return None
        payload = checkpoint_sign_payload(cfg.network_id, ledger_seq,
                                          header_hash, self._root)
        try:
            faults = getattr(self.app, "faults", None)
            if faults is not None:
                # a sealed-key/HSM failure: this interval's checkpoint
                # is skipped (metered + dumped), the next one retries
                faults.fire_point("commitment.sign-fail")
            sig = seed.sign(payload)
        except Exception as e:
            log.warning("checkpoint signing failed at ledger %d: %s — "
                        "skipping this interval", ledger_seq, e)
            if self.metrics is not None:
                self.metrics.new_meter("commitment.sign-fail").mark()
            fr = getattr(self.app, "flight_recorder", None)
            if fr is not None:
                fr.dump("checkpoint-sign-fail",
                        extra={"ledger_seq": ledger_seq,
                               "error": repr(e)})
            return None
        cp = StateCheckpoint(ledger_seq, header_hash, self._root,
                             seed.public_key.key_bytes, sig)
        self.checkpoints[ledger_seq] = cp
        # freeze the proof view (module docstring): immutable bucket
        # refs + the leaf vector that hashes to cp.merkle_root
        self._checkpoint_slots = list(slots)
        self._checkpoint_leaves = [lf[1] for lf in self._leaves] \
            if self._leaves else None
        while len(self.checkpoints) > self.CHECKPOINT_RING:
            self.checkpoints.popitem(last=False)
        if self.metrics is not None:
            self.metrics.new_meter("commitment.checkpoint.emitted").mark()
            self.metrics.new_counter(
                "commitment.checkpoint.seq").set_count(ledger_seq)
        from ..util.tracing import tracer_instant
        tracer_instant(getattr(self.app, "tracer", None),
                       "commitment.checkpoint", cat="ledger",
                       seq=ledger_seq, root=self._root.hex()[:16])
        return cp

    def checkpoint(self, seq: Optional[int] = None) -> Optional[dict]:
        """The latest (or an exact-seq) checkpoint as the JSON blob the
        admin endpoint serves and light_client_verify consumes."""
        if not self.checkpoints:
            return None
        if seq is None:
            return next(reversed(self.checkpoints.values())).to_json()
        cp = self.checkpoints.get(seq)
        return cp.to_json() if cp is not None else None

    # -- proofs --------------------------------------------------------------
    def prove_entry(self, key, bucket_list=None) -> Optional[dict]:
        """Membership proof for the NEWEST live version of `key` (first
        match walking level 0 curr → deepest snap, the bucket list's
        own read order). Returns None when the entry is absent or its
        newest record is a tombstone.

        Proofs are built against the latest CHECKPOINT's frozen view
        when one exists (so the served (proof, checkpoint) pair always
        verifies); the live bucket list is the fallback before the
        first checkpoint — those proofs verify against `root`.

        Each bucket is binary-searched on the canonical entry order
        (bucket_entry_sort_key — the identity ordering buckets are
        sorted by), so a proof costs O(levels · log entries) key
        computations, not a full O(state) scan with a serialized
        comparison per entry."""
        from ..bucket.bucket import bucket_entry_sort_key
        from ..xdr import BucketEntryType, ledger_key_sort_key
        target = (ledger_key_sort_key(key),)
        if self._checkpoint_slots is not None:
            slots = self._checkpoint_slots
        elif bucket_list is not None:
            slots = self._slots(bucket_list)
        else:
            return None
        for slot_idx, bucket in enumerate(slots):
            if bucket.get_hash() == ZERO_HASH:
                continue
            entries = bucket.entries
            lo, hi = 0, len(entries)
            while lo < hi:
                mid = (lo + hi) // 2
                if bucket_entry_sort_key(entries[mid]) < target:
                    lo = mid + 1
                else:
                    hi = mid
            if lo >= len(entries) or \
                    bucket_entry_sort_key(entries[lo]) != target:
                continue
            e = entries[lo]
            if e.disc == BucketEntryType.DEADENTRY:
                return None                  # newest record: deleted
            return self._build_proof(slots, slot_idx, bucket, lo, e)
        return None

    def _build_proof(self, slots, slot_idx: int, bucket, entry_idx: int,
                     entry) -> dict:
        entry_leaves = self._proof_entry_leaves(bucket)
        if self._checkpoint_slots is not None and \
                slots is self._checkpoint_slots and \
                self._checkpoint_leaves is not None:
            leaf_hashes = self._checkpoint_leaves
        elif self._leaves and len(self._leaves) == len(slots) and \
                all(lf is not None for lf in self._leaves):
            leaf_hashes = [lf[1] for lf in self._leaves]
        else:
            leaf_hashes = [self._leaf_hash(b)[1] for b in slots]
        proof = {
            "v": 1,
            "entry": entry.to_xdr().hex(),
            "entry_index": entry_idx,
            "entry_count": len(bucket.entries),
            "entry_path": merkle_path(entry_leaves, entry_idx),
            "bucket_hash": bucket.get_hash().hex(),
            "leaf_index": slot_idx,
            "leaf_path": merkle_path(leaf_hashes, slot_idx),
        }
        if self.metrics is not None:
            self.metrics.new_meter("commitment.proof.served").mark()
            import json as _json
            self.metrics.new_histogram("commitment.proof.bytes").update(
                len(_json.dumps(proof)))
        return proof

    def _proof_entry_leaves(self, bucket) -> List[bytes]:
        # positional leaves in the bucket's canonical (sorted) entry
        # order; only the ROOT is cached (entry_root), so a proof pays
        # one leaf re-hash pass over its bucket — bounded by bucket
        # size, off the close path (admin requests post to main)
        return self._entry_leaves(bucket)
