"""Close-time glue for the native transaction-apply fast path.

native/applyc.c implements the fee + apply phases of a ledger close for
the replay workload's subset (plain v1 envelopes, payment /
create-account / set_options ops, ed25519-only signer sets, protocol
>= 10). This module decides per
close whether the engine may run, feeds it, and installs its outputs so
everything downstream of the apply loop — result hash, bucket-list delta,
tx/fee history rows, close meta, invariants — runs unchanged Python over
identical state.

The engine returns {"bail": reason} (or None) for ANY input outside its
subset before mutating shared state, so the Python apply path (the
differential-test oracle, tests/test_native_apply.py) remains the single
source of semantics. Every ineligibility/bailout — decided here or
inside the engine — classifies to a reason metered as
`ledger.apply.native-bail.<reason>` (ISSUE 9 forensics: the op-coverage
order of ROADMAP item 2 follows observed traffic, not the alphabet).

Gate: SCT_NATIVE_APPLY=0 disables (mirroring SCT_NATIVE_XDR); an absent
compiler disables silently.
"""

from __future__ import annotations

from typing import List, Optional


def _classify_engine_bail(reason: str) -> str:
    """Engine reason string -> metric-safe reason. `op-<n>` carries the
    numeric wire type; name it (`op-manage-sell-offer`) so operators
    read traffic, not enum values."""
    if reason.startswith("op-"):
        try:
            from .apply_stats import op_type_name
            return "op-" + op_type_name(int(reason[3:]))
        except ValueError:
            return reason
    return reason


def _bail(stats, reason: str) -> bool:
    """Record one classified ineligibility/bailout; returns False so
    call sites read `return _bail(stats, "...")`."""
    if stats is not None:
        stats.record_bail(reason)
    return False


def native_apply_txset(lm, ltx, frames, base_fee: Optional[int],
                       verifier) -> bool:
    """Run the whole txset's fee+apply phases natively. Returns False on
    any ineligibility/bailout with NO state mutated (the caller then runs
    the Python phases); True means ltx, the header fee pool, and every
    frame's result/meta are populated exactly as the Python path would
    have. Per-op attribution and bail classification land in
    `lm.apply_stats` (ledger/apply_stats.py)."""
    stats = getattr(lm, "apply_stats", None)
    if not getattr(lm, "use_native_apply", True):
        return _bail(stats, "disabled")
    from ..native import apply_engine
    eng = apply_engine()
    if eng is None:
        return _bail(stats, "no-engine")
    from ..transactions.transaction_frame import TransactionFrame
    if ltx._changes:
        return _bail(stats, "open-changes")
    header = ltx.load_header()
    if header.ledgerVersion < 10:
        return _bail(stats, "protocol-pre10")
    for f in frames:
        if type(f) is not TransactionFrame:
            return _bail(stats, "fee-bump")  # fee bumps: Python path
    get_blob = getattr(lm.root, "get_entry_blob", None)
    if get_blob is None:
        return _bail(stats, "no-blob-lookup")
    if verifier is None:
        from ..crypto.batch_verifier import CpuSigVerifier
        verifier = CpuSigVerifier()
    params = {
        "ledgerVersion": header.ledgerVersion,
        "ledgerSeq": header.ledgerSeq,
        "closeTime": header.scpValue.closeTime,
        "baseFee": header.baseFee,
        "baseReserve": header.baseReserve,
        "effBaseFee": base_fee if base_fee is not None else header.baseFee,
        "feePool": header.feePool,
    }
    envs: List[bytes] = [f.envelope_bytes() for f in frames]
    hashes: List[bytes] = [f.contents_hash() for f in frames]
    out = eng.apply_close(params, envs, hashes, get_blob,
                          verifier.prewarm_many)
    if out is None:
        return _bail(stats, "engine-ineligible")
    if "bail" in out:
        return _bail(stats, _classify_engine_bail(out["bail"]))
    header.feePool = out["feePool"]
    ltx.inject_native_changes(out["changes"])
    for f, rb, fcb, mb in zip(frames, out["results"], out["fee_changes"],
                              out["meta"]):
        f.set_native_apply_output(rb, fcb, mb)
    if stats is not None and out.get("op_stats"):
        stats.record_native_op_table(out["op_stats"])
    return True
