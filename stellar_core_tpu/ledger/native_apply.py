"""Close-time glue for the native transaction-apply fast path.

native/applyc.c implements the fee + apply phases of a ledger close for
every wire op type, plain v1 AND fee-bump envelopes, and muxed account
references (protocol >= 10). This module decides per close whether the
engine may run, feeds it (entry lookups, batched signature verifies,
order-book scans), chooses the execution mode (conflict-graph parallel
clusters vs serial), and installs its outputs so everything downstream
of the apply loop — result hash, bucket-list delta, tx/fee history rows,
close meta, invariants — runs unchanged Python over identical state.

The engine returns {"bail": reason} (or None) for ANY input outside its
subset before mutating shared state, so the Python apply path (the
differential-test oracle, tests/test_native_apply.py) remains the single
source of semantics. Residual bail reasons after full op coverage
(ISSUE 13): non-ed25519 signer keys (`signer-key-type`), >20-signer
shapes (`multisig-shape`), wire thresholds over 255 (`threshold-range`),
due inflation payouts pre-protocol-12 (`inflation-payout`), op shapes
whose Python apply raises (`op-shape`), and op-level auth failures whose
Python result mix is unserializable (`op-auth`). Every bail classifies
to `ledger.apply.native-bail.<reason>`.

Parallel close: the engine partitions the txset into clusters by
statically-touched entries and applies disjoint clusters on worker
threads with the GIL released; the differential oracle asserts
serial-equivalence for every schedule. `apply.cluster-fail`
(util.faults) degrades a would-be-parallel close to serial — the same
close, one thread. Gate: SCT_NATIVE_APPLY=0 disables; Config
NATIVE_PARALLEL_APPLY / NATIVE_PARALLEL_WORKERS size the worker pool
(SCT_PARALLEL_APPLY=0 forces serial).
"""

from __future__ import annotations

import os
from typing import List, Optional


def _classify_engine_bail(reason: str) -> str:
    """Engine reason string -> metric-safe reason. `op-<n>` carries the
    numeric wire type; name it (`op-manage-sell-offer`) so operators
    read traffic, not enum values."""
    if reason.startswith("op-") and reason[3:].isdigit():
        try:
            from .apply_stats import op_type_name
            return "op-" + op_type_name(int(reason[3:]))
        except ValueError:
            return reason
    return reason


def _bail(stats, reason: str) -> bool:
    """Record one classified ineligibility/bailout; returns False so
    call sites read `return _bail(stats, "...")`."""
    if stats is not None:
        stats.record_bail(reason)
    return False


def parallel_workers(lm) -> int:
    """Effective worker count for the conflict-graph parallel close:
    Config NATIVE_PARALLEL_WORKERS when set (> 0), else cpu_count
    capped at 16 (measured on the bench host: wider pools keep enough
    workers scheduled under sandboxed kernels that park threads — 16
    beat 8 by 4x on the conflict-light gate leg). 1 disables
    parallelism."""
    if os.environ.get("SCT_PARALLEL_APPLY") == "0":
        return 1
    cfg = getattr(getattr(lm, "app", None), "config", None)
    if cfg is not None and not getattr(cfg, "NATIVE_PARALLEL_APPLY", True):
        return 1
    n = int(getattr(cfg, "NATIVE_PARALLEL_WORKERS", 0) or 0)
    if n > 0:
        return n
    return min(16, os.cpu_count() or 1)


def native_apply_txset(lm, ltx, frames, base_fee: Optional[int],
                       verifier, force_mode: Optional[str] = None) -> bool:
    """Run the whole txset's fee+apply phases natively. Returns False on
    any ineligibility/bailout with NO state mutated (the caller then runs
    the Python phases); True means ltx, the header fee pool + id pool,
    and every frame's result/meta are populated exactly as the Python
    path would have. Per-op attribution, bail classification, and
    cluster telemetry land in `lm.apply_stats` (ledger/apply_stats.py).

    `force_mode` ("serial"/"parallel") pins the execution mode — the
    differential oracle's forced-parallel-vs-serial equality leg."""
    stats = getattr(lm, "apply_stats", None)
    if not getattr(lm, "use_native_apply", True):
        return _bail(stats, "disabled")
    from ..native import apply_engine
    eng = apply_engine()
    if eng is None:
        return _bail(stats, "no-engine")
    from ..transactions.transaction_frame import (
        FeeBumpTransactionFrame, TransactionFrame,
    )
    if ltx._changes:
        return _bail(stats, "open-changes")
    header = ltx.load_header()
    if header.ledgerVersion < 10:
        return _bail(stats, "protocol-pre10")
    for f in frames:
        if type(f) is not TransactionFrame and \
                type(f) is not FeeBumpTransactionFrame:
            return _bail(stats, "frame-type")
    root = lm.root
    get_blob = getattr(root, "get_entry_blob", None)
    book = getattr(root, "offers_for_book_blobs", None)
    acct_offers = getattr(root, "offers_by_account_blobs", None)
    if get_blob is None or book is None or acct_offers is None:
        return _bail(stats, "no-blob-lookup")
    if verifier is None:
        from ..crypto.batch_verifier import CpuSigVerifier
        verifier = CpuSigVerifier()
    params = {
        "ledgerVersion": header.ledgerVersion,
        "ledgerSeq": header.ledgerSeq,
        "closeTime": header.scpValue.closeTime,
        "baseFee": header.baseFee,
        "baseReserve": header.baseReserve,
        "effBaseFee": base_fee if base_fee is not None else header.baseFee,
        "feePool": header.feePool,
        "idPool": header.idPool,
        "inflationSeq": header.inflationSeq,
    }
    envs: List[bytes] = [f.envelope_bytes() for f in frames]
    # fee bumps carry outer||inner contents hashes (the engine verifies
    # outer signatures over the outer hash, inner over the inner)
    hashes: List[bytes] = [
        f.contents_hash() + f.inner.contents_hash()
        if hasattr(f, "inner") else f.contents_hash()
        for f in frames]
    # tests pin the schedule (forced-parallel vs serial equality leg)
    # either per call or per manager
    mode = force_mode or getattr(lm, "native_force_mode", None) or "auto"
    workers = parallel_workers(lm)
    if mode == "parallel" and workers < 2:
        workers = 2
    if mode == "auto" and workers > 1:
        # fault site: a parallel close degrades to the same close on one
        # thread (docs/robustness.md) — never to the Python path
        from ..util.faults import check_faults
        if check_faults(getattr(lm, "app", None), "apply.cluster-fail"):
            mode = "serial"
            if stats is not None:
                stats.record_cluster_degrade()
    opts = {"workers": workers, "mode": mode}
    out = eng.apply_close(params, envs, hashes, get_blob,
                          verifier.prewarm_many, book, acct_offers, opts)
    if out is None:
        return _bail(stats, "engine-ineligible")
    if "bail" in out:
        return _bail(stats, _classify_engine_bail(out["bail"]))
    header.feePool = out["feePool"]
    header.idPool = out["idPool"]
    ltx.inject_native_changes(out["changes"])
    for f, rb, fcb, mb in zip(frames, out["results"], out["fee_changes"],
                              out["meta"]):
        f.set_native_apply_output(rb, fcb, mb)
    if stats is not None:
        if out.get("op_stats"):
            stats.record_native_op_table(out["op_stats"])
        cl = out.get("clusters")
        if cl:
            stats.record_clusters(cl["count"], cl["max_txs"],
                                  cl["workers"], bool(cl["parallel"]),
                                  apply_ns=cl.get("apply_ns", 0))
    return True
