"""Close-time glue for the native transaction-apply fast path.

native/applyc.c implements the fee + apply phases of a ledger close for
the replay workload's subset (plain v1 envelopes, payment /
create-account / set_options ops, ed25519-only signer sets, protocol
>= 10). This module decides per
close whether the engine may run, feeds it, and installs its outputs so
everything downstream of the apply loop — result hash, bucket-list delta,
tx/fee history rows, close meta, invariants — runs unchanged Python over
identical state.

The engine returns None for ANY input outside its subset before mutating
shared state, so the Python apply path (the differential-test oracle,
tests/test_native_apply.py) remains the single source of semantics.

Gate: SCT_NATIVE_APPLY=0 disables (mirroring SCT_NATIVE_XDR); an absent
compiler disables silently.
"""

from __future__ import annotations

from typing import List, Optional


def native_apply_txset(lm, ltx, frames, base_fee: Optional[int],
                       verifier) -> bool:
    """Run the whole txset's fee+apply phases natively. Returns False on
    any ineligibility/bailout with NO state mutated (the caller then runs
    the Python phases); True means ltx, the header fee pool, and every
    frame's result/meta are populated exactly as the Python path would
    have."""
    if not getattr(lm, "use_native_apply", True):
        return False
    from ..native import apply_engine
    eng = apply_engine()
    if eng is None:
        return False
    from ..transactions.transaction_frame import TransactionFrame
    if ltx._changes:
        return False  # engine reads close-start state from the root
    header = ltx.load_header()
    if header.ledgerVersion < 10:
        return False
    for f in frames:
        if type(f) is not TransactionFrame:
            return False  # fee bumps: Python path
    get_blob = getattr(lm.root, "get_entry_blob", None)
    if get_blob is None:
        return False
    if verifier is None:
        from ..crypto.batch_verifier import CpuSigVerifier
        verifier = CpuSigVerifier()
    params = {
        "ledgerVersion": header.ledgerVersion,
        "ledgerSeq": header.ledgerSeq,
        "closeTime": header.scpValue.closeTime,
        "baseFee": header.baseFee,
        "baseReserve": header.baseReserve,
        "effBaseFee": base_fee if base_fee is not None else header.baseFee,
        "feePool": header.feePool,
    }
    envs: List[bytes] = [f.envelope_bytes() for f in frames]
    hashes: List[bytes] = [f.contents_hash() for f in frames]
    out = eng.apply_close(params, envs, hashes, get_blob,
                          verifier.prewarm_many)
    if out is None:
        return False
    header.feePool = out["feePool"]
    ltx.inject_native_changes(out["changes"])
    for f, rb, fcb, mb in zip(frames, out["results"], out["fee_changes"],
                              out["meta"]):
        f.set_native_apply_output(rb, fcb, mb)
    return True
