"""LedgerManager: orders externalized values and closes ledgers.

Role parity: reference `src/ledger/LedgerManagerImpl.cpp`:
- valueExternalized (:410-490): apply in-order values, route gaps to catchup
- closeLedger (:522-728): bump seq → hash checks → sortForApply →
  processFeesSeqNums → applyTransactions → result hash → upgrades →
  ledgerClosed (bucket batch + header hash) → commit → publish queue
- startNewLedger / loadLastKnownLedger for genesis and restart.

Design note (TPU): closeLedger takes an optional BatchSigVerifier; during
catchup replay the caller pre-warms the verify cache with a whole ledger's
(or checkpoint's) signatures in one device batch, so the per-tx checks here
become cache hits.
"""

from __future__ import annotations

import base64
from typing import List, Optional

from ..crypto.hashing import SHA256, sha256
from ..database.database import Database
from ..ledger.ledgertxn import (
    InMemoryLedgerTxnRoot, LedgerTxn, LedgerTxnRoot,
)
from ..transactions.account_helpers import make_account_entry
from ..util.log import get_logger
from ..util.threads import main_thread_only
from ..xdr import (
    LedgerHeader, LedgerKey, LedgerUpgrade, StellarValue,
    StellarValueExt, TransactionHistoryEntry, TransactionSet,
    UpgradeEntryMeta, _Ext,
)

log = get_logger("Ledger")


def _be_u32(n: int) -> bytes:
    return n.to_bytes(4, "big")

GENESIS_LEDGER_SEQ = 1

# compiled structural copy (xdr/fastcodec.py) — close_ledger snapshots the
# previous header once per close
from ..xdr import fastcodec as _fastcodec  # noqa: E402
_copy_header_fast = _fastcodec.compile_copy(LedgerHeader)


class LedgerManagerState:
    LM_BOOTING_STATE = 0
    LM_SYNCED_STATE = 1
    LM_CATCHING_UP_STATE = 2


class LedgerCloseData:
    """One externalized slot worth of data (reference LedgerCloseData.h)."""

    def __init__(self, ledger_seq: int, tx_set, value: StellarValue) -> None:
        self.ledger_seq = ledger_seq
        self.tx_set = tx_set
        self.value = value


class LedgerManager:
    def __init__(self, app) -> None:
        self.app = app
        self.state = LedgerManagerState.LM_BOOTING_STATE
        cfg = app.config
        # close cockpit (ISSUE 9): ONE aggregation shared by the native
        # engine, the Python op loop, the SQL root and the bucket layer;
        # constructed before the root so state-read telemetry is wired
        # from the first lookup (docs/observability.md#close-cockpit)
        from ..ledger.apply_stats import ApplyStats
        clock = getattr(app, "clock", None)
        self.apply_stats = ApplyStats(
            metrics=getattr(app, "metrics", None),
            tracer=getattr(app, "tracer", None),
            now_fn=clock.now if clock is not None else None)
        if cfg.DATABASE == "in-memory":
            self.root = InMemoryLedgerTxnRoot()
        else:
            self.root = LedgerTxnRoot(app.database,
                                      stats=self.apply_stats)
        self.lcl_hash: bytes = b"\x00" * 32
        self.catchup_trigger = None  # set by CatchupManager wiring
        # True between a bucket-apply's state wipe and its successful LCL
        # fast-forward: no direct closes may run against half-built state
        self.entries_invalidated = False

    # -- genesis / restart --------------------------------------------------
    def start_new_ledger(self) -> None:
        cfg = self.app.config
        genesis = LedgerHeader(
            ledgerVersion=cfg.LEDGER_PROTOCOL_VERSION,
            previousLedgerHash=b"\x00" * 32,
            scpValue=StellarValue(txSetHash=b"\x00" * 32, closeTime=0,
                                  upgrades=[],
                                  ext=StellarValueExt(0, None)),
            txSetResultHash=b"\x00" * 32, bucketListHash=b"\x00" * 32,
            ledgerSeq=GENESIS_LEDGER_SEQ,
            totalCoins=cfg.GENESIS_TOTAL_COINS, feePool=0, inflationSeq=0,
            idPool=0, baseFee=cfg.TESTING_UPGRADE_DESIRED_FEE,
            baseReserve=cfg.TESTING_UPGRADE_RESERVE,
            maxTxSetSize=cfg.TESTING_UPGRADE_MAX_TX_SET_SIZE,
            skipList=[b"\x00" * 32] * 4, ext=_Ext.v0())
        self.root.set_header(genesis)
        ltx = LedgerTxn(self.root)
        root_acc = self.app.network_root_key().public_key
        ltx.create(make_account_entry(
            root_acc, cfg.GENESIS_TOTAL_COINS, 0, GENESIS_LEDGER_SEQ))
        genesis_entries = [cur for (_k, _prev, cur) in ltx.get_delta()]
        ltx.commit()
        self.lcl_hash = sha256(genesis.to_xdr())
        self._store_header(genesis)
        # seed the bucket list with the genesis delta (reference
        # startNewLedger → ledgerClosed does the same addBatch): without
        # it the root account exists in SQL but in NO bucket, so
        # BucketDB-routed reads (ISSUE 14) and bucket-apply catchup both
        # miss it. The genesis HEADER keeps bucketListHash = zero — it
        # was hashed before this batch, and every node (and every
        # catchup replay) seeds identically, so the chain from ledger 2
        # onward agrees fleet-wide.
        bm = self._bucket_manager()
        if bm is not None:
            bm.add_batch(GENESIS_LEDGER_SEQ, genesis.ledgerVersion,
                         genesis_entries, [], [])
            self._store_local_has()
        self.state = LedgerManagerState.LM_SYNCED_STATE
        log.info("started new ledger: genesis %s",
                 self.lcl_hash.hex()[:8])

    def load_last_known_ledger(self) -> bool:
        """Restore LCL from the database; returns False if no state."""
        db = getattr(self.app, "database", None)
        if db is None or self.app.config.DATABASE == "in-memory":
            return False
        row = db.execute(
            "SELECT ledgerhash, data FROM ledgerheaders ORDER BY "
            "ledgerseq DESC LIMIT 1").fetchone()
        if row is None:
            return False
        header = LedgerHeader.from_xdr(row[1])
        self.root.set_header(header)
        self.lcl_hash = bytes.fromhex(row[0])
        self.state = LedgerManagerState.LM_SYNCED_STATE
        self._restore_bucket_list()
        self._check_bucket_coverage()
        return True

    def _check_bucket_coverage(self) -> None:
        """BucketDB may only serve authoritative reads when the bucket
        list covers the root's whole SQL state. Two restart shapes
        break that: a data dir written before genesis seeding (ISSUE
        14) whose headers legitimately match an unseeded list, and a
        dir whose buckets were enabled mid-life (no HAS at all, list
        empty over populated SQL). The root account is the sentinel:
        it is the only entry ever created outside a close delta —
        everything else entered a bucket with the close that touched
        it — so if SQL has it and the bucket list disagrees, the list
        does not cover this state: detach (SQL point reads carry the
        node; a bucket-apply catchup re-attaches)."""
        root = self.root
        if not getattr(root, "bucket_backed", lambda: False)():
            return
        from ..xdr import LedgerKey
        key = LedgerKey.account(self.app.network_root_key().public_key)
        sql_blob = root._select_blob(key)
        if sql_blob is None:
            return
        served, blob = root._bucketdb.lookup(key.to_xdr())
        if not served:
            # a bucketdb.read-fail degrade during the sentinel proves
            # nothing about coverage — don't detach on a fault
            return
        if blob != sql_blob:
            root.detach_bucketdb()
            log.warning(
                "bucket list does not cover SQL state (root-account "
                "sentinel: bucket says %s, SQL has it) — bucket-backed "
                "reads disabled, SQL point reads in effect until a "
                "bucket-apply catchup heals the list",
                "absent" if blob is None else "a different entry")

    def set_last_closed_ledger(self, header: LedgerHeader,
                               ledger_hash: bytes) -> None:
        """Fast-forward the LCL to a verified downloaded header — the
        bucket-apply catchup path (reference CatchupWork sets LCL after
        ApplyBucketsWork; LedgerManagerImpl::setLastClosedLedger)."""
        assert sha256(header.to_xdr()) == ledger_hash, "header/hash mismatch"
        self.root.set_header(header)
        self.lcl_hash = ledger_hash
        self._store_header(header)
        self.entries_invalidated = False
        # a bucket-apply catchup rebuilt SQL state FROM the bucket list,
        # so the two are in sync again: (re-)attach BucketDB reads if
        # the adopted list matches what this header committed to
        # (heals a startup-time detach — ISSUE 14). Respects the
        # operator's BUCKETDB_READS=False pin.
        bm = self._bucket_manager()
        cfg = getattr(self.app, "config", None)
        if bm is not None and hasattr(self.root, "attach_bucketdb") and \
                getattr(cfg, "BUCKETDB_READS", True) and \
                bm.get_hash() == header.bucketListHash:
            self.root.attach_bucketdb(bm.bucketdb)
        log.info("LCL set to %d (%s) from catchup", header.ledgerSeq,
                 ledger_hash.hex()[:8])

    # -- accessors ----------------------------------------------------------
    @property
    def lcl_header(self) -> LedgerHeader:
        return self.root.get_header()

    def last_closed_ledger_num(self) -> int:
        return self.lcl_header.ledgerSeq

    def ltx_root(self):
        return self.root

    def header(self) -> LedgerHeader:
        return self.root.get_header()

    def is_synced(self) -> bool:
        return self.state == LedgerManagerState.LM_SYNCED_STATE

    # -- externalization ----------------------------------------------------
    @main_thread_only
    def value_externalized(self, lcd: LedgerCloseData) -> None:
        lcl = self.last_closed_ledger_num()
        if self.state == LedgerManagerState.LM_CATCHING_UP_STATE:
            # mid-catchup every value is buffered, even in-order ones —
            # closing under a concurrent bucket apply would corrupt state
            # (reference LedgerManagerImpl.cpp:410-444)
            if self.catchup_trigger is not None:
                self.catchup_trigger(lcd)
            return
        if lcd.ledger_seq == lcl + 1:
            self.close_ledger(lcd)
        elif lcd.ledger_seq <= lcl:
            log.info("skipping already-applied ledger %d", lcd.ledger_seq)
        else:
            log.warning("ledger gap: got %d, lcl %d — catchup needed",
                        lcd.ledger_seq, lcl)
            self.state = LedgerManagerState.LM_CATCHING_UP_STATE
            if self.catchup_trigger is not None:
                self.catchup_trigger(lcd)

    # -- the close ----------------------------------------------------------
    @main_thread_only
    def close_ledger(self, lcd: LedgerCloseData) -> None:
        header_prev = _copy_header_fast(self.lcl_header)
        assert lcd.ledger_seq == header_prev.ledgerSeq + 1, "non-sequential"
        assert lcd.tx_set.previous_ledger_hash == self.lcl_hash, \
            "txset based on wrong ledger"
        assert lcd.value.txSetHash == lcd.tx_set.get_contents_hash(
            hasher=getattr(self.app, "batch_hasher", None)), \
            "value/txset hash mismatch"

        verifier = getattr(self.app, "sig_verifier", None)
        metrics = getattr(self.app, "metrics", None)
        from ..util.slow_execution import LogSlowExecution
        from ..util.tracing import app_span
        recorder = getattr(self.app, "flight_recorder", None)
        on_slow = (None if recorder is None else
                   lambda elapsed: recorder.dump(
                       "slow-close",
                       extra={"ledger_seq": lcd.ledger_seq,
                              "elapsed_s": elapsed}))
        db = getattr(self.app, "database", None)
        ltx = LedgerTxn(self.root)
        try:
            # split the close into apply-vs-SQL components (reference
            # DBTimeExcluder + LogSlowExecution, LedgerManagerImpl:524-528);
            # the timers record in `finally` so failed closes still
            # contribute samples
            import time as _time
            sql_before = db.total_query_seconds if db is not None else 0.0
            t0 = _time.perf_counter()
            try:
                with LogSlowExecution("ledger close", on_slow=on_slow), \
                        app_span(self.app, "ledger.close", cat="ledger",
                                 seq=lcd.ledger_seq,
                                 txs=len(lcd.tx_set.frames)):
                    self._close_ledger_in(ltx, lcd, header_prev, verifier)
            finally:
                if metrics is not None:
                    elapsed = _time.perf_counter() - t0
                    sql_spent = (db.total_query_seconds - sql_before) \
                        if db is not None else 0.0
                    metrics.new_timer("ledger.ledger.close").update(elapsed)
                    metrics.new_timer("ledger.ledger.close.sql").update(
                        sql_spent)
                    metrics.new_timer("ledger.ledger.close.apply").update(
                        max(0.0, elapsed - sql_spent))
            if metrics is not None:
                metrics.new_meter("ledger.transaction.apply").mark(
                    len(lcd.tx_set.frames))
                metrics.new_counter("ledger.ledger.num").set_count(
                    lcd.ledger_seq)
            tl = getattr(self.app, "slot_timeline", None)
            if tl is not None:
                # closes the slot's journal: externalize → applied is the
                # local apply cost the fleet view separates from
                # propagation skew
                tl.record(lcd.ledger_seq, "ledger.applied",
                          txs=len(lcd.tx_set.frames))
        except BaseException as e:
            if ltx._open:
                ltx.rollback()   # drop children too: no dangling state
            # seal the close-cockpit window (path "failed") so per-op
            # seconds already recorded for this close can't outgrow the
            # cumulative apply wall (apply_stats.abort_close docstring)
            self.apply_stats.abort_close()
            # black box for the postmortem: spans + metrics at the moment
            # of a failed close (KeyboardInterrupt/SystemExit excluded —
            # an operator ^C is not a crash)
            if recorder is not None and isinstance(e, Exception):
                recorder.dump("close-exception", exc=e,
                              extra={"ledger_seq": lcd.ledger_seq})
            raise

    def _close_ledger_in(self, ltx, lcd: LedgerCloseData,
                         header_prev: LedgerHeader, verifier) -> None:
        from ..util.tracing import app_span
        header = ltx.load_header()
        header.ledgerSeq = lcd.ledger_seq
        header.previousLedgerHash = self.lcl_hash
        header.scpValue = lcd.value

        with app_span(self.app, "close.txset_sort", cat="ledger"):
            frames = lcd.tx_set.sort_for_apply()
            base_fee = lcd.tx_set.base_fee(header)

        # close cockpit: open the per-close stats window, classify the
        # tx mix (fee-bump / muxed counted distinctly), and bulk-warm the
        # root entry cache with the txset's statically-knowable keys so
        # apply-path reads are cache hits with measured coverage
        # (reference prefetchTransactionData; ledger/apply_stats.py)
        from ..ledger.apply_stats import frame_traits, txset_prefetch_keys
        from ..util.timer import real_perf_counter
        stats = self.apply_stats
        stats.begin_close(lcd.ledger_seq)
        fee_bumps = muxeds = 0
        for f in frames:
            fee_bump, muxed = frame_traits(f)
            fee_bumps += fee_bump
            muxeds += muxed
        stats.record_tx_counts(len(frames), fee_bumps, muxeds)
        # the bulk prefetch warms the root cache for the PYTHON apply
        # path; the native engine loads every static key itself through
        # get_entry_blob (same cache, same telemetry hooks), so running
        # both would pay the Python key-build + cache walk twice per
        # close (ISSUE 13: ~9ms/close on the replay leg). When the
        # engine is expected to run, the prefetch is DEFERRED, not
        # dropped: a bailing close still warms the cache before the
        # Python phases (below). EXCEPT with a BucketDB-backed root
        # (ISSUE 14): there the batched prefetch resolves the whole
        # txset in one bloom-filtered pass per bucket level — cheaper
        # than the engine's per-key multi-level walks — and feeds the
        # engine its entry blobs directly as cache hits.
        def _bulk_prefetch() -> None:
            with app_span(self.app, "close.prefetch", cat="ledger") as psp:
                psp.set_tag("cached",
                            self.root.prefetch(txset_prefetch_keys(frames)))

        bucket_backed = getattr(self.root, "bucket_backed",
                                lambda: False)()
        can_prefetch = bool(frames) and hasattr(self.root, "prefetch")
        if can_prefetch and (bucket_backed or
                             not self._native_covers_prefetch()):
            _bulk_prefetch()
            can_prefetch = False   # done; don't repeat on a native bail

        # fast path: the native engine runs BOTH phases in one C call and
        # installs per-frame results/meta + the close-level delta; any
        # ineligibility falls through to the Python phases with no state
        # mutated (ledger/native_apply.py)
        from ..ledger.ledgertxn import delta_to_changes
        from ..ledger.native_apply import native_apply_txset
        with app_span(self.app, "close.apply", cat="ledger",
                      txs=len(frames)) as apply_sp:
            t_apply = real_perf_counter()
            if native_apply_txset(self, ltx, frames, base_fee, verifier):
                apply_path = "native"
            else:
                apply_path = "python"
                if can_prefetch:
                    # the engine bailed: the deferred bulk prefetch runs
                    # now so the Python phases see a warm root cache
                    _bulk_prefetch()
                # phase 1: fees + seq nums for every tx, each in a nested
                # txn so the per-tx fee-processing changes become
                # txfeehistory meta (reference saves these
                # LedgerEntryChanges per tx)
                for f in frames:
                    fee_ltx = LedgerTxn(ltx)
                    try:
                        f.process_fee_seq_num(fee_ltx, base_fee)
                        f.fee_meta = delta_to_changes(fee_ltx.get_delta())
                        fee_ltx.commit()
                    except BaseException:
                        if fee_ltx._open:
                            fee_ltx.rollback()
                        raise
                # phase 2: apply, collecting results (+ invariant checks)
                # with per-op latency attribution (the cockpit's
                # Python-path histograms)
                for f in frames:
                    f.apply(ltx, verifier, stats=stats)
            apply_wall_s = real_perf_counter() - t_apply
            apply_sp.set_tag("apply_path", apply_path)
        # result hash in apply order, assembled from wire bytes:
        # TransactionResultSet XDR is count ‖ pairs, and each frame holds
        # (or lazily serializes) its own pair bytes — on the native fast
        # path no TransactionResult is ever parsed or re-serialized here
        # (tests/test_native_apply.py pins this layout against the codec).
        # STREAMED through the hash boundary (ISSUE 12 satellite): the
        # old path built the full concatenated blob before hashing, so
        # peak memory grew with the txset — the chunked stream keeps it
        # flat and identical byte-for-byte (tests/test_batch_hasher.py)
        with app_span(self.app, "close.result_hash", cat="ledger"):
            from itertools import chain
            chunks = chain((_be_u32(len(frames)),),
                           (f.result_pair_xdr() for f in frames))
            hasher = getattr(self.app, "batch_hasher", None)
            if hasher is not None:
                header.txSetResultHash = hasher.hash_stream(
                    chunks, site="result-set")
            else:
                h = SHA256()
                for c in chunks:
                    h.add(c)
                header.txSetResultHash = h.finish()

        # invariants see the TX-phase delta under the pre-upgrade header:
        # the reference hooks invariants per operation only, so upgrade
        # rewrites (prepareLiabilities initializing liabilities / erasing
        # offers) are exempt by design — they ESTABLISH the state the
        # invariants check from then on. Snapshotting the delta costs a
        # full parse+serialize pass over every changed entry, so it only
        # happens when an invariant manager will actually read it.
        # an InvariantManager with nothing enabled (the production
        # default) must not cost the snapshot either — Application always
        # constructs one
        inv = getattr(self.app, "invariant_manager", None)
        if inv is not None and not inv.enabled_names():
            inv = None
        tx_phase_delta = ltx.get_delta() if inv is not None else None
        tx_phase_header = _copy_header_fast(header) if inv is not None \
            else None

        # upgrades (after txs; reference LedgerManagerImpl.cpp:617-669):
        # a malformed or invalid upgrade in an externalized value fails
        # the whole close; valid upgrades each apply in a nested txn so
        # their entry changes land in meta + upgradehistory, and an
        # apply-time error skips that upgrade without aborting the close
        from ..herder.upgrades import Upgrades, UpgradeValidity
        applied_upgrades = []   # (LedgerUpgrade, LedgerEntryChanges rows)
        max_version = getattr(getattr(self.app, "config", None),
                              "LEDGER_PROTOCOL_VERSION", 2**32 - 1)
        for i, raw in enumerate(lcd.value.upgrades):
            validity = Upgrades.validity_for_apply(raw, header, max_version)
            if validity == UpgradeValidity.XDR_INVALID:
                raise RuntimeError("unknown upgrade at index %d" % i)
            if validity == UpgradeValidity.INVALID:
                raise RuntimeError("invalid upgrade at index %d" % i)
            up = LedgerUpgrade.from_xdr(raw)
            up_ltx = LedgerTxn(ltx)
            try:
                Upgrades.apply_to(up_ltx, up)
                changes = delta_to_changes(up_ltx.get_delta())
                up_ltx.commit()
            except RuntimeError as e:
                if up_ltx._open:
                    up_ltx.rollback()
                log.error("exception during upgrade: %s", e)
                continue
            except BaseException:
                if up_ltx._open:
                    up_ltx.rollback()
                raise
            applied_upgrades.append((up, changes, i + 1))

        # bucket-list hash over the close's delta (content-addressed chain;
        # stands in the header exactly where the reference's
        # BucketList::getHash result goes)
        # need_prev=False: the init/live/dead split below only tests
        # pre-image EXISTENCE, so native-injected deltas skip parsing
        # every pre-image entry; raw_keys=True: only DEAD entries need a
        # parsed LedgerKey (bucket dead keys), live/init keys would be
        # parsed once per touched account per close just to be dropped
        with app_span(self.app, "close.bucket_add", cat="ledger") as bsp:
            delta = ltx.get_delta(need_prev=False, raw_keys=True)
            bl = self._bucket_manager()
            bsp.set_tag("entries", len(delta))
            if bl is not None:
                init_entries, live_entries, dead_keys = [], [], []
                for kb, prev, cur in delta:
                    if cur is None:
                        dead_keys.append(LedgerKey.from_xdr(kb))
                    elif prev is None:
                        init_entries.append(cur)
                    else:
                        live_entries.append(cur)
                bl.add_batch(header.ledgerSeq, header.ledgerVersion,
                             init_entries, live_entries, dead_keys)
                bl.snapshot_ledger(header)
            else:
                h = SHA256()
                h.add(header_prev.bucketListHash)
                for kb, prev, cur in sorted(delta, key=lambda t: t[0]):
                    h.add(kb)
                    h.add(cur.to_xdr() if cur is not None else b"\xff" * 4)
                header.bucketListHash = h.finish()
                # skipList advances identically with or without a real
                # bucket list — it hangs off whatever stands in
                # bucketListHash
                from ..bucket.bucket_manager import calculate_skip_values
                calculate_skip_values(header)

        # invariants on the tx phase of the close (upgrade deltas exempt)
        if inv is not None:
            inv.check_on_ledger_close(tx_phase_delta, header_prev,
                                      tx_phase_header)

        with app_span(self.app, "close.commit", cat="ledger"):
            ltx.commit()
        with app_span(self.app, "close.header_hash", cat="ledger"):
            hasher = getattr(self.app, "batch_hasher", None)
            hb = self.root.get_header().to_xdr()
            self.lcl_hash = (hasher.digest_one(hb, site="header")
                             if hasher is not None else sha256(hb))
        # state commitment (ledger/state_commitment.py, ISSUE 12): the
        # incremental Merkle root over the post-close bucket list, plus
        # a signed light-client checkpoint on its interval — O(changed
        # levels) per close via the entry-root cache
        sce = getattr(self.app, "state_commitment", None)
        if sce is not None and bl is not None:
            with app_span(self.app, "close.commitment", cat="ledger",
                          seq=lcd.ledger_seq) as msp:
                cp = sce.on_close(bl.bucket_list, lcd.ledger_seq,
                                  self.lcl_hash)
                if sce.root is not None:
                    msp.set_tag("root", sce.root.hex()[:16])
                if cp is not None:
                    msp.set_tag("checkpoint_seq", cp.ledger_seq)
        with app_span(self.app, "close.sql_commit", cat="ledger"):
            self._store_header(self.root.get_header())
            self._store_txs(lcd, frames)
            # after the in-memory commit, like txhistory: a close that
            # fails mid-upgrade must leave no pending history rows in the
            # sqlite transaction (a catchup retry would hit the PRIMARY
            # KEY)
            for up, changes, index in applied_upgrades:
                self._store_upgrade_history(lcd.ledger_seq, up, changes,
                                            index)
            self._store_local_has()

        # seal the close-cockpit window only now that the close is
        # DURABLE (LCL advanced, SQL stored) — a failure anywhere above
        # reaches abort_close() instead, so closes.{native|python} never
        # counts a close that didn't commit. Tagging the apply span this
        # late still works: the span OBJECT is already recorded in the
        # tracer ring (spans are recorded by reference at exit), so the
        # op mix / read-set stats land in exported traces and flight
        # dumps regardless.
        close_blob = stats.end_close(apply_path, apply_wall_s,
                                     write_set=len(delta))
        if close_blob is not None:
            apply_sp.set_tag("op_mix", {
                n: d["count"] for n, d in close_blob["ops"].items()})
            apply_sp.set_tag("reads", close_blob["reads"])
            if close_blob.get("bail"):
                apply_sp.set_tag("native_bail", close_blob["bail"])

        self._emit_close_meta(lcd, frames, applied_upgrades)
        hm = getattr(self.app, "history_manager", None)
        if hm is not None:
            hm.maybe_queue_checkpoint(self)
        log.debug("closed ledger %d (%d txs) hash %s", lcd.ledger_seq,
                  len(frames), self.lcl_hash.hex()[:8])

    def _emit_close_meta(self, lcd: LedgerCloseData, frames,
                         applied_upgrades) -> None:
        """Stream the full close meta to the operator's configured
        fd/file (reference LedgerManagerImpl.cpp:590,673-678 builds
        LedgerCloseMeta alongside the apply loop and emits it once the
        close commits). txProcessing is in APPLY order; each entry
        carries the tx's result, its fee-processing changes, and the full
        apply meta — a downstream consumer can reconstruct every balance
        from the stream alone."""
        stream = getattr(self.app, "close_meta_stream", None)
        if stream is None:
            return
        from ..xdr import (
            LedgerCloseMeta, LedgerCloseMetaV0, LedgerHeaderHistoryEntry,
            TransactionResultMeta,
        )
        meta = LedgerCloseMetaV0(
            ledgerHeader=LedgerHeaderHistoryEntry(
                hash=self.lcl_hash, header=self.root.get_header(),
                ext=_Ext.v0()),
            txSet=lcd.tx_set.to_wire(),
            txProcessing=[
                TransactionResultMeta(result=f.result_pair(),
                                      feeProcessing=f.fee_meta,
                                      txApplyProcessing=f.tx_meta())
                for f in frames],
            upgradesProcessing=[
                UpgradeEntryMeta(upgrade=up, changes=changes)
                for (up, changes, _i) in applied_upgrades],
            scpInfo=[])
        try:
            stream.write_one(LedgerCloseMeta.v0(meta))
        except OSError as e:
            # a dead consumer pipe must not halt consensus; close and
            # drop the stream, keep closing ledgers (operator sees the
            # log)
            log.error("close-meta stream failed at ledger %d: %s — "
                      "disabling stream", lcd.ledger_seq, e)
            stream.close()
            self.app.close_meta_stream = None

    def _native_covers_prefetch(self) -> bool:
        """True when the native engine will run this close and therefore
        performs its own static-key loads (ledger/native_apply.py)."""
        if not getattr(self, "use_native_apply", True):
            return False
        from ..native import apply_engine
        return apply_engine() is not None

    def _bucket_manager(self):
        return getattr(self.app, "bucket_manager", None)

    def _store_local_has(self) -> None:
        """Persist the local bucket-list manifest so a restarted node can
        re-adopt its bucket files (reference keeps kHistoryArchiveState in
        PersistentState and assumeState()s it at startup)."""
        ps = getattr(self.app, "persistent_state", None)
        bm = self._bucket_manager()
        if ps is None or bm is None:
            return
        from ..history.archive_state import HistoryArchiveState
        has = HistoryArchiveState.from_bucket_list(
            self.lcl_header.ledgerSeq, bm.bucket_list)
        ps.set_state(ps.kHistoryArchiveState, has.to_json())

    def _restore_bucket_list(self) -> None:
        """Re-adopt the persisted bucket-list state after a restart
        (reference ApplicationImpl loadLastKnownLedger →
        BucketManagerImpl::assumeState)."""
        ps = getattr(self.app, "persistent_state", None)
        bm = self._bucket_manager()
        if ps is None or bm is None:
            return
        s = ps.get_state(ps.kHistoryArchiveState)
        if not s:
            return
        from ..history.archive_state import (
            HistoryArchiveState, has_level_dicts,
        )
        try:
            has = HistoryArchiveState.from_json(s)
            header = self.lcl_header
            bm.assume_state(has_level_dicts(has),
                            header.ledgerSeq, header.ledgerVersion)
            # the adopted list must hash to what the LCL header committed
            # to — a stale HAS (e.g. written before a bucket-apply catchup
            # fast-forwarded the LCL) silently forks the chain otherwise.
            # Exception: a node restarted AT genesis — the genesis header
            # predates the seeded genesis batch by construction (its
            # bucketListHash is the zero hash), so the seeded list is the
            # expected state, not a fork.
            at_genesis = (header.ledgerSeq == GENESIS_LEDGER_SEQ and
                          header.bucketListHash == b"\x00" * 32)
            if not at_genesis and bm.get_hash() != header.bucketListHash:
                raise ValueError(
                    "restored bucket list hash %s != header %s" %
                    (bm.get_hash().hex()[:16],
                     header.bucketListHash.hex()[:16]))
            log.info("restored bucket list at ledger %d from local HAS",
                     header.ledgerSeq)
        except Exception as e:  # corrupt/stale HAS or missing files:
            # degrade to an empty bucket list rather than failing startup
            # or running on wrong state (catchup heals)
            from ..bucket.bucket_list import BucketList
            bm.bucket_list = BucketList(bm._executor,
                                        adopt=bm.adopt_bucket,
                                        stats=bm._stats)
            # the empty list no longer covers this root's SQL state, so
            # BucketDB must NOT serve authoritative reads over it —
            # detach; SQL point reads carry the node until catchup heals
            # the list (ISSUE 14)
            if hasattr(self.root, "detach_bucketdb"):
                self.root.detach_bucketdb()
            log.warning("bucket-list restore failed: %s — bucket-backed "
                        "reads disabled, SQL point reads in effect", e)

    def _store_upgrade_history(self, ledger_seq: int, up, changes,
                               index: int) -> None:
        """Reference Upgrades::storeUpgradeHistory — one row per applied
        upgrade, 1-indexed like txhistory, carrying the upgrade and its
        LedgerEntryChanges."""
        db = getattr(self.app, "database", None)
        if db is None:
            return
        from ..xdr import LedgerEntryChanges as _LEC
        from ..xdr.codec import xdr_bytes as _xb
        db.execute(
            "INSERT OR REPLACE INTO upgradehistory (ledgerseq, "
            "upgradeindex, upgrade, changes) VALUES (?,?,?,?)",
            (ledger_seq, index, up.to_xdr(), _xb(_LEC, changes)))

    # -- persistence --------------------------------------------------------
    def _store_header(self, header: LedgerHeader) -> None:
        db = getattr(self.app, "database", None)
        if db is None:
            return
        hb = header.to_xdr()
        db.execute(
            "INSERT OR REPLACE INTO ledgerheaders (ledgerhash, prevhash, "
            "bucketlisthash, ledgerseq, closetime, data) VALUES "
            "(?,?,?,?,?,?)",
            (sha256(hb).hex(),
             header.previousLedgerHash.hex(), header.bucketListHash.hex(),
             header.ledgerSeq, header.scpValue.closeTime, hb))
        db.commit()

    def _store_txs(self, lcd: LedgerCloseData, frames) -> None:
        db = getattr(self.app, "database", None)
        if db is None:
            return
        tx_rows, fee_rows = [], []
        for i, f in enumerate(frames):
            h = f.contents_hash().hex()
            tx_rows.append((h, lcd.ledger_seq, i, f.envelope_bytes(),
                            f.result_pair_xdr(), f.tx_meta_xdr()))
            fee_rows.append((h, lcd.ledger_seq, i, f.fee_meta_xdr()))
        db.executemany(
            "INSERT OR REPLACE INTO txhistory (txid, ledgerseq, "
            "txindex, txbody, txresult, txmeta) VALUES (?,?,?,?,?,?)",
            tx_rows)
        db.executemany(
            "INSERT OR REPLACE INTO txfeehistory (txid, ledgerseq, "
            "txindex, txchanges) VALUES (?,?,?,?)", fee_rows)
        db.commit()
