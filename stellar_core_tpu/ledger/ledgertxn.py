"""LedgerTxn: nested in-memory copy-on-write ledger state transactions.

Role parity: reference `src/ledger/LedgerTxn*` (LedgerTxn.h:18-165): a tree
of transactions over (LedgerKey → LedgerEntry), root backed by SQL with an
entry cache and bulk commits; children see parent state copy-on-write;
commit merges down, rollback discards. Entry-type-specific SQL backends
(LedgerTxnAccountSQL.cpp etc.) correspond to the per-table writers here.

Simplifications vs reference: Python object mutability replaces the
"activeness" discipline — load() snapshots the pre-image for delta/meta
generation, and entries are owned by the innermost open txn.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

from ..database.database import Database
from ..util.cache import LRUCache
from ..xdr import (
    Asset, LedgerEntry, LedgerEntryType, LedgerHeader, LedgerKey, OfferEntry,
    ledger_entry_key,
)
from ..xdr import fastcodec
from ..crypto import strkey


def _kb(key: LedgerKey) -> bytes:
    """LedgerKey → canonical bytes (the txn tree's map key), memoized on
    the instance — keys are treated as immutable once built, and the same
    key object flows through load/commit/delta several times per access."""
    kb = key.__dict__.get("_kb")
    if kb is None:
        kb = key.to_xdr()
        key.__dict__["_kb"] = kb
    return kb


# copy-on-write primitives: compiled structural copies (xdr/fastcodec.py),
# ~4x cheaper than the pack+unpack round-trip (replay profile: entry/header
# copies were ~14% of catchup CPU)
_copy_entry = fastcodec.compile_copy(LedgerEntry)
_copy_header = fastcodec.compile_copy(LedgerHeader)


_acc_str_cache: Dict[bytes, str] = {}


def _acc_str(account_id) -> str:
    """strkey encoding for SQL row keys, memoized — a busy account's key
    is re-encoded on every load/commit otherwise (CRC16 per call)."""
    kb = account_id.key_bytes
    s = _acc_str_cache.get(kb)
    if s is None:
        if len(_acc_str_cache) > 0x10000:
            _acc_str_cache.clear()
        s = strkey.encode_public_key(kb)
        _acc_str_cache[kb] = s
    return s


def _asset_str(asset: Asset) -> str:
    import base64
    return base64.b64encode(asset.to_xdr()).decode()


def price_less(a_offer: OfferEntry, b_offer: OfferEntry) -> bool:
    """Exact fraction compare a.price < b.price, tie-break by offerID
    (reference isBetterOffer, LedgerTxn.cpp role)."""
    lhs = a_offer.price.n * b_offer.price.d
    rhs = b_offer.price.n * a_offer.price.d
    if lhs != rhs:
        return lhs < rhs
    return a_offer.offerID < b_offer.offerID


class AbstractLedgerTxnParent:
    # Exactly one child may be open under any parent — roots included
    # (reference LedgerTxn.cpp addChild: both LedgerTxn and LedgerTxnRoot
    # throw if a child is already open).
    _child: Optional["LedgerTxn"] = None

    def _register_child(self, child: "LedgerTxn") -> None:
        assert self._child is None, "parent already has an open child"
        self._child = child

    def _clear_child(self, child: "LedgerTxn") -> None:
        if self._child is child:
            self._child = None

    def get_entry(self, key: LedgerKey) -> Optional[LedgerEntry]:
        raise NotImplementedError

    def get_header(self) -> LedgerHeader:
        raise NotImplementedError

    def _all_offers_for_book(self, selling: Asset,
                             buying: Asset) -> Dict[bytes, LedgerEntry]:
        raise NotImplementedError

    def _offers_by_account(self, account_id) -> Dict[bytes, LedgerEntry]:
        raise NotImplementedError

    def commit_child(self, changes: Dict[bytes, Optional[LedgerEntry]],
                     header: LedgerHeader,
                     blobs: Optional[Dict[bytes, bytes]] = None) -> None:
        """`blobs` optionally carries known-serialized forms of entries in
        `changes` (native-injected deltas) so roots can skip
        re-serializing them."""
        raise NotImplementedError


class LedgerTxn(AbstractLedgerTxnParent):
    """A nested transaction. Exactly one child may be open at a time."""

    def __init__(self, parent: AbstractLedgerTxnParent) -> None:
        self._parent = parent
        self._changes: Dict[bytes, Optional[LedgerEntry]] = {}
        self._previous: Dict[bytes, Optional[bytes]] = {}  # pre-images (xdr)
        # parsed pre-image snapshots (same instant as _previous): get_delta
        # reads these instead of re-parsing the blob — a structural copy at
        # record time is ~4x cheaper than LedgerEntry.from_xdr at delta
        # time, and the close path takes a delta per fee/op txn
        self._prev_objs: Dict[bytes, LedgerEntry] = {}
        # serialized forms of UNTOUCHED _changes values (native-injected
        # deltas): valid only while the parsed object has never been
        # handed to a mutator — every path that exposes a mutable entry
        # pops the key. get_delta/commit reuse these instead of
        # re-serializing, the close path's main self-cost after the
        # native engine (replay profile)
        self._cur_blobs: Dict[bytes, bytes] = {}
        self._header = _copy_header(parent.get_header())
        self._open = True
        self._child: Optional["LedgerTxn"] = None
        if isinstance(parent, LedgerTxn):
            assert parent._open, "parent is sealed"
        parent._register_child(self)

    # -- header -------------------------------------------------------------
    def load_header(self) -> LedgerHeader:
        self._assert_open()
        return self._header

    def get_header(self) -> LedgerHeader:
        return self._header

    # -- entry access -------------------------------------------------------
    def _assert_open(self) -> None:
        assert self._open, "LedgerTxn is closed"
        assert self._child is None, "child transaction is open"

    def get_entry(self, key: LedgerKey) -> Optional[LedgerEntry]:
        kb = _kb(key)
        if kb in self._changes:
            cur = self._changes[kb]
            if cur is not None:
                # the caller holds an aliased reference from here on; a
                # mutation through it must not leave a stale blob behind
                self._cur_blobs.pop(kb, None)
            return cur
        return self._parent.get_entry(key)

    def load(self, key: LedgerKey) -> Optional[LedgerEntry]:
        """Load for update: snapshots the pre-image, returns a mutable entry
        owned by this txn (None if absent)."""
        self._assert_open()
        kb = _kb(key)
        if kb in self._changes:
            cur = self._changes[kb]
            if cur is not None:
                self._cur_blobs.pop(kb, None)   # handing out a mutable ref
            return cur
        base = self._parent.get_entry(key)
        if base is None:
            return None
        mine = _copy_entry(base)
        if kb not in self._previous:
            self._previous[kb] = base.to_xdr()
            self._prev_objs[kb] = _copy_entry(base)
        self._changes[kb] = mine
        return mine

    def load_without_record(self, key: LedgerKey) -> Optional[LedgerEntry]:
        """Read-only peek (reference loadWithoutRecord): no delta recorded."""
        self._assert_open()
        e = self.get_entry(key)
        return _copy_entry(e) if e is not None else None

    def inject_native_changes(self, changes) -> None:
        """Install the native apply engine's close-level delta
        (ledger/native_apply.py): `changes` is [(key_xdr, prev_xdr|None,
        cur_xdr|None)] in first-touch order, exactly what this txn's
        _previous/_changes would hold after the Python fee+apply phases.
        Entries parse once per close here instead of once per tx there."""
        self._assert_open()
        assert not self._changes, "native delta injected over live changes"
        for kb, prev_b, cur_b in changes:
            self._previous[kb] = prev_b
            if cur_b is None:
                self._changes[kb] = None
            else:
                self._changes[kb] = LedgerEntry.from_xdr(cur_b)
                self._cur_blobs[kb] = cur_b

    def create(self, entry: LedgerEntry) -> LedgerEntry:
        self._assert_open()
        key = ledger_entry_key(entry)
        kb = _kb(key)
        assert self.get_entry(key) is None, "entry already exists"
        mine = _copy_entry(entry)
        self._previous.setdefault(kb, None)
        self._cur_blobs.pop(kb, None)
        self._changes[kb] = mine
        return mine

    def _record_previous(self, kb: bytes) -> None:
        """Snapshot the parent-visible state of `kb` (blob + parsed)."""
        if kb in self._previous:
            return
        base = self._parent.get_entry(LedgerKey.from_xdr(kb))
        if base is None:
            self._previous[kb] = None
        else:
            self._previous[kb] = base.to_xdr()
            self._prev_objs[kb] = _copy_entry(base)

    def create_or_update_without_loading(self, entry: LedgerEntry) -> None:
        """Upsert with no existence check and no returned handle
        (reference createOrUpdateWithoutLoading, LedgerTxn.h: bulk-apply
        path). Still records the pre-image so deltas stay exact."""
        self._assert_open()
        key = ledger_entry_key(entry)
        kb = _kb(key)
        self._record_previous(kb)
        self._cur_blobs.pop(kb, None)
        self._changes[kb] = _copy_entry(entry)

    def erase(self, key: LedgerKey) -> None:
        self._assert_open()
        kb = _kb(key)
        existing = self.get_entry(key)
        assert existing is not None, "erasing missing entry"
        if kb not in self._previous:
            # `existing` is the parent's state here (anything recorded in
            # _changes implies _previous was already recorded)
            self._previous[kb] = existing.to_xdr()
            self._prev_objs[kb] = _copy_entry(existing)
        self._cur_blobs.pop(kb, None)
        self._changes[kb] = None

    def erase_without_loading(self, key: LedgerKey) -> None:
        """Delete with no existence check (reference eraseWithoutLoading):
        erasing an absent key is a no-op record of absence, not an error."""
        self._assert_open()
        kb = _kb(key)
        self._record_previous(kb)
        self._cur_blobs.pop(kb, None)
        self._changes[kb] = None

    # -- order book ---------------------------------------------------------
    def _all_offers_for_book(self, selling: Asset,
                             buying: Asset) -> Dict[bytes, LedgerEntry]:
        out = self._parent._all_offers_for_book(selling, buying)
        sb = (selling.to_xdr(), buying.to_xdr())
        for kb, e in self._changes.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            if e is None:
                out.pop(kb, None)
            else:
                o = e.data.value
                if (o.selling.to_xdr(), o.buying.to_xdr()) == sb:
                    out[kb] = e
                else:
                    out.pop(kb, None)
        return out

    def best_offer(self, selling: Asset, buying: Asset,
                   exclude: Optional[set] = None) -> Optional[LedgerEntry]:
        """Best (lowest-price) offer in the book, excluding offer ids in
        `exclude`."""
        self._assert_open()
        offers = self._all_offers_for_book(selling, buying)
        best: Optional[LedgerEntry] = None
        for e in offers.values():
            o = e.data.value
            if exclude and o.offerID in exclude:
                continue
            if best is None or price_less(o, best.data.value):
                best = e
        return best

    def _offers_by_account(self, account_id) -> Dict[bytes, LedgerEntry]:
        out = self._parent._offers_by_account(account_id)
        acc = account_id.to_xdr()
        for kb, e in self._changes.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            if e is None:
                out.pop(kb, None)
            elif e.data.value.sellerID.to_xdr() == acc:
                out[kb] = e
            else:
                out.pop(kb, None)
        return out

    def load_offers_by_account(self, account_id,
                               asset: Optional[Asset] = None
                               ) -> List[LedgerEntry]:
        """Load (for update) the account's offers; with `asset`, only
        offers buying or selling it (reference
        loadOffersByAccountAndAsset, LedgerTxn.h)."""
        self._assert_open()
        res = []
        for kb, view in list(self._offers_by_account(account_id).items()):
            if asset is not None:
                o = view.data.value
                # filter on the view BEFORE load(): non-matching offers
                # must not be copied or recorded in the delta
                if o.selling != asset and o.buying != asset:
                    continue
            e = self.load(LedgerKey.from_xdr(kb))
            if e is not None:
                res.append(e)
        return res

    def _all_offers(self) -> Dict[bytes, LedgerEntry]:
        out = self._parent._all_offers()
        for kb, e in self._changes.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            if e is None:
                out.pop(kb, None)
            else:
                out[kb] = e
        return out

    def load_all_offers(self) -> List[LedgerEntry]:
        """Load (for update) every offer in the ledger (reference
        loadAllOffers, LedgerTxn.h — liability-upgrade path)."""
        self._assert_open()
        res = []
        for kb in list(self._all_offers()):
            e = self.load(LedgerKey.from_xdr(kb))
            if e is not None:
                res.append(e)
        return res

    def query_inflation_winners(self, max_winners: int,
                                min_votes: int) -> List[Tuple[bytes, int]]:
        """[(accountID key_bytes, votes)] for inflation destinations with
        at least `min_votes` of balance-weighted votes, sorted votes
        descending (ties: account key descending), capped at
        `max_winners` (reference queryInflationWinners, LedgerTxn.cpp —
        including uncommitted changes in this txn chain, which the SQL
        root alone cannot see)."""
        self._assert_open()
        # innermost change wins: collect ancestor overlays root-first
        chain: List["LedgerTxn"] = []
        node: AbstractLedgerTxnParent = self
        while isinstance(node, LedgerTxn):
            chain.append(node)
            node = node._parent
        merged: Dict[bytes, Optional[LedgerEntry]] = dict(
            node._all_accounts())
        for txn in reversed(chain):
            for kb, e in txn._changes.items():
                if LedgerKey.from_xdr(kb).disc == LedgerEntryType.ACCOUNT:
                    merged[kb] = e
        votes: Dict[bytes, int] = {}
        for e in merged.values():
            if e is None:
                continue
            acc = e.data.value
            if acc.inflationDest is not None:
                k = acc.inflationDest.key_bytes
                votes[k] = votes.get(k, 0) + acc.balance
        winners = sorted(
            ((k, v) for k, v in votes.items() if v >= min_votes),
            key=lambda kv: (-kv[1], tuple(
                -c for c in strkey.encode_public_key(kv[0]).encode())))
        return winners[:max_winners]

    # -- lifecycle ----------------------------------------------------------
    def commit(self) -> None:
        self._assert_open()
        # seal only after commit_child succeeds: a transient failure there
        # (e.g. sqlite "database is locked" at the root) must leave this
        # txn open and registered so the caller can roll back — otherwise
        # the parent's child slot is bricked for every future txn
        self._parent.commit_child(self._changes, self._header,
                                  self._cur_blobs or None)
        self._open = False
        self._parent._clear_child(self)

    def rollback(self) -> None:
        assert self._open
        if self._child is not None:
            self._child.rollback()
        self._open = False
        self._changes.clear()
        self._prev_objs.clear()
        self._cur_blobs.clear()
        self._parent._clear_child(self)

    def commit_child(self, changes: Dict[bytes, Optional[LedgerEntry]],
                     header: LedgerHeader,
                     blobs: Optional[Dict[bytes, bytes]] = None) -> None:
        for kb, e in changes.items():
            self._record_previous(kb)
            b = blobs.get(kb) if (blobs and e is not None) else None
            if b is not None:
                self._cur_blobs[kb] = b
            else:
                self._cur_blobs.pop(kb, None)
            self._changes[kb] = e
        # adopt the child's header VALUES in place: callers hold references
        # from load_header(), and replacing the object would silently orphan
        # their later mutations (close_ledger sets txSetResultHash /
        # bucketListHash after per-tx child commits)
        new = _copy_header(header)
        for n, _t in type(self._header).xdr_fields:
            setattr(self._header, n, getattr(new, n))

    # -- delta (meta + invariants) ------------------------------------------
    def get_delta(self, need_prev: bool = True, raw_keys: bool = False
                  ) -> List[Tuple[LedgerKey, Optional[LedgerEntry],
                                  Optional[LedgerEntry]]]:
        """[(key, previous, current)] for every touched-and-changed entry.

        need_prev=False skips materializing the parsed pre-image for
        native-injected deltas (blob-only): `previous` is then the raw
        pre-image XDR for those entries — callers that only test
        `prev is None` (the close's init/live/dead split) must not read
        into it. Parsed pre-images recorded by load() are returned parsed
        either way.

        raw_keys=True returns the raw LedgerKey XDR instead of a parsed
        LedgerKey — the close path only needs key OBJECTS for deleted
        entries (bucket dead keys), so it parses those itself instead of
        paying ~one parse per touched account per close.

        The returned `current` entries are the LIVE _changes objects and
        must be treated READ-ONLY: unlike get_entry/load, this path does
        not invalidate _cur_blobs, so a caller mutating an entry through
        the delta would desynchronize the cached serialized form the
        commit path reuses."""
        out = []
        for kb, cur in self._changes.items():
            prev_b = self._previous.get(kb)
            if cur is None:
                cur_b = None
            else:
                cur_b = self._cur_blobs.get(kb)
                if cur_b is None:
                    cur_b = cur.to_xdr()
            if prev_b == cur_b:
                continue  # touched but unchanged
            if prev_b:
                prev = self._prev_objs.get(kb)
                if prev is None:   # injected native delta: blob only
                    prev = prev_b if not need_prev \
                        else LedgerEntry.from_xdr(prev_b)
            else:
                prev = None
            key = kb if raw_keys else LedgerKey.from_xdr(kb)
            out.append((key, prev, cur))
        return out

    def has_changes(self) -> bool:
        return bool(self._changes)

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if self._open:
            if et is None:
                self.commit()
            else:
                self.rollback()
        return False


class InMemoryLedgerTxnRoot(AbstractLedgerTxnParent):
    """Dict-backed root (reference InMemoryLedgerTxnRoot.h role; used by
    standalone/test mode)."""

    def __init__(self, header: Optional[LedgerHeader] = None) -> None:
        self._entries: Dict[bytes, bytes] = {}
        self._header = header

    def set_header(self, header: LedgerHeader) -> None:
        self._header = header

    def get_header(self) -> LedgerHeader:
        assert self._header is not None
        return self._header

    def get_entry(self, key: LedgerKey) -> Optional[LedgerEntry]:
        b = self._entries.get(_kb(key))
        return LedgerEntry.from_xdr(b) if b is not None else None

    def get_entry_blob(self, kb: bytes) -> Optional[bytes]:
        """Raw LedgerEntry XDR by key XDR — the native apply engine's
        lookup callback (no parse, no copy)."""
        return self._entries.get(kb)

    def offers_for_book_blobs(self, selling_xdr: bytes,
                              buying_xdr: bytes) -> List[bytes]:
        """Raw offer-entry blobs for one (selling, buying) book — the
        native engine's `book` callback. The engine merges its own
        overlay (created/modified/erased offers) on top; this returns
        only close-start root state."""
        out: List[bytes] = []
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            e = LedgerEntry.from_xdr(eb)
            o = e.data.value
            if o.selling.to_xdr() == selling_xdr and \
                    o.buying.to_xdr() == buying_xdr:
                out.append(eb)
        return out

    def offers_by_account_blobs(self, account_key: bytes) -> List[bytes]:
        """Raw offer-entry blobs of one seller (ed25519 key bytes) —
        the native engine's `acct_offers` callback (allow-trust
        revokes). Root order matches `_offers_by_account`, so the
        engine's merged iteration order equals the Python path's."""
        out: List[bytes] = []
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            e = LedgerEntry.from_xdr(eb)
            if e.data.value.sellerID.key_bytes == account_key:
                out.append(eb)
        return out

    def _all_offers_for_book(self, selling, buying):
        out: Dict[bytes, LedgerEntry] = {}
        sb = (selling.to_xdr(), buying.to_xdr())
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            e = LedgerEntry.from_xdr(eb)
            o = e.data.value
            if (o.selling.to_xdr(), o.buying.to_xdr()) == sb:
                out[kb] = e
        return out

    def _offers_by_account(self, account_id):
        out: Dict[bytes, LedgerEntry] = {}
        acc = account_id.to_xdr()
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc != LedgerEntryType.OFFER:
                continue
            e = LedgerEntry.from_xdr(eb)
            if e.data.value.sellerID.to_xdr() == acc:
                out[kb] = e
        return out

    def _all_offers(self):
        out: Dict[bytes, LedgerEntry] = {}
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc == LedgerEntryType.OFFER:
                out[kb] = LedgerEntry.from_xdr(eb)
        return out

    def _all_accounts(self):
        out: Dict[bytes, LedgerEntry] = {}
        for kb, eb in self._entries.items():
            if LedgerKey.from_xdr(kb).disc == LedgerEntryType.ACCOUNT:
                out[kb] = LedgerEntry.from_xdr(eb)
        return out

    def commit_child(self, changes, header, blobs=None) -> None:
        for kb, e in changes.items():
            if e is None:
                self._entries.pop(kb, None)
            else:
                b = blobs.get(kb) if blobs else None
                self._entries[kb] = b if b is not None else e.to_xdr()
        self._header = header

    def count_entries(self) -> int:
        return len(self._entries)

    def all_entries(self) -> Iterator[LedgerEntry]:
        for eb in self._entries.values():
            yield LedgerEntry.from_xdr(eb)

    def clear_entries(self) -> None:
        """Drop all ledger entries (bucket-apply catchup resets state
        before loading the snapshot)."""
        self._entries.clear()


_ENTRY_TYPE_NAMES = {
    LedgerEntryType.ACCOUNT: "account",
    LedgerEntryType.TRUSTLINE: "trustline",
    LedgerEntryType.OFFER: "offer",
    LedgerEntryType.DATA: "data",
}


class LedgerTxnRoot(AbstractLedgerTxnParent):
    """SQL-backed root with an entry cache and per-type bulk writers
    (reference LedgerTxnRoot + LedgerTxn{Account,Offer,TrustLine,Data}SQL).

    BucketDB routing (ISSUE 14, ROADMAP item 4): with a BucketDB
    attached (`attach_bucketdb`), point reads that miss the entry cache
    are served from the bloom-filtered bucket indexes instead of SQL —
    SQL stays the write-behind query index (bulk order-book scans,
    history, operator queries) and is only consulted for point reads
    when a `bucketdb.read-fail` degrade makes a bucket read
    non-authoritative. The entry cache itself is a true-LRU bound
    (ISSUE 14 satellite) whose evictions are metered, and the prefetch
    bulk-warm resolves a whole txset's keys in one batched pass per
    bucket level.

    `stats` (ledger/apply_stats.py ApplyStats) is the close cockpit's
    state-read telemetry: per-type SQL point lookups, entry-cache
    hit/miss/eviction, bucket-served reads, prefetch coverage and
    hit-rate (reference getPrefetchHitRate parity), bulk-scan row
    counts. Every hook is a no-op when no stats object is wired (tests,
    standalone tools)."""

    ENTRY_CACHE_SIZE = 4096

    def __init__(self, db: Database,
                 header: Optional[LedgerHeader] = None,
                 stats=None) -> None:
        self._db = db
        self._header = header
        self._cache: LRUCache = LRUCache(self.ENTRY_CACHE_SIZE,
                                         on_evict=self._on_cache_evict)
        self._stats = stats
        self._bucketdb = None
        # keys warmed by prefetch(): a later cache-hit on one counts as a
        # prefetch hit, a fallthrough load counts as a prefetch miss
        # (reference LedgerTxnRoot::getPrefetchHitRate). LRU-bounded at a
        # few multiples of the cache it describes — evicting the oldest
        # keys one by one instead of clearing wholesale (the old
        # bounded-set half-cache budget degraded to silent coverage loss
        # exactly when hot state outgrew it).
        self._prefetched: "OrderedDict[bytes, bool]" = OrderedDict()

    def set_header(self, header: LedgerHeader) -> None:
        self._header = header

    def get_header(self) -> LedgerHeader:
        assert self._header is not None
        return self._header

    # -- BucketDB attachment -------------------------------------------------
    def attach_bucketdb(self, bucketdb) -> None:
        """Route point reads through `bucketdb` (bucket/bucket_index.py).
        Only valid while the bucket list covers this root's entire
        entry state (enabled-before-genesis, or restored from a HAS
        that matches the LCL header) — Application.enable_buckets and
        LedgerManager enforce that."""
        self._bucketdb = bucketdb

    def detach_bucketdb(self) -> None:
        """Fall back to SQL point reads (bucket-list restore failed or
        the list is otherwise not authoritative for this state)."""
        self._bucketdb = None

    def bucket_backed(self) -> bool:
        return self._bucketdb is not None

    # -- reads --------------------------------------------------------------
    def _on_cache_evict(self, kb: bytes) -> None:
        if self._stats is not None:
            self._stats.record_cache_evictions()

    def _note_prefetched(self, kb: bytes) -> None:
        pf = self._prefetched
        pf[kb] = True
        pf.move_to_end(kb)
        while len(pf) > 4 * self.ENTRY_CACHE_SIZE:
            pf.popitem(last=False)

    def _load_blob(self, key: Optional[LedgerKey], kb: bytes
                   ) -> Tuple[Optional[bytes], str, Optional[LedgerKey]]:
        """One cache-missing point read: (blob|None, serving source,
        parsed key | None). BucketDB first when attached; SQL only when
        no BucketDB is attached or the bucket read degraded
        (`bucketdb.read-fail`). The key is parsed at most once — it is
        returned so the caller can name the entry type for the SQL
        lookup meters without re-parsing."""
        bdb = self._bucketdb
        if bdb is not None:
            served, blob = bdb.lookup(kb)
            if served:
                return blob, "bucket", key
        if key is None:
            key = LedgerKey.from_xdr(kb)
        return self._select_blob(key), "sql", key

    def get_entry(self, key: LedgerKey) -> Optional[LedgerEntry]:
        kb = _kb(key)
        hit = self._cache.maybe_get(kb)
        st = self._stats
        if hit is not None:
            blob = hit
            if st is not None:
                st.record_read(True, kb in self._prefetched)
        else:
            blob, source, _key = self._load_blob(key, kb)
            self._cache.put(kb, blob if blob is not None else b"")
            if st is not None:
                st.record_read(False, False,
                               _ENTRY_TYPE_NAMES.get(key.disc, "unknown"),
                               source=source)
        if not blob:
            return None
        return LedgerEntry.from_xdr(blob)

    def get_entry_blob(self, kb: bytes) -> Optional[bytes]:
        """Raw LedgerEntry XDR by key XDR, through the entry cache — the
        native apply engine's lookup callback."""
        hit = self._cache.maybe_get(kb)
        st = self._stats
        if hit is not None:
            if st is not None:
                st.record_read(True, kb in self._prefetched)
            return hit or None
        blob, source, pkey = self._load_blob(None, kb)
        self._cache.put(kb, blob if blob is not None else b"")
        if st is not None:
            # the key parse is only needed to NAME a SQL lookup's entry
            # type; bucket-served reads never parse it at all (this is
            # the native engine's per-entry hot path), and the SQL path
            # reuses _load_blob's parse
            etype = None if pkey is None else \
                _ENTRY_TYPE_NAMES.get(pkey.disc, "unknown")
            st.record_read(False, False, etype, source=source)
        return blob

    def offers_for_book_blobs(self, selling_xdr: bytes,
                              buying_xdr: bytes) -> List[bytes]:
        """Raw offer blobs for one book (native engine `book`
        callback); same SQL the Python path's `_all_offers_for_book`
        runs, counted into the same bulk-scan telemetry."""
        import base64
        cur = self._db.execute(
            "SELECT entry FROM offers WHERE selling=? AND buying=?",
            (base64.b64encode(selling_xdr).decode(),
             base64.b64encode(buying_xdr).decode()))
        return [blob for (blob,) in self._record_scan(cur.fetchall())]

    def offers_by_account_blobs(self, account_key: bytes) -> List[bytes]:
        """Raw offer blobs of one seller (ed25519 key bytes) — native
        engine `acct_offers` callback. Row order (the seller index →
        offerid) matches `_offers_by_account`, so the engine's merged
        iteration order equals the Python path's."""
        from ..xdr import PublicKey
        cur = self._db.execute(
            "SELECT entry FROM offers WHERE sellerid=?",
            (_acc_str(PublicKey.ed25519(account_key)),))
        return [blob for (blob,) in self._record_scan(cur.fetchall())]

    def _select_blob(self, key: LedgerKey) -> Optional[bytes]:
        t = key.disc
        v = key.value
        if t == LedgerEntryType.ACCOUNT:
            cur = self._db.execute(
                "SELECT entry FROM accounts WHERE accountid=?",
                (_acc_str(v.accountID),))
        elif t == LedgerEntryType.TRUSTLINE:
            cur = self._db.execute(
                "SELECT entry FROM trustlines WHERE accountid=? AND asset=?",
                (_acc_str(v.accountID), _asset_str(v.asset)))
        elif t == LedgerEntryType.OFFER:
            cur = self._db.execute(
                "SELECT entry FROM offers WHERE offerid=?", (v.offerID,))
        elif t == LedgerEntryType.DATA:
            cur = self._db.execute(
                "SELECT entry FROM accountdata WHERE accountid=? AND "
                "dataname=?", (_acc_str(v.accountID), v.dataName))
        else:
            raise ValueError("bad key type %d" % t)
        row = cur.fetchone()
        return row[0] if row else None

    def _record_scan(self, rows) -> list:
        if self._stats is not None:
            self._stats.record_bulk_scan(len(rows))
        return rows

    def _all_offers_for_book(self, selling, buying):
        out: Dict[bytes, LedgerEntry] = {}
        cur = self._db.execute(
            "SELECT entry FROM offers WHERE selling=? AND buying=?",
            (_asset_str(selling), _asset_str(buying)))
        for (blob,) in self._record_scan(cur.fetchall()):
            e = LedgerEntry.from_xdr(blob)
            out[_kb(ledger_entry_key(e))] = e
        return out

    def _offers_by_account(self, account_id):
        out: Dict[bytes, LedgerEntry] = {}
        cur = self._db.execute(
            "SELECT entry FROM offers WHERE sellerid=?",
            (_acc_str(account_id),))
        for (blob,) in self._record_scan(cur.fetchall()):
            e = LedgerEntry.from_xdr(blob)
            out[_kb(ledger_entry_key(e))] = e
        return out

    def _all_offers(self):
        out: Dict[bytes, LedgerEntry] = {}
        for (blob,) in self._record_scan(self._db.execute(
                "SELECT entry FROM offers").fetchall()):
            e = LedgerEntry.from_xdr(blob)
            out[_kb(ledger_entry_key(e))] = e
        return out

    def _all_accounts(self):
        out: Dict[bytes, LedgerEntry] = {}
        for (blob,) in self._record_scan(self._db.execute(
                "SELECT entry FROM accounts").fetchall()):
            e = LedgerEntry.from_xdr(blob)
            out[_kb(ledger_entry_key(e))] = e
        return out

    def prefetch(self, keys) -> int:
        """Bulk-warm the entry cache for `keys`; returns how many were
        actually cached (reference LedgerTxnRoot::prefetch,
        LedgerTxn.cpp — stops loading when the cache is half full so
        prefetch can't evict the working set). Coverage — keys resident
        afterwards (already warm + newly loaded) over keys requested —
        feeds `ledger.apply.prefetch.coverage-pct`; later root reads of
        prefetched keys count into the getPrefetchHitRate-parity
        hit/miss meters.

        With a BucketDB attached, the cold keys resolve in ONE batched
        pass per bucket level (bloom-filtered, newest-level-first —
        bucket/bucket_index.py prefetch_batch) instead of one multi-level
        walk per key; the warmed cache then feeds the native engine its
        entry blobs directly through `get_entry_blob`."""
        budget = self._cache._max // 2
        n = 0
        requested = 0
        covered = 0
        note = self._stats is not None
        loads: Dict[str, int] = {}
        bucket_loads = 0
        # pass 1: split warm keys from cold ones; cold collection stops
        # at the half-cache budget (remaining keys only count coverage,
        # exactly like the old per-key walk)
        room = max(0, budget - len(self._cache))
        cold: List[Tuple[LedgerKey, bytes]] = []
        for key in keys:
            requested += 1
            kb = _kb(key)
            if self._cache.maybe_get(kb) is not None:
                covered += 1
                if note:
                    self._note_prefetched(kb)
                continue
            if len(cold) >= room:
                continue   # over budget: keep counting coverage only
            cold.append((key, kb))
        # pass 2: resolve every cold key — one batched BucketDB pass per
        # level when attached, per-key SQL otherwise (or on degrade)
        resolved: Dict[bytes, Optional[bytes]] = {}
        bdb = self._bucketdb
        if bdb is not None and cold:
            served, resolved = bdb.prefetch_batch([kb for _k, kb in cold])
            if not served:
                resolved = {}   # degraded: fall back to per-key SQL
        for key, kb in cold:
            if kb in resolved:
                blob = resolved[kb]
                bucket_loads += 1
            else:
                blob = self._select_blob(key)
                if note:
                    name = _ENTRY_TYPE_NAMES.get(key.disc, "unknown")
                    loads[name] = loads.get(name, 0) + 1
            self._cache.put(kb, blob if blob is not None else b"")
            if note:
                self._note_prefetched(kb)
            n += 1
            covered += 1
        if self._stats is not None:
            self._stats.record_prefetch(requested, covered, loads,
                                        bucket_loads=bucket_loads)
        return n

    def clear_entries(self) -> None:
        """Drop all ledger entries + cache (bucket-apply catchup resets
        state before loading the snapshot)."""
        with self._db.transaction():
            for table in ("accounts", "trustlines", "offers",
                          "accountdata"):
                self._db.execute("DELETE FROM %s" % table)
        self._cache.clear()

    # -- commit -------------------------------------------------------------
    def commit_child(self, changes, header, blobs=None) -> None:
        with self._db.transaction():
            for kb, e in changes.items():
                key = LedgerKey.from_xdr(kb)
                if e is None:
                    self._delete(key)
                    self._cache.put(kb, b"")
                else:
                    b = blobs.get(kb) if blobs else None
                    if b is None:
                        b = e.to_xdr()
                    self._upsert(key, e, b)
                    self._cache.put(kb, b)
            self._header = header

    def _delete(self, key: LedgerKey) -> None:
        t, v = key.disc, key.value
        if t == LedgerEntryType.ACCOUNT:
            self._db.execute("DELETE FROM accounts WHERE accountid=?",
                             (_acc_str(v.accountID),))
        elif t == LedgerEntryType.TRUSTLINE:
            self._db.execute(
                "DELETE FROM trustlines WHERE accountid=? AND asset=?",
                (_acc_str(v.accountID), _asset_str(v.asset)))
        elif t == LedgerEntryType.OFFER:
            self._db.execute("DELETE FROM offers WHERE offerid=?",
                             (v.offerID,))
        elif t == LedgerEntryType.DATA:
            self._db.execute(
                "DELETE FROM accountdata WHERE accountid=? AND dataname=?",
                (_acc_str(v.accountID), v.dataName))

    def _upsert(self, key: LedgerKey, e: LedgerEntry,
                blob: Optional[bytes] = None) -> None:
        t = key.disc
        if blob is None:
            blob = e.to_xdr()
        lm = e.lastModifiedLedgerSeq
        d = e.data.value
        if t == LedgerEntryType.ACCOUNT:
            self._db.execute(
                "INSERT INTO accounts (accountid,balance,seqnum,"
                "numsubentries,flags,lastmodified,entry) VALUES (?,?,?,?,?,?,?)"
                " ON CONFLICT(accountid) DO UPDATE SET balance=excluded."
                "balance,seqnum=excluded.seqnum,numsubentries=excluded."
                "numsubentries,flags=excluded.flags,lastmodified=excluded."
                "lastmodified,entry=excluded.entry",
                (_acc_str(d.accountID), d.balance, d.seqNum, d.numSubEntries,
                 d.flags, lm, blob))
        elif t == LedgerEntryType.TRUSTLINE:
            self._db.execute(
                "INSERT INTO trustlines (accountid,asset,balance,flags,"
                "lastmodified,entry) VALUES (?,?,?,?,?,?)"
                " ON CONFLICT(accountid,asset) DO UPDATE SET balance="
                "excluded.balance,flags=excluded.flags,lastmodified="
                "excluded.lastmodified,entry=excluded.entry",
                (_acc_str(d.accountID), _asset_str(d.asset), d.balance,
                 d.flags, lm, blob))
        elif t == LedgerEntryType.OFFER:
            self._db.execute(
                "INSERT INTO offers (sellerid,offerid,selling,buying,amount,"
                "pricen,priced,price,flags,lastmodified,entry) "
                "VALUES (?,?,?,?,?,?,?,?,?,?,?)"
                " ON CONFLICT(offerid) DO UPDATE SET sellerid=excluded."
                "sellerid,selling=excluded.selling,buying=excluded.buying,"
                "amount=excluded.amount,pricen=excluded.pricen,priced="
                "excluded.priced,price=excluded.price,flags=excluded.flags,"
                "lastmodified=excluded.lastmodified,entry=excluded.entry",
                (_acc_str(d.sellerID), d.offerID, _asset_str(d.selling),
                 _asset_str(d.buying), d.amount, d.price.n, d.price.d,
                 d.price.n / d.price.d, d.flags, lm, blob))
        elif t == LedgerEntryType.DATA:
            self._db.execute(
                "INSERT INTO accountdata (accountid,dataname,lastmodified,"
                "entry) VALUES (?,?,?,?)"
                " ON CONFLICT(accountid,dataname) DO UPDATE SET lastmodified"
                "=excluded.lastmodified,entry=excluded.entry",
                (_acc_str(d.accountID), d.dataName, lm, blob))

    def count_entries(self) -> int:
        n = 0
        for table in ("accounts", "trustlines", "offers", "accountdata"):
            n += self._db.execute(
                "SELECT COUNT(*) FROM %s" % table).fetchone()[0]
        return n

    def all_entries(self) -> Iterator[LedgerEntry]:
        for table in ("accounts", "trustlines", "offers", "accountdata"):
            for (blob,) in self._db.execute(
                    "SELECT entry FROM %s" % table).fetchall():
                yield LedgerEntry.from_xdr(blob)


def delta_to_changes(delta) -> list:
    """LedgerTxn delta triples → LedgerEntryChanges wire form (reference
    meta convention: CREATED alone; STATE pre-image before
    UPDATED/REMOVED). Feeds TransactionMeta and txfeehistory rows."""
    from ..xdr import LedgerEntryChange, LedgerEntryChangeType as CT
    out = []
    for key, prev, cur in delta:
        if prev is None and cur is not None:
            out.append(LedgerEntryChange(CT.LEDGER_ENTRY_CREATED, cur))
        elif cur is None:
            out.append(LedgerEntryChange(CT.LEDGER_ENTRY_STATE, prev))
            out.append(LedgerEntryChange(CT.LEDGER_ENTRY_REMOVED, key))
        else:
            out.append(LedgerEntryChange(CT.LEDGER_ENTRY_STATE, prev))
            out.append(LedgerEntryChange(CT.LEDGER_ENTRY_UPDATED, cur))
    return out
