"""LedgerCloseMeta output stream: full per-close meta for downstream
consumers (Horizon-style ingestion pipelines).

Role parity: reference `METADATA_OUTPUT_STREAM` config knob
(`src/main/Config.h:264`) and the emission sites in
`src/ledger/LedgerManagerImpl.cpp:590,673-678` — the reference opens the
configured fd/file at startup and writes one XDR `LedgerCloseMeta` record
after every successful ledger close; tested by
`src/ledger/test/LedgerCloseMetaStreamTests.cpp`.

Stream format: RFC 5531 record marks (4-byte big-endian length, high bit
set), the same framing `util/xdrstream.py` uses for history checkpoint
files — a downstream reader needs exactly one framing implementation for
both surfaces.

Crash safety: each record is pre-assembled (mark + body) into one buffer
before any write, so records are emitted back to back and a crash can
only tear the TRAILING record — a large record may still take several
os.write calls, so tearing mid-record IS possible and the reader is
built for it: `read_close_meta_stream` tolerates a truncated tail
(returns every complete record and reports the torn one) instead of
raising, matching how the reference's consumers resume after a crash
(they re-request the last ledger and overwrite).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Tuple

from ..util.log import get_logger
from ..xdr import LedgerCloseMeta

log = get_logger("Ledger")

_MARK = struct.Struct(">I")
_LAST_FRAG = 0x80000000


class CloseMetaStream:
    """Writer end. `target` is the config string: a filesystem path
    (truncated at open, like the reference's file mode) or "fd:N" for an
    inherited file descriptor (the operator's pipe)."""

    def __init__(self, target: str) -> None:
        self.target = target
        self._owns_fd = False
        if target.startswith("fd:"):
            self._fd = int(target[3:])
        else:
            self._fd = os.open(target,
                               os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            self._owns_fd = True

    def write_one(self, meta) -> None:
        """One close's meta, framed, from one pre-assembled buffer."""
        body = meta.to_xdr()
        buf = _MARK.pack(len(body) | _LAST_FRAG) + body
        view = memoryview(buf)
        while view:
            n = os.write(self._fd, view)
            view = view[n:]

    def close(self) -> None:
        if self._owns_fd and self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


def read_close_meta_stream(path_or_fd) -> Tuple[List, Optional[str]]:
    """Reader end (the downstream consumer's side, and the test oracle).

    Returns (records, tail_error): every complete LedgerCloseMeta in
    order, plus a description of a torn trailing record if the stream
    ends mid-frame (None for a clean end).
    """
    if isinstance(path_or_fd, int):
        f = os.fdopen(os.dup(path_or_fd), "rb")
    else:
        f = open(path_or_fd, "rb")
    out: List = []
    try:
        while True:
            hdr = f.read(4)
            if not hdr:
                return out, None
            if len(hdr) < 4:
                return out, "torn record mark (%d bytes)" % len(hdr)
            n = _MARK.unpack(hdr)[0]
            if not (n & _LAST_FRAG):
                return out, "bad record mark 0x%08x" % n
            n &= ~_LAST_FRAG
            body = f.read(n)
            if len(body) < n:
                return out, "torn record body (%d of %d bytes)" % (
                    len(body), n)
            out.append(LedgerCloseMeta.from_xdr(body))
    finally:
        f.close()


def iter_close_meta(path_or_fd) -> Iterator:
    """Convenience: yield complete records, silently stopping at a torn
    tail."""
    records, _err = read_close_meta_stream(path_or_fd)
    yield from records
