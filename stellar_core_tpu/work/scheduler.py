"""WorkScheduler: cranks the work tree one step per main-loop turn.

Role parity: reference `src/work/WorkScheduler.cpp:39-69` — posts a single
crank to the io_context per turn so long work trees never starve consensus.
"""

from __future__ import annotations

from typing import List, Optional

from ..util.timer import VirtualClock
from .basic_work import BasicWork, State


class WorkScheduler:
    def __init__(self, clock: VirtualClock) -> None:
        self.clock = clock
        self._roots: List[BasicWork] = []
        self._scheduled = False

    def schedule_work(self, work: BasicWork, on_done=None) -> BasicWork:
        work.start(on_done)
        work.set_wake_cb(self._schedule_crank)
        self._roots.append(work)
        self._schedule_crank()
        return work

    def _schedule_crank(self) -> None:
        if self._scheduled:
            return
        self._scheduled = True
        self.clock.post(self._crank)

    def _crank(self) -> None:
        self._scheduled = False
        live = [w for w in self._roots if not w.is_done()]
        for w in live:
            w.crank_work()
        self._roots = [w for w in self._roots if not w.is_done()]
        # repost only while a root can actually take a step: parked
        # (WAITING/RETRYING) roots re-arm via their wake_cb, and an idle
        # action queue is what lets the virtual clock advance to the
        # retry/backoff timers those roots are sleeping on
        if any(w.is_crankable() for w in self._roots):
            self._schedule_crank()

    def all_done(self) -> bool:
        return not self._roots

    def abort_all(self) -> None:
        for w in self._roots:
            w.abort()
        self._schedule_crank()
