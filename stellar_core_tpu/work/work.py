"""Work trees: Work (children), WorkSequence, BatchWork, ConditionalWork.

Role parity: reference `src/work/Work.{h,cpp}`, `WorkSequence.cpp`,
`BatchWork.cpp` (bounded-concurrency yieldMoreWork), `ConditionalWork.cpp`.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

from .basic_work import FAILURE, RUNNING, SUCCESS, WAITING, BasicWork, State


class Work(BasicWork):
    """A work node with children: runs children to completion (cranking one
    pending child per step), then does its own do_work."""

    def __init__(self, clock, name, max_retries=5) -> None:
        super().__init__(clock, name, max_retries)
        self.children: List[BasicWork] = []

    def add_work(self, w: BasicWork) -> BasicWork:
        w._parent = self
        self.children.append(w)
        if w.state == State.PENDING:
            w.start()
        return w

    def on_reset(self) -> None:
        self.children.clear()
        self.do_reset()

    def do_reset(self) -> None:
        pass

    def do_work(self) -> State:
        return SUCCESS

    def _any_failed(self) -> bool:
        return any(c.state in (State.FAILURE, State.ABORTED)
                   for c in self.children)

    def _all_done(self) -> bool:
        return all(c.is_done() for c in self.children)

    def on_run(self) -> State:
        for c in self.children:
            if c.is_crankable():
                c.crank_work()
                break
        if self._any_failed():
            return FAILURE
        if self._all_done():
            return self.do_work()
        # every live child is WAITING/RETRYING: park; their wake_up (or
        # retry timer) propagates up and re-arms this work — busy-cranking
        # here would pin the virtual clock and starve those very timers
        if any(c.is_crankable() for c in self.children):
            return RUNNING
        return WAITING


class WorkSequence(BasicWork):
    """Children executed strictly in order (reference WorkSequence)."""

    def __init__(self, clock, name, sequence: List[BasicWork],
                 max_retries=5) -> None:
        super().__init__(clock, name, max_retries)
        self.sequence = sequence
        self._idx = 0
        for w in sequence:
            w._parent = self

    def on_reset(self) -> None:
        self._idx = 0
        for w in self.sequence:
            if w.is_done():
                w.state = State.PENDING   # re-armed on next on_run

    def on_run(self) -> State:
        if self._idx >= len(self.sequence):
            return SUCCESS
        cur = self.sequence[self._idx]
        if cur.state == State.PENDING:
            cur._parent = self
            cur.start()
        if not cur.is_done():
            cur.crank_work()
            if not cur.is_done():
                # park while the child WAITs/RETRIes; its wake_up (or
                # retry timer) re-arms this sequence
                return RUNNING if cur.is_crankable() else WAITING
        if cur.state != State.SUCCESS:
            return FAILURE
        self._idx += 1
        return RUNNING if self._idx < len(self.sequence) else SUCCESS


class BatchWork(Work):
    """Bounded-concurrency batch: keeps up to `max_concurrent` children
    running, pulling new ones from yield_more_work (reference BatchWork)."""

    def __init__(self, clock, name, max_concurrent: int = 8,
                 max_retries=5) -> None:
        super().__init__(clock, name, max_retries)
        self.max_concurrent = max_concurrent
        self._exhausted = False

    def yield_more_work(self) -> Optional[BasicWork]:
        raise NotImplementedError

    def on_reset(self) -> None:
        self.children.clear()
        self._exhausted = False
        self.do_reset()

    def on_run(self) -> State:
        # harvest finished, fail fast
        if self._any_failed():
            return FAILURE
        self.children = [c for c in self.children if not c.is_done()]
        while not self._exhausted and \
                len(self.children) < self.max_concurrent:
            w = self.yield_more_work()
            if w is None:
                self._exhausted = True
                break
            self.add_work(w)
        for c in self.children:
            if c.is_crankable():
                c.crank_work()
        if self.children:
            if any(c.is_crankable() or c.is_done() for c in self.children):
                return RUNNING   # finished children are harvested next crank
            return WAITING       # all blocked; children wake us
        return self.do_work() if self._exhausted else RUNNING


class ConditionalWork(BasicWork):
    """Runs inner work once a condition becomes true (reference
    ConditionalWork)."""

    # re-check cadence while parked on a false condition (reference
    # ConditionalWork sleepDelay); virtual seconds cost nothing in tests
    POLL_DELAY = 0.1

    def __init__(self, clock, name, condition: Callable[[], bool],
                 inner: BasicWork) -> None:
        super().__init__(clock, name, 0)
        self.condition = condition
        self.inner = inner
        self._condition_met = False   # latched once true (reference
        inner._parent = self          # ConditionalWork clears mConditionFn)
        from ..util.timer import VirtualTimer
        self._poll_timer = VirtualTimer(clock)

    def on_reset(self) -> None:
        self._condition_met = False
        if self.inner.is_done():
            self.inner.state = State.PENDING   # re-armed when gate opens

    def on_run(self) -> State:
        if not self._condition_met:
            if not self.condition():
                # park instead of busy-polling (the poll would pin the
                # scheduler and starve sibling retry timers); the timer
                # re-checks on a cadence
                self._poll_timer.expires_from_now(self.POLL_DELAY)
                self._poll_timer.async_wait(self.wake_up)
                return WAITING
            self._condition_met = True
        if self.inner.state == State.PENDING:
            self.inner.start()
        if not self.inner.is_done():
            self.inner.crank_work()
            if not self.inner.is_done():
                return RUNNING if self.inner.is_crankable() else WAITING
        return SUCCESS if self.inner.state == State.SUCCESS else FAILURE


class FunctionWork(BasicWork):
    """Small adapter: run a callable once (used by tests and simple steps)."""

    def __init__(self, clock, name, fn: Callable[[], bool],
                 max_retries=0) -> None:
        super().__init__(clock, name, max_retries)
        self.fn = fn

    def on_run(self) -> State:
        return SUCCESS if self.fn() else FAILURE
