"""BasicWork: cooperative async job state machine.

Role parity: reference `src/work/BasicWork.{h,cpp}:25-106` — states
PENDING/RUNNING/WAITING/SUCCESS/FAILURE/RETRYING/ABORTING with bounded
retries and exponential backoff; `wakeUp` re-arms WAITING work; one
`onRun` step per crank keeps the main thread responsive.
"""

from __future__ import annotations

from enum import Enum
from typing import Callable, Optional

from ..util.log import get_logger
from ..util.timer import VirtualClock, VirtualTimer

log = get_logger("Work")


class State(Enum):
    PENDING = 0
    RUNNING = 1
    WAITING = 2
    SUCCESS = 3
    FAILURE = 4
    RETRYING = 5
    ABORTING = 6
    ABORTED = 7


# what on_run may return
RUNNING = State.RUNNING
WAITING = State.WAITING
SUCCESS = State.SUCCESS
FAILURE = State.FAILURE


RETRY_NEVER = 0
RETRY_ONCE = 1
RETRY_A_FEW = 5
RETRY_A_LOT = 32


class BasicWork:
    # decorrelated-jitter retry backoff (docs/robustness.md): delay_k is
    # uniform in [BASE, 3 * delay_{k-1}], capped — a fleet of works that
    # failed on one shared cause (archive outage, dead peer) desyncs
    # instead of re-firing as a synchronized retry storm
    RETRY_BACKOFF_BASE = 0.5
    RETRY_BACKOFF_CAP = 256.0

    def __init__(self, clock: VirtualClock, name: str,
                 max_retries: int = RETRY_A_FEW) -> None:
        self.clock = clock
        self.name = name
        self.max_retries = max_retries
        self.retries = 0
        self.state = State.PENDING
        self._retry_timer = VirtualTimer(clock)
        self._last_retry_delay = 0.0
        self._on_done: Optional[Callable[[State], None]] = None

    # -- subclass hooks -----------------------------------------------------
    def on_reset(self) -> None:
        pass

    def on_run(self) -> State:
        raise NotImplementedError

    def on_abort(self) -> bool:
        """Return True when abort is complete."""
        return True

    def on_success(self) -> None:
        pass

    def on_failure_raise(self) -> None:
        pass

    def on_failure_retry(self) -> None:
        pass

    # -- lifecycle ----------------------------------------------------------
    def start(self, on_done: Optional[Callable] = None) -> None:
        assert self.state in (State.PENDING, State.SUCCESS, State.FAILURE,
                              State.ABORTED)
        self._on_done = on_done
        self.retries = 0
        self._last_retry_delay = 0.0
        self.on_reset()
        self.state = State.RUNNING

    def is_done(self) -> bool:
        return self.state in (State.SUCCESS, State.FAILURE, State.ABORTED)

    def is_crankable(self) -> bool:
        """True when crank_work would actually run a step (WAITING and
        RETRYING work only progresses via wake_up / its retry timer)."""
        return self.state in (State.RUNNING, State.ABORTING)

    def crank_work(self) -> None:
        if self.is_done() or self.state in (State.WAITING, State.RETRYING,
                                            State.PENDING):
            return
        if self.state == State.ABORTING:
            if self.on_abort():
                self._finish(State.ABORTED)
            return
        try:
            res = self.on_run()
        except Exception as e:
            log.warning("work %s raised: %s", self.name, e)
            res = State.FAILURE
        if res == State.FAILURE:
            if self.retries < self.max_retries:
                self._schedule_retry()
            else:
                self.on_failure_raise()
                self._finish(State.FAILURE)
        elif res == State.SUCCESS:
            self.on_success()
            self._finish(State.SUCCESS)
        elif res in (State.RUNNING, State.WAITING):
            self.state = res

    def _schedule_retry(self) -> None:
        self.on_failure_retry()
        self.state = State.RETRYING
        from ..util import rnd
        prev = self._last_retry_delay or self.RETRY_BACKOFF_BASE
        delay = min(self.RETRY_BACKOFF_CAP,
                    rnd.g_random.uniform(self.RETRY_BACKOFF_BASE,
                                         prev * 3.0))
        self._last_retry_delay = delay
        self.retries += 1

        def fire() -> None:
            if self.state == State.RETRYING:
                self.on_reset()
                self.state = State.RUNNING
                self.wake_up()

        # always a real timer, virtual clocks included: WAITING/RETRYING
        # propagates up the work tree (work.py) so the scheduler goes
        # idle, the virtual clock advances to this deadline, and the
        # jittered delays keep co-failed works off the same tick
        self._retry_timer.expires_from_now(delay)
        self._retry_timer.async_wait(fire)

    def wake_up(self) -> None:
        if self.state == State.WAITING:
            self.state = State.RUNNING
        cb = getattr(self, "_wake_cb", None)
        if cb is not None:
            cb()
        # a woken child must wake the whole ancestor chain: parents park
        # in WAITING when every child is blocked, and the scheduler only
        # re-cranks on a root wake
        self.wake_up_parent()

    def set_wake_cb(self, cb: Callable[[], None]) -> None:
        self._wake_cb = cb

    def abort(self) -> None:
        if not self.is_done():
            self.state = State.ABORTING

    def _finish(self, st: State) -> None:
        self.state = st
        if self._on_done is not None:
            self._on_done(st)
        self.wake_up_parent()

    def wake_up_parent(self) -> None:
        p = getattr(self, "_parent", None)
        if p is not None:
            p.wake_up()

    def get_status(self) -> str:
        return "%s: %s" % (self.name, self.state.name)
