"""SQL persistence over sqlite3.

Role parity: reference `src/database/Database.{h,cpp}` (soci session wrapper,
prepared-statement cache, schema versioning, query metrics). sqlite3 module
caches statements internally; we add schema management and timing metrics.
"""

from __future__ import annotations

import sqlite3
import time
from typing import Any, Iterable, Optional

from ..util.log import get_logger

log = get_logger("Database")

SCHEMA_VERSION = 3

_SCHEMA = [
    """CREATE TABLE IF NOT EXISTS storestate (
        statename TEXT PRIMARY KEY, state TEXT)""",
    """CREATE TABLE IF NOT EXISTS accounts (
        accountid TEXT PRIMARY KEY, balance INTEGER, seqnum INTEGER,
        numsubentries INTEGER, flags INTEGER, lastmodified INTEGER,
        entry BLOB)""",
    """CREATE TABLE IF NOT EXISTS trustlines (
        accountid TEXT, asset TEXT, balance INTEGER, flags INTEGER,
        lastmodified INTEGER, entry BLOB,
        PRIMARY KEY (accountid, asset))""",
    """CREATE TABLE IF NOT EXISTS offers (
        sellerid TEXT, offerid INTEGER PRIMARY KEY, selling TEXT,
        buying TEXT, amount INTEGER, pricen INTEGER, priced INTEGER,
        price REAL, flags INTEGER, lastmodified INTEGER, entry BLOB)""",
    """CREATE INDEX IF NOT EXISTS offers_by_book
        ON offers (selling, buying, price, offerid)""",
    """CREATE INDEX IF NOT EXISTS offers_by_seller ON offers (sellerid)""",
    """CREATE TABLE IF NOT EXISTS accountdata (
        accountid TEXT, dataname TEXT, lastmodified INTEGER, entry BLOB,
        PRIMARY KEY (accountid, dataname))""",
    """CREATE TABLE IF NOT EXISTS ledgerheaders (
        ledgerhash TEXT PRIMARY KEY, prevhash TEXT, bucketlisthash TEXT,
        ledgerseq INTEGER UNIQUE, closetime INTEGER, data BLOB)""",
    """CREATE TABLE IF NOT EXISTS txhistory (
        txid TEXT, ledgerseq INTEGER, txindex INTEGER, txbody BLOB,
        txresult BLOB, txmeta BLOB, PRIMARY KEY (ledgerseq, txindex))""",
    """CREATE TABLE IF NOT EXISTS txfeehistory (
        txid TEXT, ledgerseq INTEGER, txindex INTEGER, txchanges BLOB,
        PRIMARY KEY (ledgerseq, txindex))""",
    """CREATE TABLE IF NOT EXISTS scphistory (
        nodeid TEXT, ledgerseq INTEGER, envelope BLOB)""",
    """CREATE TABLE IF NOT EXISTS scpquorums (
        qsethash TEXT PRIMARY KEY, lastledgerseq INTEGER, qset BLOB)""",
    """CREATE TABLE IF NOT EXISTS peers (
        ip TEXT, port INTEGER, nextattempt INTEGER, numfailures INTEGER,
        type INTEGER, PRIMARY KEY (ip, port))""",
    """CREATE TABLE IF NOT EXISTS bans (nodeid TEXT PRIMARY KEY)""",
    """CREATE TABLE IF NOT EXISTS publishqueue (
        ledgerseq INTEGER PRIMARY KEY, state TEXT)""",
    """CREATE TABLE IF NOT EXISTS pubsub (
        resid TEXT PRIMARY KEY, lastread INTEGER)""",
    """CREATE TABLE IF NOT EXISTS upgradehistory (
        ledgerseq INTEGER NOT NULL, upgradeindex INTEGER NOT NULL,
        upgrade BLOB NOT NULL, changes BLOB NOT NULL,
        PRIMARY KEY (ledgerseq, upgradeindex))""",
]


class Database:
    def __init__(self, path: str = ":memory:", metrics=None) -> None:
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=MEMORY")
        self._conn.execute("PRAGMA synchronous=OFF")
        self._metrics = metrics
        self._init_schema()

    # -- schema -------------------------------------------------------------
    def _init_schema(self) -> None:
        for stmt in _SCHEMA:
            self._conn.execute(stmt)
        cur = self._conn.execute(
            "SELECT state FROM storestate WHERE statename='databaseschema'")
        row = cur.fetchone()
        if row is None:
            self.set_state("databaseschema", str(SCHEMA_VERSION))
        else:
            v = int(row[0])
            if v > SCHEMA_VERSION:
                raise RuntimeError("database schema %d newer than binary" % v)
            # migrations v -> SCHEMA_VERSION (reference Database::upgrade)
            # v1 -> v2: txfeehistory; v2 -> v3: upgradehistory — both
            # created above by the CREATE IF NOT EXISTS pass, so each
            # step is just the bump
            self.set_state("databaseschema", str(SCHEMA_VERSION))
        self._conn.commit()

    # -- storestate kv ------------------------------------------------------
    def get_state(self, name: str) -> Optional[str]:
        cur = self._conn.execute(
            "SELECT state FROM storestate WHERE statename=?", (name,))
        row = cur.fetchone()
        return row[0] if row else None

    def set_state(self, name: str, value: str) -> None:
        self._conn.execute(
            "INSERT INTO storestate (statename, state) VALUES (?, ?) "
            "ON CONFLICT(statename) DO UPDATE SET state=excluded.state",
            (name, value))

    # -- execution ----------------------------------------------------------
    # total_query_seconds accumulates ALL SQL time (exec + commit) so
    # callers can exclude DB time from their own timers (reference
    # DBTimeExcluder, LedgerManagerImpl.cpp:525)
    total_query_seconds = 0.0

    def execute(self, sql: str, params: Iterable[Any] = ()) -> sqlite3.Cursor:
        t0 = time.perf_counter()
        cur = self._conn.execute(sql, tuple(params))
        dt = time.perf_counter() - t0
        self.total_query_seconds += dt
        if self._metrics is not None:
            self._metrics.new_timer("database.query.exec").update(dt)
        return cur

    def executemany(self, sql: str, rows) -> None:
        t0 = time.perf_counter()
        self._conn.executemany(sql, rows)
        dt = time.perf_counter() - t0
        self.total_query_seconds += dt
        if self._metrics is not None:
            self._metrics.new_timer("database.query.exec").update(dt)

    def commit(self) -> None:
        t0 = time.perf_counter()
        self._conn.commit()
        self.total_query_seconds += time.perf_counter() - t0

    def rollback(self) -> None:
        self._conn.rollback()

    def close(self) -> None:
        self._conn.close()

    class _Tx:
        def __init__(self, db: "Database") -> None:
            self._db = db

        def __enter__(self):
            return self._db

        def __exit__(self, et, ev, tb):
            if et is None:
                self._db.commit()
            else:
                self._db.rollback()
            return False

    def transaction(self) -> "Database._Tx":
        return Database._Tx(self)
