"""LocalNode: quorum-set math (quorum slices, v-blocking sets, weights).

Role parity: reference `src/scp/LocalNode.{h,cpp}:57-91` — isQuorumSlice,
isVBlocking, isQuorum (transitive closure), findClosestVBlocking,
getNodeWeight. Pure functions over SCPQuorumSet; no I/O.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Set

from ..xdr import NodeID, SCPQuorumSet

UINT64_MAX = 2**64 - 1


def _nid(n: NodeID) -> bytes:
    return n.key_bytes


class LocalNode:
    def __init__(self, node_id: NodeID, is_validator: bool,
                 qset: SCPQuorumSet) -> None:
        self.node_id = node_id
        self.is_validator = is_validator
        self.qset = qset
        from ..crypto.hashing import sha256
        self.qset_hash = sha256(qset.to_xdr())

    def update_quorum_set(self, qset: SCPQuorumSet) -> None:
        from ..crypto.hashing import sha256
        self.qset = qset
        self.qset_hash = sha256(qset.to_xdr())

    # -- static quorum math --------------------------------------------------
    @staticmethod
    def is_quorum_slice(qset: SCPQuorumSet, nodes: Set[bytes]) -> bool:
        """Does `nodes` contain a slice of qset?"""
        count = sum(1 for v in qset.validators if _nid(v) in nodes)
        count += sum(1 for inner in qset.innerSets
                     if LocalNode.is_quorum_slice(inner, nodes))
        return count >= qset.threshold

    @staticmethod
    def is_v_blocking(qset: SCPQuorumSet, nodes: Set[bytes]) -> bool:
        """Does `nodes` intersect every slice of qset? Equivalent: qset can't
        reach threshold without `nodes`."""
        if qset.threshold == 0:
            return False
        left = qset.threshold
        total = len(qset.validators) + len(qset.innerSets)
        # how many members may be 'lost' while still reaching threshold
        slack = total - qset.threshold
        blocked = sum(1 for v in qset.validators if _nid(v) in nodes)
        blocked += sum(1 for inner in qset.innerSets
                       if LocalNode.is_v_blocking(inner, nodes))
        return blocked > slack

    @staticmethod
    def is_v_blocking_filter(qset: SCPQuorumSet, envelopes: Iterable,
                             filt: Callable) -> bool:
        nodes = {_nid(e.statement.nodeID) for e in envelopes
                 if filt(e.statement)}
        return LocalNode.is_v_blocking(qset, nodes)

    @staticmethod
    def is_quorum(local_qset: Optional[SCPQuorumSet], envelopes: Dict,
                  qset_of: Callable, filt: Callable) -> bool:
        """Transitive quorum check: nodes passing `filt` whose quorum sets
        (via qset_of(statement)) are recursively satisfied. `envelopes` maps
        nodeID-bytes → envelope. If local_qset given, the final set must also
        be a slice for the local node (reference LocalNode::isQuorum)."""
        nodes = {nb for nb, e in envelopes.items() if filt(e.statement)}
        while True:
            def ok(nb: bytes) -> bool:
                q = qset_of(envelopes[nb].statement)
                return q is not None and LocalNode.is_quorum_slice(q, nodes)
            pruned = {nb for nb in nodes if ok(nb)}
            if pruned == nodes:
                break
            nodes = pruned
        if not nodes:
            return False
        if local_qset is not None:
            return LocalNode.is_quorum_slice(local_qset, nodes)
        return True

    @staticmethod
    def find_closest_v_blocking(qset: SCPQuorumSet, nodes: Set[bytes],
                                excluded: Optional[bytes] = None
                                ) -> List[bytes]:
        """Smallest subset of `nodes` that is v-blocking for qset
        (greedy, reference findClosestVBlocking)."""
        leftTillBlock = 1 + (len(qset.validators) + len(qset.innerSets)
                             - qset.threshold)
        res: List[bytes] = []
        candidates: List[List[bytes]] = []
        for v in qset.validators:
            nb = _nid(v)
            if nb == excluded:
                continue
            if nb in nodes:
                candidates.append([nb])
        for inner in qset.innerSets:
            sub = LocalNode.find_closest_v_blocking(inner, nodes, excluded)
            if sub:
                candidates.append(sub)
        candidates.sort(key=len)
        for c in candidates:
            leftTillBlock -= 1
            res.extend(c)
            if leftTillBlock == 0:
                return res
        return []  # not blockable with these nodes

    # -- weights (nomination leader election) --------------------------------
    @staticmethod
    def get_node_weight(node: bytes, qset: SCPQuorumSet) -> int:
        """Weight in [0, 2^64): fraction of slices containing node,
        approximated hierarchically (reference getNodeWeight)."""
        n = qset.threshold
        d = len(qset.validators) + len(qset.innerSets)
        if d == 0:
            return 0
        for v in qset.validators:
            if _nid(v) == node:
                return (UINT64_MAX * n) // d
        for inner in qset.innerSets:
            w = LocalNode.get_node_weight(node, inner)
            if w > 0:
                return (w * n) // d
        return 0


def all_nodes_of(qset: SCPQuorumSet) -> Set[bytes]:
    out = {_nid(v) for v in qset.validators}
    for inner in qset.innerSets:
        out |= all_nodes_of(inner)
    return out
