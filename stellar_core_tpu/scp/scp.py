"""SCP facade: slot registry + envelope entry point.

Role parity: reference `src/scp/SCP.{h,cpp}:30-77` — receiveEnvelope,
nominate, slot GC, state introspection/JSON, restore from persisted
envelopes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..util.threads import main_thread_only
from ..xdr import NodeID, SCPEnvelope, SCPQuorumSet
from .ballot import BallotProtocol
from .driver import SCPDriver
from .local_node import LocalNode
from .slot import Slot


class SCP:
    class EnvelopeState:
        INVALID = BallotProtocol.EnvelopeState.INVALID
        VALID = BallotProtocol.EnvelopeState.VALID
        # signature verify in flight on the batch backend; resolution is
        # delivered via the recv_scp_envelope on_verified callback
        PENDING = 2

    def __init__(self, driver: SCPDriver, node_id: NodeID,
                 is_validator: bool, qset: SCPQuorumSet) -> None:
        self.driver = driver
        self.local_node = LocalNode(node_id, is_validator, qset)
        self.known_slots: Dict[int, Slot] = {}

    # -- slots --------------------------------------------------------------
    def get_slot(self, idx: int, create: bool = True) -> Optional[Slot]:
        s = self.known_slots.get(idx)
        if s is None and create:
            s = Slot(idx, self)
            self.known_slots[idx] = s
        return s

    def purge_slots(self, max_slot_index: int) -> None:
        """Drop slots below max_slot_index (reference purgeSlots)."""
        for idx in [i for i in self.known_slots if i < max_slot_index]:
            del self.known_slots[idx]

    # -- protocol entry points ----------------------------------------------
    @main_thread_only
    def receive_envelope(self, envelope: SCPEnvelope) -> int:
        return self.get_slot(
            envelope.statement.slotIndex).process_envelope(envelope)

    @main_thread_only
    def nominate(self, slot_index: int, value: bytes,
                 previous_value: bytes) -> bool:
        assert self.local_node.is_validator
        return self.get_slot(slot_index).nominate(value, previous_value)

    def stop_nomination(self, slot_index: int) -> None:
        s = self.get_slot(slot_index, False)
        if s:
            s.stop_nomination()

    def update_local_quorum_set(self, qset: SCPQuorumSet) -> None:
        self.local_node.update_quorum_set(qset)

    # -- introspection ------------------------------------------------------
    def get_latest_messages_send(self, slot_index: int) -> List[SCPEnvelope]:
        s = self.get_slot(slot_index, False)
        return s.get_latest_messages_send() if s else []

    def get_current_state(self, slot_index: int) -> List[SCPEnvelope]:
        s = self.get_slot(slot_index, False)
        return s.get_current_state() if s else []

    def get_externalizing_state(self, slot_index: int) -> List[SCPEnvelope]:
        s = self.get_slot(slot_index, False)
        if s is None:
            return []
        return [e for e in s.get_current_state()]

    @main_thread_only
    def set_state_from_envelope(self, envelope: SCPEnvelope) -> None:
        """Restore persisted state (reference setStateFromEnvelope)."""
        self.get_slot(envelope.statement.slotIndex).set_state_from_envelope(
            envelope)

    def empty(self) -> bool:
        return not self.known_slots

    def get_json_info(self, limit: int = 2) -> dict:
        idxs = sorted(self.known_slots)[-limit:]
        return {str(i): self.known_slots[i].get_json_info() for i in idxs}
