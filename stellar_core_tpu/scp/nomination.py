"""NominationProtocol: federated nomination of candidate values.

Role parity: reference `src/scp/NominationProtocol.{h,cpp}:337` — leader
election by weighted hash per round, vote/accept federated voting over
values, candidate confirmation feeding the ballot protocol via
combineCandidates.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..xdr import (
    SCPEnvelope, SCPNomination, SCPPledges, SCPStatement, SCPStatementType,
)
from .local_node import LocalNode, all_nodes_of


class NominationProtocol:
    def __init__(self, slot) -> None:
        self.slot = slot
        self.round_number = 0
        self.votes: Set[bytes] = set()
        self.accepted: Set[bytes] = set()
        self.candidates: Set[bytes] = set()
        self.latest_nominations: Dict[bytes, SCPEnvelope] = {}
        self.last_envelope: Optional[SCPEnvelope] = None
        self.round_leaders: Set[bytes] = set()
        self.nomination_started = False
        self.latest_composite: Optional[bytes] = None
        self.previous_value: bytes = b""

    def _driver(self):
        return self.slot.scp.driver

    def _journal(self, event: str, **tags) -> None:
        """First occurrence of a nomination milestone (own vote, value
        accepted, candidate confirmed) into the slot timeline."""
        tl = getattr(self.slot.scp.driver, "timeline", None)
        if tl is not None:
            tl.record(self.slot.slot_index, event, dedupe=True, **tags)

    def _local(self) -> LocalNode:
        return self.slot.scp.local_node

    def _qset_of(self, st: SCPStatement):
        return self.slot.get_quorum_set_from_statement(st)

    # ------------------------------------------------------------ leaders
    def update_round_leaders(self) -> None:
        local = self._local()
        qset = local.qset
        leaders: Set[bytes] = set()
        top_priority = 0
        nodes = all_nodes_of(qset)
        nodes.add(local.node_id.key_bytes)
        for nb in sorted(nodes):
            w = LocalNode.get_node_weight(nb, qset) \
                if nb != local.node_id.key_bytes else 2**64 - 1
            if w == 0:
                continue
            from ..xdr import PublicKey
            nid = PublicKey.ed25519(nb)
            gi = self._driver().compute_hash_node(
                self.slot.slot_index, self.previous_value, False,
                self.round_number, nid)
            if gi >= w:
                continue  # not eligible this round
            prio = self._driver().compute_hash_node(
                self.slot.slot_index, self.previous_value, True,
                self.round_number, nid)
            if prio > top_priority:
                top_priority = prio
                leaders = {nb}
            elif prio == top_priority:
                leaders.add(nb)
        if not leaders:
            leaders = {local.node_id.key_bytes}
        # leaders ACCUMULATE across rounds (reference updateRoundLeaders):
        # a new round adds its leader without forgetting previous ones
        self.round_leaders |= leaders

    # ------------------------------------------------------------- intake
    @staticmethod
    def is_sane(st: SCPStatement) -> bool:
        nom = st.pledges.value
        if not nom.votes and not nom.accepted:
            return False
        return (sorted(nom.votes) == list(nom.votes) and
                len(set(nom.votes)) == len(nom.votes) and
                sorted(nom.accepted) == list(nom.accepted) and
                len(set(nom.accepted)) == len(nom.accepted))

    def _is_newer(self, st: SCPStatement, old: SCPStatement) -> bool:
        a, b = st.pledges.value, old.pledges.value
        return (set(b.votes) <= set(a.votes) and
                set(b.accepted) <= set(a.accepted) and
                (len(a.votes) > len(b.votes) or
                 len(a.accepted) > len(b.accepted)))

    def process_envelope(self, envelope: SCPEnvelope) -> int:
        from .ballot import BallotProtocol
        st = envelope.statement
        nb = st.nodeID.key_bytes
        if not self.is_sane(st):
            return BallotProtocol.EnvelopeState.INVALID
        old = self.latest_nominations.get(nb)
        if old is not None and not self._is_newer(st, old.statement):
            return BallotProtocol.EnvelopeState.INVALID
        self.latest_nominations[nb] = envelope
        if not self.nomination_started:
            return BallotProtocol.EnvelopeState.VALID
        from .driver import ValidationLevel
        modified = False
        new_candidates = False
        # echo a round leader's BEST new value (reference
        # getNewValueFromNomination — one value per message, highest
        # value-hash among those we don't already vote for)
        if nb in self.round_leaders:
            v = self._pick_leader_value(envelope)
            if v is not None:
                self.votes.add(v)
                self._journal("nominate.vote", round=self.round_number)
                self._driver().nominating_value(self.slot.slot_index, v)
                modified = True
        # federated voting on each known value
        for v in self._all_known_values():
            if v in self.accepted:
                continue
            if self._federated_accept_value(v):
                lvl = self._driver().validate_value(
                    self.slot.slot_index, v, True)
                if lvl != ValidationLevel.FULLY_VALIDATED:
                    alt = self._driver().extract_valid_value(
                        self.slot.slot_index, v)
                    if alt is None:
                        continue
                    v = alt
                self.accepted.add(v)
                self.votes.add(v)
                self._journal("nominate.accept",
                              accepted=len(self.accepted))
                modified = True
        for v in sorted(self.accepted):
            if v in self.candidates:
                continue
            if self._federated_ratify_value(v):
                self.candidates.add(v)
                self._journal("nominate.candidate",
                              candidates=len(self.candidates))
                new_candidates = True
        if modified:
            self._emit_nomination()
        if new_candidates:
            self.latest_composite = self._driver().combine_candidates(
                self.slot.slot_index, sorted(self.candidates))
            if self.latest_composite is not None:
                self._driver().updated_candidate_value(
                    self.slot.slot_index, self.latest_composite)
                self.slot.bump_state(self.latest_composite, force=False)
        return BallotProtocol.EnvelopeState.VALID

    def _all_known_values(self) -> List[bytes]:
        out: Set[bytes] = set(self.votes)
        for env in self.latest_nominations.values():
            nom = env.statement.pledges.value
            out.update(nom.votes)
            out.update(nom.accepted)
        return sorted(out)

    def _federated_accept_value(self, v: bytes) -> bool:
        def accepted_pred(st: SCPStatement) -> bool:
            return v in st.pledges.value.accepted

        def votes_pred(st: SCPStatement) -> bool:
            return v in st.pledges.value.votes
        local = self._local()
        if LocalNode.is_v_blocking_filter(
                local.qset, self.latest_nominations.values(),
                accepted_pred):
            return True
        return LocalNode.is_quorum(
            local.qset, self.latest_nominations, self._qset_of,
            lambda st: votes_pred(st) or accepted_pred(st))

    def _federated_ratify_value(self, v: bytes) -> bool:
        return LocalNode.is_quorum(
            self._local().qset, self.latest_nominations, self._qset_of,
            lambda st: v in st.pledges.value.accepted)

    # ------------------------------------------------------------ nominate
    def nominate(self, value: bytes, previous_value: bytes,
                 timed_out: bool = False) -> bool:
        from .driver import SCPTimerID
        if timed_out and not self.nomination_started:
            return False
        self.nomination_started = True
        self.previous_value = previous_value
        self.round_number += 1
        ss = getattr(self._driver(), "scp_stats", None)
        if ss is not None:
            # consensus cockpit (ISSUE 19): round count per slot; a
            # timed_out re-entry is a nomination-timer-driven round
            ss.nomination_round(self.slot.slot_index, self.round_number,
                                timed_out)
        self.update_round_leaders()
        modified = False
        if self._local().node_id.key_bytes in self.round_leaders:
            if value not in self.votes:
                self.votes.add(value)
                self._journal("nominate.vote", round=self.round_number)
                modified = True
            self._driver().nominating_value(self.slot.slot_index, value)
        # regardless of own leadership, adopt the best new value from every
        # round leader we have heard from (reference nominate)
        for nb in self.round_leaders:
            env = self.latest_nominations.get(nb)
            if env is not None:
                v = self._pick_leader_value(env)
                if v is not None:  # _pick skips values already voted
                    self.votes.add(v)
                    self._driver().nominating_value(self.slot.slot_index, v)
                    modified = True
        # re-arm next round
        timeout = self._driver().compute_timeout(self.round_number)
        self._driver().setup_timer(
            self.slot.slot_index, SCPTimerID.NOMINATION, timeout,
            lambda: self.nominate(value, previous_value, True))
        if modified:
            self._emit_nomination()
        return modified

    def _pick_leader_value(self, env: SCPEnvelope) -> Optional[bytes]:
        """Highest value-hash among the leader's votes (reference
        getNewValueFromNomination)."""
        from .driver import ValidationLevel
        nom = env.statement.pledges.value
        best, best_h = None, -1
        for v in list(nom.votes) + list(nom.accepted):
            lvl = self._driver().validate_value(self.slot.slot_index, v,
                                                True)
            if lvl != ValidationLevel.FULLY_VALIDATED:
                v2 = self._driver().extract_valid_value(
                    self.slot.slot_index, v)
                if v2 is None:
                    continue
                v = v2
            if v in self.votes:
                continue  # only NEW values (reference :472-491)
            h = self._driver().compute_value_hash(
                self.slot.slot_index, self.previous_value,
                self.round_number, v)
            if h >= best_h:  # ties: later (higher) value wins (reference)
                best, best_h = v, h
        return best

    def stop_nomination(self) -> None:
        self.nomination_started = False

    def _emit_nomination(self) -> None:
        from .ballot import BallotProtocol
        local = self._local()
        st = SCPStatement(
            nodeID=local.node_id, slotIndex=self.slot.slot_index,
            pledges=SCPPledges(
                SCPStatementType.SCP_ST_NOMINATE,
                SCPNomination(quorumSetHash=local.qset_hash,
                              votes=sorted(self.votes),
                              accepted=sorted(self.accepted))))
        env = self.slot.create_envelope(st)
        if self.process_envelope(env) == BallotProtocol.EnvelopeState.VALID:
            if self.last_envelope is None or self._is_newer(
                    st, self.last_envelope.statement):
                self.last_envelope = env
                if local.is_validator:
                    self._driver().emit_envelope(env)

    def set_state_from_envelope(self, envelope) -> None:
        """Restore persisted nomination state (reference
        setStateFromEnvelope)."""
        if self.nomination_started:
            raise RuntimeError("cannot set state after nomination started")
        st = envelope.statement
        self.latest_nominations[st.nodeID.key_bytes] = envelope
        nom = st.pledges.value
        for a in nom.accepted:
            self.accepted.add(a)
        for v in nom.votes:
            self.votes.add(v)
        self.last_envelope = envelope

    def get_json_info(self) -> dict:
        return {
            "roundnumber": self.round_number,
            "started": self.nomination_started,
            "votes": len(self.votes),
            "accepted": len(self.accepted),
            "candidates": len(self.candidates),
        }
