"""Slot: one consensus round = nomination + ballot protocol instances.

Role parity: reference `src/scp/Slot.{h,cpp}:121` — envelope dispatch,
quorum-set resolution from statements, statement-to-envelope signing,
externalization bookkeeping.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..xdr import (
    SCPEnvelope, SCPQuorumSet, SCPStatement, SCPStatementType,
)
from .ballot import BallotProtocol
from .nomination import NominationProtocol

# slot-timeline event per statement type: "first time we saw <phase>
# from <sender>" (dedup per sender keeps the journal bounded while
# preserving first-arrival times for fleet flood-latency attribution)
_SEEN_EVENT = {
    SCPStatementType.SCP_ST_NOMINATE: "nominate.seen",
    SCPStatementType.SCP_ST_PREPARE: "prepare.seen",
    SCPStatementType.SCP_ST_CONFIRM: "confirm.seen",
    SCPStatementType.SCP_ST_EXTERNALIZE: "externalize.seen",
}


class Slot:
    def __init__(self, slot_index: int, scp) -> None:
        self.slot_index = slot_index
        self.scp = scp
        self.nomination = NominationProtocol(self)
        self.ballot = BallotProtocol(self)
        self.fully_validated = scp.local_node.is_validator
        self.got_v_blocking = False

    # -- envelope plumbing --------------------------------------------------
    def create_envelope(self, st: SCPStatement) -> SCPEnvelope:
        env = SCPEnvelope(statement=st, signature=b"")
        self.scp.driver.sign_envelope(env)
        return env

    def process_envelope(self, envelope: SCPEnvelope,
                         is_self: bool = False) -> int:
        st = envelope.statement
        assert st.slotIndex == self.slot_index
        own = is_self or \
            st.nodeID.key_bytes == self.scp.local_node.node_id.key_bytes
        tl = getattr(self.scp.driver, "timeline", None)
        if tl is not None and not own:
            # a flood echo of our own statement is not a peer arrival
            tl.record(self.slot_index, _SEEN_EVENT[st.pledges.disc],
                      node=st.nodeID.key_bytes.hex(), dedupe=True)
        ss = getattr(self.scp.driver, "scp_stats", None)
        if ss is not None:
            # consensus cockpit (ISSUE 19): count EVERY peer arrival —
            # the timeline above dedups to first-arrivals, the cockpit's
            # envelopes-per-slot baseline must see the full flood
            from .scp_stats import STATEMENT_KIND
            ss.envelope_received(self.slot_index,
                                 STATEMENT_KIND[st.pledges.disc],
                                 st.nodeID.key_bytes.hex(), is_self=own)
        if st.pledges.disc == SCPStatementType.SCP_ST_NOMINATE:
            return self.nomination.process_envelope(envelope)
        return self.ballot.process_envelope(envelope, is_self)

    def set_state_from_envelope(self, envelope: SCPEnvelope) -> None:
        st = envelope.statement
        if st.nodeID.key_bytes != self.scp.local_node.node_id.key_bytes or \
                st.slotIndex != self.slot_index:
            return  # not our own persisted state; ignore
        if st.pledges.disc == SCPStatementType.SCP_ST_NOMINATE:
            self.nomination.set_state_from_envelope(envelope)
        else:
            self.ballot.set_state_from_envelope(envelope)

    # -- quorum sets --------------------------------------------------------
    def get_quorum_set_from_statement(
            self, st: SCPStatement) -> Optional[SCPQuorumSet]:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_EXTERNALIZE:
            h = st.pledges.value.commitQuorumSetHash
        elif t == SCPStatementType.SCP_ST_NOMINATE:
            h = st.pledges.value.quorumSetHash
        elif t == SCPStatementType.SCP_ST_PREPARE:
            h = st.pledges.value.quorumSetHash
        else:
            h = st.pledges.value.quorumSetHash
        return self.scp.driver.get_qset(h)

    # -- actions ------------------------------------------------------------
    def nominate(self, value: bytes, previous_value: bytes,
                 timed_out: bool = False) -> bool:
        return self.nomination.nominate(value, previous_value, timed_out)

    def bump_state(self, value: bytes, force: bool) -> bool:
        return self.ballot.bump_state(value, force)

    def stop_nomination(self) -> None:
        self.nomination.stop_nomination()

    def get_latest_composite_candidate(self) -> Optional[bytes]:
        return self.nomination.latest_composite

    # -- introspection ------------------------------------------------------
    def get_latest_messages_send(self) -> List[SCPEnvelope]:
        out = []
        if self.nomination.last_envelope is not None:
            out.append(self.nomination.last_envelope)
        if self.ballot.last_envelope_emit is not None:
            out.append(self.ballot.last_envelope_emit)
        return out

    def get_current_state(self) -> List[SCPEnvelope]:
        """All latest envelopes known for this slot (for SCP state
        re-broadcast)."""
        out = list(self.nomination.latest_nominations.values())
        out.extend(self.ballot.latest_envelopes.values())
        return out

    def externalized_value(self) -> Optional[bytes]:
        return self.ballot.externalized_value()

    def get_json_info(self) -> dict:
        return {
            "index": self.slot_index,
            "nomination": self.nomination.get_json_info(),
            "ballot": self.ballot.get_json_info(),
        }
