"""SCPDriver: the abstract callback surface binding SCP to its host.

Role parity: reference `src/scp/SCPDriver.h:66-236` — value validation and
combination, quorum-set lookup, envelope signing/verification/emission,
timers, externalization notification, and hash functions for nomination
leader election. Herder is the sole production subclass; tests mock it.
"""

from __future__ import annotations

import struct
from enum import Enum
from typing import Optional

from ..crypto.hashing import sha256
from ..xdr import SCPEnvelope, SCPQuorumSet, NodeID, Value


class ValidationLevel(Enum):
    INVALID = 0
    FULLY_VALIDATED = 1
    MAYBE_VALID = 2


class SCPTimerID:
    NOMINATION = 0
    BALLOT = 1


class SCPDriver:
    # span tracer (util/tracing.py). When attached (Herder wires the
    # application's), the protocol notification hooks below emit instant
    # events so a Chrome-trace of a round shows nomination/ballot
    # progression between the close spans. None (the default) keeps
    # standalone/test drivers silent.
    tracer = None
    # per-slot event journal (util/slot_timeline.py). When attached, the
    # same hooks (plus the envelope-seen sites in scp/slot.py and the
    # vote/accept sites in scp/nomination.py) journal the slot's
    # consensus progression for the fleet aggregator — always on, unlike
    # the tracer.
    timeline = None
    # consensus cockpit (scp/scp_stats.py, ISSUE 19). When attached
    # (Herder wires the application's), the envelope sites in
    # scp/slot.py, the round hooks in scp/nomination.py and
    # scp/ballot.py, and the timer plumbing in herder/herder.py feed
    # per-slot phase/round/envelope attribution. None keeps
    # standalone/test drivers cockpit-free.
    scp_stats = None

    def _trace_instant(self, name: str, slot_index: int, **tags) -> None:
        from ..util.tracing import tracer_instant
        tracer_instant(self.tracer, name, cat="scp", slot=slot_index,
                       **tags)
        tl = self.timeline
        if tl is not None:
            tl.record(slot_index, name, dedupe=True, **tags)

    # -- values -------------------------------------------------------------
    def validate_value(self, slot_index: int, value: bytes,
                       nomination: bool) -> ValidationLevel:
        return ValidationLevel.MAYBE_VALID

    def extract_valid_value(self, slot_index: int,
                            value: bytes) -> Optional[bytes]:
        return None

    def combine_candidates(self, slot_index: int,
                           candidates: list) -> Optional[bytes]:
        raise NotImplementedError

    # -- envelopes ----------------------------------------------------------
    def sign_envelope(self, envelope: SCPEnvelope) -> None:
        raise NotImplementedError

    def emit_envelope(self, envelope: SCPEnvelope) -> None:
        raise NotImplementedError

    # -- quorum sets --------------------------------------------------------
    def get_qset(self, qset_hash: bytes) -> Optional[SCPQuorumSet]:
        raise NotImplementedError

    # -- timers -------------------------------------------------------------
    def setup_timer(self, slot_index: int, timer_id: int, timeout: float,
                    cb) -> None:
        raise NotImplementedError

    def compute_timeout(self, round_number: int) -> int:
        """Linear backoff capped (reference computeTimeout: min(round, cap)
        seconds with cap 30 * 60? — reference uses 1s per round up to
        MAX_TIMEOUT_SECONDS=30*60). Whole seconds, like the reference's
        std::chrono::seconds — SCP pacing stays on integer arithmetic
        (FL1)."""
        return min(round_number, 30 * 60)

    # -- notifications (optional hooks; base emits trace instants so any
    # subclass calling super() keeps round/ballot timing visible) ----------
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        # no instant here: Herder overrides this and emits the richer
        # "scp.externalize" event (with nominate→externalize latency);
        # a base-class event would just be a dead near-duplicate
        pass

    def nominating_value(self, slot_index: int, value: bytes) -> None:
        self._trace_instant("scp.nominating", slot_index)

    def updated_candidate_value(self, slot_index: int,
                                value: bytes) -> None:
        self._trace_instant("scp.candidate_updated", slot_index)

    def started_ballot_protocol(self, slot_index: int, ballot) -> None:
        self._trace_instant("scp.ballot.start", slot_index,
                            counter=getattr(ballot, "counter", None))

    def accepted_ballot_prepared(self, slot_index: int, ballot) -> None:
        self._trace_instant("scp.ballot.accept_prepared", slot_index,
                            counter=getattr(ballot, "counter", None))

    def confirmed_ballot_prepared(self, slot_index: int, ballot) -> None:
        self._trace_instant("scp.ballot.confirm_prepared", slot_index,
                            counter=getattr(ballot, "counter", None))

    def accepted_commit(self, slot_index: int, ballot) -> None:
        self._trace_instant("scp.ballot.accept_commit", slot_index,
                            counter=getattr(ballot, "counter", None))

    def ballot_did_hear_from_quorum(self, slot_index: int, ballot) -> None:
        self._trace_instant("scp.ballot.heard_quorum", slot_index,
                            counter=getattr(ballot, "counter", None))

    # -- hashing for nomination leader election -----------------------------
    HASH_N = 1
    HASH_P = 2
    HASH_K = 3

    def compute_hash_node(self, slot_index: int, prev: bytes,
                          is_priority: bool, round_number: int,
                          node_id: NodeID) -> int:
        h = sha256(struct.pack(">Q", slot_index) + prev +
                   struct.pack(">II", self.HASH_P if is_priority
                               else self.HASH_N, round_number) +
                   node_id.key_bytes)
        return int.from_bytes(h[:8], "big")

    def compute_value_hash(self, slot_index: int, prev: bytes,
                           round_number: int, value: bytes) -> int:
        h = sha256(struct.pack(">Q", slot_index) + prev +
                   struct.pack(">II", self.HASH_K, round_number) + value)
        return int.from_bytes(h[:8], "big")
