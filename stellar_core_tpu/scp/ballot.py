"""BallotProtocol: PREPARE → CONFIRM → EXTERNALIZE federated voting.

Role parity: reference `src/scp/BallotProtocol.{h,cpp}` (2,244 lines; state
machine entry points attemptAcceptPrepared / attemptConfirmPrepared /
attemptAcceptCommit / attemptConfirmCommit, BallotProtocol.h:183-200).
Implemented from the SCP internet-draft semantics:

- a ballot is (counter, value); ballots totally ordered lexicographically,
  "compatible" = same value.
- PREPARE statement (b, p, p', nC, nH): votes prepare(b); accepts
  prepare(p) and prepare(p'); votes commit(counters [nC, nH], b.value)
  when nC > 0.
- CONFIRM statement (b, nPrepared, nCommit, nH): accepts
  prepare((nPrepared, b.value)); votes commit([nCommit, ∞), b.value);
  accepts commit([nCommit, nH], b.value).
- EXTERNALIZE statement (commit, nH): accepts commit([commit.counter, ∞)),
  accepts prepare((∞, commit.value)).

federated-accept(stmt-votes, stmt-accepts) = v-blocking set accepts, OR a
quorum votes-or-accepts. federated-ratify = quorum accepts.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..xdr import (
    SCPBallot, SCPConfirm, SCPEnvelope, SCPExternalize, SCPPledges,
    SCPPrepare, SCPStatement, SCPStatementType, Value,
)
from .local_node import LocalNode

UINT32_MAX = 2**32 - 1

Ballot = Tuple[int, bytes]  # (counter, value)


def _bt(b: SCPBallot) -> Ballot:
    return (b.counter, b.value)


def _mk(b: Ballot) -> SCPBallot:
    return SCPBallot(counter=b[0], value=b[1])


def compatible(a: Ballot, b: Ballot) -> bool:
    return a[1] == b[1]


def less_and_compatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and compatible(a, b)


def less_and_incompatible(a: Ballot, b: Ballot) -> bool:
    return a <= b and not compatible(a, b)


class SCPPhase:
    PREPARE = 0
    CONFIRM = 1
    EXTERNALIZE = 2


class BallotProtocol:
    def __init__(self, slot) -> None:
        self.slot = slot
        self.phase = SCPPhase.PREPARE
        self.b: Optional[Ballot] = None          # current ballot
        self.p: Optional[Ballot] = None          # prepared
        self.pp: Optional[Ballot] = None         # prepared prime
        self.c: Optional[Ballot] = None          # commit (low)
        self.h: Optional[Ballot] = None          # high
        self.value_override: Optional[bytes] = None
        self.latest_envelopes: Dict[bytes, SCPEnvelope] = {}
        self.last_stmt_xdr: Optional[bytes] = None
        self.heard_from_quorum = False
        self.current_message_level = 0
        self.timer_counter = 0

    # ------------------------------------------------------------------ util
    def _driver(self):
        return self.slot.scp.driver

    def _local(self) -> LocalNode:
        return self.slot.scp.local_node

    def _qset_of(self, st: SCPStatement):
        return self.slot.get_quorum_set_from_statement(st)

    # -------------------------------------------------- statement predicates
    @staticmethod
    def statement_ballot_counter(st: SCPStatement) -> int:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            return st.pledges.value.ballot.counter
        if t == SCPStatementType.SCP_ST_CONFIRM:
            return st.pledges.value.ballot.counter
        return UINT32_MAX  # EXTERNALIZE

    @staticmethod
    def is_statement_sane(st: SCPStatement, is_self: bool) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            b, pr, ppr = _bt(p.ballot), p.prepared, p.preparedPrime
            if not (is_self or b[0] > 0):
                return False
            if pr is not None and ppr is not None:
                if not (_bt(ppr) < _bt(pr) and
                        not compatible(_bt(ppr), _bt(pr))):
                    return False
            if ppr is not None and pr is None:
                return False
            if p.nH > 0 and (pr is None or p.nH > pr.counter):
                return False
            if p.nC > 0 and not (p.nH > 0 and b[0] >= p.nH >= p.nC):
                return False
            return True
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            b = _bt(c.ballot)
            return (b[0] > 0 and c.nH <= c.nPrepared and
                    0 < c.nCommit <= c.nH <= b[0])
        if t == SCPStatementType.SCP_ST_EXTERNALIZE:
            e = st.pledges.value
            return e.commit.counter > 0 and e.nH >= e.commit.counter
        return False

    # "st accepts prepare(ballot)"
    @staticmethod
    def has_prepared_ballot(ballot: Ballot, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return ((p.prepared is not None and
                     less_and_compatible(ballot, _bt(p.prepared))) or
                    (p.preparedPrime is not None and
                     less_and_compatible(ballot, _bt(p.preparedPrime))))
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            prepared = (c.nPrepared, c.ballot.value)
            return less_and_compatible(ballot, prepared)
        e = st.pledges.value
        return compatible(ballot, (0, e.commit.value))

    # "st votes prepare(ballot)" (vote-or-accept)
    @staticmethod
    def votes_prepared(ballot: Ballot, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return (less_and_compatible(ballot, _bt(p.ballot)) or
                    BallotProtocol.has_prepared_ballot(ballot, st))
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return compatible(ballot, (0, c.ballot.value))
        e = st.pledges.value
        return compatible(ballot, (0, e.commit.value))

    # commit interval predicates for value v over [lo, hi]
    @staticmethod
    def accepts_commit(v: bytes, lo: int, hi: int,
                       st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return (c.ballot.value == v and
                    c.nCommit <= lo and hi <= c.nH)
        if t == SCPStatementType.SCP_ST_EXTERNALIZE:
            e = st.pledges.value
            return e.commit.value == v and e.commit.counter <= lo
        return False

    @staticmethod
    def votes_commit(v: bytes, lo: int, hi: int, st: SCPStatement) -> bool:
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            return (p.ballot.value == v and p.nC > 0 and
                    p.nC <= lo and hi <= p.nH)
        if t == SCPStatementType.SCP_ST_CONFIRM:
            c = st.pledges.value
            return c.ballot.value == v and c.nCommit <= lo
        e = st.pledges.value
        return e.commit.value == v and e.commit.counter <= lo

    # ------------------------------------------------------ federated voting
    def _federated_accept(self, votes_pred: Callable, accepted_pred) -> bool:
        local = self._local()
        if LocalNode.is_v_blocking_filter(
                local.qset, self.latest_envelopes.values(), accepted_pred):
            return True

        def vote_or_accept(st: SCPStatement) -> bool:
            return votes_pred(st) or accepted_pred(st)
        return LocalNode.is_quorum(
            local.qset, self.latest_envelopes, self._qset_of,
            vote_or_accept)

    def _federated_ratify(self, accepted_pred: Callable) -> bool:
        return LocalNode.is_quorum(
            self._local().qset, self.latest_envelopes, self._qset_of,
            accepted_pred)

    # --------------------------------------------------------------- intake
    class EnvelopeState:
        INVALID = 0
        VALID = 1

    def process_envelope(self, envelope: SCPEnvelope, is_self: bool) -> int:
        st = envelope.statement
        nb = st.nodeID.key_bytes
        if not self.is_statement_sane(st, is_self):
            return self.EnvelopeState.INVALID
        old = self.latest_envelopes.get(nb)
        if old is not None and not self._is_newer(st, old.statement):
            return self.EnvelopeState.INVALID
        if not is_self and not self._validate_values(st):
            return self.EnvelopeState.INVALID
        self.latest_envelopes[nb] = envelope
        self.advance_slot(st)
        return self.EnvelopeState.VALID

    @staticmethod
    def _is_newer(st: SCPStatement, old: SCPStatement) -> bool:
        tn, to = st.pledges.disc, old.pledges.disc
        if tn != to:
            order = {SCPStatementType.SCP_ST_PREPARE: 0,
                     SCPStatementType.SCP_ST_CONFIRM: 1,
                     SCPStatementType.SCP_ST_EXTERNALIZE: 2}
            return order[tn] > order[to]
        if tn == SCPStatementType.SCP_ST_PREPARE:
            a, b = st.pledges.value, old.pledges.value
            key_a = (_bt(a.ballot),
                     _bt(a.prepared) if a.prepared else (0, b""),
                     _bt(a.preparedPrime) if a.preparedPrime else (0, b""),
                     a.nH)
            key_b = (_bt(b.ballot),
                     _bt(b.prepared) if b.prepared else (0, b""),
                     _bt(b.preparedPrime) if b.preparedPrime else (0, b""),
                     b.nH)
            return key_a > key_b
        if tn == SCPStatementType.SCP_ST_CONFIRM:
            a, b = st.pledges.value, old.pledges.value
            ka = (_bt(a.ballot), a.nPrepared, a.nCommit, a.nH)
            kb = (_bt(b.ballot), b.nPrepared, b.nCommit, b.nH)
            return ka > kb
        return False  # EXTERNALIZE statements are final

    def _validate_values(self, st: SCPStatement) -> bool:
        from .driver import ValidationLevel
        values = set()
        t = st.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = st.pledges.value
            if p.ballot.counter:
                values.add(p.ballot.value)
            if p.prepared is not None:
                values.add(p.prepared.value)
        elif t == SCPStatementType.SCP_ST_CONFIRM:
            values.add(st.pledges.value.ballot.value)
        else:
            values.add(st.pledges.value.commit.value)
        for v in values:
            lvl = self._driver().validate_value(self.slot.slot_index, v,
                                                False)
            if lvl == ValidationLevel.INVALID:
                return False
        return True

    # -------------------------------------------------------------- bumping
    def bump_state(self, value: bytes, force: bool = True,
                   counter: Optional[int] = None) -> bool:
        if not force and self.b is not None:
            return False
        if self.phase != SCPPhase.PREPARE and \
                self.phase != SCPPhase.CONFIRM:
            return False
        n = counter if counter is not None else (
            1 if self.b is None else self.b[0] + 1)
        if self.phase == SCPPhase.CONFIRM:
            # value is locked in confirm phase
            value = self.h[1]
        target = (n, self.value_override
                  if self.value_override is not None else
                  (self.h[1] if self.h is not None else value))
        if self.phase == SCPPhase.PREPARE and self.h is not None:
            target = (n, self.h[1])
        elif self.phase == SCPPhase.PREPARE:
            target = (n, value)
        updated = self._update_current_value(target)
        if updated:
            self._driver().started_ballot_protocol(
                self.slot.slot_index, _mk(self.b))
            self._emit_current_statement()
            self._check_heard_from_quorum()
        return updated

    def _update_current_value(self, ballot: Ballot) -> bool:
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        if self.b is None:
            ok = True
        elif self.phase == SCPPhase.CONFIRM and \
                not compatible(ballot, self.b):
            return False
        elif self.b > ballot:
            return False
        elif self.b == ballot:
            return False
        else:
            ok = True
        # commit guard: cannot change value while c is set
        if self.c is not None and not compatible(ballot, self.c):
            return False
        self.b = ballot
        return ok

    def abandon_ballot(self, n: int = 0) -> bool:
        """Timer fired or externally poked: move to a higher counter with
        the best known value (reference abandonBallot)."""
        v = self.slot.get_latest_composite_candidate()
        if not v:
            if self.b is not None:
                v = self.b[1]
        if not v:
            return False
        if n == 0:
            return self.bump_state(v, True)
        return self.bump_state(v, True, n)

    # ------------------------------------------------------- advance engine
    def advance_slot(self, hint: SCPStatement) -> None:
        self.current_message_level += 1
        if self.current_message_level >= 50:
            raise RuntimeError("maximum number of transitions reached")
        did = True
        while did:
            did = False
            self._update_current_if_needed(hint)
            if self.attempt_accept_prepared(hint):
                did = True
            if self.attempt_confirm_prepared(hint):
                did = True
            if self.attempt_accept_commit(hint):
                did = True
            if self.attempt_confirm_commit(hint):
                did = True
        if self.current_message_level == 1:
            # only check bump/quorum at the top of the reentrancy stack
            self._attempt_bump()
            self._check_heard_from_quorum()
        self.current_message_level -= 1

    def _update_current_if_needed(self, hint: SCPStatement) -> None:
        if self.phase == SCPPhase.PREPARE and self.p is not None:
            if self.b is None or self.b < self.p:
                self._update_current_value(self.p)

    # prepare candidates from all statements, descending
    def _prepare_candidates(self) -> List[Ballot]:
        out: Set[Ballot] = set()
        for env in self.latest_envelopes.values():
            st = env.statement
            t = st.pledges.disc
            if t == SCPStatementType.SCP_ST_PREPARE:
                p = st.pledges.value
                if p.ballot.counter:
                    out.add(_bt(p.ballot))
                if p.prepared is not None:
                    out.add(_bt(p.prepared))
                if p.preparedPrime is not None:
                    out.add(_bt(p.preparedPrime))
            elif t == SCPStatementType.SCP_ST_CONFIRM:
                c = st.pledges.value
                out.add((c.nPrepared, c.ballot.value))
                out.add((UINT32_MAX, c.ballot.value))
            else:
                e = st.pledges.value
                out.add((UINT32_MAX, e.commit.value))
        return sorted(out, reverse=True)

    def attempt_accept_prepared(self, hint: SCPStatement) -> bool:
        if self.phase != SCPPhase.PREPARE and \
                self.phase != SCPPhase.CONFIRM:
            return False
        for cand in self._prepare_candidates():
            if self.phase == SCPPhase.CONFIRM:
                # only interested in ballots compatible with commit value
                if not (self.p is not None and
                        less_and_compatible(cand, self.p)) and \
                        not compatible(cand, self.h):
                    continue
            if self.p is not None and cand <= self.p:
                break  # nothing new below current prepared
            if self.pp is not None and cand <= self.pp:
                continue
            accepted = self._federated_accept(
                lambda st, c=cand: self.votes_prepared(c, st),
                lambda st, c=cand: self.has_prepared_ballot(c, st))
            if accepted:
                return self._set_prepared(cand)
        return False

    def _set_prepared(self, ballot: Ballot) -> bool:
        did = False
        if self.p is None or self.p < ballot:
            if self.p is not None and not compatible(self.p, ballot):
                if self.pp is None or self.pp < self.p:
                    self.pp = self.p
            self.p = ballot
            did = True
        elif self.p > ballot and not compatible(self.p, ballot):
            if self.pp is None or self.pp < ballot:
                self.pp = ballot
                did = True
        if did:
            # abort commit if prepared aborts it: p incompatible >= c
            if self.c is not None and self.h is not None:
                incompatible = (
                    (self.p is not None and
                     less_and_incompatible(self.h, self.p)) or
                    (self.pp is not None and
                     less_and_incompatible(self.h, self.pp)))
                if incompatible:
                    self.c = None
            self._driver().accepted_ballot_prepared(self.slot.slot_index,
                                                    _mk(self.p))
            self._emit_current_statement()
        return did

    def attempt_confirm_prepared(self, hint: SCPStatement) -> bool:
        if self.phase != SCPPhase.PREPARE or self.p is None:
            return False
        # find highest ratified prepared ballot → h; then extend down to c
        new_h = None
        for cand in self._prepare_candidates():
            if self.h is not None and cand <= self.h:
                break
            if self._federated_ratify(
                    lambda st, c=cand: self.has_prepared_ballot(c, st)):
                new_h = cand
                break
        if new_h is None:
            return False
        did = False
        if self.h is None or new_h > self.h:
            self.h = new_h
            did = True
            if self.b is not None and new_h > self.b:
                self._update_current_value(new_h)
        # compute c: lowest ballot such that the whole range [c, h] is
        # confirmed prepared and nothing aborts it
        if did and self.c is None and self.b is not None:
            if self.p is not None and \
                    less_and_incompatible(self.h, self.p):
                pass
            elif self.pp is not None and \
                    less_and_incompatible(self.h, self.pp):
                pass
            elif self.b <= self.h and compatible(self.b, self.h):
                new_c = None
                for cand in sorted(self._prepare_candidates()):
                    if cand < self.b:
                        continue
                    if not less_and_compatible(cand, self.h):
                        continue
                    if self._federated_ratify(
                            lambda st, c=cand: self.has_prepared_ballot(
                                c, st)):
                        new_c = cand
                        break
                if new_c is not None:
                    self.c = new_c
        if did:
            self._driver().confirmed_ballot_prepared(self.slot.slot_index,
                                                     _mk(self.h))
            self._emit_current_statement()
        return did

    # commit boundaries for a value
    def _commit_boundaries(self, v: bytes) -> List[int]:
        out: Set[int] = set()
        for env in self.latest_envelopes.values():
            st = env.statement
            t = st.pledges.disc
            if t == SCPStatementType.SCP_ST_PREPARE:
                p = st.pledges.value
                if p.ballot.value == v and p.nC > 0:
                    out.add(p.nC)
                    out.add(p.nH)
            elif t == SCPStatementType.SCP_ST_CONFIRM:
                c = st.pledges.value
                if c.ballot.value == v:
                    out.add(c.nCommit)
                    out.add(c.nH)
            else:
                e = st.pledges.value
                if e.commit.value == v:
                    out.add(e.commit.counter)
                    out.add(e.nH)
        return sorted(out)

    def _find_extended_interval(self, v: bytes, pred) -> Optional[
            Tuple[int, int]]:
        """Largest [lo, hi] over the boundary grid where pred holds for
        every (lo, hi) — scanning from the top (reference
        findExtendedInterval)."""
        boundaries = self._commit_boundaries(v)
        best: Optional[Tuple[int, int]] = None
        cur: Optional[Tuple[int, int]] = None
        for bval in reversed(boundaries):
            if cur is None:
                cand = (bval, bval)
            else:
                cand = (bval, cur[1])
            if pred(cand[0], cand[1]):
                cur = cand
                best = cur
            elif cur is not None:
                break
        return best

    def attempt_accept_commit(self, hint: SCPStatement) -> bool:
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        # work off the hint's ballot value
        t = hint.pledges.disc
        if t == SCPStatementType.SCP_ST_PREPARE:
            p = hint.pledges.value
            if p.nC == 0:
                return False
            ballot = (p.nH, p.ballot.value)
        elif t == SCPStatementType.SCP_ST_CONFIRM:
            c = hint.pledges.value
            ballot = (c.nH, c.ballot.value)
        else:
            e = hint.pledges.value
            ballot = (e.nH, e.commit.value)
        if self.phase == SCPPhase.CONFIRM and \
                not compatible(ballot, self.h):
            return False
        v = ballot[1]

        def pred(lo: int, hi: int) -> bool:
            return self._federated_accept(
                lambda st: self.votes_commit(v, lo, hi, st),
                lambda st: self.accepts_commit(v, lo, hi, st))
        interval = self._find_extended_interval(v, pred)
        if interval is None:
            return False
        lo, hi = interval
        # sanity: don't regress
        if self.phase == SCPPhase.CONFIRM and self.h is not None and \
                hi <= self.h[0] and (self.c[0], self.h[0]) == (lo, hi):
            return False
        if self.phase == SCPPhase.PREPARE:
            if self.p is not None and not compatible((0, v), self.p) and \
                    self.p[0] >= lo:
                # accepting commit of an aborted value would be unsafe
                if not less_and_compatible((lo, v), self.p):
                    pass
            self.phase = SCPPhase.CONFIRM
        self.c = (lo, v)
        self.h = (hi, v)
        if self.b is None or self.b[0] < hi or self.b[1] != v:
            self.b = (max(hi, self.b[0] if self.b else 0), v)
        self.p = (self.p[0], v) if (self.p and self.p[1] == v) else self.p
        self._driver().accepted_commit(self.slot.slot_index, _mk(self.c))
        self._emit_current_statement()
        return True

    def attempt_confirm_commit(self, hint: SCPStatement) -> bool:
        if self.phase != SCPPhase.CONFIRM or self.c is None:
            return False
        v = self.c[1]

        def pred(lo: int, hi: int) -> bool:
            return self._federated_ratify(
                lambda st: self.accepts_commit(v, lo, hi, st))
        interval = self._find_extended_interval(v, pred)
        if interval is None:
            return False
        lo, hi = interval
        self.c = (lo, v)
        self.h = (hi, v)
        self.phase = SCPPhase.EXTERNALIZE
        self._emit_current_statement()
        self.slot.stop_nomination()
        self._driver().value_externalized(self.slot.slot_index, v)
        return True

    def _attempt_bump(self) -> bool:
        """v-blocking set is ahead → jump to their lowest counter
        (repeat)."""
        if self.phase not in (SCPPhase.PREPARE, SCPPhase.CONFIRM):
            return False
        did = False
        while True:
            prev_b = self.b
            local_counter = self.b[0] if self.b is not None else 0
            counters = sorted({self.statement_ballot_counter(e.statement)
                               for e in self.latest_envelopes.values()
                               if self.statement_ballot_counter(e.statement)
                               > local_counter})
            target = None
            for n in counters:
                if LocalNode.is_v_blocking_filter(
                        self._local().qset, self.latest_envelopes.values(),
                        lambda st, n=n:
                        self.statement_ballot_counter(st) >= n):
                    target = n
                    # take the lowest v-blocking counter
                    break
            if target is None:
                return did
            self.abandon_ballot(target)
            if self.b == prev_b:
                return did  # bump had no effect; avoid spinning
            did = True

    # ------------------------------------------------------ timers / quorum
    def _check_heard_from_quorum(self) -> None:
        if self.b is None:
            return
        bn = self.b[0]

        def pred(st: SCPStatement) -> bool:
            return self.statement_ballot_counter(st) >= bn
        if LocalNode.is_quorum(self._local().qset, self.latest_envelopes,
                               self._qset_of, pred):
            was = self.heard_from_quorum
            self.heard_from_quorum = True
            if self.phase != SCPPhase.EXTERNALIZE:
                self._arm_timer()
            if not was:
                self._driver().ballot_did_hear_from_quorum(
                    self.slot.slot_index, _mk(self.b))
        else:
            self.heard_from_quorum = False

    def _arm_timer(self) -> None:
        from .driver import SCPTimerID
        if self.b is None or self.timer_counter == self.b[0]:
            return
        self.timer_counter = self.b[0]
        timeout = self._driver().compute_timeout(self.b[0])
        self._driver().setup_timer(
            self.slot.slot_index, SCPTimerID.BALLOT, timeout,
            self._on_timeout)

    def _on_timeout(self) -> None:
        self.timer_counter = 0
        self.abandon_ballot(0)

    # ------------------------------------------------------------- emission
    def _make_statement(self) -> SCPStatement:
        local = self._local()
        qh = local.qset_hash
        if self.phase == SCPPhase.PREPARE:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_PREPARE,
                SCPPrepare(
                    quorumSetHash=qh,
                    ballot=_mk(self.b) if self.b else SCPBallot(
                        counter=0, value=b""),
                    prepared=_mk(self.p) if self.p else None,
                    preparedPrime=_mk(self.pp) if self.pp else None,
                    nC=self.c[0] if self.c else 0,
                    nH=self.h[0] if self.h else 0))
        elif self.phase == SCPPhase.CONFIRM:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_CONFIRM,
                SCPConfirm(ballot=_mk(self.b),
                           nPrepared=self.p[0],
                           nCommit=self.c[0], nH=self.h[0],
                           quorumSetHash=qh))
        else:
            pl = SCPPledges(
                SCPStatementType.SCP_ST_EXTERNALIZE,
                SCPExternalize(commit=_mk(self.c), nH=self.h[0],
                               commitQuorumSetHash=qh))
        return SCPStatement(nodeID=local.node_id,
                            slotIndex=self.slot.slot_index, pledges=pl)

    def _emit_current_statement(self) -> None:
        st = self._make_statement()
        env = self.slot.create_envelope(st)
        # process our own statement first; broadcast only if it sticks
        if self.process_envelope(env, is_self=True) == \
                self.EnvelopeState.VALID:
            sx = st.to_xdr()
            if self.last_stmt_xdr != sx:
                self.last_stmt_xdr = sx
                if self._local().is_validator:
                    self._driver().emit_envelope(env)

    # --------------------------------------------------------------- state
    def get_json_info(self) -> dict:
        phase_names = {0: "PREPARE", 1: "CONFIRM", 2: "EXTERNALIZE"}
        return {
            "phase": phase_names[self.phase],
            "ballot": {"counter": self.b[0]} if self.b else None,
            "prepared": {"counter": self.p[0]} if self.p else None,
            "heard": self.heard_from_quorum,
        }

    def externalized_value(self) -> Optional[bytes]:
        if self.phase == SCPPhase.EXTERNALIZE:
            return self.c[1]
        return None
